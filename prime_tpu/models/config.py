"""Model architecture configs (Llama-3 family presets).

Frozen + hashable so a config can ride as a static jit argument.
"""

from __future__ import annotations

from dataclasses import dataclass, replace


@dataclass(frozen=True)
class ModelConfig:
    name: str
    vocab_size: int
    d_model: int
    n_layers: int
    n_heads: int
    n_kv_heads: int
    d_ff: int
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    rms_eps: float = 1e-5
    tie_embeddings: bool = False
    # decoupled per-head width (Qwen3/Gemma-style); None derives from d_model
    head_dim_override: int | None = None
    # q/k/v projection biases (Qwen2 family)
    attn_bias: bool = False
    # output-projection bias too (Llama-arch checkpoints with attention_bias;
    # Qwen2 biases only q/k/v)
    attn_out_bias: bool = False
    # per-head RMSNorm on q/k before rope (Qwen3 family)
    qk_norm: bool = False
    # full-width RMSNorm on the flat q/k projections before the head reshape
    # (OLMo-2: the rms statistic spans all heads jointly)
    qk_norm_full: bool = False
    # --- Gemma-family architecture knobs ---
    act: str = "silu"                 # MLP activation: "silu" | "gelu_tanh"
    norm_plus_one: bool = False       # RMSNorm scales by (1 + w)
    post_norms: bool = False          # norms on block outputs (Gemma2/3, OLMo-2)
    # input norms before each sublayer (every family EXCEPT OLMo-2, which is
    # post-norm only: sublayer output normed before the residual add)
    pre_norms: bool = True
    scale_embed: bool = False         # hidden *= sqrt(d_model) after embedding
    attn_softcap: float = 0.0         # tanh softcap on attention scores
    final_softcap: float = 0.0        # tanh softcap on output logits
    query_scale: float | None = None  # sm_scale = query_scale**-0.5 (else head_dim)
    sliding_window: int = 0           # window size for the sliding layers
    # which layers slide when sliding_window > 0: "even" (Gemma2 alternation,
    # even-index layers slide) | "uniform" (every layer slides, Mistral-style)
    # | "N:1" (Gemma3-style period: N sliding layers then 1 global, e.g.
    # "5:1"). Explicit so a config wanting a different pattern fails loudly
    # instead of silently inheriting the Gemma2 alternation.
    sliding_pattern: str = "even"
    # Gemma3: sliding (local) layers rope with their own base frequency;
    # None = all layers share rope_theta
    rope_local_theta: float | None = None
    # linear RoPE position scaling on the global-layer table (Gemma3 4b+
    # long-context stretch: factor 8)
    rope_scale: float = 1.0
    # Llama 3.1+ frequency-dependent rope scaling: (factor, low_freq_factor,
    # high_freq_factor, original_max_position). Mutually exclusive with
    # rope_scale; tuple-typed so the config stays hashable for jit
    rope_llama3: tuple[float, float, float, float] | None = None
    # YaRN NTK-by-parts scaling: (factor, beta_fast, beta_slow,
    # original_max_position, attention_factor) — attention_factor resolved at
    # load (incl. mscale variants) so model code just scales the tables
    rope_yarn: tuple[float, float, float, float, float] | None = None
    # GPT-OSS ships yarn with truncate=false: correction bounds stay
    # fractional instead of floor/ceil, shifting the interpolation ramp
    rope_yarn_truncate: bool = True
    # Phi-3.5 LongRoPE: (short_factors, long_factors, original_max_position,
    # attention_factor) — per-dim learned frequency rescales; the long set
    # applies when the table covers more than the pretrained range
    rope_longrope: tuple[tuple[float, ...], tuple[float, ...], float, float] | None = None
    # Phi-2-style partial rotary: only the first head_dim*partial_rotary
    # features of each head rotate, the tail passes through position-free
    partial_rotary: float = 1.0
    # GPT-OSS attention sinks: one learned logit per head joins every
    # softmax normalization (no value contribution) — a drain for attention
    # mass that otherwise piles onto early tokens
    attn_sinks: bool = False
    # mixture-of-experts (0 experts = dense MLP; Mixtral-style top-k routing)
    n_experts: int = 0
    experts_per_token: int = 2
    capacity_factor: float = 1.25
    # renormalize the chosen top-k gates to sum 1 (Mixtral, Qwen3-MoE w/
    # norm_topk_prob=True); False keeps raw softmax mass
    norm_topk: bool = True
    # GPT-OSS: biases on the router and every expert projection
    moe_bias: bool = False
    # GPT-OSS clamped GLU: ff = (up+1) * gate * sigmoid(1.702*gate) with
    # gate clamped above and up clamped both ways at this limit (0 = plain
    # silu gating)
    moe_glu_clamp: float = 0.0
    # --- DeepSeekMoE knobs ---
    # always-on shared expert(s): a dense silu MLP of width
    # n_shared_experts * d_ff added to every token's routed output
    n_shared_experts: int = 0
    # routing score function: "softmax" (Mixtral/Qwen) | "sigmoid"
    # (DeepSeek-V3 independent per-expert scores)
    moe_score_func: str = "softmax"
    # learned selection-only bias (V3 aux-loss-free balancing: shifts WHICH
    # experts are picked, never the gate values)
    moe_score_bias: bool = False
    # multiplier on the final routed combine weights (routed_scaling_factor)
    routed_scaling_factor: float = 1.0
    # V3 node-limited routing: experts partition into moe_n_groups groups,
    # only the moe_topk_groups best (by top-2 score sum) stay selectable
    moe_n_groups: int = 1
    moe_topk_groups: int = 1
    # DeepSeek first_k_dense_replace: the first k layers run a DENSE MLP of
    # width dense_ff (HF intermediate_size) instead of the MoE — the forward
    # scans the dense-prefix stack and the MoE stack separately
    first_k_dense: int = 0
    dense_ff: int | None = None  # dense-prefix MLP width (defaults to d_ff)

    # --- DeepSeek-style multi-head latent attention (MLA) ---
    # kv_lora_rank set => MLA: K/V live as ONE shared per-token latent
    # [c_kv (kv_lora_rank); k_pe (qk_rope_head_dim)] instead of per-head
    # K/V — the decode cache shrinks ~(2*H*hd)/(rank+rope)x. q_lora_rank
    # adds the low-rank query path (DeepSeek-V2/V3; the Lite models use a
    # direct query projection).
    kv_lora_rank: int | None = None
    q_lora_rank: int | None = None
    qk_rope_head_dim: int = 64    # roped sub-head, shared across heads (MQA-style)
    qk_nope_head_dim: int = 128   # position-free sub-head, absorbed into the latent
    v_head_dim: int = 128         # per-head value width out of the latent
    # DeepSeek-yarn long-context: multiplier on the MLA softmax scale
    # (yarn_get_mscale(factor, mscale_all_dim)^2 — HF applies it to
    # attention scaling, NOT the rope tables)
    attn_scale_mult: float = 1.0

    @property
    def head_dim(self) -> int:
        return self.head_dim_override or self.d_model // self.n_heads

    @property
    def mla(self) -> bool:
        return self.kv_lora_rank is not None

    @property
    def mla_cache_dim(self) -> int:
        """Per-token latent the cache stores: [c_kv; roped k_pe]."""
        assert self.kv_lora_rank is not None
        return self.kv_lora_rank + self.qk_rope_head_dim

    @property
    def is_moe(self) -> bool:
        return self.n_experts > 0

    @property
    def param_count(self) -> int:
        embed = self.vocab_size * self.d_model
        head = 0 if self.tie_embeddings else self.d_model * self.vocab_size
        if self.mla:
            nope, rope = self.qk_nope_head_dim, self.qk_rope_head_dim
            rank, vd, h = self.kv_lora_rank, self.v_head_dim, self.n_heads
            if self.q_lora_rank is not None:
                attn = self.d_model * self.q_lora_rank + self.q_lora_rank * (
                    1 + h * (nope + rope)
                )
            else:
                attn = self.d_model * h * (nope + rope)
            attn += (
                self.d_model * (rank + rope)  # wkv_a
                + rank                        # kv_a_norm
                + rank * h * (nope + vd)      # wkv_b
                + h * vd * self.d_model       # wo
            )
        else:
            attn = self.d_model * self.head_dim * (2 * self.n_heads + 2 * self.n_kv_heads)
        if self.attn_bias:
            attn += self.head_dim * (self.n_heads + 2 * self.n_kv_heads)
        if self.attn_out_bias:
            attn += self.d_model
        if self.qk_norm:
            attn += 2 * self.head_dim
        if self.qk_norm_full:
            attn += (self.n_heads + self.n_kv_heads) * self.head_dim
        if self.attn_sinks:
            attn += self.n_heads
        if self.is_moe:
            mlp = self.n_experts * 3 * self.d_model * self.d_ff + self.d_model * self.n_experts
            if self.moe_bias:
                mlp += self.n_experts * (2 * self.d_ff + self.d_model) + self.n_experts
            if self.moe_score_bias:
                mlp += self.n_experts
            if self.n_shared_experts:
                mlp += 3 * self.d_model * self.n_shared_experts * self.d_ff
        else:
            mlp = 3 * self.d_model * self.d_ff
        norms = ((2 if self.pre_norms else 0) + (2 if self.post_norms else 0)) * self.d_model
        if self.first_k_dense:
            dense_mlp = 3 * self.d_model * (self.dense_ff or self.d_ff)
            mlp_total = (
                (self.n_layers - self.first_k_dense) * mlp
                + self.first_k_dense * dense_mlp
            )
        else:
            mlp_total = self.n_layers * mlp
        return (
            embed + head + self.n_layers * (attn + norms) + mlp_total + self.d_model
        )

    def scaled(self, **overrides) -> "ModelConfig":
        return replace(self, **overrides)


MODEL_PRESETS: dict[str, ModelConfig] = {
    "llama3-8b": ModelConfig(
        name="llama3-8b",
        vocab_size=128256,
        d_model=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        d_ff=14336,
    ),
    "llama3-70b": ModelConfig(
        name="llama3-70b",
        vocab_size=128256,
        d_model=8192,
        n_layers=80,
        n_heads=64,
        n_kv_heads=8,
        d_ff=28672,
    ),
    # Llama 3.2: frequency-dependent llama3 rope scaling (factor 32 over the
    # 8k pretraining window) — matches the released checkpoints' config.json
    "llama3.2-1b": ModelConfig(
        name="llama3.2-1b",
        vocab_size=128256,
        d_model=2048,
        n_layers=16,
        n_heads=32,
        n_kv_heads=8,
        d_ff=8192,
        tie_embeddings=True,
        rope_llama3=(32.0, 1.0, 4.0, 8192.0),
    ),
    "llama3.2-3b": ModelConfig(
        name="llama3.2-3b",
        vocab_size=128256,
        d_model=3072,
        n_layers=28,
        n_heads=24,
        n_kv_heads=8,
        d_ff=8192,
        tie_embeddings=True,
        rope_llama3=(32.0, 1.0, 4.0, 8192.0),
    ),
    # Qwen2.5 family: q/k/v biases, 1M rope theta, small sizes tie embeddings
    "qwen2.5-0.5b": ModelConfig(
        name="qwen2.5-0.5b",
        vocab_size=151936,
        d_model=896,
        n_layers=24,
        n_heads=14,
        n_kv_heads=2,
        d_ff=4864,
        max_seq_len=32768,
        rope_theta=1000000.0,
        rms_eps=1e-6,
        tie_embeddings=True,
        attn_bias=True,
    ),
    "qwen2.5-1.5b": ModelConfig(
        name="qwen2.5-1.5b",
        vocab_size=151936,
        d_model=1536,
        n_layers=28,
        n_heads=12,
        n_kv_heads=2,
        d_ff=8960,
        max_seq_len=32768,
        rope_theta=1000000.0,
        rms_eps=1e-6,
        tie_embeddings=True,
        attn_bias=True,
    ),
    "qwen2.5-7b": ModelConfig(
        name="qwen2.5-7b",
        vocab_size=152064,
        d_model=3584,
        n_layers=28,
        n_heads=28,
        n_kv_heads=4,
        d_ff=18944,
        max_seq_len=32768,
        rope_theta=1000000.0,
        rms_eps=1e-6,
        attn_bias=True,
    ),
    "qwen2.5-14b": ModelConfig(
        name="qwen2.5-14b",
        vocab_size=152064,
        d_model=5120,
        n_layers=48,
        n_heads=40,
        n_kv_heads=8,
        d_ff=13824,
        max_seq_len=32768,
        rope_theta=1000000.0,
        rms_eps=1e-6,
        attn_bias=True,
    ),
    # Qwen3 family: decoupled head_dim 128, per-head q/k RMSNorm, no biases
    "qwen3-0.6b": ModelConfig(
        name="qwen3-0.6b",
        vocab_size=151936,
        d_model=1024,
        n_layers=28,
        n_heads=16,
        n_kv_heads=8,
        d_ff=3072,
        max_seq_len=40960,
        rope_theta=1000000.0,
        rms_eps=1e-6,
        tie_embeddings=True,
        head_dim_override=128,
        qk_norm=True,
    ),
    "qwen3-4b": ModelConfig(
        name="qwen3-4b",
        vocab_size=151936,
        d_model=2560,
        n_layers=36,
        n_heads=32,
        n_kv_heads=8,
        d_ff=9728,
        max_seq_len=40960,
        rope_theta=1000000.0,
        rms_eps=1e-6,
        tie_embeddings=True,
        head_dim_override=128,
        qk_norm=True,
    ),
    "qwen3-8b": ModelConfig(
        name="qwen3-8b",
        vocab_size=151936,
        d_model=4096,
        n_layers=36,
        n_heads=32,
        n_kv_heads=8,
        d_ff=12288,
        max_seq_len=40960,
        rope_theta=1000000.0,
        rms_eps=1e-6,
        head_dim_override=128,
        qk_norm=True,
    ),
    # Gemma 2 family: GeGLU, (1+w) norms, post-norms, scaled embeddings,
    # softcapping, alternating 4k sliding-window / global layers
    "gemma2-2b": ModelConfig(
        name="gemma2-2b",
        vocab_size=256000,
        d_model=2304,
        n_layers=26,
        n_heads=8,
        n_kv_heads=4,
        d_ff=9216,
        max_seq_len=8192,
        rope_theta=10000.0,
        rms_eps=1e-6,
        tie_embeddings=True,
        head_dim_override=256,
        act="gelu_tanh",
        norm_plus_one=True,
        post_norms=True,
        scale_embed=True,
        attn_softcap=50.0,
        final_softcap=30.0,
        query_scale=256,
        sliding_window=4096,
    ),
    "gemma2-9b": ModelConfig(
        name="gemma2-9b",
        vocab_size=256000,
        d_model=3584,
        n_layers=42,
        n_heads=16,
        n_kv_heads=8,
        d_ff=14336,
        max_seq_len=8192,
        rope_theta=10000.0,
        rms_eps=1e-6,
        tie_embeddings=True,
        head_dim_override=256,
        act="gelu_tanh",
        norm_plus_one=True,
        post_norms=True,
        scale_embed=True,
        attn_softcap=50.0,
        final_softcap=30.0,
        query_scale=256,
        sliding_window=4096,
    ),
    "gemma2-27b": ModelConfig(
        name="gemma2-27b",
        vocab_size=256000,
        d_model=4608,
        n_layers=46,
        n_heads=32,
        n_kv_heads=16,
        d_ff=36864,
        max_seq_len=8192,
        rope_theta=10000.0,
        rms_eps=1e-6,
        tie_embeddings=True,
        head_dim_override=128,
        act="gelu_tanh",
        norm_plus_one=True,
        post_norms=True,
        scale_embed=True,
        attn_softcap=50.0,
        final_softcap=30.0,
        query_scale=144,
        sliding_window=4096,
    ),
    # OLMo-2 family: post-norm-only blocks (no input norms; sublayer outputs
    # normed before the residual add) + full-width q/k RMSNorm
    "olmo2-7b": ModelConfig(
        name="olmo2-7b",
        vocab_size=100352,
        d_model=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=32,
        d_ff=11008,
        max_seq_len=4096,
        rope_theta=500000.0,
        rms_eps=1e-6,
        pre_norms=False,
        post_norms=True,
        qk_norm_full=True,
    ),
    "olmo2-13b": ModelConfig(
        name="olmo2-13b",
        vocab_size=100352,
        d_model=5120,
        n_layers=40,
        n_heads=40,
        n_kv_heads=40,
        d_ff=13824,
        max_seq_len=4096,
        rope_theta=500000.0,
        rms_eps=1e-6,
        pre_norms=False,
        post_norms=True,
        qk_norm_full=True,
    ),
    # Phi-3 family: llama math behind fused qkv/gate_up projections (split at
    # load); phi-4 shares the phi3 model_type with a 100k vocab
    "phi3-mini": ModelConfig(
        name="phi3-mini",
        vocab_size=32064,
        d_model=3072,
        n_layers=32,
        n_heads=32,
        n_kv_heads=32,
        d_ff=8192,
        max_seq_len=4096,
        rope_theta=10000.0,
        rms_eps=1e-5,
        sliding_window=2047,        # every layer slides (released 4k config)
        sliding_pattern="uniform",
    ),
    "phi4-14b": ModelConfig(
        name="phi4-14b",
        vocab_size=100352,
        d_model=5120,
        n_layers=40,
        n_heads=40,
        n_kv_heads=10,
        d_ff=17920,
        max_seq_len=16384,
        rope_theta=250000.0,
        rms_eps=1e-5,
    ),
    # Qwen3-MoE: qk-norm attention over 128 fine-grained experts, top-8,
    # raw-softmax gates renormalized per norm_topk_prob (True on the released
    # 30B-A3B), expert width 768 (moe_intermediate_size)
    "qwen3-30b-a3b": ModelConfig(
        name="qwen3-30b-a3b",
        vocab_size=151936,
        d_model=2048,
        n_layers=48,
        n_heads=32,
        n_kv_heads=4,
        d_ff=768,
        max_seq_len=32768,
        rope_theta=1000000.0,
        rms_eps=1e-6,
        head_dim_override=128,
        qk_norm=True,
        n_experts=128,
        experts_per_token=8,
        norm_topk=True,
        capacity_factor=2.0,
    ),
    # Gemma 3 family (text towers): Gemma2's GeGLU/(1+w)/post-norms/scaled
    # embeddings, minus the softcaps, plus per-head qk-norm, a 5:1
    # sliding/global schedule, and dual-frequency rope (global 1M — linearly
    # scaled x8 on 4b+ — local 10k). max_seq_len capped at 32k here (the
    # no-cache rope table is materialized at max_seq_len; serving longer
    # contexts sizes tables from the KV capacity instead).
    "gemma3-1b": ModelConfig(
        name="gemma3-1b",
        vocab_size=262144,
        d_model=1152,
        n_layers=26,
        n_heads=4,
        n_kv_heads=1,
        d_ff=6912,
        max_seq_len=32768,
        rope_theta=1000000.0,
        rope_local_theta=10000.0,
        rms_eps=1e-6,
        tie_embeddings=True,
        head_dim_override=256,
        qk_norm=True,
        act="gelu_tanh",
        norm_plus_one=True,
        post_norms=True,
        scale_embed=True,
        query_scale=256,
        sliding_window=512,
        sliding_pattern="5:1",
    ),
    "gemma3-4b": ModelConfig(
        name="gemma3-4b",
        vocab_size=262208,
        d_model=2560,
        n_layers=34,
        n_heads=8,
        n_kv_heads=4,
        d_ff=10240,
        max_seq_len=32768,
        rope_theta=1000000.0,
        rope_local_theta=10000.0,
        rope_scale=8.0,
        rms_eps=1e-6,
        tie_embeddings=True,
        head_dim_override=256,
        qk_norm=True,
        act="gelu_tanh",
        norm_plus_one=True,
        post_norms=True,
        scale_embed=True,
        query_scale=256,
        sliding_window=1024,
        sliding_pattern="5:1",
    ),
    "gemma3-12b": ModelConfig(
        name="gemma3-12b",
        vocab_size=262208,
        d_model=3840,
        n_layers=48,
        n_heads=16,
        n_kv_heads=8,
        d_ff=15360,
        max_seq_len=32768,
        rope_theta=1000000.0,
        rope_local_theta=10000.0,
        rope_scale=8.0,
        rms_eps=1e-6,
        tie_embeddings=True,
        head_dim_override=256,
        qk_norm=True,
        act="gelu_tanh",
        norm_plus_one=True,
        post_norms=True,
        scale_embed=True,
        query_scale=256,
        sliding_window=1024,
        sliding_pattern="5:1",
    ),
    "mixtral-8x7b": ModelConfig(
        name="mixtral-8x7b",
        vocab_size=32000,
        d_model=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        d_ff=14336,
        max_seq_len=32768,
        rope_theta=1000000.0,
        n_experts=8,
        experts_per_token=2,
    ),
    # small configs for tests / benches that still exercise every code path
    "debug-128m": ModelConfig(
        name="debug-128m",
        vocab_size=32000,
        d_model=768,
        n_layers=12,
        n_heads=12,
        n_kv_heads=4,
        d_ff=2048,
        max_seq_len=2048,
    ),
    # GPT-OSS (openai 2025): all-MoE with router+expert biases and clamped
    # GLU, per-head attention sinks, even-alternating sliding window 128,
    # q/k/v/o biases, non-truncated YaRN x32 over a 4k pretrain range.
    # attention_factor = mscale_of(32) = 0.1*ln(32)+1 ≈ 1.3466 (resolved here
    # like every other preset so model code only scales tables).
    "gpt-oss-20b": ModelConfig(
        name="gpt-oss-20b",
        vocab_size=201088,
        d_model=2880,
        n_layers=24,
        n_heads=64,
        n_kv_heads=8,
        d_ff=2880,
        max_seq_len=32768,
        rope_theta=150000.0,
        rms_eps=1e-5,
        head_dim_override=64,
        attn_bias=True,
        attn_out_bias=True,
        attn_sinks=True,
        sliding_window=128,
        sliding_pattern="even",
        rope_yarn=(32.0, 32.0, 1.0, 4096.0, 1.3465735902799727),
        rope_yarn_truncate=False,
        n_experts=32,
        experts_per_token=4,
        norm_topk=True,
        capacity_factor=2.0,
        moe_bias=True,
        moe_glu_clamp=7.0,
    ),
    "gpt-oss-120b": ModelConfig(
        name="gpt-oss-120b",
        vocab_size=201088,
        d_model=2880,
        n_layers=36,
        n_heads=64,
        n_kv_heads=8,
        d_ff=2880,
        max_seq_len=32768,
        rope_theta=150000.0,
        rms_eps=1e-5,
        head_dim_override=64,
        attn_bias=True,
        attn_out_bias=True,
        attn_sinks=True,
        sliding_window=128,
        sliding_pattern="even",
        rope_yarn=(32.0, 32.0, 1.0, 4096.0, 1.3465735902799727),
        rope_yarn_truncate=False,
        n_experts=128,
        experts_per_token=4,
        norm_topk=True,
        capacity_factor=2.0,
        moe_bias=True,
        moe_glu_clamp=7.0,
    ),
    "tiny-test": ModelConfig(
        name="tiny-test",
        vocab_size=512,
        d_model=128,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        d_ff=256,
        max_seq_len=512,
    ),
    # DeepSeek-V2-Lite at published scale (15.7B total / ~2.4B active):
    # MLA with direct query projection, 64 fine-grained experts (top-6) + 2
    # shared, one dense-prefix layer — the real checkpoint's architecture
    # (HF deepseek-ai/DeepSeek-V2-Lite config.json values)
    "deepseek-v2-lite": ModelConfig(
        name="deepseek-v2-lite",
        vocab_size=102400,
        d_model=2048,
        n_layers=27,
        n_heads=16,
        n_kv_heads=16,
        d_ff=1408,            # moe_intermediate_size (per-expert width)
        max_seq_len=32768,
        rope_theta=10000.0,
        rms_eps=1e-6,
        kv_lora_rank=512,
        qk_rope_head_dim=64,
        qk_nope_head_dim=128,
        v_head_dim=128,
        n_experts=64,
        experts_per_token=6,
        capacity_factor=2.0,
        n_shared_experts=2,
        moe_score_func="softmax",  # V2 gates with softmax (V3 moved to sigmoid)
        routed_scaling_factor=1.0,
        norm_topk=False,           # V2-Lite ships norm_topk_prob=false
        first_k_dense=1,
        dense_ff=10944,            # the prefix layer's dense intermediate
        # the checkpoint's yarn long-context: factor 40 over a 4096 window;
        # mscale == mscale_all_dim == 0.707 so the table attention factor
        # cancels to 1.0 and the whole mscale rides the softmax scale:
        # (0.1*0.707*ln(40)+1)^2. max_seq_len capped at 32k (the no-cache
        # forward materializes rope tables at this length; serving sizes
        # tables from KV capacity, so longer contexts still work).
        rope_yarn=(40.0, 32.0, 1.0, 4096.0, 1.0),
        attn_scale_mult=1.5896261651208736,
    ),
    # ~1B dense model with DeepSeek-V2-dimension MLA (rank 512 latent, 64
    # rope, 128 nope/value heads): the bench model for the latent-cache
    # long-context story — its decode cache is ~9x smaller than a
    # GQA model's at the same context
    "mla-1b": ModelConfig(
        name="mla-1b",
        vocab_size=32000,
        d_model=2048,
        n_layers=16,
        n_heads=16,
        n_kv_heads=16,
        d_ff=8192,
        max_seq_len=8192,
        kv_lora_rank=512,
        qk_rope_head_dim=64,
        qk_nope_head_dim=128,
        v_head_dim=128,
    ),
    # DeepSeek-V2-Lite-shaped MLA at test scale: direct query projection
    # (q_lora_rank=None), shared-latent KV cache, absorbed decode
    "tiny-mla": ModelConfig(
        name="tiny-mla",
        vocab_size=512,
        d_model=128,
        n_layers=2,
        n_heads=4,
        n_kv_heads=4,  # MLA has no GQA grouping; kept == n_heads for clarity
        d_ff=256,
        max_seq_len=512,
        kv_lora_rank=32,
        qk_rope_head_dim=16,
        qk_nope_head_dim=32,
        v_head_dim=32,
    ),
    # DeepSeek-V2/V3-style low-rank query path at test scale
    "tiny-mla-qlora": ModelConfig(
        name="tiny-mla-qlora",
        vocab_size=512,
        d_model=128,
        n_layers=2,
        n_heads=4,
        n_kv_heads=4,
        d_ff=256,
        max_seq_len=512,
        kv_lora_rank=32,
        q_lora_rank=48,
        qk_rope_head_dim=16,
        qk_nope_head_dim=32,
        v_head_dim=32,
    ),
    "tiny-moe": ModelConfig(
        name="tiny-moe",
        vocab_size=512,
        d_model=128,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        d_ff=256,
        max_seq_len=512,
        n_experts=4,
        experts_per_token=2,
        capacity_factor=2.0,
    ),
    # DeepSeek-V3 architecture at test scale: MLA + sigmoid-scored routing
    # with a selection-only balance bias, routed scaling, and an always-on
    # shared expert. Dense-prefix layers (first_k_dense_replace) and group
    # routing (n_group) are modeled too — covered by the HF-parity fixtures
    # in tests/test_mla.py rather than this preset
    "tiny-deepseek": ModelConfig(
        name="tiny-deepseek",
        vocab_size=512,
        d_model=128,
        n_layers=2,
        n_heads=4,
        n_kv_heads=4,
        d_ff=64,              # per-expert width (fine-grained experts)
        max_seq_len=512,
        kv_lora_rank=32,
        q_lora_rank=48,
        qk_rope_head_dim=16,
        qk_nope_head_dim=32,
        v_head_dim=32,
        n_experts=8,
        experts_per_token=2,
        capacity_factor=2.0,
        n_shared_experts=2,
        moe_score_func="sigmoid",
        moe_score_bias=True,
        routed_scaling_factor=2.5,
    ),
    # GPT-OSS architecture at test scale: sinks + biased clamped-GLU MoE +
    # alternating window + non-truncated yarn, all exercised on CPU
    "tiny-gptoss": ModelConfig(
        name="tiny-gptoss",
        vocab_size=512,
        d_model=128,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        d_ff=96,
        max_seq_len=512,
        rope_theta=150000.0,
        head_dim_override=32,
        attn_bias=True,
        attn_out_bias=True,
        attn_sinks=True,
        sliding_window=8,
        sliding_pattern="even",
        rope_yarn=(32.0, 32.0, 1.0, 64.0, 1.3465735902799727),
        rope_yarn_truncate=False,
        n_experts=4,
        experts_per_token=2,
        norm_topk=True,
        capacity_factor=2.0,
        moe_bias=True,
        moe_glu_clamp=7.0,
    ),
}


def get_config(name: str) -> ModelConfig:
    try:
        return MODEL_PRESETS[name]
    except KeyError:
        valid = ", ".join(sorted(MODEL_PRESETS))
        raise ValueError(f"Unknown model {name!r}: expected one of {valid}") from None
