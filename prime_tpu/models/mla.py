"""Multi-head latent attention (DeepSeek-V2/V3-style), TPU-first.

MLA stores ONE shared latent per token instead of per-head K/V: the cache
column is ``[c_kv (kv_lora_rank); roped k_pe (qk_rope_head_dim)]`` — for
DeepSeek-V2 dims that is 512+64 floats vs 2*H*hd (e.g. 2*32*128 = 8192), a
~14x smaller decode cache, which on TPU means ~14x less KV HBM traffic per
step and 14x longer context per chip.

This implementation uses the ABSORBED formulation everywhere (prefill and
decode): the per-head no-position query is projected into latent space
through W_kv_b's key half, so attention itself is plain GQA with ONE kv
head of width rank+rope —

    q_joint = [q_nope @ W_kc ; rope(q_pe)]          (B, H, S, rank+rope)
    k_joint = [rmsnorm(c_kv) ; rope(k_pe)]          (B, 1, S, rank+rope)
    scores  = q_joint . k_joint                      (== DeepSeek's two-part dot)
    ctx     = probs @ k_joint, keep first `rank`     (== probs @ c_kv exactly)
    out     = (ctx @ W_vc per head) @ wo

so every existing attention path (XLA grouped einsum, flash-decode pallas
kernel, chunked prefill, the continuous engine's slot cache) serves MLA
unchanged — the value tensor IS the key tensor and the rope tail is simply
dropped after the weighted sum. The softmax scale is (nope+rope)^-0.5, the
full query head width, matching DeepSeek.

Weights per layer (dense query unless ``q_lora_rank``):
    wq                 (d, H*(nope+rope))        [or wq_a/q_a_norm/wq_b]
    wkv_a              (d, rank+rope)
    kv_a_norm          (rank,)
    wkv_b              (rank, H*(nope+v))
    wo                 (H*v, d)

`naive_mla_attention` recomputes full per-head K/V from the latent (the
paper's textbook form) and exists as the parity oracle for tests.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from prime_tpu.models.config import ModelConfig
from prime_tpu.ops.attention import decode_attention, multi_head_attention
from prime_tpu.ops.rope import apply_rope_rows


def init_mla_attn_params(keys, config: ModelConfig, dtype, dense) -> dict:
    """The MLA attention weight dict for init_params (layer-stacked)."""
    d, layers = config.d_model, config.n_layers
    h = config.n_heads
    rank, rope = config.kv_lora_rank, config.qk_rope_head_dim
    nope, v = config.qk_nope_head_dim, config.v_head_dim
    weights = {
        "wkv_a": dense(keys[2], (layers, d, rank + rope), d),
        "kv_a_norm": jnp.ones((layers, rank), dtype=dtype),
        "wkv_b": dense(keys[3], (layers, rank, h * (nope + v)), rank),
        "wo": dense(keys[4], (layers, h * v, d), h * v),
    }
    if config.q_lora_rank is not None:
        qr = config.q_lora_rank
        weights |= {
            "wq_a": dense(keys[1], (layers, d, qr), d),
            "q_a_norm": jnp.ones((layers, qr), dtype=dtype),
            # keys[13]: every lower index belongs to a llama.init_params
            # weight (5/6/7 are the MLP stack) — sharing one would correlate
            # the two matrices at from-scratch init
            "wq_b": dense(keys[13], (layers, qr, h * (nope + rope)), qr),
        }
    else:
        weights["wq"] = dense(keys[1], (layers, d, h * (nope + rope)), d)
    return weights


def _rms(x: jnp.ndarray, weight: jnp.ndarray, config: ModelConfig) -> jnp.ndarray:
    # the shared rms_norm honors norm_plus_one; the latent/query low-rank
    # norms (kv_a_norm/q_a_norm) are init'd to ones, so plain scaling there
    # is the DeepSeek convention either way
    from prime_tpu.ops.norms import rms_norm

    return rms_norm(x, weight, config.rms_eps, plus_one=config.norm_plus_one)


# MLA reuses the shared attention ops through the absorbed joint-latent
# form, which cannot express these per-head attention features — reject
# them loudly instead of silently running different numerics
_UNSUPPORTED_WITH_MLA = (
    ("sliding_window", 0),
    ("attn_softcap", 0.0),
    ("attn_sinks", False),
    ("qk_norm", False),
    ("qk_norm_full", False),
    ("attn_bias", False),
    ("query_scale", None),
    ("partial_rotary", 1.0),
)


def validate_mla_config(config: ModelConfig) -> None:
    bad = [
        name for name, default in _UNSUPPORTED_WITH_MLA
        if getattr(config, name) != default
    ]
    if bad:
        raise ValueError(
            f"MLA (kv_lora_rank set) does not support {', '.join(bad)}: the "
            "absorbed latent attention has no per-head K to apply them to"
        )


def _split_wkv_b(lp, config: ModelConfig):
    """(w_kc, s_kc, w_vc, s_vc): the absorb/value halves of wkv_b with their
    int8 per-output-channel scales split alongside (None scales when fp).
    The scales fold exactly: the absorb einsum contracts the nope axis, so
    s_kc multiplies q_nope (the other contracted operand); the value einsum
    emits the v axis, so s_vc scales the output."""
    rank = config.kv_lora_rank
    h, nope, v = config.n_heads, config.qk_nope_head_dim, config.v_head_dim
    w = lp["wkv_b"]
    if isinstance(w, tuple):
        q8, s8 = w  # (rank, h*(nope+v)) int8, (1, h*(nope+v)) fp32
        wr = q8.reshape(rank, h, nope + v)
        sr = s8.reshape(h, nope + v)
        return wr[..., :nope], sr[..., :nope], wr[..., nope:], sr[..., nope:]
    wr = w.reshape(rank, h, nope + v)
    return wr[..., :nope], None, wr[..., nope:], None


def _queries_and_latent(x, lp, config: ModelConfig, cos_rows, sin_rows):
    """Shared front half: joint queries (B,H,S,rank+rope) and the per-token
    joint latent column (B,S,rank+rope) ready for the cache."""
    from prime_tpu.models.quantize import matmul as _mm

    batch, seq, _ = x.shape
    h = config.n_heads
    rank, rope = config.kv_lora_rank, config.qk_rope_head_dim
    nope = config.qk_nope_head_dim

    if "wq_a" in lp:
        q_lat = _rms(_mm(x, lp["wq_a"]), lp["q_a_norm"], config)
        q = _mm(q_lat, lp["wq_b"])
    else:
        q = _mm(x, lp["wq"])
    q = q.reshape(batch, seq, h, nope + rope)
    q_nope, q_pe = q[..., :nope], q[..., nope:]
    q_pe = apply_rope_rows(q_pe, cos_rows, sin_rows)

    kv = _mm(x, lp["wkv_a"])  # (B, S, rank+rope)
    c_kv = _rms(kv[..., :rank], lp["kv_a_norm"], config)
    k_pe = apply_rope_rows(kv[..., None, rank:], cos_rows, sin_rows)[:, :, 0, :]

    # absorb W_kv_b's key half into the query: q_nope -> latent space
    w_kc, s_kc, _, _ = _split_wkv_b(lp, config)
    if s_kc is not None:  # int8: fold the scales into the contracted operand
        q_nope = q_nope * s_kc[None, None].astype(q_nope.dtype)
    q_lat = jnp.einsum("bshn,rhn->bshr", q_nope, w_kc.astype(q_nope.dtype))
    q_joint = jnp.concatenate([q_lat, q_pe], axis=-1)  # (B, S, H, rank+rope)
    latent = jnp.concatenate([c_kv, k_pe], axis=-1)    # (B, S, rank+rope)
    return q_joint.transpose(0, 2, 1, 3), latent


def _project_out(ctx_latent, lp, config: ModelConfig):
    """(B, H, S, rank) latent context -> per-head values -> d_model."""
    from prime_tpu.models.quantize import matmul as _mm

    batch, h, seq, rank = ctx_latent.shape
    v = config.v_head_dim
    _, _, w_vc, s_vc = _split_wkv_b(lp, config)
    out = jnp.einsum("bhsr,rhv->bshv", ctx_latent, w_vc.astype(ctx_latent.dtype))
    if s_vc is not None:  # int8: v is the output axis, scales fold there
        out = out * s_vc[None, None].astype(out.dtype)
    return _mm(out.reshape(batch, seq, h * v), lp["wo"])


def mla_attention_block(
    x, lp, positions, rope_tables, config: ModelConfig,
    k_cache, v_cache, cache_lengths, decode: bool, attn_impl: str,
    prefill_offset=None,
):
    """Drop-in replacement for llama._attention_block on MLA configs.

    Cache contract: the joint latent rides the standard KVCache ``k`` array
    with KH=1 and head width rank+rope; ``v`` is a 1-wide dummy that passes
    through untouched (llama.init_cache allocates it). The attention ops
    receive the SAME latent array as both K and V and the rope tail of the
    weighted sum is discarded — probs @ [c_kv;k_pe] restricted to the first
    `rank` columns equals probs @ c_kv exactly.
    """
    batch, seq, _ = x.shape
    rank = config.kv_lora_rank
    # attn_scale_mult: DeepSeek-yarn mscale^2 rides the softmax scale
    sm_scale = (
        (config.qk_nope_head_dim + config.qk_rope_head_dim) ** -0.5
        * config.attn_scale_mult
    )
    cos, sin = rope_tables
    cos_rows, sin_rows = cos[positions], sin[positions]

    normed = _rms(x, lp["attn_norm"], config) if "attn_norm" in lp else x
    q_joint, latent = _queries_and_latent(normed, lp, config, cos_rows, sin_rows)

    new_k_cache = k_cache
    if decode:
        assert k_cache is not None and cache_lengths is not None
        col = latent.transpose(0, 2, 1)[:, None]  # (B, 1, rank+rope, 1)

        def one(c, n, idx):
            return jax.lax.dynamic_update_slice(c, n, (0, 0, idx))

        new_k_cache = jax.vmap(one)(k_cache, col, cache_lengths)
        ctx = decode_attention(
            q_joint, new_k_cache, new_k_cache, cache_lengths + 1, sm_scale,
            impl=attn_impl,
        )
    elif prefill_offset is not None:
        from prime_tpu.ops.attention import cache_prefill_attention

        off = prefill_offset.astype(jnp.int32)
        block = latent.transpose(0, 2, 1)[:, None]  # (B, 1, rank+rope, S)
        if off.ndim == 0:
            zero = jnp.zeros((), dtype=jnp.int32)
            new_k_cache = jax.lax.dynamic_update_slice(
                k_cache, block, (zero, zero, zero, off)
            )
        else:
            def one_row(c, n, idx):
                return jax.lax.dynamic_update_slice(c, n, (0, 0, idx))

            new_k_cache = jax.vmap(one_row)(k_cache, block, off)
        ctx = cache_prefill_attention(q_joint, new_k_cache, new_k_cache, off, sm_scale)
    else:
        kj = latent[:, None]  # (B, 1, S, rank+rope): one shared kv head
        ctx = multi_head_attention(q_joint, kj, kj, sm_scale, impl=attn_impl)
        if k_cache is not None:
            new_k_cache = jax.lax.dynamic_update_slice(
                k_cache, latent.transpose(0, 2, 1)[:, None], (0, 0, 0, 0)
            )

    out = _project_out(ctx[..., :rank], lp, config)
    if "attn_post_norm" in lp:
        out = _rms(out, lp["attn_post_norm"], config)
    return x + out, new_k_cache, v_cache, None, None


def naive_mla_attention(x, lp, positions, rope_tables, config: ModelConfig):
    """Textbook (non-absorbed) MLA for one no-cache block: full per-head K/V
    recomputed from the latent, standard attention. Parity oracle only."""
    batch, seq, _ = x.shape
    h = config.n_heads
    rank, rope = config.kv_lora_rank, config.qk_rope_head_dim
    nope, vd = config.qk_nope_head_dim, config.v_head_dim
    from prime_tpu.models.quantize import matmul as _mm

    cos, sin = rope_tables
    cos_rows, sin_rows = cos[positions], sin[positions]
    normed = _rms(x, lp["attn_norm"], config) if "attn_norm" in lp else x

    if "wq_a" in lp:
        q = _mm(_rms(_mm(normed, lp["wq_a"]), lp["q_a_norm"], config), lp["wq_b"])
    else:
        q = _mm(normed, lp["wq"])
    q = q.reshape(batch, seq, h, nope + rope)
    q_nope, q_pe = q[..., :nope], apply_rope_rows(q[..., nope:], cos_rows, sin_rows)

    kv = _mm(normed, lp["wkv_a"])
    c_kv = _rms(kv[..., :rank], lp["kv_a_norm"], config)
    k_pe = apply_rope_rows(kv[..., None, rank:], cos_rows, sin_rows)  # (B,S,1,rope)

    w_kc, s_kc, w_vc, s_vc = _split_wkv_b(lp, config)
    k_nope = jnp.einsum("bsr,rhn->bshn", c_kv, w_kc.astype(c_kv.dtype))
    if s_kc is not None:
        k_nope = k_nope * s_kc[None, None].astype(k_nope.dtype)
    v = jnp.einsum("bsr,rhv->bshv", c_kv, w_vc.astype(c_kv.dtype))
    if s_vc is not None:
        v = v * s_vc[None, None].astype(v.dtype)

    k = jnp.concatenate([k_nope, jnp.broadcast_to(k_pe, (batch, seq, h, rope))], -1)
    qf = jnp.concatenate([q_nope, q_pe], -1)
    sm_scale = (nope + rope) ** -0.5 * config.attn_scale_mult
    ctx = multi_head_attention(
        qf.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3),
        sm_scale, impl="xla",
    )
    out = _mm(ctx.transpose(0, 2, 1, 3).reshape(batch, seq, h * vd), lp["wo"])
    if "attn_post_norm" in lp:
        out = _rms(out, lp["attn_post_norm"], config)
    return x + out
