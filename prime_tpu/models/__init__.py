"""JAX model zoo: the native inference/eval compute path.

Pure-functional transformers (param pytrees + jitted apply fns), Llama-3
family first. ``get_config(name)`` resolves presets; ``prime_tpu.models.llama``
has init/forward; ``prime_tpu.models.sampler`` decodes with a KV cache.
"""

from prime_tpu.models.config import MODEL_PRESETS, ModelConfig, get_config

__all__ = ["ModelConfig", "MODEL_PRESETS", "get_config"]
