"""Llama-family transformer: pure-functional JAX, scan-over-layers, KV cache.

Design (TPU-first, not a port):
- Layer parameters are **stacked** along a leading n_layers axis and the
  decoder runs as one ``lax.scan`` — one compiled layer body regardless of
  depth, fast compiles, and clean (L, ...) sharding.
- One forward serves three regimes via static shape/flags: training (no
  cache), prefill (writes the cache), decode (S=1 against the cache).
- All matmuls in bf16 on the MXU with fp32 softmax/norm accumulation; the
  causal prefill path dispatches to the pallas flash kernel on TPU
  (prime_tpu.ops.pallas_attention).
- SPMD: pure functions of pytrees — sharding comes from the caller via
  NamedSharding on params/batch (prime_tpu.parallel.sharding), no mesh logic
  in model code.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp

from prime_tpu.models.config import ModelConfig
from prime_tpu.models.quantize import matmul as _mm
from prime_tpu.ops.attention import (
    _apply_softcap,
    cache_prefill_attention,
    decode_attention,
    multi_head_attention,
)
from prime_tpu.ops.norms import rms_norm
from prime_tpu.ops.rope import apply_rope_rows, rope_frequencies

Params = dict[str, Any]


class KVCache(NamedTuple):
    """Per-layer stacked KV cache: k/v are (L, B, KH, head_dim, C).

    The cache is stored **feature-major** (head_dim in sublanes, cache slots in
    lanes) so decode reads are lane-aligned for any head_dim: C is always a
    multiple of 128, head_dim often is not (llama3.2 uses 64). With the
    conventional (C, head_dim) layout the flash-decode kernel would pad 64
    lanes to 128 and read twice the cache bytes — fatal for a path that is
    pure HBM bandwidth.

    Optional int8 quantization (``init_cache(quantized=True)``): k/v hold int8
    with per-slot fp32 scales (L, B, KH, 1, C) — decode is pure HBM bandwidth,
    so halving the cache bytes is up to ~2x decode throughput at long context.
    The scales fold EXACTLY into the decode einsums (scores scale per key
    slot, value scale folds into the softmax weights), so the only error is
    the int8 rounding itself (~0.4% RMS per tensor).
    """

    k: jnp.ndarray
    v: jnp.ndarray
    lengths: jnp.ndarray  # (B,) valid entries per sequence
    k_scale: jnp.ndarray | None = None  # (L, B, KH, 1, C) fp32 when quantized
    v_scale: jnp.ndarray | None = None

    @property
    def capacity(self) -> int:
        return self.k.shape[4]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


def init_cache(
    config: ModelConfig,
    batch: int,
    capacity: int,
    dtype=jnp.bfloat16,
    quantized: bool = False,
) -> KVCache:
    if config.mla:
        # MLA: ONE shared latent column [c_kv; roped k_pe] per token rides
        # the k array (KH=1, width rank+rope); v is a 1-wide dummy the scan
        # carries untouched (models/mla.py) — the latent is already ~10x
        # smaller than per-head K/V, so int8 cache quant is not wired here
        if quantized:
            raise ValueError("MLA caches are latent-compressed; kv_quant is unsupported")
        return KVCache(
            k=jnp.zeros((config.n_layers, batch, 1, config.mla_cache_dim, capacity), dtype=dtype),
            v=jnp.zeros((config.n_layers, batch, 1, 1, capacity), dtype=dtype),
            lengths=jnp.zeros((batch,), dtype=jnp.int32),
        )
    shape = (config.n_layers, batch, config.n_kv_heads, config.head_dim, capacity)
    scale_shape = (config.n_layers, batch, config.n_kv_heads, 1, capacity)
    if quantized:
        return KVCache(
            k=jnp.zeros(shape, dtype=jnp.int8),
            v=jnp.zeros(shape, dtype=jnp.int8),
            lengths=jnp.zeros((batch,), dtype=jnp.int32),
            k_scale=jnp.zeros(scale_shape, dtype=jnp.float32),
            v_scale=jnp.zeros(scale_shape, dtype=jnp.float32),
        )
    return KVCache(
        k=jnp.zeros(shape, dtype=dtype),
        v=jnp.zeros(shape, dtype=dtype),
        lengths=jnp.zeros((batch,), dtype=jnp.int32),
    )


def quantize_kv(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-slot symmetric int8: x is (..., head_dim, S). Returns (q, scale)
    with scale shaped (..., 1, S)."""
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-2, keepdims=True)
    scale = absmax / 127.0
    q = jnp.round(x.astype(jnp.float32) / jnp.maximum(scale, 1e-12)).astype(jnp.int8)
    return q, scale


def _norm(x: jnp.ndarray, weight: jnp.ndarray, config: ModelConfig) -> jnp.ndarray:
    return rms_norm(x, weight, config.rms_eps, plus_one=config.norm_plus_one)


def _lora_kernel_eligible(w: Any, x: jnp.ndarray, b: jnp.ndarray) -> bool:
    """Gate for the fused gathered-LoRA pallas kernel (ops/pallas_lora.py):
    plain (unquantized) 2-D base weight, single device (a bare pallas_call
    cannot partition under SPMD jit — same rule as the quantized-matmul
    kernels), and a TPU backend — or interpret mode, which is how the CPU
    test matrix pins the kernel bit-identical to the einsum chain. Real
    TPUs additionally need lane-aligned projection dims; interpret mode
    relaxes that so tiny test models still exercise the kernel."""
    from prime_tpu.models.quantize import _mesh_context_active
    from prime_tpu.ops.attention import _pallas_interpret

    if isinstance(w, tuple) or getattr(w, "ndim", 0) != 2:
        return False
    if _mesh_context_active():
        return False
    if _pallas_interpret():
        return True
    return (
        jax.default_backend() == "tpu"
        and x.shape[-1] % 128 == 0
        and b.shape[-1] % 128 == 0
    )


def _lora_mm(
    x: jnp.ndarray,               # (B, S, d_in) projection input
    lp: Params,                   # one layer's params (may carry lora stacks)
    name: str,                    # target projection ("wq", "w_down", ...)
    adapter_ids: jnp.ndarray | None,  # (B,) int32 per-row bank slots
) -> jnp.ndarray:
    """One adapted projection: ``x @ W`` plus, when the layer carries a
    multi-LoRA bank stack for this target, the per-row gathered BGMV-style
    delta ``(x @ A[idx]) @ B'[idx]`` (serve/adapters.py — B' has the LoRA
    scale folded in; bank slot 0 is the all-zeros base adapter, so base rows
    add an exact zero). Factor math runs in fp32 like ``merge_lora``'s delta
    — the factors are tiny, no reason to round them — and the delta is added
    in the activation dtype, mirroring the merged path's cast.

    When eligible, base + gather + delta run as ONE pallas program
    (ops/pallas_lora.fused_lora_matmul — the adapter gather happens in the
    kernel's BlockSpec index maps, so the stacked bank is never copied per
    row); the kernel replicates this chain's rounding exactly and the einsum
    path below stays the non-TPU/mesh reference."""
    a = lp.get(f"lora:{name}:a")  # (A, d_in, r) this layer's stacked A
    if a is None or adapter_ids is None:
        return _mm(x, lp[name])
    b = lp[f"lora:{name}:b"]      # (A, r, d_out)
    w = lp[name]
    if _lora_kernel_eligible(w, x, b):
        from prime_tpu.ops.attention import _pallas_interpret
        from prime_tpu.ops.pallas_lora import fused_lora_matmul

        return fused_lora_matmul(
            x, w, a, b, adapter_ids, interpret=_pallas_interpret()
        )
    y = _mm(x, w)
    a_rows = a[adapter_ids].astype(jnp.float32)   # (B, d_in, r) row gather
    b_rows = b[adapter_ids].astype(jnp.float32)   # (B, r, d_out)
    h = jnp.einsum("bsd,bdr->bsr", x.astype(jnp.float32), a_rows)
    delta = jnp.einsum("bsr,bro->bso", h, b_rows)
    return y + delta.astype(y.dtype)


def merge_adapter_stacks(stack: Params, adapters: dict | None, rows: slice) -> Params:
    """Merge a multi-LoRA bank's per-target ``(L, A, ...)`` factor stacks
    into a layer-param stack under reserved ``lora:<target>:a/b`` keys, so
    the stacks scan with the layer params (one compiled layer body, adapters
    included) — sliced by the same ``rows`` the layer stacks use. Targets
    absent from this stack (e.g. attention keys of a different stack) are
    skipped."""
    if adapters is None:
        return stack
    merged = dict(stack)
    for name, ab in adapters["layers"].items():
        if name not in stack:
            continue
        merged[f"lora:{name}:a"] = ab["a"][rows]
        merged[f"lora:{name}:b"] = ab["b"][rows]
    return merged


def init_params(rng: jax.Array, config: ModelConfig, dtype=jnp.bfloat16) -> Params:
    """Random init (truncated-normal-ish scaled); checkpoint loaders overwrite."""
    keys = jax.random.split(rng, 17)
    d, hd = config.d_model, config.head_dim
    h, kh, ff, layers = config.n_heads, config.n_kv_heads, config.d_ff, config.n_layers
    # DeepSeek dense prefix: the MLP stacks cover only the tail layers — at
    # 256-expert scale, building full-length expert stacks just to slice
    # them would be a multi-GB transient allocation
    mlp_layers = layers - config.first_k_dense
    # Gemma-style (1+w) norms are zero-initialized (≡ unit scale)
    norm_init = jnp.zeros if config.norm_plus_one else jnp.ones

    def dense(key, shape, fan_in):
        return (jax.random.normal(key, shape, dtype=jnp.float32) * (fan_in**-0.5)).astype(dtype)

    if config.is_moe:
        experts = config.n_experts
        mlp_weights = {
            # router stays fp32: routing decisions are precision-sensitive
            "router": dense(keys[9], (mlp_layers, d, experts), d).astype(jnp.float32),
            "w_gate": dense(keys[5], (mlp_layers, experts, d, ff), d),
            "w_up": dense(keys[6], (mlp_layers, experts, d, ff), d),
            "w_down": dense(keys[7], (mlp_layers, experts, ff, d), ff),
        }
        if config.moe_bias:  # GPT-OSS: router + every expert projection
            mlp_weights |= {
                "router_bias": jnp.zeros((mlp_layers, experts), dtype=jnp.float32),
                "b_gate": jnp.zeros((mlp_layers, experts, ff), dtype=dtype),
                "b_up": jnp.zeros((mlp_layers, experts, ff), dtype=dtype),
                "b_down": jnp.zeros((mlp_layers, experts, d), dtype=dtype),
            }
        if config.moe_score_bias:  # DeepSeek-V3 aux-free balance bias (fp32,
            # selection-only — updated out-of-band, not by the loss)
            mlp_weights["score_bias"] = jnp.zeros((mlp_layers, experts), dtype=jnp.float32)
        if config.n_shared_experts:  # DeepSeekMoE always-on shared expert(s)
            sf = config.n_shared_experts * ff
            mlp_weights |= {
                "w_shared_gate": dense(keys[10], (mlp_layers, d, sf), d),
                "w_shared_up": dense(keys[11], (mlp_layers, d, sf), d),
                "w_shared_down": dense(keys[12], (mlp_layers, sf, d), sf),
            }
    else:
        mlp_weights = {
            "w_gate": dense(keys[5], (mlp_layers, d, ff), d),
            "w_up": dense(keys[6], (mlp_layers, d, ff), d),
            "w_down": dense(keys[7], (mlp_layers, ff, d), ff),
        }

    attn_biases = {}
    if config.attn_bias:  # Qwen2-style q/k/v biases (no output bias)
        attn_biases = {
            "bq": jnp.zeros((layers, h * hd), dtype=dtype),
            "bk": jnp.zeros((layers, kh * hd), dtype=dtype),
            "bv": jnp.zeros((layers, kh * hd), dtype=dtype),
        }
    if config.attn_out_bias:  # Llama-arch attention_bias biases o_proj too
        attn_biases["bo"] = jnp.zeros((layers, d), dtype=dtype)
    if config.qk_norm:  # Qwen3-style per-head q/k RMSNorm (weights shared across heads)
        attn_biases |= {
            "q_norm": norm_init((layers, hd), dtype=dtype),
            "k_norm": norm_init((layers, hd), dtype=dtype),
        }
    if config.qk_norm_full:  # OLMo-2: rms statistic spans all heads jointly
        attn_biases |= {
            "q_norm_full": norm_init((layers, h * hd), dtype=dtype),
            "k_norm_full": norm_init((layers, kh * hd), dtype=dtype),
        }
    if config.attn_sinks:  # GPT-OSS: per-head sink logits (fp32 — they live
        # inside the softmax normalization)
        attn_biases["sinks"] = jnp.zeros((layers, h), dtype=jnp.float32)
    if config.post_norms:  # Gemma2/OLMo-2 norms on the block outputs
        attn_biases |= {
            "attn_post_norm": norm_init((layers, d), dtype=dtype),
            "mlp_post_norm": norm_init((layers, d), dtype=dtype),
        }
    pre_norms = (
        {
            "attn_norm": norm_init((layers, d), dtype=dtype),
            "mlp_norm": norm_init((layers, d), dtype=dtype),
        }
        if config.pre_norms
        else {}
    )
    if config.mla:
        from prime_tpu.models.mla import init_mla_attn_params

        attn_weights = init_mla_attn_params(keys, config, dtype, dense)
    else:
        attn_weights = {
            "wq": dense(keys[1], (layers, d, h * hd), d),
            "wk": dense(keys[2], (layers, d, kh * hd), d),
            "wv": dense(keys[3], (layers, d, kh * hd), d),
            "wo": dense(keys[4], (layers, h * hd, d), h * hd),
        }
    shared_keys = {**attn_weights, **pre_norms, **attn_biases}
    params: Params = {
        "embed": dense(keys[0], (config.vocab_size, d), d),
        "layers": {
            **shared_keys,
            **mlp_weights,
        },
        "final_norm": norm_init((d,), dtype=dtype),
    }
    if config.first_k_dense:
        # DeepSeek dense-prefix: the first k layers swap the MoE for a dense
        # MLP of width dense_ff. Attention/norm/bias stacks were built over
        # ALL layers — split them; the MLP stacks were already built
        # tail-sized (mlp_layers).
        kd = config.first_k_dense
        dff = config.dense_ff or ff
        params["layers"] = {
            key: (value[kd:] if key in shared_keys else value)
            for key, value in params["layers"].items()
        }
        params["dense_layers"] = {
            **{key: value[:kd] for key, value in shared_keys.items()},
            "w_gate": dense(keys[14], (kd, d, dff), d),
            "w_up": dense(keys[15], (kd, d, dff), d),
            "w_down": dense(keys[16], (kd, dff, d), dff),
        }
    if not config.tie_embeddings:
        params["lm_head"] = dense(keys[8], (d, config.vocab_size), d)
    return params


def sliding_layer_flags(config: ModelConfig) -> jnp.ndarray:
    """(n_layers,) bool: which layers use the sliding window. The pattern is
    an explicit config field so non-Gemma2 window schemes can't silently
    inherit the even alternation. Shared by forward() and the pipeline (where
    the flags shard over pp alongside the layer stack)."""
    if not config.sliding_window:
        return jnp.zeros((config.n_layers,), dtype=bool)
    if config.sliding_pattern == "even":  # Gemma2: even layers slide
        return jnp.arange(config.n_layers) % 2 == 0
    if config.sliding_pattern == "uniform":  # Mistral-style: all layers slide
        return jnp.ones((config.n_layers,), dtype=bool)
    if config.sliding_pattern.endswith(":1"):  # Gemma3 "5:1": every (N+1)th is global
        period = int(config.sliding_pattern[:-2]) + 1
        return (jnp.arange(config.n_layers) + 1) % period != 0
    raise ValueError(
        f"Unknown sliding_pattern {config.sliding_pattern!r} "
        "(want 'even' | 'uniform' | 'N:1')"
    )


def _attention_block(
    x: jnp.ndarray,               # (B, S, D)
    lp: Params,                   # one layer's params
    positions: jnp.ndarray,       # (B, S)
    rope_tables: tuple[jnp.ndarray, jnp.ndarray],
    config: ModelConfig,
    k_cache: jnp.ndarray | None,  # (B, KH, hd, C) this layer (int8 when quantized)
    v_cache: jnp.ndarray | None,
    cache_lengths: jnp.ndarray | None,
    decode: bool,
    attn_impl: str,
    k_scale: jnp.ndarray | None = None,  # (B, KH, 1, C) when quantized
    v_scale: jnp.ndarray | None = None,
    prefill_offset: jnp.ndarray | None = None,  # () chunked prefill: write+attend at offset
    sliding: jnp.ndarray | None = None,  # () traced bool: this layer uses the window
    rope_tables_local: tuple[jnp.ndarray, jnp.ndarray] | None = None,
    mesh=None,  # mesh-aware impls: "ring" (context-parallel training),
    #             "sharded" (serve decode: flash kernel under shard_map)
    adapter_ids: jnp.ndarray | None = None,  # (B,) multi-LoRA bank slots
):
    batch, seq, _ = x.shape
    h, kh, hd = config.n_heads, config.n_kv_heads, config.head_dim
    sm_scale = (config.query_scale or hd) ** -0.5
    gemma_kw = dict(
        softcap=config.attn_softcap, window=config.sliding_window, sliding=sliding,
        sinks=lp.get("sinks"),
    )
    cos, sin = rope_tables
    # gather the seq-sized rows FIRST, then (Gemma3) select local vs global
    # by the traced per-layer flag — selecting full (max_pos, D/2) tables in
    # every scanned layer would waste HBM bandwidth in the decode hot loop
    cos_rows, sin_rows = cos[positions], sin[positions]  # (B, S, D/2)
    if rope_tables_local is not None and sliding is not None:
        cos_rows = jnp.where(sliding, rope_tables_local[0][positions], cos_rows)
        sin_rows = jnp.where(sliding, rope_tables_local[1][positions], sin_rows)

    # OLMo-2 is post-norm only: no input norm param, the raw residual feeds in
    normed = _norm(x, lp["attn_norm"], config) if "attn_norm" in lp else x
    q = _lora_mm(normed, lp, "wq", adapter_ids)
    k = _lora_mm(normed, lp, "wk", adapter_ids)
    v = _lora_mm(normed, lp, "wv", adapter_ids)
    if "bq" in lp:  # Qwen2-style q/k/v biases
        q, k, v = q + lp["bq"], k + lp["bk"], v + lp["bv"]
    if "q_norm_full" in lp:  # OLMo-2: full-width RMSNorm before the head split
        q = _norm(q, lp["q_norm_full"], config)
        k = _norm(k, lp["k_norm_full"], config)
    q = q.reshape(batch, seq, h, hd)
    k = k.reshape(batch, seq, kh, hd)
    v = v.reshape(batch, seq, kh, hd)
    if "q_norm" in lp:  # Qwen3/Gemma3-style per-head RMSNorm before rope
        q = _norm(q, lp["q_norm"], config)
        k = _norm(k, lp["k_norm"], config)
    q = apply_rope_rows(q, cos_rows, sin_rows)
    k = apply_rope_rows(k, cos_rows, sin_rows)

    q = q.transpose(0, 2, 1, 3)  # (B, H, S, hd)
    k = k.transpose(0, 2, 1, 3)
    v = v.transpose(0, 2, 1, 3)

    quantized = k_scale is not None
    new_k_cache, new_v_cache = k_cache, v_cache
    new_k_scale, new_v_scale = k_scale, v_scale
    if decode:
        assert k_cache is not None and cache_lengths is not None
        # scatter this step's k/v column into each sequence's next free slot
        def put(cache, col):  # cache (B, KH, *, C), col (B, KH, *, 1)
            def one(c, n, idx):
                return jax.lax.dynamic_update_slice(c, n, (0, 0, idx))

            return jax.vmap(one)(cache, col, cache_lengths)

        k_col = k.transpose(0, 1, 3, 2)  # (B, KH, hd, 1)
        v_col = v.transpose(0, 1, 3, 2)
        if quantized:
            k_q, k_s = quantize_kv(k_col)
            v_q, v_s = quantize_kv(v_col)
            new_k_cache, new_k_scale = put(k_cache, k_q), put(k_scale, k_s)
            new_v_cache, new_v_scale = put(v_cache, v_q), put(v_scale, v_s)
        else:
            new_k_cache = put(k_cache, k_col)
            new_v_cache = put(v_cache, v_col)
        attn = decode_attention(
            q, new_k_cache, new_v_cache, cache_lengths + 1, sm_scale, impl=attn_impl,
            k_scale=new_k_scale, v_scale=new_v_scale, mesh=mesh, **gemma_kw,
        )
    elif prefill_offset is not None:
        # chunked prefill: write this chunk's K/V into the cache at the
        # offset, then attend over the cache (earlier chunks + reused prefix
        # are visible; within-chunk attention stays causal via the mask).
        # int8 caches are exact here too: scales are PER-SLOT and chunks
        # write disjoint slots, so each chunk quantizes its own columns once.
        assert k_cache is not None
        off = prefill_offset.astype(jnp.int32)
        k_t = k.transpose(0, 1, 3, 2)  # (B, KH, hd, S)
        v_t = v.transpose(0, 1, 3, 2)
        k_block_scale = v_block_scale = None
        if quantized:
            k_t, k_block_scale = quantize_kv(k_t)  # int8 + (B, KH, 1, S) scales
            v_t, v_block_scale = quantize_kv(v_t)
        if off.ndim == 0:  # one shared chunk offset
            zero = jnp.zeros((), dtype=jnp.int32)

            def put_shared(cache, block):
                return jax.lax.dynamic_update_slice(cache, block, (zero, zero, zero, off))

            new_k_cache = put_shared(k_cache, k_t)
            new_v_cache = put_shared(v_cache, v_t)
            if quantized:
                new_k_scale = put_shared(k_scale, k_block_scale)
                new_v_scale = put_shared(v_scale, v_block_scale)
        else:  # (B,): per-row window starts (speculative verify)
            def put_rows(cache, block):
                def one(c, n, idx):
                    return jax.lax.dynamic_update_slice(c, n, (0, 0, idx))

                return jax.vmap(one)(cache, block, off)

            new_k_cache = put_rows(k_cache, k_t)
            new_v_cache = put_rows(v_cache, v_t)
            if quantized:
                new_k_scale = put_rows(k_scale, k_block_scale)
                new_v_scale = put_rows(v_scale, v_block_scale)
        attn = cache_prefill_attention(
            q, new_k_cache, new_v_cache, off, sm_scale,
            k_scale=new_k_scale if quantized else None,
            v_scale=new_v_scale if quantized else None,
            **gemma_kw,
        )
    elif attn_impl == "ring":
        # context-parallel training: the sequence axis is sharded over the
        # mesh's `sp` axis and KV blocks rotate via ring attention
        # (parallel/ring_attention.py); no-cache path only. The uniform
        # window/softcap/sink knobs ride the ring fold; PER-LAYER sliding
        # schedules can't (the hop cap must be static and uniform across
        # the scanned layers), which forward() rejects up front.
        from prime_tpu.parallel.ring_attention import ring_self_attention
        from prime_tpu.parallel.sharding import ring_qkv_axes

        batch_axis, head_axis = ring_qkv_axes(mesh, kh)
        attn = ring_self_attention(
            q, k, v, mesh, seq_axis="sp", sm_scale=sm_scale,
            window=config.sliding_window, softcap=config.attn_softcap,
            sinks=lp.get("sinks"),
            batch_axis=batch_axis, head_axis=head_axis,
        )
    else:
        attn = multi_head_attention(q, k, v, sm_scale, impl=attn_impl, **gemma_kw)
        if k_cache is not None:
            # prefill: stage the prompt's k/v feature-major at slots [0, S)
            k_t = k.transpose(0, 1, 3, 2)  # (B, KH, hd, S)
            v_t = v.transpose(0, 1, 3, 2)
            if quantized:
                k_q, k_s = quantize_kv(k_t)
                v_q, v_s = quantize_kv(v_t)
                new_k_cache = jax.lax.dynamic_update_slice(k_cache, k_q, (0, 0, 0, 0))
                new_v_cache = jax.lax.dynamic_update_slice(v_cache, v_q, (0, 0, 0, 0))
                new_k_scale = jax.lax.dynamic_update_slice(k_scale, k_s, (0, 0, 0, 0))
                new_v_scale = jax.lax.dynamic_update_slice(v_scale, v_s, (0, 0, 0, 0))
            else:
                new_k_cache = jax.lax.dynamic_update_slice(k_cache, k_t, (0, 0, 0, 0))
                new_v_cache = jax.lax.dynamic_update_slice(v_cache, v_t, (0, 0, 0, 0))

    attn = attn.transpose(0, 2, 1, 3).reshape(batch, seq, h * hd)
    out = _lora_mm(attn, lp, "wo", adapter_ids)
    if "bo" in lp:  # Llama-arch attention_bias checkpoints bias o_proj too
        out = out + lp["bo"]
    if "attn_post_norm" in lp:  # Gemma2-style post-norm before the residual add
        out = _norm(out, lp["attn_post_norm"], config)
    return x + out, new_k_cache, new_v_cache, new_k_scale, new_v_scale


def _mlp_block(
    x: jnp.ndarray, lp: Params, config: ModelConfig,
    adapter_ids: jnp.ndarray | None = None,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Dense or sparse-MoE feed-forward. Returns (residual output, aux loss)."""
    normed = _norm(x, lp["mlp_norm"], config) if "mlp_norm" in lp else x
    # key-presence decides, not config.is_moe alone: a DeepSeek dense-prefix
    # layer (first_k_dense) carries a plain MLP inside an MoE model
    if config.is_moe and "router" in lp:
        from prime_tpu.ops.moe import moe_mlp

        y, aux = moe_mlp(
            normed,
            lp["router"],
            lp["w_gate"],
            lp["w_up"],
            lp["w_down"],
            k=config.experts_per_token,
            capacity_factor=config.capacity_factor,
            norm_topk=config.norm_topk,
            router_b=lp.get("router_bias"),
            b_gate=lp.get("b_gate"),
            b_up=lp.get("b_up"),
            b_down=lp.get("b_down"),
            glu_clamp=config.moe_glu_clamp,
            score_func=config.moe_score_func,
            select_bias=lp.get("score_bias"),
            routed_scale=config.routed_scaling_factor,
            route_groups=config.moe_n_groups,
            route_topk_groups=config.moe_topk_groups,
        )
        if "w_shared_gate" in lp:
            # DeepSeekMoE shared expert(s): a dense always-on silu MLP added
            # to the routed output (every token, no capacity, no routing)
            shared_gate = jax.nn.silu(_mm(normed, lp["w_shared_gate"]))
            y = y + _mm(shared_gate * _mm(normed, lp["w_shared_up"]), lp["w_shared_down"])
        if "mlp_post_norm" in lp:
            y = _norm(y, lp["mlp_post_norm"], config)
        return x + y, aux
    act = jax.nn.silu if config.act == "silu" else _gelu_tanh
    gate = act(_lora_mm(normed, lp, "w_gate", adapter_ids))
    up = _lora_mm(normed, lp, "w_up", adapter_ids)
    y = _lora_mm(gate * up, lp, "w_down", adapter_ids)
    if "mlp_post_norm" in lp:  # Gemma2-style post-norm before the residual add
        y = _norm(y, lp["mlp_post_norm"], config)
    return x + y, jnp.zeros((), jnp.float32)


def _gelu_tanh(x: jnp.ndarray) -> jnp.ndarray:
    """HF's gelu_pytorch_tanh (the Gemma MLP activation)."""
    return jax.nn.gelu(x, approximate=True)


def forward(
    params: Params,
    tokens: jnp.ndarray,                 # (B, S) int32
    config: ModelConfig,
    positions: jnp.ndarray | None = None,  # (B, S); default arange (+ prefill_offset)
    cache: KVCache | None = None,
    decode: bool = False,
    attn_impl: str = "auto",
    return_aux: bool = False,
    prefill_offset: jnp.ndarray | None = None,  # () traced; chunked prefill at offset
    remat: str = "none",  # "none" | "full" | "dots" — training-path rematerialization
    longrope_select: int | None = None,  # static run-length bound for LongRoPE
    mesh=None,  # mesh-aware attn impls — "ring": mesh whose `sp` axis shards
    #           the sequence; "sharded": serving mesh for the shard_mapped
    #           flash-decode dispatch (parallel/decode_sharded.py)
    last_positions: jnp.ndarray | None = None,  # (B,) → logits only at these rows
    adapters: dict | None = None,  # multi-LoRA bank stacks (serve/adapters.py)
    adapter_ids: jnp.ndarray | None = None,  # (B,) int32 per-row bank slots
):
    """Run the transformer. Returns (logits (B, S, V) fp32, updated cache),
    plus the summed MoE load-balance aux loss when ``return_aux``.
    With ``last_positions`` the head matmul runs on ONE gathered position per
    row and logits are (B, 1, V): a prefill that only needs each sequence's
    next-token logits skips S× the unembedding FLOPs and never materializes
    the (B, S, V) fp32 buffer (~8 GB at B=4, S=4k, llama vocab — observed
    crashing the remote TPU compile helper before this path existed).

    - training:        cache=None, decode=False
    - prefill:         cache=init_cache(...), decode=False
    - chunked prefill: cache w/ lengths=offset, decode=False,
                       prefill_offset=offset — writes this chunk's KV at
                       [offset, offset+S) and attends over the cache, so a
                       long prompt (or a suffix after a reused prefix) feeds
                       in S-token chunks with O(S·C) attention memory
    - decode step:     cache=<filled>, decode=True, S must be 1
    """
    batch, seq = tokens.shape
    if config.mla:
        from prime_tpu.models.mla import validate_mla_config

        # loud rejection of per-head attention features the absorbed latent
        # form can't express (window/softcap/sinks/qk_norm/bias/...)
        validate_mla_config(config)
    if attn_impl == "ring":
        # context parallelism is a TRAINING-path mode: the KV cache's slot
        # axis is not ring-sharded (long-context decode is long_context.py's
        # sp path), and per-layer sliding schedules would need a per-layer
        # static hop cap the uniform scan can't express
        if config.mla:
            raise ValueError(
                "attn_impl='ring' does not serve MLA configs yet (the ring "
                "fold rotates per-head K/V, not the shared latent)"
            )
        if cache is not None:
            raise ValueError("attn_impl='ring' serves the no-cache (training) path only")
        if mesh is None or "sp" not in mesh.shape:
            raise ValueError("attn_impl='ring' needs mesh with an 'sp' axis")
        if config.sliding_window and config.sliding_pattern != "uniform":
            raise ValueError(
                "attn_impl='ring' supports uniform window schedules only "
                f"(got pattern {config.sliding_pattern!r}); per-layer "
                "schedules need a per-layer static hop cap"
            )
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(seq, dtype=jnp.int32)[None, :], (batch, seq))
        if prefill_offset is not None:
            off = prefill_offset.astype(jnp.int32)
            positions = positions + (off[:, None] if off.ndim else off)
    max_pos = cache.capacity if cache is not None else max(seq, config.max_seq_len)
    rope_tables = rope_frequencies(
        # MLA ropes only the shared qk_rope sub-head; the nope part and the
        # latent are position-free
        config.qk_rope_head_dim if config.mla else config.head_dim,
        max_pos, config.rope_theta,
        scale=config.rope_scale, llama3=config.rope_llama3, yarn=config.rope_yarn,
        yarn_truncate=config.rope_yarn_truncate, longrope=config.rope_longrope,
        # LongRoPE short/long selection follows the run's actual position
        # bound (static at trace time): callers that know their true bound
        # (sampler: prompt+max_new) pass it; otherwise cache runs can reach
        # capacity and no-cache runs only touch seq positions. One run keeps
        # ONE factor set — HF's mid-generation dynamic switch re-ropes new
        # queries against keys cached under the other set, which this stack
        # deliberately avoids. Serving guidance: size a continuous engine's
        # capacity <= the pretrained range when short-context behavior must
        # match HF's short factors.
        longrope_select=(
            longrope_select
            if longrope_select is not None
            else (cache.capacity if cache is not None else seq)
        ),
        partial=config.partial_rotary,
    )
    # Gemma3: local (sliding) layers use an unscaled short-range frequency
    rope_tables_local = (
        rope_frequencies(config.head_dim, max_pos, config.rope_local_theta)
        if config.rope_local_theta is not None
        else None
    )

    x = params["embed"][tokens]
    if config.scale_embed:  # Gemma normalizes hidden states by sqrt(d_model)
        x = x * jnp.asarray(config.d_model**0.5, dtype=x.dtype)

    layer_params = params["layers"]
    cache_lengths = cache.lengths if cache is not None else None
    aux0 = jnp.zeros((), jnp.float32)
    # Per-layer sliding flag rides the scan so one compiled body serves both
    # kinds. The pattern is an explicit config field (ModelConfig.sliding_pattern)
    # so non-Gemma2 window schemes can't silently inherit the even alternation.
    sliding_flags = sliding_layer_flags(config)

    quantized = cache is not None and cache.quantized

    def layer_fn(carry, scanned):
        x, aux_sum = carry
        if quantized:
            lp, sliding, k_c, v_c, k_s, v_s = scanned
        else:
            lp, sliding, k_c, v_c = scanned
            k_s = v_s = None
        if config.mla:
            from prime_tpu.models.mla import mla_attention_block

            x, new_k, new_v, new_ks, new_vs = mla_attention_block(
                x, lp, positions, rope_tables, config,
                k_c, v_c, cache_lengths, decode, attn_impl,
                prefill_offset=prefill_offset,
            )
        else:
            x, new_k, new_v, new_ks, new_vs = _attention_block(
                x, lp, positions, rope_tables, config,
                k_c, v_c, cache_lengths, decode, attn_impl,
                k_scale=k_s, v_scale=v_s, prefill_offset=prefill_offset,
                sliding=sliding, rope_tables_local=rope_tables_local,
                mesh=mesh, adapter_ids=adapter_ids,
            )
        x, aux = _mlp_block(x, lp, config, adapter_ids=adapter_ids)
        ys = (new_k, new_v, new_ks, new_vs) if quantized else (new_k, new_v)
        return (x, aux_sum + aux), ys

    # DeepSeek first_k_dense: the dense-prefix stack scans first, then the
    # MoE stack — same layer_fn (the MLP branch keys off each stack's own
    # params), cache arrays split at the static boundary and re-joined.
    # The join concatenates the full cache each step — the price of keeping
    # ONE uniform KVCache contract for every consumer (engine slots,
    # sp_cache_spec, checkpoints); acceptable while prefix models serve
    # single-host (kd<=3), revisit with a pre-split cache if it shows up
    # on a profile
    kd = config.first_k_dense
    stacks = (
        [(params["dense_layers"], slice(0, kd)), (layer_params, slice(kd, None))]
        if kd
        else [(layer_params, slice(0, None))]
    )
    # multi-LoRA bank: the per-target (L, A, ...) factor stacks ride the
    # layer scan under reserved lora:* keys, sliced by each stack's rows —
    # _lora_mm gathers each batch row's factors by adapter_ids inside the
    # scanned body (serve/adapters.py; no bank → byte-identical programs)
    stacks = [
        (merge_adapter_stacks(stack, adapters, rows), rows)
        for stack, rows in stacks
    ]

    if cache is not None:
        new_ks = new_vs = None
        k_parts, v_parts, ks_parts, vs_parts = [], [], [], []
        aux_total = aux0
        for stack, rows in stacks:
            if quantized:
                xs = (
                    stack, sliding_flags[rows], cache.k[rows], cache.v[rows],
                    cache.k_scale[rows], cache.v_scale[rows],
                )
                (x, aux_total), (part_k, part_v, part_ks, part_vs) = jax.lax.scan(
                    layer_fn, (x, aux_total), xs
                )
                ks_parts.append(part_ks)
                vs_parts.append(part_vs)
            else:
                (x, aux_total), (part_k, part_v) = jax.lax.scan(
                    layer_fn, (x, aux_total),
                    (stack, sliding_flags[rows], cache.k[rows], cache.v[rows]),
                )
            k_parts.append(part_k)
            v_parts.append(part_v)

        def join(parts):
            return parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=0)

        if quantized:
            new_ks, new_vs = join(ks_parts), join(vs_parts)
        new_lengths = cache.lengths + (1 if decode else seq)
        new_cache = KVCache(
            k=join(k_parts), v=join(v_parts), lengths=new_lengths,
            k_scale=new_ks, v_scale=new_vs,
        )
    else:

        def layer_fn_nocache(carry, scanned):
            lp, sliding = scanned
            x, aux_sum = carry
            if config.mla:
                from prime_tpu.models.mla import mla_attention_block

                x, _, _, _, _ = mla_attention_block(
                    x, lp, positions, rope_tables, config,
                    None, None, None, False, attn_impl,
                )
            else:
                x, _, _, _, _ = _attention_block(
                    x, lp, positions, rope_tables, config, None, None, None, False, attn_impl,
                    sliding=sliding, rope_tables_local=rope_tables_local,
                    mesh=mesh, adapter_ids=adapter_ids,
                )
            x, aux = _mlp_block(x, lp, config, adapter_ids=adapter_ids)
            return (x, aux_sum + aux), None

        if remat not in ("none", "full", "dots"):
            raise ValueError(f"Unknown remat {remat!r} (want 'none' | 'full' | 'dots')")
        if remat != "none":
            # WITHOUT this, reverse-mode AD through the scan saves every
            # layer's residuals (activation memory = n_layers × per-layer);
            # checkpointing recomputes them in the backward pass. "dots"
            # keeps matmul outputs (cheap HBM, expensive to recompute on the
            # MXU) and drops the elementwise rest — the usual TPU trade.
            policy = (
                None
                if remat == "full"
                else jax.checkpoint_policies.dots_with_no_batch_dims_saveable
            )
            layer_fn_nocache = jax.checkpoint(
                layer_fn_nocache, policy=policy, prevent_cse=False
            )

        aux_total = aux0
        for stack, rows in stacks:
            (x, aux_total), _ = jax.lax.scan(
                layer_fn_nocache, (x, aux_total), (stack, sliding_flags[rows])
            )
        new_cache = None

    x = _norm(x, params["final_norm"], config)
    if last_positions is not None:
        # gather BEFORE the head matmul (see docstring)
        x = jnp.take_along_axis(
            x, last_positions.astype(jnp.int32)[:, None, None], axis=1
        )
    head = params["embed"].T if config.tie_embeddings else params["lm_head"]
    logits = _apply_softcap((x @ head).astype(jnp.float32), config.final_softcap)
    if return_aux:
        return logits, new_cache, aux_total
    return logits, new_cache
