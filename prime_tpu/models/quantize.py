"""int8 / int4 weight quantization for serving (W8A16 / W4A16).

At small decode batches the weight matrices — not the KV cache — dominate
HBM traffic (every step reads every layer's weights once), so quantized
weights are the other half of the decode-bandwidth story next to the int8
KV cache.

int8 scheme: per-output-channel symmetric. A quantized matrix is the pytree
tuple ``(q int8 (..., in, out), scale fp32 (..., 1, out))`` and the matmul
dequantizes by scaling the OUTPUT columns — ``x @ (q * s) == (x @ q) * s``
exactly, so XLA reads int8 from HBM and fuses the convert + scale into the
matmul epilogue; the fp weights are never materialized.

int4 scheme: group-wise symmetric along the REDUCTION axis (AWQ/GPTQ-style,
group=128 input channels), because 4 bits with one scale per whole column
loses too much signal. Storage is NIBBLE-PACKED uint8 — two 4-bit values
per byte, low nibble = first half of the group, high nibble = second half —
NOT the jnp.int4 dtype: int4 arrays cannot cross a jit boundary on every
backend (the tunneled axon plugin's shard_arg recurses on them), and a
packed uint8 carrier moves the same 4 bits/weight while staying a
first-class dtype everywhere. The tuple is ``(packed uint8 (..., in/2,
out), scale fp32 (..., groups, 1, out))``; the matmul sign-extends the
nibbles in-graph and splits the reduction into per-group partials —
``sum_g (x_lo @ lo_g + x_hi @ hi_g) * s_g`` — so HBM streams half the
int8 bytes and the MXU still sees batched bf16 matmuls.

Norms, embeddings, the router, and the LM head stay in their original dtype
(gathers and the final fp32 logits matmul have different numerics); the
seven big per-layer matrices are what move the bytes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

QUANTIZED_LAYER_KEYS = (
    "wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down",
    # MLA projections (models/mla.py): wkv_a/wq_a/wq_b flow through matmul();
    # wkv_b's absorb/value einsums fold the per-output-channel int8 scales
    # themselves (_split_wkv_b)
    "wkv_a", "wq_a", "wq_b", "wkv_b",
    # DeepSeekMoE always-on shared expert (dense path through matmul())
    "w_shared_gate", "w_shared_up", "w_shared_down",
)


def quantize_weight(w: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-output-channel (last axis) symmetric int8."""
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2, keepdims=True)
    scale = absmax / 127.0
    q = jnp.round(w.astype(jnp.float32) / jnp.maximum(scale, 1e-12)).astype(jnp.int8)
    return q, scale


def quantize_params_int8(params: dict) -> dict:
    """Return a params tree whose big layer matrices are (int8, scale) tuples.

    MoE expert stacks quantize the same way (the per-output-channel axis is
    still the last one). The rest of the tree is shared by reference.
    """
    out = dict(params)
    for subtree in ("layers", "dense_layers"):  # dense_layers: DeepSeek prefix
        if subtree not in params:
            continue
        layers = dict(params[subtree])
        for key in QUANTIZED_LAYER_KEYS:
            if key in layers and not isinstance(layers[key], tuple):
                layers[key] = quantize_weight(layers[key])
        out[subtree] = layers
    return out


INT4_GROUP = 128


def quantize_weight_int4(
    w: jnp.ndarray, group: int = INT4_GROUP
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Group-wise (reduction axis) symmetric int4, nibble-packed into uint8:
    one fp32 scale per ``group`` input channels per output channel. Falls
    back to a single group when the reduction dim doesn't divide; an odd
    reduction dim (can't pack pairs) keeps an unpacked int8 carrier, which
    ``_matmul_int4`` detects by shape."""
    *lead, d_in, d_out = w.shape
    g = group if d_in % group == 0 else d_in
    groups = d_in // g
    wg = w.astype(jnp.float32).reshape(*lead, groups, g, d_out)
    absmax = jnp.max(jnp.abs(wg), axis=-2, keepdims=True)  # (..., groups, 1, out)
    scale = absmax / 7.0
    q = jnp.clip(jnp.round(wg / jnp.maximum(scale, 1e-12)), -8, 7).astype(jnp.int8)
    if g % 2:  # odd group: no pair packing; int8 carrier, same scale layout
        return q.reshape(*lead, d_in, d_out), scale
    # low nibble = first half-group, high nibble = second half-group
    lo = q[..., : g // 2, :].astype(jnp.uint8) & 0xF
    hi = q[..., g // 2 :, :].astype(jnp.uint8) & 0xF
    packed = lo | (hi << 4)
    return packed.reshape(*lead, d_in // 2, d_out), scale


def quantize_params_int4(params: dict, group: int = INT4_GROUP) -> dict:
    """Params tree with the big DENSE layer matrices as (int4, scale) tuples.

    MoE expert stacks are left untouched (the grouped-reduction einsum isn't
    wired through the expert dispatch path) — quantize those with
    :func:`quantize_params_int8` first if needed; int8 tuples and int4
    tuples coexist in one tree, ``matmul`` dispatches on dtype."""
    out = dict(params)
    for subtree in ("layers", "dense_layers"):  # dense_layers: DeepSeek prefix
        if subtree not in params:
            continue
        layers = dict(params[subtree])
        for key in QUANTIZED_LAYER_KEYS:
            if key == "wkv_b":
                # the MLA absorb einsum CONTRACTS wkv_b's reduction axis,
                # where int4's group scales live — only int8's output-channel
                # scheme folds there; a later int8 pass picks this key up
                continue
            w = layers.get(key)
            if w is not None and not isinstance(w, tuple) and w.ndim == 3:
                layers[key] = quantize_weight_int4(w, group=group)
        out[subtree] = layers
    return out


def quantize_kv_int4(x: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-slot symmetric int4 for the KV cache, nibble-packed along
    head_dim: x is (..., head_dim, S) feature-major; returns (packed uint8
    (..., head_dim/2, S), scale fp32 (..., 1, S)). Same scales layout as the
    int8 ``quantize_kv`` — the flash-decode kernel's scale plumbing is
    shared; only the carrier (and the VMEM widening) differs. Low nibble =
    features [0, D/2), high nibble = [D/2, D), matching the weight packing
    convention so one unpack rule serves both."""
    d = x.shape[-2]
    if d % 2:
        raise ValueError(f"head_dim {d} must be even to nibble-pack the KV cache")
    absmax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-2, keepdims=True)
    scale = absmax / 7.0
    q = jnp.clip(
        jnp.round(x.astype(jnp.float32) / jnp.maximum(scale, 1e-12)), -8, 7
    ).astype(jnp.int8)
    lo = q[..., : d // 2, :].astype(jnp.uint8) & 0xF
    hi = q[..., d // 2 :, :].astype(jnp.uint8) & 0xF
    return lo | (hi << 4), scale


def unpack_kv_int4(packed: jnp.ndarray) -> jnp.ndarray:
    """Widen a nibble-packed KV array (..., head_dim/2, S) back to fp32
    (..., head_dim, S) — the XLA reference path's dequant (scales applied by
    the caller) and the ground truth the pallas int4 decode is tested
    against."""
    lo, hi = _unpack_nibbles(packed)
    return jnp.concatenate([lo, hi], axis=-2).astype(jnp.float32)


def _unpack_nibbles(packed: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Sign-extend the two 4-bit values in each uint8 to int8 in [-8, 7]."""
    lo = ((packed & 0xF).astype(jnp.int8) ^ 8) - 8
    hi = ((packed >> 4).astype(jnp.int8) ^ 8) - 8
    return lo, hi


def _mesh_context_active() -> bool:
    """True inside any mesh context (``with mesh:`` or ``jax.set_mesh``) with
    more than one device — the SPMD regime where a bare ``pl.pallas_call``
    cannot partition under jit (same rule the attention ops document: mesh
    callers must take the XLA path). Checks both the legacy physical-mesh
    thread resource and the newer abstract-mesh context, tolerating either
    being absent across jax versions."""
    try:
        from jax._src import mesh as mesh_lib
    except Exception:  # pragma: no cover — internal layout moved
        return False
    physical = getattr(
        getattr(getattr(mesh_lib, "thread_resources", None), "env", None),
        "physical_mesh", None,
    )
    if physical is not None and not physical.empty and physical.size > 1:
        return True
    get_abstract = getattr(mesh_lib, "get_abstract_mesh", None)
    if get_abstract is not None:
        abstract = get_abstract()
        if (
            abstract is not None
            and not getattr(abstract, "empty", True)
            and getattr(abstract, "size", 1) > 1
        ):
            return True
    return False


def _int4_pallas_eligible(x: jnp.ndarray, q: jnp.ndarray, interpret: bool) -> bool:
    """Gate the fused pallas int4 kernel to the regime it exists for: the
    SINGLE-DEVICE decode/gemv path on TPU (few activation rows, per-layer
    2-D packed weights, lane-aligned output). Prefill and training keep the
    XLA path — they are MXU-bound, not weight-bandwidth-bound — as do
    stacked (pre-scan-slice) weights and CPU runs (unless interpret mode is
    forced for tests). Under an active multi-device mesh context the XLA
    unpack chain runs instead: a bare pallas_call cannot partition under
    SPMD jit (ADVICE r5). A multi-chip host WITHOUT a mesh stays eligible —
    unsharded jit commits to one device, where the kernel is exactly the
    weight-bandwidth win it was built for."""
    import numpy as np

    if q.ndim != 2 or q.dtype != jnp.uint8:
        return False
    if q.shape[-1] % 128:
        return False
    rows = int(np.prod(x.shape[:-1]))
    if rows > 32:
        return False
    if _mesh_context_active():
        return False
    return interpret or jax.default_backend() == "tpu"


def _matmul_int4(x: jnp.ndarray, q: jnp.ndarray, scale: jnp.ndarray) -> jnp.ndarray:
    """Per-group partial matmuls, scaled then summed over groups: exact
    w.r.t. ``x @ dequant(q, scale)`` up to fp accumulation order. ``q`` is
    nibble-packed uint8 (rows = d_in/2) or, for an odd reduction dim, an
    unpacked int8 carrier (rows = d_in).

    On TPU in the decode regime the packed case dispatches to the fused
    pallas kernel (ops/pallas_quant.py): XLA materializes the unpack chain's
    intermediates to HBM, forfeiting the nibble packing's bandwidth halving;
    the kernel unpacks in VMEM so HBM streams exactly the packed bytes."""
    d_in = x.shape[-1]
    d_out = q.shape[-1]
    groups = scale.shape[-3]
    g = d_in // groups
    # the kernel's in-loop activation slice is on the LANE dim: group
    # boundaries must be 128-aligned (always true for INT4_GROUP=128; a
    # single whole-dim group is the full lane dim, also fine)
    lane_aligned = g % 128 == 0 or groups == 1
    from prime_tpu.ops.attention import _pallas_interpret

    interpret = _pallas_interpret()
    if (
        q.shape[-2] * 2 == d_in
        and lane_aligned
        and _int4_pallas_eligible(x, q, interpret)
    ):
        from prime_tpu.ops.pallas_quant import int4_matmul

        y = int4_matmul(
            x.reshape(-1, d_in), q, scale[..., 0, :].astype(jnp.float32),
            interpret=interpret,
        )
        return y.reshape(*x.shape[:-1], d_out)
    xg = x.reshape(*x.shape[:-1], groups, g)
    s = scale[..., 0, :]  # (..., groups, out)
    if q.shape[-2] == d_in:  # odd-group int8 carrier
        qg = q.reshape(*q.shape[:-2], groups, g, d_out)
        y = jnp.einsum("...gi,gio->...go", xg, qg.astype(x.dtype))
        return jnp.sum(y * s.astype(y.dtype), axis=-2)
    pg = q.reshape(*q.shape[:-2], groups, g // 2, d_out)
    lo, hi = _unpack_nibbles(pg)
    y = jnp.einsum("...gi,gio->...go", xg[..., : g // 2], lo.astype(x.dtype))
    y = y + jnp.einsum("...gi,gio->...go", xg[..., g // 2 :], hi.astype(x.dtype))
    return jnp.sum(y * s.astype(y.dtype), axis=-2)


def matmul(x: jnp.ndarray, w) -> jnp.ndarray:
    """``x @ w`` where w may be an int8 or int4 quantized (q, scale) tuple."""
    if isinstance(w, tuple):
        q, scale = w
        # grouped (int4) scheme carries a per-group scale axis the
        # per-output-channel int8 scheme doesn't have
        if scale.ndim == q.ndim + 1:
            return _matmul_int4(x, q, scale)
        # int8 read from HBM; convert fuses into the matmul, scale into its
        # epilogue (output columns), so this is exact w.r.t. x @ (q*scale)
        y = x @ q.astype(x.dtype)
        return y * scale.astype(y.dtype)[..., 0, :]
    return x @ w


def einsum(spec: str, activations: jnp.ndarray, w, out_scale_shape) -> jnp.ndarray:
    """``jnp.einsum(spec, activations, w)`` where w may be a quantized
    (q, scale) tuple. ``out_scale_shape`` reshapes the per-output-channel
    scale for broadcast against the einsum result (the scheme's single owner
    lives here — callers never unpack the tuple themselves)."""
    if isinstance(w, tuple):
        q, scale = w
        y = jnp.einsum(spec, activations, q.astype(activations.dtype))
        return y * scale[..., 0, :].astype(y.dtype).reshape(out_scale_shape)
    return jnp.einsum(spec, activations, w)


def is_quantized(params: dict) -> bool:
    layers = params.get("layers", {})
    return any(isinstance(layers.get(k), tuple) for k in QUANTIZED_LAYER_KEYS)
