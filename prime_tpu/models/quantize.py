"""int8 weight quantization for serving (W8A16).

At small decode batches the weight matrices — not the KV cache — dominate
HBM traffic (every step reads every layer's weights once), so int8 weights
are the other half of the decode-bandwidth story next to the int8 KV cache.

Scheme: per-output-channel symmetric int8. A quantized matrix is the pytree
tuple ``(q int8 (..., in, out), scale fp32 (..., 1, out))`` and the matmul
dequantizes by scaling the OUTPUT columns — ``x @ (q * s) == (x @ q) * s``
exactly, so XLA reads int8 from HBM and fuses the convert + scale into the
matmul epilogue; the fp weights are never materialized.

Norms, embeddings, the router, and the LM head stay in their original dtype
(gathers and the final fp32 logits matmul have different numerics); the
seven big per-layer matrices are what move the bytes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

QUANTIZED_LAYER_KEYS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


def quantize_weight(w: jnp.ndarray) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Per-output-channel (last axis) symmetric int8."""
    absmax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2, keepdims=True)
    scale = absmax / 127.0
    q = jnp.round(w.astype(jnp.float32) / jnp.maximum(scale, 1e-12)).astype(jnp.int8)
    return q, scale


def quantize_params_int8(params: dict) -> dict:
    """Return a params tree whose big layer matrices are (int8, scale) tuples.

    MoE expert stacks quantize the same way (the per-output-channel axis is
    still the last one). The rest of the tree is shared by reference.
    """
    layers = dict(params["layers"])
    for key in QUANTIZED_LAYER_KEYS:
        if key in layers:
            layers[key] = quantize_weight(layers[key])
    out = dict(params)
    out["layers"] = layers
    return out


def matmul(x: jnp.ndarray, w) -> jnp.ndarray:
    """``x @ w`` where w may be a quantized (q, scale) tuple."""
    if isinstance(w, tuple):
        q, scale = w
        # int8 read from HBM; convert fuses into the matmul, scale into its
        # epilogue (output columns), so this is exact w.r.t. x @ (q*scale)
        y = x @ q.astype(x.dtype)
        return y * scale.astype(y.dtype)[..., 0, :]
    return x @ w


def einsum(spec: str, activations: jnp.ndarray, w, out_scale_shape) -> jnp.ndarray:
    """``jnp.einsum(spec, activations, w)`` where w may be a quantized
    (q, scale) tuple. ``out_scale_shape`` reshapes the per-output-channel
    scale for broadcast against the einsum result (the scheme's single owner
    lives here — callers never unpack the tuple themselves)."""
    if isinstance(w, tuple):
        q, scale = w
        y = jnp.einsum(spec, activations, q.astype(activations.dtype))
        return y * scale[..., 0, :].astype(y.dtype).reshape(out_scale_shape)
    return jnp.einsum(spec, activations, w)


def is_quantized(params: dict) -> bool:
    return isinstance(params.get("layers", {}).get("wq"), tuple)
