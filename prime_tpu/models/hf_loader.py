"""HuggingFace checkpoint → prime_tpu param pytree (Llama / Qwen2 / Mixtral).

Maps the HF ``LlamaForCausalLM``-shaped state dict (which Qwen2 and Mixtral
share, modulo q/k/v biases and expert blocks) onto the stacked-layer layout of
prime_tpu.models.llama (leading n_layers axis per leaf, weights transposed to
(in, out) for right-multiplication). RoPE conventions match: both use the
rotate-half formulation with inv_freq = theta^(-2i/d). Decoupled head_dim
(Qwen3/Gemma-style config.head_dim != hidden_size/num_heads) is carried via
ModelConfig.head_dim_override.

Loads from a local directory containing ``*.safetensors`` (or a torch
``pytorch_model.bin``); zero-egress environments ship checkpoints with pods.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any

import jax.numpy as jnp
import numpy as np

from prime_tpu.models.config import ModelConfig


# model_type values whose math this loader reproduces exactly. Families that
# SHARE Llama state-dict key names but need different math — gemma v1
# ((1+w) norms + sqrt(d) embed scale + GeGLU), etc. — must fail loudly here
# rather than load and silently produce garbage logits.
SUPPORTED_MODEL_TYPES = frozenset(
    {
        "llama",
        "mistral",
        "mixtral",
        "qwen2",
        "qwen3",
        "qwen3_moe",
        "gemma2",
        "gemma3_text",
        "gemma3",
        "phi3",
        "olmo2",
        "gpt_oss",
        "deepseek_v3",
    }
)


def _gemma3_sliding_pattern(hf_config: Any) -> str:
    """Gemma3's layer schedule as an "N:1" pattern string, validated against
    the config's own declaration (layer_types list or sliding_window_pattern
    int). A schedule this loader can't reproduce raises instead of silently
    roping/masking the wrong layers."""
    layer_types = getattr(hf_config, "layer_types", None)
    if layer_types:
        period = None
        for i, kind in enumerate(layer_types):
            if kind == "full_attention":
                period = i + 1
                break
        if period is None:
            return "uniform"  # every layer slides
        expected = [
            "full_attention" if (i + 1) % period == 0 else "sliding_attention"
            for i in range(len(layer_types))
        ]
        if list(layer_types) != expected:
            raise ValueError(
                f"Gemma3 layer_types {layer_types!r} is not a periodic N:1 schedule; "
                "this loader reproduces periodic schedules only"
            )
        return f"{period - 1}:1"
    pattern = getattr(hf_config, "sliding_window_pattern", None) or 6
    return f"{int(pattern) - 1}:1"


def _parse_yarn(rope_scaling: dict, factor: float, default_max: float) -> tuple:
    """HF _compute_yarn_parameters semantics, shared by the generic and
    DeepSeek branches: (factor, beta_fast, beta_slow, original_max,
    attention_factor). The attention factor resolves from mscale /
    mscale_all_dim exactly as transformers does."""
    import math

    def mscale_of(scale: float, m: float = 1.0) -> float:
        return 1.0 if scale <= 1 else 0.1 * m * math.log(scale) + 1.0

    attention_factor = rope_scaling.get("attention_factor")
    mscale = rope_scaling.get("mscale")
    mscale_all_dim = rope_scaling.get("mscale_all_dim")
    if attention_factor is None:
        if mscale and mscale_all_dim:
            attention_factor = mscale_of(factor, mscale) / mscale_of(factor, mscale_all_dim)
        else:
            attention_factor = mscale_of(factor)
    return (
        factor,
        float(rope_scaling.get("beta_fast") or 32.0),
        float(rope_scaling.get("beta_slow") or 1.0),
        float(rope_scaling.get("original_max_position_embeddings") or default_max),
        float(attention_factor),
    )


def _deepseek_config_from_hf(hf_config: Any, name: str) -> ModelConfig:
    """DeepSeek-V3: MLA + sigmoid-scored MoE with selection bias + shared
    experts + dense-prefix layers (first_k_dense_replace, two-scan forward)
    + node-limited group routing (n_group/topk_group) + DeepSeek-yarn
    long-context (NTK-by-parts tables on the rope sub-head, mscale_all_dim^2
    on the softmax scale). Non-yarn rope_scaling types are rejected."""
    first_dense = int(getattr(hf_config, "first_k_dense_replace", 0) or 0)
    # DeepSeek-yarn: NTK-by-parts frequencies over the qk_rope sub-head with
    # the attention factor on cos/sin, PLUS mscale_all_dim^2 on the softmax
    # scale itself (HF DeepseekV3Attention) — the table machinery is shared
    # with the other yarn families, the scale multiplier is MLA-specific
    rope_yarn = None
    yarn_truncate = True
    attn_scale_mult = 1.0
    rope_scaling = getattr(hf_config, "rope_scaling", None)
    if rope_scaling:
        import math

        if not isinstance(rope_scaling, dict):
            raise ValueError(f"deepseek_v3 rope_scaling must be a dict, got {rope_scaling!r}")
        rope_type = rope_scaling.get("rope_type", rope_scaling.get("type"))
        if rope_type != "yarn":
            raise ValueError(
                f"deepseek_v3 rope_scaling type {rope_type!r} is not modeled "
                "(yarn is the family's published long-context scheme)"
            )
        factor = float(rope_scaling["factor"])
        rope_yarn = _parse_yarn(rope_scaling, factor, hf_config.max_position_embeddings)
        yarn_truncate = bool(rope_scaling.get("truncate", True))
        mscale_all_dim = rope_scaling.get("mscale_all_dim")
        if mscale_all_dim:
            # HF DeepseekV3Attention: mscale^2 rides the softmax scale itself
            attn_scale_mult = (0.1 * mscale_all_dim * math.log(factor) + 1.0) ** 2 if factor > 1 else 1.0
    scoring = getattr(hf_config, "scoring_func", "sigmoid") or "sigmoid"
    return ModelConfig(
        name=name,
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=hf_config.num_attention_heads,  # MLA has no GQA grouping
        d_ff=int(getattr(hf_config, "moe_intermediate_size", 0) or hf_config.intermediate_size),
        max_seq_len=min(int(getattr(hf_config, "max_position_embeddings", 8192) or 8192), 32768),
        rope_theta=getattr(hf_config, "rope_theta", 10000.0),
        rms_eps=getattr(hf_config, "rms_norm_eps", 1e-6),
        tie_embeddings=bool(getattr(hf_config, "tie_word_embeddings", False)),
        kv_lora_rank=int(hf_config.kv_lora_rank),
        q_lora_rank=(
            int(hf_config.q_lora_rank) if getattr(hf_config, "q_lora_rank", None) else None
        ),
        qk_rope_head_dim=int(hf_config.qk_rope_head_dim),
        qk_nope_head_dim=int(hf_config.qk_nope_head_dim),
        v_head_dim=int(hf_config.v_head_dim),
        rope_yarn=rope_yarn,
        rope_yarn_truncate=yarn_truncate,
        attn_scale_mult=attn_scale_mult,
        n_experts=int(getattr(hf_config, "n_routed_experts", 0) or 0),
        # first_k_dense_replace: the prefix layers run a dense MLP of the
        # full intermediate width (the two-scan forward handles the split)
        first_k_dense=first_dense,
        dense_ff=int(hf_config.intermediate_size) if first_dense else None,
        experts_per_token=int(getattr(hf_config, "num_experts_per_tok", 8) or 8),
        n_shared_experts=int(getattr(hf_config, "n_shared_experts", 0) or 0),
        moe_score_func=scoring,
        moe_score_bias=True,  # the e_score_correction_bias buffer always ships
        routed_scaling_factor=float(getattr(hf_config, "routed_scaling_factor", 1.0) or 1.0),
        moe_n_groups=int(getattr(hf_config, "n_group", 1) or 1),
        moe_topk_groups=int(getattr(hf_config, "topk_group", 1) or 1),
        norm_topk=bool(getattr(hf_config, "norm_topk_prob", True)),
        # HF routing is dropless; give capacity routing the same headroom
        # every other HF MoE gets (advisor r3)
        **({"capacity_factor": 2.0} if getattr(hf_config, "n_routed_experts", 0) else {}),
    )


def config_from_hf(hf_config: Any, name: str = "hf-model") -> ModelConfig:
    model_type = getattr(hf_config, "model_type", "") or ""
    if model_type == "deepseek_v3":
        return _deepseek_config_from_hf(hf_config, name)
    if model_type == "gemma3":
        # multimodal wrapper config: the text tower is what this loader maps
        # (vision weights are ignored by params_from_state_dict's key lookup)
        inner = getattr(hf_config, "text_config", None)
        if inner is None:
            raise ValueError(
                "gemma3 config has no text_config; pass the text tower's config"
            )
        if isinstance(inner, dict):
            from types import SimpleNamespace

            inner = SimpleNamespace(**inner)
        if not getattr(inner, "model_type", ""):
            inner.model_type = "gemma3_text"
        return config_from_hf(inner, name=name)
    derived_head_dim = hf_config.hidden_size // hf_config.num_attention_heads
    explicit_head_dim = getattr(hf_config, "head_dim", None)
    # Empty model_type (hand-written configs, this repo's own tests) is
    # treated as llama-like; anything else must be explicitly supported.
    if model_type and model_type not in SUPPORTED_MODEL_TYPES:
        raise ValueError(
            f"Unsupported model_type {model_type!r}: this loader reproduces the math of "
            f"{sorted(SUPPORTED_MODEL_TYPES)} only. Checkpoint families that share Llama "
            "state-dict keys but diverge in math (gemma, deepseek, ...) would load "
            "without error and produce wrong logits, so they are rejected."
        )
    # Qwen2 checkpoints carry q/k/v biases unconditionally; Llama-family
    # configs declare them via attention_bias
    attn_bias = bool(getattr(hf_config, "attention_bias", False)) or model_type == "qwen2"
    # Phi-2/Phi-3 partial rotary: only the first head_dim*factor features
    # rotate (ops/rope.apply_rope_rows passes the tail through)
    partial_rotary = float(getattr(hf_config, "partial_rotary_factor", 1.0) or 1.0)
    if model_type == "qwen3_moe":
        # the uniform layer scan needs every layer sparse; a mixed
        # dense/sparse schedule would silently run dense layers through the
        # router, so reject the configs that declare one
        if getattr(hf_config, "mlp_only_layers", None):
            raise ValueError(
                "qwen3_moe with mlp_only_layers (mixed dense/sparse layers) "
                "is not supported; every layer must be sparse"
            )
        if int(getattr(hf_config, "decoder_sparse_step", 1) or 1) != 1:
            raise ValueError("qwen3_moe decoder_sparse_step != 1 is not supported")
    gemma3 = model_type == "gemma3_text"
    gemma = model_type == "gemma2" or gemma3
    # Gemma3 4b+ stretch global-layer rope linearly (factor 8); local layers
    # keep their own unscaled base frequency
    rope_scaling = getattr(hf_config, "rope_scaling", None) or {}
    if isinstance(rope_scaling, dict):
        rope_type = rope_scaling.get("rope_type", rope_scaling.get("type", "linear"))
        rope_factor = float(rope_scaling.get("factor", 1.0) or 1.0)
    else:
        rope_type, rope_factor = "linear", 1.0
    rope_llama3 = None
    rope_yarn = None
    rope_longrope = None
    yarn_truncate = True
    if rope_type == "default":  # HF's explicit no-scaling marker
        rope_factor = 1.0
    elif rope_type == "llama3" and rope_scaling:
        # Llama 3.1/3.2 frequency-dependent scaling — carried as its own
        # tuple; the linear factor must not ALSO divide the frequencies
        rope_llama3 = (
            rope_factor,
            float(rope_scaling.get("low_freq_factor", 1.0) or 1.0),
            float(rope_scaling.get("high_freq_factor", 4.0) or 4.0),
            float(rope_scaling.get("original_max_position_embeddings", 8192) or 8192),
        )
        rope_factor = 1.0
    elif rope_type == "yarn" and rope_scaling:
        import math

        # HF treats ANY falsy truncate (false, null, 0) as non-truncating;
        # mirror that truthiness or a "truncate": null config would load
        # with silently divergent correction bounds (GPT-OSS ships false)
        yarn_truncate = bool(rope_scaling.get("truncate", True))

        rope_yarn = _parse_yarn(
            rope_scaling, rope_factor, getattr(hf_config, "max_position_embeddings", 8192)
        )
        rope_factor = 1.0
    elif rope_type == "longrope" and rope_scaling:
        import math

        # Phi-3.5 LongRoPE: per-dim learned frequency rescales. Phi3-family
        # configs derive the attention temperature from the ratio of the
        # (extended) max positions to the pretrained range, NOT from a
        # "factor" key (HF modeling_rope_utils._compute_longrope_parameters)
        short = rope_scaling.get("short_factor")
        long = rope_scaling.get("long_factor")
        if not short or not long:
            raise ValueError("longrope rope_scaling needs short_factor and long_factor lists")
        # HF semantics exactly (_compute_longrope_parameters): ONLY a
        # top-level original_max_position_embeddings counts (Phi3 carries
        # it there; a rope_scaling-nested copy is IGNORED by HF), and it
        # derives the temperature from max/original; without it the
        # pretrained range is max_position_embeddings itself and the
        # temperature comes from the rope_scaling "factor" key
        original_max = float(getattr(hf_config, "original_max_position_embeddings", 0) or 0)
        if original_max:
            lr_factor = float(hf_config.max_position_embeddings) / original_max
        else:
            original_max = float(hf_config.max_position_embeddings)
            lr_factor = float(rope_scaling.get("factor") or 1.0)
        attention_factor = rope_scaling.get("attention_factor")
        if attention_factor is None:
            attention_factor = (
                1.0
                if lr_factor <= 1.0
                else math.sqrt(1.0 + math.log(lr_factor) / math.log(original_max))
            )
        rope_longrope = (
            tuple(float(f) for f in short),
            tuple(float(f) for f in long),
            original_max,
            float(attention_factor),
        )
        rope_factor = 1.0
    elif rope_scaling and rope_type != "linear":
        raise ValueError(
            f"Unsupported rope_scaling type {rope_type!r} "
            "(linear/llama3/yarn/longrope only); "
            "loading would silently distort long-range attention"
        )
    if gemma3:
        sliding_pattern = _gemma3_sliding_pattern(hf_config)
    elif gemma:
        sliding_pattern = "even"
    elif model_type == "gpt_oss":
        # GPT-OSS alternates sliding/full starting with sliding (layer_types
        # in the config); validate rather than assume — a checkpoint with a
        # different schedule must not silently window the wrong layers
        layer_types = getattr(hf_config, "layer_types", None)
        if layer_types:
            expected = [
                "sliding_attention" if i % 2 == 0 else "full_attention"
                for i in range(len(layer_types))
            ]
            if list(layer_types) != expected:
                raise ValueError(
                    f"gpt_oss layer_types {layer_types!r} is not the even-alternating "
                    "schedule this loader reproduces"
                )
        sliding_pattern = "even"
    else:
        sliding_pattern = "uniform"
    # sparse MoE: Mixtral names the count num_local_experts, Qwen3-MoE
    # num_experts; nonzero is THE MoE signal (capacity_factor keys off it too)
    n_experts = (
        getattr(hf_config, "num_local_experts", 0)
        or getattr(hf_config, "num_experts", 0)
        or 0
    )
    return ModelConfig(
        head_dim_override=(
            explicit_head_dim if explicit_head_dim not in (None, derived_head_dim) else None
        ),
        attn_bias=attn_bias,
        # Llama-arch attention_bias biases o_proj as well; Qwen2 does not
        attn_out_bias=bool(getattr(hf_config, "attention_bias", False)),
        qk_norm=model_type in ("qwen3", "qwen3_moe", "gemma3_text"),
        # OLMo-2: post-norm-only blocks and full-width q/k norms
        qk_norm_full=model_type == "olmo2",
        pre_norms=model_type != "olmo2",
        # Gemma2/3: GeGLU, (1+w) norms, post-norms, scaled embeddings; Gemma2
        # adds softcapped scores/logits, Gemma3 drops the caps and adds
        # qk-norm + dual-frequency rope
        act="gelu_tanh" if gemma else "silu",
        norm_plus_one=gemma,
        post_norms=gemma or model_type == "olmo2",
        scale_embed=gemma,
        attn_softcap=float(getattr(hf_config, "attn_logit_softcapping", 0.0) or 0.0),
        final_softcap=float(getattr(hf_config, "final_logit_softcapping", 0.0) or 0.0),
        query_scale=getattr(hf_config, "query_pre_attn_scalar", None),
        # Gemma2 alternates sliding/global (even layers slide); Gemma3 runs a
        # periodic N:1 schedule; Mistral v0.1 slides every layer. Other
        # families' window configs are rejected by the allowlist above rather
        # than silently mapped to a pattern.
        sliding_window=(
            int(getattr(hf_config, "sliding_window", 0) or 0)
            if model_type in ("gemma2", "gemma3_text", "mistral", "phi3", "gpt_oss")
            else 0
        ),
        sliding_pattern=sliding_pattern,
        # GPT-OSS: per-head sink logits, biased router/experts, clamped GLU
        attn_sinks=model_type == "gpt_oss",
        moe_bias=model_type == "gpt_oss",
        moe_glu_clamp=7.0 if model_type == "gpt_oss" else 0.0,
        rope_yarn_truncate=yarn_truncate,
        rope_longrope=rope_longrope,
        partial_rotary=partial_rotary,
        rope_local_theta=(
            float(getattr(hf_config, "rope_local_base_freq", 10000.0) or 10000.0)
            if gemma3
            else None
        ),
        rope_scale=rope_factor,
        rope_llama3=rope_llama3,
        rope_yarn=rope_yarn,
        name=name,
        vocab_size=hf_config.vocab_size,
        d_model=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=getattr(hf_config, "num_key_value_heads", hf_config.num_attention_heads),
        # sparse models size their experts by moe_intermediate_size (Qwen3-MoE
        # 768 vs a dense intermediate the all-sparse stack never uses); only
        # qwen3_moe among the supported types carries the key
        d_ff=(
            int(getattr(hf_config, "moe_intermediate_size", 0) or 0)
            or hf_config.intermediate_size
        ),
        # capped: the no-cache forward materializes rope tables at max_seq_len
        # (two pairs for dual-frequency models — ~256MB at gemma3's 131k);
        # serving sizes tables from the KV capacity, and a longer training
        # seq still sizes its own table via max(seq, max_seq_len)
        max_seq_len=min(int(getattr(hf_config, "max_position_embeddings", 8192) or 8192), 32768),
        rope_theta=getattr(hf_config, "rope_theta", 10000.0),
        rms_eps=getattr(hf_config, "rms_norm_eps", 1e-5),
        # Gemma's config default ties embeddings, so checkpoints omit the key
        # from config.json; Llama-family defaults to untied
        tie_embeddings=getattr(hf_config, "tie_word_embeddings", gemma),
        # (Qwen3-MoE checkpoints also choose whether top-k gates
        # renormalize, norm_topk_prob below)
        n_experts=n_experts,
        # fallbacks track each family's OWN transformers defaults: a pared
        # config.json that omits a key must load with the math transformers
        # would use, not this loader's preference
        experts_per_token=(
            getattr(hf_config, "num_experts_per_tok", None)
            or (8 if model_type == "qwen3_moe" else 2)
        ),
        norm_topk=bool(getattr(hf_config, "norm_topk_prob", model_type != "qwen3_moe")),
        # HF routing is dropless; this stack's capacity routing drops tokens
        # above capacity_factor. Any HF MoE checkpoint (keyed off n_experts,
        # not a second model-type list a future MoE family could miss) gets
        # the same 2.0 headroom the hand-written presets use, or routing
        # imbalance silently zeroes dropped tokens' expert output (advisor
        # r3). Dense models keep the ModelConfig default by omission.
        **({"capacity_factor": 2.0} if n_experts else {}),
    )


def _read_state_dict(checkpoint_dir: str | Path) -> dict[str, np.ndarray]:
    checkpoint_dir = Path(checkpoint_dir)
    tensors: dict[str, np.ndarray] = {}
    safetensor_files = sorted(checkpoint_dir.glob("*.safetensors"))
    if safetensor_files:
        from safetensors.numpy import load_file

        for file in safetensor_files:
            tensors.update(load_file(str(file)))
        return tensors
    bins = sorted(checkpoint_dir.glob("pytorch_model*.bin"))
    if bins:
        import torch

        for file in bins:
            state = torch.load(str(file), map_location="cpu", weights_only=True)
            tensors.update({k: v.float().numpy() for k, v in state.items()})
        return tensors
    raise FileNotFoundError(f"No *.safetensors or pytorch_model*.bin under {checkpoint_dir}")


def params_from_state_dict(
    state: dict[str, np.ndarray], config: ModelConfig, dtype=jnp.bfloat16,
    rope_interleave: bool = False,
) -> dict[str, Any]:
    """Convert an HF LlamaForCausalLM state dict to the stacked param pytree.

    ``rope_interleave`` (DeepSeek checkpoints): the rope sub-head's features
    are stored pair-interleaved; the loader de-interleaves the PRODUCING
    weight columns once so the runtime uses the standard rotate-half rope
    with no per-step permute."""

    def get(name: str) -> np.ndarray:
        # bare → LlamaForCausalLM → Gemma3 multimodal text-tower prefixes
        candidates = (
            name,
            f"model.{name}",
            f"model.language_model.{name}",
            f"language_model.model.{name}",
        )
        for candidate in candidates:
            if candidate in state:
                return np.asarray(state[candidate])
        raise KeyError(f"Missing weight {name!r} (have {len(state)} tensors)")

    def stacked(template: str, transpose: bool) -> jnp.ndarray:
        mats = []
        for layer in range(config.n_layers):
            w = get(template.format(layer))
            mats.append(w.T if transpose else w)
        return jnp.asarray(np.stack(mats), dtype=dtype)

    def present(name: str) -> bool:
        try:
            get(name)
        except KeyError:
            return False
        return True

    def stacked_rows(template: str, start: int, stop: int) -> jnp.ndarray:
        """Row-slice of a fused projection, per layer, transposed to (in, out).
        Phi3 fuses q/k/v into qkv_proj and gate/up into gate_up_proj — rows
        are stacked in declaration order, so a static slice recovers each."""
        mats = []
        for layer in range(config.n_layers):
            mats.append(get(template.format(layer))[start:stop].T)
        return jnp.asarray(np.stack(mats), dtype=dtype)

    if config.is_moe and present("layers.0.mlp.experts.gate_up_proj"):
        # GPT-OSS fused expert tensors: gate_up_proj (E, D, 2F) with gate on
        # even output columns and up on odd ([..., ::2] / [..., 1::2] in the
        # HF forward), stored activation-major so NO transpose; down_proj
        # (E, F, D) likewise. Router is a Linear (E, D) -> transposed, with
        # bias; every projection carries a bias.
        def stacked_fused(suffix: str, pick) -> jnp.ndarray:
            return jnp.asarray(
                np.stack(
                    [
                        pick(get(f"layers.{layer}.mlp.experts.{suffix}"))
                        for layer in range(config.n_layers)
                    ]
                ),
                dtype=dtype,
            )

        mlp_weights = {
            "router": jnp.asarray(
                np.stack(
                    [
                        get(f"layers.{layer}.mlp.router.weight").T
                        for layer in range(config.n_layers)
                    ]
                ),
                dtype=jnp.float32,
            ),
            "router_bias": jnp.asarray(
                np.stack(
                    [
                        get(f"layers.{layer}.mlp.router.bias")
                        for layer in range(config.n_layers)
                    ]
                ),
                dtype=jnp.float32,
            ),
            "w_gate": stacked_fused("gate_up_proj", lambda w: w[..., ::2]),
            "w_up": stacked_fused("gate_up_proj", lambda w: w[..., 1::2]),
            "b_gate": stacked_fused("gate_up_proj_bias", lambda b: b[..., ::2]),
            "b_up": stacked_fused("gate_up_proj_bias", lambda b: b[..., 1::2]),
            "w_down": stacked_fused("down_proj", lambda w: w),
            "b_down": stacked_fused("down_proj_bias", lambda b: b),
        }
    elif config.is_moe:
        # two expert layouts share the same math:
        # - Mixtral: block_sparse_moe.gate (router) + experts.M.{w1,w2,w3}
        #   (w1 = gate_proj, w3 = up_proj, both (F, D); w2 = down_proj (D, F))
        # - Qwen3-MoE: mlp.gate (router) + mlp.experts.M.{gate,up,down}_proj
        moe_layers = range(config.first_k_dense, config.n_layers)
        first_moe = config.first_k_dense  # prefix layers are dense (DeepSeek)
        if present(f"layers.{first_moe}.mlp.experts.0.gate_proj.weight"):
            router_t = "layers.{}.mlp.gate.weight"
            gate_t = "layers.{}.mlp.experts.{}.gate_proj.weight"
            up_t = "layers.{}.mlp.experts.{}.up_proj.weight"
            down_t = "layers.{}.mlp.experts.{}.down_proj.weight"
        else:
            router_t = "layers.{}.block_sparse_moe.gate.weight"
            gate_t = "layers.{}.block_sparse_moe.experts.{}.w1.weight"
            up_t = "layers.{}.block_sparse_moe.experts.{}.w3.weight"
            down_t = "layers.{}.block_sparse_moe.experts.{}.w2.weight"

        def stacked_experts(template: str) -> jnp.ndarray:
            layers_out = []
            for layer in moe_layers:
                experts = [
                    get(template.format(layer, expert)).T
                    for expert in range(config.n_experts)
                ]
                layers_out.append(np.stack(experts))
            return jnp.asarray(np.stack(layers_out), dtype=dtype)  # (L, E, in, out)

        mlp_weights = {
            "router": jnp.asarray(
                np.stack(
                    [get(router_t.format(layer)).T for layer in moe_layers]
                ),
                dtype=jnp.float32,  # router decisions stay fp32
            ),
            "w_gate": stacked_experts(gate_t),
            "w_up": stacked_experts(up_t),
            "w_down": stacked_experts(down_t),
        }
        if config.moe_score_bias:
            # DeepSeek-V3 aux-free balance bias (a buffer on the gate)
            mlp_weights["score_bias"] = jnp.asarray(
                np.stack(
                    [
                        get(f"layers.{layer}.mlp.gate.e_score_correction_bias")
                        for layer in moe_layers
                    ]
                ),
                dtype=jnp.float32,
            )
        if config.n_shared_experts:
            # DeepSeekMoE always-on shared expert (one fused dense MLP;
            # only the MoE layers carry it)
            def stacked_shared(template: str) -> jnp.ndarray:
                return jnp.asarray(
                    np.stack([get(template.format(i)).T for i in moe_layers]),
                    dtype=dtype,
                )

            mlp_weights |= {
                "w_shared_gate": stacked_shared("layers.{}.mlp.shared_experts.gate_proj.weight"),
                "w_shared_up": stacked_shared("layers.{}.mlp.shared_experts.up_proj.weight"),
                "w_shared_down": stacked_shared("layers.{}.mlp.shared_experts.down_proj.weight"),
            }
    elif present("layers.0.mlp.gate_up_proj.weight"):
        # Phi3 fused MLP: gate rows then up rows
        mlp_weights = {
            "w_gate": stacked_rows("layers.{}.mlp.gate_up_proj.weight", 0, config.d_ff),
            "w_up": stacked_rows(
                "layers.{}.mlp.gate_up_proj.weight", config.d_ff, 2 * config.d_ff
            ),
            "w_down": stacked("layers.{}.mlp.down_proj.weight", transpose=True),
        }
    else:
        mlp_weights = {
            "w_gate": stacked("layers.{}.mlp.gate_proj.weight", transpose=True),
            "w_up": stacked("layers.{}.mlp.up_proj.weight", transpose=True),
            "w_down": stacked("layers.{}.mlp.down_proj.weight", transpose=True),
        }

    attn_biases = {}
    if config.attn_bias:
        attn_biases = {
            "bq": stacked("layers.{}.self_attn.q_proj.bias", transpose=False),
            "bk": stacked("layers.{}.self_attn.k_proj.bias", transpose=False),
            "bv": stacked("layers.{}.self_attn.v_proj.bias", transpose=False),
        }
    if config.attn_out_bias:
        # Llama-arch attention_bias=True biases o_proj too — dropping it
        # would silently offset every layer's attention output
        attn_biases["bo"] = stacked("layers.{}.self_attn.o_proj.bias", transpose=False)
    if config.qk_norm:
        attn_biases |= {
            "q_norm": stacked("layers.{}.self_attn.q_norm.weight", transpose=False),
            "k_norm": stacked("layers.{}.self_attn.k_norm.weight", transpose=False),
        }
    if config.qk_norm_full:  # OLMo-2: same checkpoint names, full-width weights
        attn_biases |= {
            "q_norm_full": stacked("layers.{}.self_attn.q_norm.weight", transpose=False),
            "k_norm_full": stacked("layers.{}.self_attn.k_norm.weight", transpose=False),
        }
    if config.attn_sinks:  # GPT-OSS per-head sink logits (fp32 in the softmax)
        attn_biases["sinks"] = jnp.asarray(
            np.stack(
                [get(f"layers.{layer}.self_attn.sinks") for layer in range(config.n_layers)]
            ),
            dtype=jnp.float32,
        )
    if not config.pre_norms:
        # OLMo-2: post-norm only — the checkpoint has NO input norms, and its
        # q_norm/k_norm are FULL-WIDTH (rms over all heads jointly)
        norm_keys = {
            "attn_post_norm": stacked(
                "layers.{}.post_attention_layernorm.weight", transpose=False
            ),
            "mlp_post_norm": stacked(
                "layers.{}.post_feedforward_layernorm.weight", transpose=False
            ),
        }
    elif config.post_norms:
        # Gemma2 norm naming: post_attention_layernorm is a POST-norm on the
        # attention output; the pre-MLP norm is pre_feedforward_layernorm
        norm_keys = {
            "attn_norm": stacked("layers.{}.input_layernorm.weight", transpose=False),
            "attn_post_norm": stacked(
                "layers.{}.post_attention_layernorm.weight", transpose=False
            ),
            "mlp_norm": stacked("layers.{}.pre_feedforward_layernorm.weight", transpose=False),
            "mlp_post_norm": stacked(
                "layers.{}.post_feedforward_layernorm.weight", transpose=False
            ),
        }
    else:
        norm_keys = {
            "attn_norm": stacked("layers.{}.input_layernorm.weight", transpose=False),
            "mlp_norm": stacked("layers.{}.post_attention_layernorm.weight", transpose=False),
        }
    if config.mla:
        # DeepSeek MLA: q (direct or low-rank) + kv_a (latent+rope, MQA) +
        # kv_b (per-head nope/value halves). HF's rope_interleave stores the
        # rope features pair-interleaved ([x0,y0,x1,y1,...]); de-interleave
        # the producing columns so standard rotate-half rope applies.
        nope, rope = config.qk_nope_head_dim, config.qk_rope_head_dim
        perm = np.concatenate([np.arange(0, rope, 2), np.arange(1, rope, 2)])

        def deinterleave_q(w: np.ndarray) -> np.ndarray:
            # w (in, H*(nope+rope)): permute each head's rope columns
            if not rope_interleave:
                return w
            w = w.copy()
            for head in range(config.n_heads):
                base = head * (nope + rope) + nope
                w[:, base : base + rope] = w[:, base + perm]
            return w

        def deinterleave_kpe(w: np.ndarray) -> np.ndarray:
            # w (in, rank+rope): permute the trailing shared-rope columns
            if not rope_interleave:
                return w
            w = w.copy()
            base = config.kv_lora_rank
            w[:, base : base + rope] = w[:, base + perm]
            return w

        def stacked_via(template: str, fix) -> jnp.ndarray:
            return jnp.asarray(
                np.stack([fix(get(template.format(i)).T) for i in range(config.n_layers)]),
                dtype=dtype,
            )

        attn_weights = {
            "wkv_a": stacked_via("layers.{}.self_attn.kv_a_proj_with_mqa.weight", deinterleave_kpe),
            "kv_a_norm": stacked("layers.{}.self_attn.kv_a_layernorm.weight", transpose=False),
            "wkv_b": stacked("layers.{}.self_attn.kv_b_proj.weight", transpose=True),
        }
        if config.q_lora_rank is not None:
            attn_weights |= {
                "wq_a": stacked("layers.{}.self_attn.q_a_proj.weight", transpose=True),
                "q_a_norm": stacked("layers.{}.self_attn.q_a_layernorm.weight", transpose=False),
                "wq_b": stacked_via("layers.{}.self_attn.q_b_proj.weight", deinterleave_q),
            }
        else:
            attn_weights["wq"] = stacked_via("layers.{}.self_attn.q_proj.weight", deinterleave_q)
    elif present("layers.0.self_attn.qkv_proj.weight"):
        # Phi3 fused attention: q rows, then k rows, then v rows
        q_rows = config.n_heads * config.head_dim
        kv_rows = config.n_kv_heads * config.head_dim
        attn_weights = {
            "wq": stacked_rows("layers.{}.self_attn.qkv_proj.weight", 0, q_rows),
            "wk": stacked_rows(
                "layers.{}.self_attn.qkv_proj.weight", q_rows, q_rows + kv_rows
            ),
            "wv": stacked_rows(
                "layers.{}.self_attn.qkv_proj.weight", q_rows + kv_rows, q_rows + 2 * kv_rows
            ),
        }
    else:
        attn_weights = {
            "wq": stacked("layers.{}.self_attn.q_proj.weight", transpose=True),
            "wk": stacked("layers.{}.self_attn.k_proj.weight", transpose=True),
            "wv": stacked("layers.{}.self_attn.v_proj.weight", transpose=True),
        }
    shared_keys = {
        **attn_weights,
        "wo": stacked("layers.{}.self_attn.o_proj.weight", transpose=True),
        **norm_keys,
        **attn_biases,
    }
    params: dict[str, Any] = {
        "embed": jnp.asarray(get("embed_tokens.weight"), dtype=dtype),
        "layers": {**shared_keys, **mlp_weights},
        "final_norm": jnp.asarray(get("norm.weight"), dtype=dtype),
    }
    if config.first_k_dense:
        # DeepSeek dense prefix: attention/norm stacks cover ALL layers —
        # split them (transiently ~2x those stacks on device; attention is
        # a small fraction of a prefix model next to its expert weights, so
        # the peak is dominated by the experts either way); the MoE stacks
        # above were already built over the MoE tail only, and the prefix
        # layers carry a plain gate/up/down MLP
        kd = config.first_k_dense
        params["layers"] = {
            **{key: value[kd:] for key, value in shared_keys.items()},
            **mlp_weights,
        }

        def stacked_prefix(template: str) -> jnp.ndarray:
            return jnp.asarray(
                np.stack([get(template.format(i)).T for i in range(kd)]), dtype=dtype
            )

        params["dense_layers"] = {
            **{key: value[:kd] for key, value in shared_keys.items()},
            "w_gate": stacked_prefix("layers.{}.mlp.gate_proj.weight"),
            "w_up": stacked_prefix("layers.{}.mlp.up_proj.weight"),
            "w_down": stacked_prefix("layers.{}.mlp.down_proj.weight"),
        }
    if not config.tie_embeddings:
        params["lm_head"] = jnp.asarray(np.asarray(state["lm_head.weight"]).T, dtype=dtype)
    return params


def load_hf_checkpoint(
    checkpoint_dir: str | Path, dtype=jnp.bfloat16
) -> tuple[dict[str, Any], ModelConfig]:
    """Load (params, config) from a local HF Llama checkpoint directory."""
    import json

    checkpoint_dir = Path(checkpoint_dir)
    hf_cfg_raw = json.loads((checkpoint_dir / "config.json").read_text())

    class _Cfg:
        def __init__(self, d):
            self.__dict__.update(d)

    config = config_from_hf(_Cfg(hf_cfg_raw), name=checkpoint_dir.name)
    state = _read_state_dict(checkpoint_dir)
    return (
        params_from_state_dict(
            state, config, dtype=dtype,
            # transformers' DeepseekV3Config DEFAULTS rope_interleave to
            # True — a config.json that omits the key still means
            # interleaved weights, so the fallback must track that default
            rope_interleave=bool(
                hf_cfg_raw.get(
                    "rope_interleave", hf_cfg_raw.get("model_type") == "deepseek_v3"
                )
            ),
        ),
        config,
    )
