"""Prompt-lookup speculative decoding: draft-free multi-token decode.

The reference serves models through hosted inference (SURVEY.md §2.2
``/inference``) and never decodes locally; this framework's native serving
path decodes one token per forward pass, each pass reading every weight from
HBM. Speculative decoding amortizes that read: propose D draft tokens by
n-gram lookup in the sequence's own history (prompt + generation so far —
"prompt-lookup decoding", the draft-model-free variant), then verify all D in
ONE forward pass over the KV cache. Greedy verification is exact: emitted
tokens are identical to plain ``generate`` token-for-token; matching drafts
just arrive D-at-a-time for one weight read. Sampled verification
(temperature > 0) is rejection sampling against the point-mass n-gram
proposal — exact in distribution (Leviathan et al. 2023 scheme specialized
to a deterministic draft).

TPU-first construction — the whole loop is one jitted ``lax.while_loop``:
- static shapes throughout: the verify window is always (B, D+1); the
  history buffer is (B, S+N) with per-row valid lengths;
- the verify pass reuses the chunked-prefill path (write K/V at each row's
  cache length, attend with per-row offsets) — no new attention math;
- per-row acceptance: each sequence advances by its own 1..D+1 tokens per
  iteration (bonus token included), rows never block each other;
- rejected drafts leave stale K/V beyond the row's cache length — invisible
  (slots >= length are masked) and overwritten by the next window.

Gains scale with how repetitive the continuation is w.r.t. its own context
(extractive QA, code edits, gsm8k-style restated numbers). Worst case is one
token per pass, like plain decode, plus the D-slot verify overhead. Measured
on v5e-1, llama3.2-1b bf16, b8 p128+128 periodic context: 1503 -> 2379 tok/s
(1.58x) at draft_len=4.

Exactness caveat: the (B, D+1) verify matmul and the (B, 1) decode matmul can
round bf16 activations differently. Greedy: "exact" means exact in argmax
space — a near-tied argmax can flip vs plain decode. Sampled: "exact in
distribution" holds for the distribution induced by the verify pass's
logits, which match plain decode's up to that same bf16 rounding (standard
for batched-verify speculation; bit-identical in fp32, immaterial for
trained checkpoints).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from prime_tpu.models.config import ModelConfig
from prime_tpu.models.llama import KVCache, forward
from prime_tpu.models.sampler import (
    GenerationResult,
    _sample,
    finalize_tokens,
    run_prefill,
)


def propose_ngram_drafts(
    history: jnp.ndarray,   # (B, T) token history, pad beyond lengths
    lengths: jnp.ndarray,   # (B,) valid tokens in history
    draft_len: int,
) -> jnp.ndarray:
    """(B, draft_len) drafts: find the most recent earlier occurrence of each
    row's last bigram and copy the tokens that followed it. Rows with no
    match repeat their last token — a wrong draft only costs the acceptance,
    never correctness."""
    batch, total = history.shape
    t0 = jnp.take_along_axis(history, (lengths - 2)[:, None], axis=1)  # (B, 1)
    t1 = jnp.take_along_axis(history, (lengths - 1)[:, None], axis=1)
    positions = jnp.arange(total)[None, :]                             # (1, T)
    shifted = jnp.roll(history, -1, axis=1)                            # history[:, j+1]
    # bigram at (j, j+1) matches, with the draft window starting at j+2
    # strictly before the current tail bigram
    match = (
        (history == t0)
        & (shifted == t1)
        & (positions < (lengths - 2)[:, None])
    )
    best = jnp.max(jnp.where(match, positions, -1), axis=1)            # (B,)
    start = jnp.clip(best + 2, 0, total - draft_len)

    def gather_row(row, s):
        return jax.lax.dynamic_slice(row, (s,), (draft_len,))

    drafts = jax.vmap(gather_row)(history, start)
    # a tail-adjacent match (e.g. a constant run, whose previous bigram sits
    # one position back) reads past the row's valid length into the pad
    # region — repeat the trailing token there instead, so the drafter
    # predicts "the run continues" rather than proposing pads. Without this
    # the MOST favorable regime (tight loops) capped acceptance at 1.
    offsets = start[:, None] + jnp.arange(draft_len)[None, :]
    drafts = jnp.where(offsets < lengths[:, None], drafts, t1)
    fallback = jnp.broadcast_to(t1, (batch, draft_len))
    return jnp.where((best >= 0)[:, None], drafts, fallback)


def verify_window_tokens(
    logits: jnp.ndarray,   # (B, D+1, V) fp32 — the verify pass's outputs
    drafts: jnp.ndarray,   # (B, D) proposed tokens
    temps: jnp.ndarray,    # (B,) traced; 0 = greedy argmax acceptance
    top_ps: jnp.ndarray,   # (B,) traced; active only where temps > 0
    accept_rng: jnp.ndarray,
    fix_rng: jnp.ndarray,
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """The ONE owner of speculative accept/correct math, per-row.

    Greedy rows (temp 0) accept drafts matching argmax and take the argmax
    bonus/correction; sampled rows rejection-sample against the point-mass
    n-gram proposal (accept draft x with prob p(x); on rejection draw from
    the residual with x zeroed) — exact in distribution. Temperature scaling
    then the nucleus filter, matching sampler.scaled_logits' ordering.
    Returns (tokens_round (B, D+1), n_acc (B,)): positions <= n_acc of
    tokens_round are this round's emissions (accepted drafts + the
    bonus/correction at position n_acc).
    """
    from prime_tpu.models.sampler import top_p_filter

    batch, window, _ = logits.shape
    draft_len = window - 1
    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)       # (B, D+1)
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None, None]
    wants_nucleus = jnp.any((top_ps < 1.0) & (temps > 0.0))
    filtered = jax.lax.cond(
        wants_nucleus, lambda x: top_p_filter(x, top_ps[:, None]), lambda x: x, scaled
    )
    probs = jax.nn.softmax(filtered, axis=-1)
    draft_p = jnp.squeeze(
        jnp.take_along_axis(probs[:, :draft_len, :], drafts[:, :, None], axis=2), axis=2
    )                                                                # (B, D)
    uniform = jax.random.uniform(accept_rng, (batch, draft_len))
    greedy_row = (temps == 0.0)[:, None]
    accept = jnp.where(greedy_row, drafts == greedy_tok[:, :draft_len], uniform < draft_p)
    n_acc = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1)
    pos = n_acc                                                      # (B,) 0..D
    p_pos = jax.vmap(lambda p, i: p[i])(probs, pos)                  # (B, V)
    rejected = pos < draft_len
    draft_at = jax.vmap(lambda d, i: d[jnp.minimum(i, draft_len - 1)])(drafts, pos)
    vocab_ids = jnp.arange(probs.shape[-1])[None, :]
    residual = jnp.where(rejected[:, None] & (vocab_ids == draft_at[:, None]), 0.0, p_pos)
    corrected_sampled = jax.random.categorical(
        fix_rng, jnp.log(jnp.maximum(residual, 1e-30))
    ).astype(jnp.int32)
    corrected_greedy = jax.vmap(lambda g, i: g[i])(greedy_tok, pos)
    corrected = jnp.where(temps == 0.0, corrected_greedy, corrected_sampled)
    padded = jnp.concatenate([drafts, jnp.zeros((batch, 1), jnp.int32)], axis=1)
    emit_ids = jnp.arange(draft_len + 1)[None, :]
    tokens_round = jnp.where(emit_ids == pos[:, None], corrected[:, None], padded)
    return tokens_round, n_acc


class _SpecCarry(NamedTuple):
    cache: KVCache
    history: jnp.ndarray     # (B, S+N) prompt + emitted tokens
    lengths: jnp.ndarray     # (B,) valid history tokens
    cache_len: jnp.ndarray   # (B,) cache entries whose K/V are valid
    emitted: jnp.ndarray     # (B,) generated-token counts
    done: jnp.ndarray        # (B,)
    rng: jnp.ndarray         # sampling key (unused in greedy mode)


@functools.partial(
    jax.jit,
    static_argnames=(
        "config", "max_new_tokens", "draft_len", "eos_id", "pad_id", "attn_impl",
        "cache_spec", "temperature", "nucleus", "kv_quant",
    ),
)
def spec_generate(
    params,
    prompt_tokens: jnp.ndarray,    # (B, S) right-padded with pad_id
    prompt_lengths: jnp.ndarray,   # (B,)
    config: ModelConfig,
    max_new_tokens: int = 128,
    draft_len: int = 4,
    eos_id: int = -1,
    pad_id: int = 0,
    attn_impl: str = "auto",
    cache_spec=None,
    temperature: float = 0.0,
    top_p=1.0,                     # traced; active only with nucleus=True
    nucleus: bool = False,
    rng: jnp.ndarray | None = None,
    kv_quant: bool = False,        # int8 cache; verify windows quantize per-slot
) -> GenerationResult:
    """Generation via prompt-lookup speculation.

    temperature == 0 verifies in argmax space and emits exactly the tokens
    plain greedy ``generate`` would. temperature > 0 uses deterministic-
    proposal rejection sampling (Leviathan et al.): draft token x is accepted
    with probability p(x) — its full model probability, since the n-gram
    proposal is a point mass — and on rejection the correction is drawn from
    the residual p with x zeroed. The OUTPUT DISTRIBUTION is exactly the
    autoregressive sampling distribution at the same temperature/top_p; only
    the number of forward passes changes. logprobs are returned as zeros.
    """
    batch, prompt_len = prompt_tokens.shape
    if temperature > 0.0 and rng is None:
        raise ValueError("sampled speculative decoding needs an rng key")
    if rng is None:
        rng = jax.random.PRNGKey(0)  # never consumed on the greedy path
    # history is padded so a (draft_len+1) scatter window starting at any
    # valid row length stays in-bounds (no silent dynamic_slice clamping);
    # the cache matches because verify windows scribble up to draft_len+1
    # slots past a row's valid length
    total = prompt_len + max_new_tokens + draft_len + 1
    last, cache = run_prefill(
        params, prompt_tokens, prompt_lengths, config, capacity=total,
        attn_impl=attn_impl, cache_spec=cache_spec, kv_quant=kv_quant,
    )
    rng, first_rng = jax.random.split(rng)
    first = _sample(last, temperature, first_rng, top_p, nucleus).astype(jnp.int32)
    first_done = first == eos_id

    # the first token occupies a buffer slot even when it is EOS
    # (generate's contract: lengths exclude the EOS, the token stays)
    pad_tail = jnp.full((batch, total - prompt_len), pad_id, jnp.int32)
    history0 = jax.vmap(lambda row, idx, tok: row.at[idx].set(tok))(
        jnp.concatenate([prompt_tokens, pad_tail], axis=1), prompt_lengths, first
    )
    carry = _SpecCarry(
        cache=cache,
        history=history0,
        lengths=prompt_lengths + 1,
        cache_len=prompt_lengths.astype(jnp.int32),
        emitted=jnp.ones((batch,), jnp.int32),
        done=first_done,
        rng=rng,
    )

    def cond(c: _SpecCarry):
        return jnp.any(~c.done & (c.emitted < max_new_tokens))

    def body(c: _SpecCarry) -> _SpecCarry:
        drafts = propose_ngram_drafts(c.history, c.lengths, draft_len)  # (B, D)
        last_tok = jnp.take_along_axis(c.history, (c.lengths - 1)[:, None], axis=1)
        window = jnp.concatenate([last_tok, drafts], axis=1)            # (B, D+1)

        verify_cache = c.cache._replace(lengths=c.cache_len)
        logits, new_cache = forward(
            params,
            window,
            config,
            cache=verify_cache,
            decode=False,
            attn_impl=attn_impl,
            prefill_offset=c.cache_len,
        )
        next_rng = c.rng
        if temperature == 0.0:
            greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)      # (B, D+1)
            # leading run of drafts the model itself would have produced
            agree = drafts == greedy[:, :-1]                            # (B, D)
            n_acc = jnp.sum(jnp.cumprod(agree.astype(jnp.int32), axis=1), axis=1)
            tokens_round = greedy
        else:
            # per-row shared verify math (verify_window_tokens is the one
            # owner of the accept/residual/bonus scheme, shared with the
            # continuous engine's per-slot mixed-temperature path)
            next_rng, accept_rng, fix_rng = jax.random.split(c.rng, 3)
            temps_vec = jnp.full((batch,), temperature, jnp.float32)
            top_vec = (
                jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), (batch,))
                if nucleus
                else jnp.ones((batch,), jnp.float32)
            )
            tokens_round, n_acc = verify_window_tokens(
                logits, drafts, temps_vec, top_vec, accept_rng, fix_rng
            )

        # emitted this round: tokens_round[0..n_acc] — accepted drafts + the
        # bonus/correction token. Truncate at the first EOS and at the budget.
        emit_ids = jnp.arange(draft_len + 1)[None, :]
        in_run = emit_ids <= n_acc[:, None]
        is_eos = (tokens_round == eos_id) & in_run
        # index of the first EOS within the run (draft_len+1 if none)
        eos_first = jnp.min(
            jnp.where(is_eos, emit_ids, draft_len + 1), axis=1
        )
        run_len = jnp.minimum(n_acc + 1, eos_first + 1)                 # EOS included
        budget = max_new_tokens - c.emitted
        run_len = jnp.minimum(run_len, budget)
        run_len = jnp.where(c.done, 0, run_len)

        keep = emit_ids < run_len[:, None]
        tokens_out = jnp.where(keep, tokens_round, pad_id)

        def scatter_row(row, start, vals, m):
            window_old = jax.lax.dynamic_slice(row, (start,), (draft_len + 1,))
            merged = jnp.where(m, vals, window_old)
            return jax.lax.dynamic_update_slice(row, merged, (start,))

        history = jax.vmap(scatter_row)(c.history, c.lengths, tokens_out, keep)

        new_done = c.done | (eos_first <= n_acc) | (c.emitted + run_len >= max_new_tokens)
        # cache rows advance past the verified tokens actually kept; the
        # window wrote K/V for [cache_len, cache_len + D + 1) but only the
        # first run_len entries (last token + accepted drafts) stay valid
        new_cache_len = c.cache_len + jnp.where(c.done, 0, run_len)
        return _SpecCarry(
            cache=new_cache._replace(lengths=new_cache_len),
            history=history,
            lengths=c.lengths + run_len,
            cache_len=new_cache_len,
            emitted=c.emitted + run_len,
            done=new_done,
            rng=next_rng,
        )

    final = jax.lax.while_loop(cond, body, carry)

    # each row's generation starts at its own prompt length
    def row_gen(row, s):
        return jax.lax.dynamic_slice(row, (s,), (max_new_tokens,))

    generated = jax.vmap(row_gen)(final.history, prompt_lengths)
    # the shared output contract: pad after the first EOS, lengths exclude it
    cleaned, gen_lengths = finalize_tokens(generated, eos_id, pad_id)
    return GenerationResult(
        tokens=cleaned,
        lengths=gen_lengths,
        logprobs=jnp.zeros((batch, max_new_tokens), jnp.float32),
    )
