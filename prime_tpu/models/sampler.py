"""Autoregressive generation with a KV cache.

TPU-first decode loop: prefill once over the padded prompt batch (flash
attention), then ``lax.scan`` over decode steps — the whole generation is two
compiled programs, no per-token Python dispatch. Right-padded prompts with
per-sequence lengths; finished sequences keep emitting ``pad_id`` so shapes
stay static.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from prime_tpu.models.config import ModelConfig
from prime_tpu.models.llama import KVCache, forward, init_cache


class GenerationResult(NamedTuple):
    tokens: jnp.ndarray        # (B, max_new_tokens) generated ids (pad after EOS)
    lengths: jnp.ndarray       # (B,) generated tokens before EOS (exclusive)
    logprobs: jnp.ndarray      # (B, max_new_tokens) logprob of each sampled token


def top_p_filter(logits: jnp.ndarray, top_p) -> jnp.ndarray:
    """Nucleus filtering with static shapes: tokens outside the smallest set
    with cumulative probability >= top_p get -inf. ``top_p`` is TRACED — a
    scalar, or anything broadcastable against ``logits[..., :1]`` (the
    serving engine passes a per-row vector) — so it varies per request
    without recompiling. The single owner of this math; the continuous-
    batching engine samples through it too."""
    top_p = jnp.asarray(top_p)[..., None] if jnp.ndim(top_p) else top_p
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    cumulative = jnp.cumsum(jax.nn.softmax(sorted_logits, axis=-1), axis=-1)
    # keep every token whose PRECEDING cumulative mass is < top_p (the
    # first token crossing the threshold stays in the nucleus)
    keep_sorted = jnp.concatenate(
        [jnp.ones_like(cumulative[..., :1], dtype=bool), cumulative[..., :-1] < top_p],
        axis=-1,
    )
    cutoff = jnp.min(
        jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1, keepdims=True
    )
    return jnp.where(logits >= cutoff, logits, -jnp.inf)


def scaled_logits(logits: jnp.ndarray, temperature: float, top_p, nucleus: bool) -> jnp.ndarray:
    """The sampling distribution's logits: temperature scaling then the
    nucleus filter. ONE owner for this ordering — the speculative verifier
    computes acceptance probabilities from the same function plain sampling
    draws from, so the two can never drift."""
    logits = logits / temperature
    if nucleus:
        logits = top_p_filter(logits, top_p)
    return logits


def _sample(
    logits: jnp.ndarray,
    temperature: float,
    rng: jax.Array,
    top_p=1.0,
    nucleus: bool = False,
) -> jnp.ndarray:
    """``nucleus`` is the static switch (compile-time); ``top_p`` itself is a
    TRACED scalar so serving clients can vary it per request without
    triggering a full recompile of the generation program."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1)
    return jax.random.categorical(rng, scaled_logits(logits, temperature, top_p, nucleus), axis=-1)


def run_prefill(
    params,
    prompt_tokens: jnp.ndarray,    # (B, S) right-padded with pad_id
    prompt_lengths: jnp.ndarray,   # (B,)
    config: ModelConfig,
    capacity: int,
    attn_impl: str = "auto",
    cache_spec=None,
    kv_quant: bool = False,
) -> tuple[jnp.ndarray, KVCache]:
    """Shared prefill: init the cache (optionally layout-pinned), run the
    prompt, fix the per-sequence lengths, and return each row's next-token
    logits. One owner for this block keeps ``generate`` and the speculative
    decoder (models/speculative.py) byte-identical up to the first token."""
    batch = prompt_tokens.shape[0]
    cache = init_cache(
        config, batch, capacity, dtype=params["embed"].dtype, quantized=kv_quant
    )
    if cache_spec is not None:
        # pin the cache layout before it enters the scan carry — XLA would
        # otherwise be free to replicate the zeros init across the mesh
        cache = cache._replace(
            k=jax.lax.with_sharding_constraint(cache.k, cache_spec),
            v=jax.lax.with_sharding_constraint(cache.v, cache_spec),
        )
        if cache.quantized:
            cache = cache._replace(
                k_scale=jax.lax.with_sharding_constraint(cache.k_scale, cache_spec),
                v_scale=jax.lax.with_sharding_constraint(cache.v_scale, cache_spec),
            )
    # next-token logits live at each sequence's last real position — gather
    # it inside forward, before the unembedding (skips S× the head FLOPs and
    # the (B, S, V) fp32 logits buffer, which at long context dwarfs HBM)
    logits, cache = forward(
        params, prompt_tokens, config, cache=cache, decode=False,
        attn_impl=attn_impl, last_positions=prompt_lengths - 1,
    )
    # cache was filled for the padded length; true lengths are per-sequence
    cache = cache._replace(lengths=prompt_lengths.astype(jnp.int32))
    return logits[:, 0, :], cache


def finalize_tokens(
    generated: jnp.ndarray, eos_id: int, pad_id: int
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """The output contract both decoders share: everything after the first
    EOS becomes pad (the EOS itself stays in the buffer), and lengths count
    the tokens strictly before it."""
    max_new = generated.shape[1]
    position = jnp.arange(max_new)[None, :]
    first_eos = jnp.min(jnp.where(generated == eos_id, position, max_new), axis=1)
    cleaned = jnp.where(position <= first_eos[:, None], generated, pad_id)
    return cleaned, first_eos


@functools.partial(
    jax.jit,
    static_argnames=(
        "config", "max_new_tokens", "temperature", "nucleus", "eos_id", "pad_id",
        "attn_impl", "cache_spec", "kv_quant",
    ),
)
def generate(
    params,
    prompt_tokens: jnp.ndarray,    # (B, S) right-padded with pad_id
    prompt_lengths: jnp.ndarray,   # (B,)
    config: ModelConfig,
    rng: jax.Array,
    max_new_tokens: int = 128,
    temperature: float = 0.0,
    top_p=1.0,                     # traced scalar; active only with nucleus=True
    nucleus: bool = False,         # static switch for top-p filtering
    eos_id: int = -1,              # -1 disables EOS stopping
    pad_id: int = 0,
    attn_impl: str = "auto",
    cache_spec=None,               # PartitionSpec for the (L,B,KH,hd,C) cache; needs jax.set_mesh
    kv_quant: bool = False,        # int8 KV cache (halved decode HBM traffic)
) -> GenerationResult:
    batch, prompt_len = prompt_tokens.shape
    last, cache = run_prefill(
        params, prompt_tokens, prompt_lengths, config,
        capacity=prompt_len + max_new_tokens,
        attn_impl=attn_impl, cache_spec=cache_spec, kv_quant=kv_quant,
    )

    rng, step_rng = jax.random.split(rng)
    first_tokens = _sample(last, temperature, step_rng, top_p, nucleus)
    first_logprobs = jnp.take_along_axis(
        jax.nn.log_softmax(last, axis=-1), first_tokens[:, None], axis=1
    )[:, 0]

    # ---- decode loop ----
    class Carry(NamedTuple):
        cache: KVCache
        tokens: jnp.ndarray      # (B,) last sampled
        done: jnp.ndarray        # (B,) bool
        rng: jax.Array

    def step(carry: Carry, _):
        logits, new_cache = forward(
            params,
            carry.tokens[:, None],
            config,
            positions=carry.cache.lengths[:, None],
            cache=carry.cache,
            decode=True,
            attn_impl=attn_impl,
        )
        step_logits = logits[:, 0, :]
        rng, step_rng = jax.random.split(carry.rng)
        sampled = _sample(step_logits, temperature, step_rng, top_p, nucleus)
        sampled = jnp.where(carry.done, pad_id, sampled)
        logprob = jnp.take_along_axis(
            jax.nn.log_softmax(step_logits, axis=-1), sampled[:, None], axis=1
        )[:, 0]
        done = carry.done | (sampled == eos_id)
        return Carry(new_cache, sampled, done, rng), (sampled, jnp.where(carry.done, 0.0, logprob))

    init_done = jnp.zeros((batch,), dtype=bool) | (first_tokens == eos_id)
    carry = Carry(cache, first_tokens, init_done, rng)
    if max_new_tokens > 1:
        carry, (rest_tokens, rest_logprobs) = jax.lax.scan(
            step, carry, None, length=max_new_tokens - 1
        )
        all_tokens = jnp.concatenate([first_tokens[:, None], rest_tokens.T], axis=1)
        all_logprobs = jnp.concatenate([first_logprobs[:, None], rest_logprobs.T], axis=1)
    else:
        all_tokens = first_tokens[:, None]
        all_logprobs = first_logprobs[:, None]

    # length = tokens strictly before the first EOS (a sampled token that
    # happens to equal pad_id is still a real token and counts)
    cleaned, gen_lengths = finalize_tokens(all_tokens, eos_id, pad_id)
    return GenerationResult(tokens=cleaned, lengths=gen_lengths, logprobs=all_logprobs)
