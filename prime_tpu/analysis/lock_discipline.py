"""Checker 1 — lock discipline (rule ``lock-discipline``).

For every class that owns a ``threading.Lock``/``RLock``/``Condition``, the
set of attributes the class itself treats as lock-guarded is *inferred*: any
``self.X`` that is written (assigned, augmented, subscript-stored, deleted,
or mutated through a known container-mutator method) inside a
``with self.<lock>:`` block, in any method. Every other access of those
attributes — read or write — from a method of the same class that is not
under the lock is a finding: the engine stats-snapshot lock, the membership
shared-client, the flight-recorder rings, and the prefix-cache refcount
hardening of PRs 3-6 were all hand-caught instances of exactly this drift.

The ``outer = self`` closure idiom is understood: the serve server binds
``outer = self`` and hands ``outer`` to a nested handler class whose methods
run on HTTP threads — ``with outer._lock:`` acquires the same lock and
``outer.attr`` accesses the same state, so those nested bodies are analyzed
as the owning class's code (deferred: ``__init__``'s straight-line
constructor statements stay exempt, but functions *defined* inside it run
later, on other threads, and are checked).

What the inference deliberately skips:

- ``__init__``'s own statements (construction precedes sharing);
- attributes holding intrinsically thread-safe objects (``queue.Queue``
  family, ``threading.Event``/``Semaphore``, ``collections.deque`` — their
  single-call operations are atomic), detected from their ``__init__``
  assignment;
- methods whose docstring declares the caller-holds-the-lock contract
  (``"lock held"`` / ``"caller holds the lock"`` …): their bodies count as
  under the lock for both inference and checking, so the repo's existing
  ``_finish``/``_write_sink`` helper idiom is recognized, and the contract
  doc-comment becomes machine-read instead of reviewer-read;
- code inside nested ``def``/``lambda`` under a ``with`` block (it runs
  later, when the lock is NOT held — textual nesting is not temporal
  nesting).

Intentionally lock-free sites (single-writer flags, GIL-atomic reads on hot
paths) belong in ``baseline.toml`` with a one-line justification, or behind
an inline ``# prime-lint: ignore[lock-discipline] why`` pragma.
"""

from __future__ import annotations

import ast
import re
from typing import Iterator

from prime_tpu.analysis.core import Finding, Project, SourceFile, call_name

RULE = "lock-discipline"

LOCK_FACTORIES = {
    "threading.Lock",
    "threading.RLock",
    "threading.Condition",
    "Lock",
    "RLock",
    "Condition",
}
THREADSAFE_FACTORIES = {
    "queue.Queue",
    "queue.LifoQueue",
    "queue.PriorityQueue",
    "queue.SimpleQueue",
    "Queue",
    "SimpleQueue",
    "threading.Event",
    "threading.Semaphore",
    "threading.BoundedSemaphore",
    "threading.Barrier",
    "Event",
    "Semaphore",
    "BoundedSemaphore",
    "Barrier",
    "collections.deque",
    "deque",
}
# container methods that mutate their receiver — ``self.x.append(...)``
# under the lock marks ``x`` guarded just like ``self.x = ...`` does
MUTATORS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "update", "setdefault", "pop", "popleft", "popitem", "remove",
    "discard", "clear", "move_to_end", "rotate", "sort", "reverse",
    "put", "put_nowait",
}
_LOCKISH_NAME = re.compile(r"lock|mutex|cond", re.IGNORECASE)
_HELD_DOC = re.compile(
    r"lock (?:is )?held|caller holds? the (?:\w+ )?lock|holding the (?:\w+ )?lock|"
    r"called with the (?:\w+ )?lock",
    re.IGNORECASE,
)


def _root(node: ast.AST, selves: set[str]) -> str | None:
    """Attribute name an access roots at, when the receiver is ``self`` or
    a known alias of it: ``self.x[k].y`` / ``outer.x`` -> ``"x"``."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id in selves
        ):
            return node.attr
        node = node.value
    return None


def _direct_attr(node: ast.AST, selves: set[str]) -> str | None:
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id in selves
    ):
        return node.attr
    return None


class _ClassInfo:
    def __init__(self, node: ast.ClassDef) -> None:
        self.node = node
        self.selves: set[str] = {"self"}
        self.lock_attrs: set[str] = set()
        self.threadsafe_attrs: set[str] = set()


def _collect_aliases(info: _ClassInfo) -> None:
    """Names bound as plain aliases of ``self`` (``outer = self``)."""
    for node in ast.walk(info.node):
        if (
            isinstance(node, ast.Assign)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            for target in node.targets:
                if isinstance(target, ast.Name):
                    info.selves.add(target.id)


def _classify_attrs(info: _ClassInfo) -> None:
    """Which attrs hold locks, which hold intrinsically thread-safe
    containers (from their constructor-call assignments anywhere in the
    class), plus lock-ish names acquired via ``with``."""
    for node in ast.walk(info.node):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            factory = call_name(node.value.func)
            if factory is None:
                continue
            for target in node.targets:
                attr = _root(target, info.selves)
                if attr is None:
                    continue
                if factory in LOCK_FACTORIES:
                    info.lock_attrs.add(attr)
                elif factory in THREADSAFE_FACTORIES:
                    info.threadsafe_attrs.add(attr)
        elif isinstance(node, ast.With):
            for item in node.items:
                attr = _root(item.context_expr, info.selves)
                if attr is not None and _LOCKISH_NAME.search(attr):
                    info.lock_attrs.add(attr)


def _acquires(stmt: ast.With, lock_attrs: set[str], selves: set[str]) -> bool:
    for item in stmt.items:
        if isinstance(item.context_expr, ast.Attribute):
            attr = _root(item.context_expr, selves)
            if attr is not None and attr in lock_attrs:
                return True
    return False


def _iter_with_lock_context(
    body: list[ast.stmt], lock_attrs: set[str], selves: set[str], under_lock: bool
) -> Iterator[tuple[ast.AST, bool]]:
    """Yield every AST node in ``body`` exactly once, paired with whether
    the class lock is held at that node. A ``with self.<lock>:`` body is
    held; nested ``def``/``lambda`` bodies are NOT (they execute later) —
    textual nesting is not temporal nesting."""

    def visit(node: ast.AST, held: bool) -> Iterator[tuple[ast.AST, bool]]:
        yield node, held
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            inner = node.body if isinstance(node.body, list) else [node.body]
            for child in inner:
                yield from visit(child, False)
            return
        if isinstance(node, ast.With) and _acquires(node, lock_attrs, selves):
            for item in node.items:
                yield from visit(item.context_expr, held)
            for child in node.body:
                yield from visit(child, True)
            return
        for child in ast.iter_child_nodes(node):
            yield from visit(child, held)

    for stmt in body:
        yield from visit(stmt, under_lock)


def _write_roots(node: ast.AST, selves: set[str]) -> list[str]:
    """Attribute roots this single node writes/mutates (non-recursive:
    the traversal visits children itself)."""
    out: list[str] = []
    if isinstance(node, ast.Assign):
        targets = node.targets
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        targets = [node.target]
    elif isinstance(node, ast.Delete):
        targets = node.targets
    elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
        if node.func.attr in MUTATORS:
            attr = _root(node.func.value, selves)
            if attr is not None:
                out.append(attr)
        return out
    else:
        return out
    for target in targets:
        attr = _root(target, selves)
        if attr is not None:
            out.append(attr)
    return out


def _method_holds_lock(fn: ast.FunctionDef | ast.AsyncFunctionDef) -> bool:
    doc = ast.get_docstring(fn) or ""
    return bool(_HELD_DOC.search(doc))


def _execution_units(
    node: ast.ClassDef,
) -> list[tuple[str, list[ast.stmt], bool, bool]]:
    """(label, body, entry-lock-held, in-nested-class) triples to analyze.

    Methods other than ``__init__`` are units as-is. ``__init__``'s
    straight-line statements are construction (exempt), but every function
    *defined* inside it — a closure, or a method of a nested handler class —
    runs later on whatever thread calls it, so each top-most such def is its
    own unit. Units inside a nested class have their own ``self`` (the
    nested class's), so only the ``outer = self`` aliases reach back to the
    owning instance there. (Defs nested inside other methods are handled in
    place by the traversal's held=False descent.)"""
    def collect_topmost_defs(
        n: ast.AST, in_class: bool, out: list
    ) -> None:
        for child in ast.iter_child_nodes(n):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append((child, in_class))
                continue  # inner defs handled by the unit's traversal
            collect_topmost_defs(
                child, in_class or isinstance(child, ast.ClassDef), out
            )

    units: list[tuple[str, list[ast.stmt], bool, bool]] = []
    for fn in node.body:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if fn.name != "__init__":
            units.append((fn.name, fn.body, _method_holds_lock(fn), False))
            continue
        # top-most defs within __init__ (not contained in another def)
        defs: list[tuple[ast.FunctionDef | ast.AsyncFunctionDef, bool]] = []
        collect_topmost_defs(fn, False, defs)
        for sub, in_class in defs:
            units.append(
                (f"__init__.{sub.name}", sub.body, _method_holds_lock(sub), in_class)
            )
    return units


def _check_class(src: SourceFile, node: ast.ClassDef) -> list[Finding]:
    info = _ClassInfo(node)
    _collect_aliases(info)
    _classify_attrs(info)
    if not info.lock_attrs:
        return []
    units = _execution_units(node)

    def unit_selves(in_class: bool) -> set[str]:
        return (info.selves - {"self"}) if in_class else info.selves

    # pass 1: infer the guarded attribute set from writes under the lock
    guarded: set[str] = set()
    for _label, body, held0, in_class in units:
        selves = unit_selves(in_class)
        for sub, held in _iter_with_lock_context(body, info.lock_attrs, selves, held0):
            if not held:
                continue
            for attr in _write_roots(sub, selves):
                if attr not in info.lock_attrs and attr not in info.threadsafe_attrs:
                    guarded.add(attr)
    if not guarded:
        return []

    # pass 2: flag any unlocked access (read or write) of a guarded attr
    findings: list[Finding] = []
    lock_name = sorted(info.lock_attrs)[0]
    for label, body, held0, in_class in units:
        selves = unit_selves(in_class)
        for sub, held in _iter_with_lock_context(body, info.lock_attrs, selves, held0):
            if held:
                continue
            attr = _direct_attr(sub, selves)
            if attr is not None and attr in guarded:
                findings.append(
                    Finding(
                        RULE,
                        src.path,
                        sub.lineno,
                        f"{node.name}.{attr}",
                        f"{node.name}.{label} touches .{attr} outside the "
                        f"lock, but the class writes it under "
                        f"`with self.{lock_name}:` elsewhere",
                    )
                )
    return findings


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    seen: set[tuple[str, int, str]] = set()
    for src in project.files:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            for finding in _check_class(src, node):
                key = (finding.path, finding.line, finding.symbol)
                if key in seen:
                    continue
                seen.add(key)
                findings.append(finding)
    return findings
