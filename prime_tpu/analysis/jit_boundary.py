"""Checker 2 — jit-boundary purity & donation safety.

Rule ``jit-purity``: a function handed to ``jax.jit`` is traced once and
replayed as a device program — host effects inside it either run at trace
time only (silently wrong: a ``time.monotonic()`` freezes to a constant, a
metrics ``.inc()`` fires once per compile, not per step) or break tracing
outright (lock acquisition under ``jax.checkpoint`` re-trace). The checker
finds functions that are jitted — by ``jax.jit(fn, ...)`` call, ``@jax.jit``
/ ``@partial(jax.jit, ...)`` decoration — and flags host-state touches in
their bodies: ``time.*``, ``os.environ``/``os.getenv``, ``threading.*``,
host ``random.*``, ``print``/``open``/``input``, lock use
(``with self.<lock>`` / ``.acquire()``), and obs-layer calls (``TRACER``,
``REGISTRY``, ``self.registry``, ``self._m_*`` metric handles) — metrics
record *around* dispatches, never inside them (obs/metrics.py registry
contract).

Rule ``jit-donation``: an argument listed in ``donate_argnums`` is dead the
moment the jitted call dispatches — XLA may alias its buffer for the output.
Reading it afterwards returns poisoned memory on TPU (and works by accident
on CPU, which is why reviews kept catching it late: PR 2's error-path
``_fail_in_flight`` ordering was exactly this bug). The checker resolves
donation positions through the repo's builder idiom —

    def _make_decode(self):
        def decode(params, cache, last): ...
        return jax.jit(decode, donate_argnums=(1, 2))
    ...
    self._decode_fn = self._make_decode()

— so a call ``self._decode_fn(p, cache, last)`` taints ``cache``/``last``
(plain names or ``self.x`` attributes), and any later read of a tainted
value in the same caller body, without an intervening rebind, is a finding.
The same tracking covers locally-jitted functions
(``f = jax.jit(g, donate_argnums=...)``) and decorated ones. Statement
order is source order — good enough for the straight-line dispatch code
this rule exists for; loop-carried resurrection is out of scope.
"""

from __future__ import annotations

import ast

from prime_tpu.analysis.core import (
    Finding,
    Project,
    SourceFile,
    attr_root,
    call_name,
    self_attr,
)

PURITY_RULE = "jit-purity"
DONATION_RULE = "jit-donation"

_JIT_NAMES = {"jax.jit", "jit", "pjit", "jax.pjit"}
_PARTIAL_NAMES = {"partial", "functools.partial"}

# dotted-prefix denylist for host state inside a traced function
_IMPURE_PREFIXES = (
    "time.",
    "threading.",
    "random.",
    "os.environ",
    "os.getenv",
    "os.putenv",
)
_IMPURE_CALLS = {"print", "open", "input"}
_OBS_NAMES = {"TRACER", "REGISTRY"}


def _donate_positions(call: ast.Call) -> tuple[int, ...]:
    for kw in call.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                out = []
                for elt in v.elts:
                    if isinstance(elt, ast.Constant) and isinstance(elt.value, int):
                        out.append(elt.value)
                return tuple(out)
    return ()


def _is_jit_call(call: ast.Call) -> bool:
    return call_name(call.func) in _JIT_NAMES


# -- purity -------------------------------------------------------------------


def _purity_offender(node: ast.AST) -> str | None:
    """A host-state touch at this node, or None."""
    if isinstance(node, ast.Call):
        name = call_name(node.func)
        if name is not None:
            if name in _IMPURE_CALLS:
                return name
            for prefix in _IMPURE_PREFIXES:
                if name == prefix.rstrip(".") or name.startswith(prefix):
                    return name
            root = name.split(".", 1)[0]
            if root in _OBS_NAMES:
                return name
        if isinstance(node.func, ast.Attribute) and node.func.attr in (
            "acquire",
            "release",
        ):
            return f"{call_name(node.func) or node.func.attr}()"
    if isinstance(node, ast.Attribute):
        dotted = call_name(node)
        if dotted in ("os.environ",):
            return dotted
        attr = self_attr(node)
        if attr is not None and (attr.startswith("_m_") or attr == "registry"):
            return f"self.{attr}"
    if isinstance(node, ast.With):
        for item in node.items:
            attr = attr_root(item.context_expr)
            if attr is not None and "lock" in attr.lower():
                return f"with self.{attr}"
    return None


def _check_purity(src: SourceFile, fn: ast.FunctionDef, jit_site: int) -> list[Finding]:
    findings: list[Finding] = []
    seen: set[str] = set()
    for node in ast.walk(fn):
        offender = _purity_offender(node)
        if offender is None or offender in seen:
            continue
        # ast.walk yields a Call before its func chain: `os.environ.get(...)`
        # already reported covers the bare `os.environ` attribute inside it
        if any(prior.startswith(offender + ".") for prior in seen):
            continue
        seen.add(offender)
        line = getattr(node, "lineno", fn.lineno)
        findings.append(
            Finding(
                PURITY_RULE,
                src.path,
                line,
                f"{fn.name}:{offender}",
                f"`{fn.name}` is jitted (line {jit_site}) but touches host "
                f"state: {offender} — effects inside a traced function run "
                "at trace time, not per call",
            )
        )
    return findings


# -- collection of jitted functions and donation maps -------------------------


class _FileJitIndex:
    """Per-file: which local FunctionDefs are jitted, which class methods
    build donating jitted callables, which self attrs hold them."""

    def __init__(self) -> None:
        self.jitted: list[tuple[ast.FunctionDef, int, tuple[int, ...]]] = []
        # ClassName -> builder method name -> donate positions
        self.builders: dict[str, dict[str, tuple[int, ...]]] = {}
        # ClassName -> self attr name -> donate positions
        self.attr_fns: dict[str, dict[str, tuple[int, ...]]] = {}
        # plain local names bound to donating jitted callables:
        # (scope id) -> name -> positions — handled inline per function


def _local_defs(scope: ast.AST) -> dict[str, ast.FunctionDef]:
    out: dict[str, ast.FunctionDef] = {}
    body = getattr(scope, "body", [])
    for stmt in body:
        if isinstance(stmt, ast.FunctionDef):
            out[stmt.name] = stmt
    return out


def _index_file(src: SourceFile) -> _FileJitIndex:
    index = _FileJitIndex()

    # decorated functions
    for node in ast.walk(src.tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        for dec in node.decorator_list:
            donate: tuple[int, ...] = ()
            is_jit = False
            if call_name(dec) in _JIT_NAMES:
                is_jit = True
            elif isinstance(dec, ast.Call):
                dec_name = call_name(dec.func)
                if dec_name in _JIT_NAMES:
                    is_jit = True
                    donate = _donate_positions(dec)
                elif dec_name in _PARTIAL_NAMES and dec.args:
                    if call_name(dec.args[0]) in _JIT_NAMES:
                        is_jit = True
                        donate = _donate_positions(dec)
            if is_jit:
                index.jitted.append((node, node.lineno, donate))

    # jax.jit(fn, ...) call sites whose first arg resolves to a local def in
    # the enclosing scope (module, function, or method body)
    def scan_scope(scope: ast.AST, class_name: str | None) -> None:
        defs = _local_defs(scope)
        for node in ast.walk(scope):
            if isinstance(node, ast.Call) and _is_jit_call(node) and node.args:
                target = node.args[0]
                if isinstance(target, ast.Name) and target.id in defs:
                    index.jitted.append(
                        (defs[target.id], node.lineno, _donate_positions(node))
                    )

    scan_scope(src.tree, None)
    for node in ast.walk(src.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scan_scope(node, None)

    # builder methods: `return jax.jit(fn, donate_argnums=...)` inside a
    # method -> the method's name maps to those donation positions
    for cls in ast.walk(src.tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        builders: dict[str, tuple[int, ...]] = {}
        for fn in cls.body:
            if not isinstance(fn, ast.FunctionDef):
                continue
            for node in ast.walk(fn):
                if (
                    isinstance(node, ast.Return)
                    and isinstance(node.value, ast.Call)
                    and _is_jit_call(node.value)
                ):
                    donate = _donate_positions(node.value)
                    if donate:
                        builders[fn.name] = donate
        if not builders:
            continue
        index.builders[cls.name] = builders
        # self.X = self._make_decode()  ->  attr X carries the donation map
        attr_fns: dict[str, tuple[int, ...]] = {}
        for node in ast.walk(cls):
            if (
                isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)
                and not node.value.args
            ):
                method = self_attr(node.value.func)
                if method in builders:
                    for target in node.targets:
                        attr = self_attr(target)
                        if attr is not None:
                            attr_fns[attr] = builders[method]
        index.attr_fns[cls.name] = attr_fns
    return index


# -- donation: use-after-donate in callers ------------------------------------


def _expr_key(node: ast.expr) -> tuple[str, str] | None:
    """Taintable argument forms: a plain name or an exact ``self.x``."""
    if isinstance(node, ast.Name):
        return ("name", node.id)
    attr = self_attr(node)
    if attr is not None:
        return ("attr", attr)
    return None


def _check_donation_in_fn(
    src: SourceFile,
    fn: ast.FunctionDef | ast.AsyncFunctionDef,
    attr_fns: dict[str, tuple[int, ...]],
) -> list[Finding]:
    """Scan one caller body: jitted-call sites taint their donated args;
    any later read of a tainted name/attr without a rebind is a finding."""
    findings: list[Finding] = []

    # local `f = jax.jit(g, donate_argnums=...)` bindings inside this fn
    local_fns: dict[str, tuple[int, ...]] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _is_jit_call(node.value):
                donate = _donate_positions(node.value)
                if donate:
                    for target in node.targets:
                        if isinstance(target, ast.Name):
                            local_fns[target.id] = donate

    # donating call sites in this fn
    calls: list[tuple[ast.Call, tuple[int, ...], str]] = []
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        callee_attr = self_attr(node.func)
        if callee_attr is not None and callee_attr in attr_fns:
            calls.append((node, attr_fns[callee_attr], f"self.{callee_attr}"))
        elif isinstance(node.func, ast.Name) and node.func.id in local_fns:
            calls.append((node, local_fns[node.func.id], node.func.id))

    if not calls:
        return findings

    # loads/stores of names and self attrs across the fn, with line numbers
    loads: list[tuple[tuple[str, str], int]] = []
    stores: list[tuple[tuple[str, str], int]] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Name):
            key = ("name", node.id)
            if isinstance(node.ctx, ast.Load):
                loads.append((key, node.lineno))
            else:
                stores.append((key, node.lineno))
        elif isinstance(node, ast.Attribute):
            attr = self_attr(node)
            if attr is None:
                continue
            key = ("attr", attr)
            if isinstance(node.ctx, ast.Load):
                loads.append((key, node.lineno))
            else:
                stores.append((key, node.lineno))

    loads.sort(key=lambda pair: pair[1])
    for call, positions, callee in calls:
        call_end = getattr(call, "end_lineno", call.lineno)
        for pos in positions:
            if pos >= len(call.args):
                continue
            key = _expr_key(call.args[pos])
            if key is None:
                continue
            # rebinding at/after the call (e.g. `x = f(x)`) clears the taint
            # from that line on
            clear_lines = sorted(
                line for k, line in stores if k == key and line >= call.lineno
            )
            for load_key, line in loads:
                if load_key != key or line <= call_end:
                    continue
                if any(s <= line for s in clear_lines):
                    break  # rebound before (or at) this read
                label = key[1] if key[0] == "name" else f"self.{key[1]}"
                findings.append(
                    Finding(
                        DONATION_RULE,
                        src.path,
                        line,
                        f"{fn.name}:{label}",
                        f"`{label}` is donated to `{callee}` (donate_argnums "
                        f"position {pos}, call at line {call.lineno}) but read "
                        "afterwards — a donated buffer may be aliased by the "
                        "output and is invalid after dispatch",
                    )
                )
                break  # one finding per donated arg per call
    return findings


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    for src in project.files:
        index = _index_file(src)
        seen_fns: set[int] = set()
        for fn, jit_line, _donate in index.jitted:
            if id(fn) in seen_fns:
                continue
            seen_fns.add(id(fn))
            findings.extend(_check_purity(src, fn, jit_line))
        # donation checking inside every class that holds donating callables
        for cls in ast.walk(src.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            attr_fns = index.attr_fns.get(cls.name, {})
            for fn in cls.body:
                if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    findings.extend(_check_donation_in_fn(src, fn, attr_fns))
        # module-level / free functions: local jit bindings only
        for fn in src.tree.body:
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                findings.extend(_check_donation_in_fn(src, fn, {}))
    return findings
