"""CLI: ``python -m prime_tpu.analysis [--check] [...]``.

Default mode prints every non-waived finding and exits 0 (exploration);
``--check`` exits 1 on any non-waived finding OR any stale waiver — the CI
contract: the tree is clean modulo a baseline that can only shrink.
``--format github`` prints findings as workflow annotations so the CI job
surfaces them inline on the PR diff.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from prime_tpu.analysis import (
    CHECKERS,
    DEFAULT_BASELINE,
    RULES_BY_CHECKER,
    Project,
    apply_baseline,
    load_baseline,
    run_checks,
)


def _find_root(start: Path) -> Path:
    for candidate in (start, *start.parents):
        if (candidate / "prime_tpu").is_dir():
            return candidate
    return start


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m prime_tpu.analysis",
        description="prime-lint: serving-stack invariant checkers "
        "(lock discipline, jit boundaries, obs catalog, knob registry)",
    )
    parser.add_argument(
        "--check",
        action="store_true",
        help="exit non-zero on any non-waived finding or stale waiver (CI mode)",
    )
    parser.add_argument(
        "--root",
        default=None,
        help="repo root (default: auto-detect the directory holding prime_tpu/)",
    )
    parser.add_argument(
        "--baseline",
        default=None,
        help=f"waiver file (default: {DEFAULT_BASELINE.name} next to the package)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true", help="ignore the waiver file"
    )
    parser.add_argument(
        "--rules",
        default=None,
        help=f"comma-separated checker subset from: {', '.join(CHECKERS)}",
    )
    parser.add_argument(
        "--format",
        choices=("text", "github"),
        default="text",
        help="'github' prints ::error workflow annotations",
    )
    args = parser.parse_args(argv)

    root = Path(args.root) if args.root else _find_root(Path.cwd().resolve())
    if not (root / "prime_tpu").is_dir():
        print(f"error: no prime_tpu/ package under {root}", file=sys.stderr)
        return 2
    checkers = None
    if args.rules:
        checkers = [r.strip() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in checkers if r not in CHECKERS]
        if unknown:
            print(
                f"error: unknown checker(s) {unknown}; valid: {sorted(CHECKERS)}",
                file=sys.stderr,
            )
            return 2

    project = Project.from_root(root)
    findings = run_checks(project, checkers)

    waivers = []
    if not args.no_baseline:
        baseline_path = Path(args.baseline) if args.baseline else DEFAULT_BASELINE
        if baseline_path.exists():
            try:
                waivers = load_baseline(baseline_path)
            except ValueError as e:
                print(f"error: bad baseline: {e}", file=sys.stderr)
                return 2
    if checkers is not None:
        # a --rules subset leaves the other checkers' waivers dormant, not
        # stale: only waivers whose rule a selected checker can emit take
        # part in matching (and in stale detection)
        selected_rules = set().union(*(RULES_BY_CHECKER[c] for c in checkers))
        waivers = [w for w in waivers if w.rule in selected_rules]
    active, waived, stale = apply_baseline(findings, waivers)

    for finding in active:
        if args.format == "github":
            print(
                f"::error file={finding.path},line={finding.line},"
                f"title=prime-lint[{finding.rule}]::{finding.message}"
            )
        else:
            print(finding.render())
    for waiver in stale:
        msg = (
            f"stale waiver: ({waiver.rule}, {waiver.path}, {waiver.symbol}) "
            f"matched nothing — the violation it excused is gone; delete it "
            f"(reason was: {waiver.reason})"
        )
        if args.format == "github":
            print(
                "::error file=prime_tpu/analysis/baseline.toml,"
                f"title=prime-lint[stale-waiver]::{msg}"
            )
        else:
            print(msg)

    n_files = len(project.files)
    print(
        f"prime-lint: {n_files} files, {len(active)} finding(s), "
        f"{len(waived)} waived, {len(stale)} stale waiver(s)",
        file=sys.stderr,
    )
    if args.check and (active or stale):
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
