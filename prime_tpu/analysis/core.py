"""prime-lint core: project scanning, findings, waivers.

The serve stack's correctness invariants — lock discipline, jit-boundary
purity, the obs catalog contract, the env-knob registry — were each hardened
by hand across PRs 2-6 (see docs/analysis.md for the rule-by-rule history).
This package turns those review checklists into machine-enforced checks:
dependency-free AST analysis (stdlib ``ast`` only — the suite must run in a
bare CI container before any wheel installs), one module per checker, a
checked-in waiver file (``analysis/baseline.toml``) whose every entry carries
a justification, and a CLI (``python -m prime_tpu.analysis --check``) CI runs
as its own job.

A checker is a function ``check(project: Project) -> list[Finding]``. The
:class:`Project` hands it parsed ASTs for every production module plus the
doc files the contract checkers cross-reference; it can be built from a repo
root or (in tests) from an in-memory ``{path: source}`` mapping.

Suppression, most-local first:
- ``# prime-lint: ignore[rule-name] <why>`` on the flagged line — for sites
  whose justification belongs next to the code;
- a ``[[waiver]]`` entry in ``baseline.toml`` keyed ``(rule, path, symbol)``
  — for accepted pre-existing violations; ``reason`` is mandatory, and a
  waiver matching nothing is itself reported (rule ``stale-waiver``) so the
  baseline can only shrink honestly.
"""

from __future__ import annotations

import ast
import fnmatch
import re
from dataclasses import dataclass, field
from pathlib import Path

# skipped entirely: test fixtures simulate product behavior (fake planes
# start threads and read env on purpose) and this package's own checker
# sources quote the very patterns they hunt for
EXCLUDE_DIRS = ("analysis", "testing")

_PRAGMA_RE = re.compile(r"#\s*prime-lint:\s*ignore\[([a-z0-9_,\- ]+)\]")


@dataclass(frozen=True)
class Finding:
    """One rule violation at one site.

    ``symbol`` is the stable waiver key (e.g. ``ClassName.attr``,
    ``fn:offender``, a metric/span/knob name) — line numbers drift with
    every edit, so waivers match on ``(rule, path, symbol)`` instead.
    """

    rule: str
    path: str  # repo-relative, forward slashes
    line: int
    symbol: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class SourceFile:
    path: str  # repo-relative
    source: str
    tree: ast.Module
    lines: list[str] = field(default_factory=list)

    def pragma_rules(self, line: int) -> set[str]:
        """Rules suppressed by a ``# prime-lint: ignore[...]`` pragma on the
        given 1-based line (or the line above, for long statements)."""
        out: set[str] = set()
        for candidate in (line, line - 1):
            if 1 <= candidate <= len(self.lines):
                m = _PRAGMA_RE.search(self.lines[candidate - 1])
                if m:
                    out.update(p.strip() for p in m.group(1).split(","))
        return out


class Project:
    """Everything the checkers read: parsed production modules + doc files."""

    def __init__(
        self,
        files: dict[str, str],
        docs: dict[str, str] | None = None,
        root: Path | None = None,
    ) -> None:
        self.root = root
        self.docs = docs or {}
        self.files: list[SourceFile] = []
        self.parse_errors: list[Finding] = []
        for path, source in sorted(files.items()):
            try:
                tree = ast.parse(source, filename=path)
            except SyntaxError as e:
                self.parse_errors.append(
                    Finding("parse-error", path, e.lineno or 1, path, str(e))
                )
                continue
            self.files.append(SourceFile(path, source, tree, source.splitlines()))

    @classmethod
    def from_root(cls, root: str | Path) -> "Project":
        root = Path(root)
        files: dict[str, str] = {}
        pkg = root / "prime_tpu"
        for path in sorted(pkg.rglob("*.py")):
            rel = path.relative_to(root).as_posix()
            parts = rel.split("/")
            if any(part in EXCLUDE_DIRS for part in parts[1:-1]):
                continue
            files[rel] = path.read_text(encoding="utf-8")
        docs: dict[str, str] = {}
        for doc in ("docs/observability.md", "docs/architecture.md"):
            p = root / doc
            if p.exists():
                docs[doc] = p.read_text(encoding="utf-8")
        return cls(files, docs, root=root)

    def doc(self, path: str) -> str | None:
        return self.docs.get(path)

    def pragma_rules(self, path: str, line: int) -> set[str]:
        """Rules an inline pragma suppresses at (path, line). Applied
        centrally by ``run_checks`` so every checker honors pragmas the
        same way. Unknown paths (doc files) have no pragmas."""
        if not hasattr(self, "_by_path"):
            self._by_path = {src.path: src for src in self.files}
        src = self._by_path.get(path)
        return src.pragma_rules(line) if src is not None else set()


@dataclass(frozen=True)
class Waiver:
    rule: str
    path: str  # fnmatch pattern (exact paths match themselves)
    symbol: str  # fnmatch pattern
    reason: str

    def matches(self, finding: Finding) -> bool:
        return (
            self.rule == finding.rule
            and fnmatch.fnmatchcase(finding.path, self.path)
            and fnmatch.fnmatchcase(finding.symbol, self.symbol)
        )


def _parse_toml(text: str, filename: str) -> dict:
    """Parse the baseline file: stdlib ``tomllib`` when the interpreter has
    it, else a deliberately tiny fallback grammar (``[[waiver]]`` headers +
    ``key = "basic string"`` pairs + comments) so the linter runs on the
    3.10 containers the test suite supports. baseline.toml stays inside that
    subset by construction — the writer of a fancier entry finds out here."""
    try:
        from prime_tpu.utils.compat import TOMLLIB_AVAILABLE, tomllib

        if TOMLLIB_AVAILABLE:
            return tomllib.loads(text)
    except ImportError:  # pragma: no cover — compat shim always importable
        pass
    waivers: list[dict] = []
    current: dict | None = None
    for lineno, raw in enumerate(text.splitlines(), start=1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line == "[[waiver]]":
            current = {}
            waivers.append(current)
            continue
        m = re.match(r'^([A-Za-z0-9_-]+)\s*=\s*"((?:[^"\\]|\\.)*)"\s*(?:#.*)?$', line)
        if m and current is not None:
            current[m.group(1)] = m.group(2).replace('\\"', '"').replace("\\\\", "\\")
            continue
        raise ValueError(
            f"{filename}:{lineno}: unsupported TOML (fallback parser handles "
            f'only [[waiver]] tables with key = "string" pairs): {line!r}'
        )
    return {"waiver": waivers}


def load_baseline(path: str | Path) -> list[Waiver]:
    path = Path(path)
    data = _parse_toml(path.read_text(encoding="utf-8"), str(path))
    waivers: list[Waiver] = []
    for i, entry in enumerate(data.get("waiver", [])):
        missing = [k for k in ("rule", "path", "symbol", "reason") if not entry.get(k)]
        if missing:
            raise ValueError(
                f"{path}: waiver #{i + 1} is missing required field(s) "
                f"{missing} — every waiver must name its rule/path/symbol "
                "and justify itself"
            )
        waivers.append(
            Waiver(entry["rule"], entry["path"], entry["symbol"], entry["reason"])
        )
    return waivers


def apply_baseline(
    findings: list[Finding], waivers: list[Waiver]
) -> tuple[list[Finding], list[Finding], list[Waiver]]:
    """Split findings into (active, waived); also return waivers that
    matched nothing — stale entries the caller reports for cleanup."""
    active: list[Finding] = []
    waived: list[Finding] = []
    used: set[int] = set()
    for finding in findings:
        hit = None
        for i, waiver in enumerate(waivers):
            if waiver.matches(finding):
                hit = i
                break
        if hit is None:
            active.append(finding)
        else:
            used.add(hit)
            waived.append(finding)
    stale = [w for i, w in enumerate(waivers) if i not in used]
    return active, waived, stale


# -- shared AST helpers -------------------------------------------------------


def attr_root(node: ast.AST) -> str | None:
    """The ``self``-attribute name a store/load expression roots at:
    ``self.x`` / ``self.x[k]`` / ``self.x.y.z`` all return ``"x"``;
    anything not rooted at ``self`` returns None."""
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            return node.attr
        node = node.value
    return None


def self_attr(node: ast.AST) -> str | None:
    """``self.x`` exactly (no deeper chain) -> ``"x"``, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def call_name(node: ast.expr) -> str | None:
    """Dotted name of a call target: ``jax.jit`` -> ``"jax.jit"``,
    ``jit`` -> ``"jit"``, anything unresolvable -> None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def const_str(node: ast.expr | None) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None
