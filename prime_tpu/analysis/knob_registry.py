"""Checker 4 — the ``PRIME_*`` environment-knob registry.

A knob that three modules read three different ways, with three different
defaults and no documentation, is how PR 6's review found
``PRIME_SERVE_PREFIX_CACHE_HOST_MB`` wired but undescribed and this PR found
``PRIME_TPU_FLASH_DECODE_MIN_C`` / ``PRIME_TPU_PALLAS_INTERPRET`` /
``PRIME_NUM_WORKERS`` undocumented entirely. Four rules pin the contract to
the "Environment knobs" table in docs/architecture.md:

- ``knob-direct-read`` — a ``PRIME_*`` name read straight off
  ``os.environ`` / ``os.getenv`` anywhere outside ``core/config.py``: all
  reads go through the ``env_str``/``env_flag``/``env_int``/``env_float``
  helpers (uniform unset/junk semantics, one grep-able surface). Writes
  (exporting env for a child process) are fine.
- ``knob-undocumented`` — a knob read in code with no row in the table.
- ``knob-stale-doc`` — a table row naming a knob (or a paired CLI flag) the
  code never mentions.
- ``knob-default-drift`` — the helper-call default (literals and
  module-level constants are resolved) disagrees with the table's default
  column; likewise a paired CLI flag whose ``click.option`` declares a
  literal non-None default that disagrees (the None-default "defer to env"
  idiom is skipped on purpose — that pairing cannot drift).
"""

from __future__ import annotations

import ast
import re

from prime_tpu.analysis.core import Finding, Project, call_name, const_str

DOC_PATH = "docs/architecture.md"
HELPER_FILE = "prime_tpu/core/config.py"
HELPERS = {"env_str", "env_flag", "env_int", "env_float"}

_KNOB_RE = re.compile(r"^PRIME_[A-Z0-9_]+$")
_BACKTICK_RE = re.compile(r"`([^`]+)`")

_TRUE_WORDS = {"1", "true", "on", "yes"}
_FALSE_WORDS = {"0", "false", "off", "no"}
_UNSET_WORDS = {"", "unset", "-", "—", "none"}


class _KnobUse:
    def __init__(
        self, name: str, path: str, line: int, direct: bool, default: object
    ) -> None:
        self.name = name
        self.path = path
        self.line = line
        self.direct = direct
        self.default = default  # resolved literal, or _UNRESOLVED


_UNRESOLVED = object()


def _module_constants(tree: ast.Module) -> dict[str, object]:
    """Module-level ``NAME = <literal>`` bindings, for resolving helper
    defaults like ``env_float("...", DEFAULT_PREFIX_CACHE_MB)``."""
    out: dict[str, object] = {}
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Constant):
            for target in stmt.targets:
                if isinstance(target, ast.Name):
                    out[target.id] = stmt.value.value
    return out


def _resolve_default(node: ast.expr | None, constants: dict[str, object]) -> object:
    if node is None:
        return _UNRESOLVED
    if isinstance(node, ast.Constant):
        return node.value
    if isinstance(node, ast.Name) and node.id in constants:
        return constants[node.id]
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        inner = _resolve_default(node.operand, constants)
        if isinstance(inner, (int, float)):
            return -inner
    return _UNRESOLVED


def _collect_uses(project: Project) -> tuple[list[_KnobUse], set[str]]:
    """Knob read sites (helper + direct) and the set of every PRIME_* string
    literal appearing anywhere — env *writes* and registry dicts count as
    code sites for staleness, just not as reads."""
    uses: list[_KnobUse] = []
    mentioned: set[str] = set()
    for src in project.files:
        constants = _module_constants(src.tree)
        for node in ast.walk(src.tree):
            if (
                isinstance(node, ast.Constant)
                and isinstance(node.value, str)
                and _KNOB_RE.match(node.value)
            ):
                mentioned.add(node.value)
            if not isinstance(node, (ast.Call, ast.Subscript)):
                continue
            # helper reads: env_str("PRIME_X", default) (bare or dotted)
            if isinstance(node, ast.Call):
                fn = call_name(node.func)
                base = fn.rsplit(".", 1)[-1] if fn else None
                if base in HELPERS and node.args:
                    name = const_str(node.args[0])
                    if name and _KNOB_RE.match(name):
                        default_node = node.args[1] if len(node.args) > 1 else None
                        if default_node is None:
                            for kw in node.keywords:
                                if kw.arg == "default":
                                    default_node = kw.value
                        uses.append(
                            _KnobUse(
                                name,
                                src.path,
                                node.lineno,
                                direct=False,
                                default=_resolve_default(default_node, constants),
                            )
                        )
                        continue
                # direct reads: os.environ.get / os.getenv
                if fn in ("os.environ.get", "os.getenv", "environ.get") and node.args:
                    name = const_str(node.args[0])
                    if name and _KNOB_RE.match(name):
                        uses.append(
                            _KnobUse(name, src.path, node.lineno, True, _UNRESOLVED)
                        )
            else:  # Subscript: os.environ["PRIME_X"] loads only
                if (
                    isinstance(node.ctx, ast.Load)
                    and call_name(node.value) in ("os.environ", "environ")
                ):
                    name = const_str(node.slice)
                    if name and _KNOB_RE.match(name):
                        uses.append(
                            _KnobUse(name, src.path, node.lineno, True, _UNRESOLVED)
                        )
    return uses, mentioned


# -- doc side -----------------------------------------------------------------


class _DocKnob:
    def __init__(self, name: str, flag: str | None, default: str, line: int) -> None:
        self.name = name
        self.flag = flag
        self.default = default
        self.line = line


def _doc_knob_rows(doc_text: str) -> list[_DocKnob]:
    """Rows of every architecture.md table with an ``env`` header column
    (the consolidated knobs table; the per-subsystem mini-tables keep their
    own shape and are ignored unless they adopt the header)."""
    from prime_tpu.analysis.obs_contract import _parse_tables

    out: list[_DocKnob] = []
    for table in _parse_tables(doc_text):
        headers = table["headers"]
        if "env" not in headers or "default" not in headers:
            continue
        env_col = headers.index("env")
        default_col = headers.index("default")
        flag_col = headers.index("cli flag") if "cli flag" in headers else None
        for line, cells in table["rows"]:
            if len(cells) <= max(env_col, default_col):
                continue
            names = [
                t for t in _BACKTICK_RE.findall(cells[env_col]) if _KNOB_RE.match(t)
            ]
            if not names:
                continue
            flag = None
            if flag_col is not None and len(cells) > flag_col:
                flags = [
                    t
                    for t in _BACKTICK_RE.findall(cells[flag_col])
                    if t.startswith("--")
                ]
                flag = flags[0] if flags else None
            default = cells[default_col].strip().strip("`")
            # "256 (MiB)" -> "256"; "0 = off" -> "0"
            default = re.split(r"[(=]", default)[0].strip().strip("`")
            for name in names:
                out.append(_DocKnob(name, flag, default, line))
    return out


def _defaults_agree(code_default: object, doc_default: str) -> bool:
    doc = doc_default.strip().lower()
    if code_default is _UNRESOLVED:
        return True  # can't resolve -> can't drift-check; not a finding
    if isinstance(code_default, bool):
        return doc in (_TRUE_WORDS if code_default else _FALSE_WORDS | _UNSET_WORDS)
    if isinstance(code_default, (int, float)):
        try:
            return float(doc) == float(code_default)
        except ValueError:
            return False
    if code_default is None:
        return doc in _UNSET_WORDS
    if isinstance(code_default, str):
        if code_default == "":
            return doc in _UNSET_WORDS
        return doc == code_default.lower()
    return True


def _cli_option_sites(project: Project) -> list[tuple[str, object, str, int]]:
    """(flag-string, literal default or _UNRESOLVED, path, line) for every
    ``click.option``/``option`` decorator call with a leading ``--flag``."""
    out = []
    for src in project.files:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = call_name(node.func)
            if fn not in ("click.option", "option", "click.argument"):
                continue
            flags = [
                s
                for s in (const_str(a) for a in node.args)
                if s is not None and s.startswith("--")
            ]
            if not flags:
                continue
            default: object = _UNRESOLVED
            for kw in node.keywords:
                if kw.arg == "default" and isinstance(kw.value, ast.Constant):
                    default = kw.value.value
            for flag in flags:
                out.append((flag, default, src.path, node.lineno))
    return out


def check(project: Project) -> list[Finding]:
    findings: list[Finding] = []
    uses, mentioned = _collect_uses(project)

    for use in uses:
        if use.direct and use.path != HELPER_FILE:
            findings.append(
                Finding(
                    "knob-direct-read",
                    use.path,
                    use.line,
                    use.name,
                    f"{use.name} is read directly from os.environ — route it "
                    "through prime_tpu.core.config env_str/env_flag/env_int/"
                    "env_float",
                )
            )

    doc = project.doc(DOC_PATH)
    if doc is None:
        findings.append(
            Finding(
                "knob-catalog-missing",
                DOC_PATH,
                1,
                DOC_PATH,
                "docs/architecture.md not found — no knobs table to check "
                "against",
            )
        )
        return findings
    rows = _doc_knob_rows(doc)
    documented = {row.name for row in rows}
    row_by_name = {row.name: row for row in rows}

    seen_undoc: set[str] = set()
    for use in uses:
        if use.name not in documented and use.name not in seen_undoc:
            seen_undoc.add(use.name)
            findings.append(
                Finding(
                    "knob-undocumented",
                    use.path,
                    use.line,
                    use.name,
                    f"{use.name} is read here but has no row in the "
                    f"{DOC_PATH} Environment knobs table",
                )
            )

    cli_sites = _cli_option_sites(project)
    for row in rows:
        if row.name not in mentioned:
            findings.append(
                Finding(
                    "knob-stale-doc",
                    DOC_PATH,
                    row.line,
                    row.name,
                    f"knobs table documents {row.name} but nothing in "
                    "prime_tpu mentions it",
                )
            )
            continue
        # default drift vs every resolvable read site
        for use in uses:
            if use.name != row.name or use.default is _UNRESOLVED:
                continue
            if not _defaults_agree(use.default, row.default):
                findings.append(
                    Finding(
                        "knob-default-drift",
                        use.path,
                        use.line,
                        row.name,
                        f"{row.name} default in code is {use.default!r} but "
                        f"the knobs table says `{row.default}`",
                    )
                )
        # paired CLI flag: must exist, and a literal non-None default must
        # agree with the documented default
        if row.flag:
            matches = [
                (flag, default, path, line)
                for flag, default, path, line in cli_sites
                if row.flag == flag or flag.startswith(row.flag + "/")
            ]
            if not matches:
                findings.append(
                    Finding(
                        "knob-stale-doc",
                        DOC_PATH,
                        row.line,
                        row.name,
                        f"knobs table pairs {row.name} with `{row.flag}` but "
                        "no click.option declares that flag",
                    )
                )
            else:
                for _flag, default, path, line in matches:
                    if default is _UNRESOLVED or default is None:
                        continue  # None = "defer to env", cannot drift
                    if not _defaults_agree(default, row.default):
                        findings.append(
                            Finding(
                                "knob-default-drift",
                                path,
                                line,
                                row.name,
                                f"`{row.flag}` default {default!r} disagrees "
                                f"with the documented {row.name} default "
                                f"`{row.default}`",
                            )
                        )
    return findings
