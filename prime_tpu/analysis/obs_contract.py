"""Checker 3 — observability contract (docs/observability.md is the truth).

PR 1 established the rule that every telemetry surface is cataloged in one
place; PRs 4-6 each grew the metric set and updated the catalog by hand — and
review caught drift twice (stats keys vs catalog in PR 4, the tier-labeled
histogram rename in PR 6). This checker makes the contract bidirectional and
machine-checked:

- ``obs-metric-undocumented`` — a metric family registered in code (a
  literal-name ``.counter(...)`` / ``.gauge(...)`` / ``.histogram(...)`` on
  any registry) that never appears in docs/observability.md;
- ``obs-metric-kind-drift`` — the catalog row's *type* column disagrees with
  the registration kind (the same drift :func:`lint_prometheus_text`'s
  catalog mode catches at exposition time — see docs/analysis.md);
- ``obs-metric-stale`` — a catalog table row naming a family no code
  registers (a rename left the old row behind);
- ``obs-span-undocumented`` / ``obs-span-stale`` — the same contract for
  span names (``TRACER.span("x.y")`` / ``TRACER.emit("x.y", ...)`` sites vs
  the "Span catalog" table).

:func:`load_metrics_catalog` is the shared doc parser: the pytest suite
feeds its output to ``lint_prometheus_text(text, catalog=...)`` so a live
``/metrics`` exposition is held to the same document — code, docs, and
exposition cannot drift pairwise-independently.
"""

from __future__ import annotations

import ast
import re

from prime_tpu.analysis.core import Finding, Project, const_str

DOC_PATH = "docs/observability.md"

_METRIC_KINDS = {"counter", "gauge", "histogram"}
_METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SPAN_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*\.[a-z0-9_.]+$")
_BACKTICK_RE = re.compile(r"`([^`]+)`")
# inline doc mentions like `client_http_requests_total{method,status}`;
# at least one underscore so single backticked words ("tier", "device")
# don't count as documented metric families
_INLINE_METRIC_RE = re.compile(r"`([a-z][a-z0-9]*(?:_[a-z0-9]+)+)(?:\{[^}`]*\})?`")


# -- code side ----------------------------------------------------------------


def _metric_registrations(project: Project) -> list[tuple[str, str, str, int]]:
    """(name, kind, path, line) for literal metric registrations."""
    out = []
    for src in project.files:
        if src.path.endswith("obs/metrics.py"):
            continue  # the registry itself, not a user of it
        for node in ast.walk(src.tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _METRIC_KINDS
                and node.args
            ):
                name = const_str(node.args[0])
                if name and _METRIC_NAME_RE.match(name):
                    out.append((name, node.func.attr, src.path, node.lineno))
    return out


def _span_sites(project: Project) -> list[tuple[str, str, int]]:
    """(name, path, line) for literal span/emit names."""
    out = []
    for src in project.files:
        if src.path.endswith("obs/trace.py"):
            continue  # the tracer itself
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            name = const_str(node.args[0])
            if not name or not _SPAN_NAME_RE.match(name):
                continue
            func = node.func
            is_span_call = (
                isinstance(func, ast.Attribute) and func.attr in ("span", "emit")
            ) or (isinstance(func, ast.Name) and func.id == "span")
            if is_span_call:
                out.append((name, src.path, node.lineno))
    return out


# -- doc side -----------------------------------------------------------------


def _strip_fences(text: str) -> str:
    """Drop fenced code blocks — a ``` fence's backticks would otherwise
    pair with the next inline backtick and swallow whole prose regions."""
    out: list[str] = []
    fenced = False
    for line in text.splitlines():
        if line.strip().startswith("```"):
            fenced = not fenced
            continue
        if not fenced:
            out.append(line)
    return "\n".join(out)


def _parse_tables(text: str) -> list[dict]:
    """Markdown tables as {headers: [...], rows: [(line, cells)]}."""
    tables: list[dict] = []
    lines = text.splitlines()
    i = 0
    while i < len(lines):
        line = lines[i].strip()
        if line.startswith("|") and i + 1 < len(lines):
            sep = lines[i + 1].strip()
            if sep.startswith("|") and set(sep) <= set("|-: "):
                headers = [c.strip().lower() for c in line.strip("|").split("|")]
                rows = []
                j = i + 2
                while j < len(lines) and lines[j].strip().startswith("|"):
                    cells = [c.strip() for c in lines[j].strip().strip("|").split("|")]
                    rows.append((j + 1, cells))
                    j += 1
                tables.append({"headers": headers, "rows": rows})
                i = j
                continue
        i += 1
    return tables


def _names_in_cell(cell: str) -> list[str]:
    """Backticked identifiers in a table cell, label-suffix stripped:
    ```a_total` / `b_total``` -> [a_total, b_total]."""
    out = []
    for token in _BACKTICK_RE.findall(cell):
        token = token.split("{")[0].strip()
        if token:
            out.append(token)
    return out


def load_metrics_catalog(doc_text: str) -> dict[str, str]:
    """Metric family -> declared type, from every observability.md table
    with ``metric`` and ``type`` header columns. This is the catalog the
    exposition lint (``lint_prometheus_text(text, catalog=...)``) and the
    static kind check both consume — one parse, two enforcement points."""
    return {name: kind for name, kind, _line in _doc_metric_rows(doc_text)}


def _doc_metric_rows(doc_text: str) -> list[tuple[str, str, int]]:
    """(name, kind, doc line) per catalog table row entry."""
    out = []
    for table in _parse_tables(doc_text):
        headers = table["headers"]
        if "metric" not in headers or "type" not in headers:
            continue
        name_col = headers.index("metric")
        type_col = headers.index("type")
        for line, cells in table["rows"]:
            if len(cells) <= max(name_col, type_col):
                continue
            kind = cells[type_col].strip().strip("`")
            for name in _names_in_cell(cells[name_col]):
                if _METRIC_NAME_RE.match(name):
                    out.append((name, kind, line))
    return out


def _doc_span_rows(doc_text: str) -> list[tuple[str, int]]:
    out = []
    for table in _parse_tables(doc_text):
        headers = table["headers"]
        if "span" not in headers:
            continue
        name_col = headers.index("span")
        for line, cells in table["rows"]:
            if len(cells) > name_col:
                for name in _names_in_cell(cells[name_col]):
                    if _SPAN_NAME_RE.match(name):
                        out.append((name, line))
    return out


def check(project: Project) -> list[Finding]:
    doc = project.doc(DOC_PATH)
    if doc is None:
        return [
            Finding(
                "obs-catalog-missing",
                DOC_PATH,
                1,
                DOC_PATH,
                "docs/observability.md not found — the obs contract has no "
                "catalog to check against",
            )
        ]
    findings: list[Finding] = []

    # any backticked mention anywhere in the doc counts as "documented"
    # (prose and tables alike); STALENESS is judged on table rows only
    prose = _strip_fences(doc)
    documented_metrics = set(_INLINE_METRIC_RE.findall(prose))
    documented_spans = {
        t for t in _BACKTICK_RE.findall(prose) if _SPAN_NAME_RE.match(t)
    }

    regs = _metric_registrations(project)
    reg_kinds: dict[str, set[str]] = {}
    for name, kind, _path, _line in regs:
        reg_kinds.setdefault(name, set()).add(kind)

    seen_undocumented: set[str] = set()
    for name, kind, path, line in regs:
        if name not in documented_metrics and name not in seen_undocumented:
            seen_undocumented.add(name)
            findings.append(
                Finding(
                    "obs-metric-undocumented",
                    path,
                    line,
                    name,
                    f"metric `{name}` ({kind}) is registered here but has no "
                    f"row in {DOC_PATH}",
                )
            )

    for name, kind, line in _doc_metric_rows(doc):
        if name not in reg_kinds:
            findings.append(
                Finding(
                    "obs-metric-stale",
                    DOC_PATH,
                    line,
                    name,
                    f"catalog row documents `{name}` but no code registers it",
                )
            )
        elif kind in _METRIC_KINDS and kind not in reg_kinds[name]:
            findings.append(
                Finding(
                    "obs-metric-kind-drift",
                    DOC_PATH,
                    line,
                    name,
                    f"catalog says `{name}` is a {kind}, code registers "
                    f"{'/'.join(sorted(reg_kinds[name]))}",
                )
            )

    spans = _span_sites(project)
    span_names = {name for name, _path, _line in spans}
    seen_spans: set[str] = set()
    for name, path, line in spans:
        if name not in documented_spans and name not in seen_spans:
            seen_spans.add(name)
            findings.append(
                Finding(
                    "obs-span-undocumented",
                    path,
                    line,
                    name,
                    f"span `{name}` is emitted here but absent from the "
                    f"{DOC_PATH} span catalog",
                )
            )
    for name, line in _doc_span_rows(doc):
        if name not in span_names:
            findings.append(
                Finding(
                    "obs-span-stale",
                    DOC_PATH,
                    line,
                    name,
                    f"span catalog row documents `{name}` but no code emits it",
                )
            )
    return findings
