"""prime-lint: invariant checkers for the serving stack.

Four AST-based checkers (stdlib-only, no third-party deps) enforce the
contracts PRs 2-6 hardened by hand — see docs/analysis.md for the rule
catalog and per-rule history:

- ``lock-discipline`` (:mod:`.lock_discipline`) — attributes a class writes
  under its own lock must never be touched off-lock;
- ``jit-purity`` / ``jit-donation`` (:mod:`.jit_boundary`) — functions
  handed to ``jax.jit`` stay host-state-free, and donated buffers are never
  read after dispatch;
- obs contract (:mod:`.obs_contract`) — metric and span names in code and
  the docs/observability.md catalog agree bidirectionally;
- knob registry (:mod:`.knob_registry`) — ``PRIME_*`` env reads go through
  the core.config helpers, are documented in docs/architecture.md, and
  agree with their paired CLI flag defaults.

Run ``python -m prime_tpu.analysis`` (or ``scripts/prime_lint.py``) locally;
CI runs ``--check`` as the ``analysis`` job. Accepted violations live in
``prime_tpu/analysis/baseline.toml``, one justification per entry.
"""

from __future__ import annotations

from pathlib import Path

from prime_tpu.analysis import (
    jit_boundary,
    knob_registry,
    lock_discipline,
    obs_contract,
)
from prime_tpu.analysis.core import (
    Finding,
    Project,
    Waiver,
    apply_baseline,
    load_baseline,
)

CHECKERS = {
    "lock": lock_discipline.check,
    "jit": jit_boundary.check,
    "obs": obs_contract.check,
    "knobs": knob_registry.check,
}

# every rule each checker can emit — `--rules` subsetting uses this to scope
# stale-waiver detection to the checkers that actually ran (a waiver for an
# unselected rule is dormant, not stale)
RULES_BY_CHECKER = {
    "lock": {"lock-discipline"},
    "jit": {"jit-purity", "jit-donation"},
    "obs": {
        "obs-metric-undocumented",
        "obs-metric-stale",
        "obs-metric-kind-drift",
        "obs-span-undocumented",
        "obs-span-stale",
        "obs-catalog-missing",
    },
    "knobs": {
        "knob-direct-read",
        "knob-undocumented",
        "knob-stale-doc",
        "knob-default-drift",
        "knob-catalog-missing",
    },
}

DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.toml"


def run_checks(
    project: Project, checkers: list[str] | None = None
) -> list[Finding]:
    """All findings (pre-baseline), parse errors included, stably ordered.
    Inline ``# prime-lint: ignore[rule]`` pragmas are applied here, once,
    for every checker — a finding whose flagged line carries a pragma for
    its rule is dropped (doc-side findings have no source line to carry a
    pragma and are baseline-only)."""
    findings = list(project.parse_errors)
    for name, checker in CHECKERS.items():
        if checkers is None or name in checkers:
            findings.extend(checker(project))
    findings = [
        f for f in findings if f.rule not in project.pragma_rules(f.path, f.line)
    ]
    findings.sort(key=lambda f: (f.path, f.line, f.rule, f.symbol))
    return findings


__all__ = [
    "CHECKERS",
    "DEFAULT_BASELINE",
    "Finding",
    "Project",
    "Waiver",
    "apply_baseline",
    "load_baseline",
    "run_checks",
]
