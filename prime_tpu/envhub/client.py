"""Environments Hub REST client.

Wire surface: /envhub/environments (list/resolve/create), versions
(push/pull/delete), per-env secrets, actions log. Push uploads the archive
bytes with the content hash; pull downloads a version archive.
"""

from __future__ import annotations

import base64
from typing import Any

from prime_tpu.core.client import APIClient
from prime_tpu.core.exceptions import NotFoundError
from prime_tpu.envhub.packaging import build_archive, content_hash, read_env_metadata


class EnvHubClient:
    def __init__(self, client: APIClient | None = None) -> None:
        self.api = client or APIClient()

    # -- listing/resolution ---------------------------------------------------

    def list(self, owner: str | None = None) -> list[dict[str, Any]]:
        params = {"owner": owner} if owner else {}
        data = self.api.get("/envhub/environments", params=params)
        return data.get("items", []) if isinstance(data, dict) else data

    def get(self, name: str) -> dict[str, Any]:
        return self.api.get(f"/envhub/environments/{name}")

    def status(self, name: str) -> dict[str, Any]:
        return self.api.get(f"/envhub/environments/{name}/status")

    def versions(self, name: str) -> list[dict[str, Any]]:
        data = self.api.get(f"/envhub/environments/{name}/versions")
        return data.get("items", []) if isinstance(data, dict) else data

    def delete(self, name: str) -> None:
        self.api.delete(f"/envhub/environments/{name}")

    def delete_version(self, name: str, version: str) -> None:
        self.api.delete(f"/envhub/environments/{name}/versions/{version}")

    # -- push / pull -----------------------------------------------------------

    def push(self, env_dir: str, visibility: str = "private") -> dict[str, Any]:
        """Archive + hash + resolve-or-create + upload (reference env.py:1039)."""
        metadata = read_env_metadata(env_dir)
        digest = content_hash(env_dir)
        try:
            existing = self.get(metadata["name"])
            if existing.get("contentHash") == digest:
                return {**existing, "unchanged": True}
        except NotFoundError:
            pass
        archive = build_archive(env_dir)  # built only when actually uploading
        payload = {
            "name": metadata["name"],
            "version": metadata["version"],
            "description": metadata["description"],
            "tags": metadata["tags"],
            "tpu": metadata["tpu"],
            "contentHash": digest,
            "visibility": visibility,
            "archiveB64": base64.b64encode(archive).decode(),
        }
        return self.api.post("/envhub/environments/push", json=payload, idempotent_post=True)

    def pull(self, name: str, version: str | None = None) -> tuple[bytes, dict[str, Any]]:
        params = {"version": version} if version else {}
        data = self.api.get(f"/envhub/environments/{name}/pull", params=params)
        return base64.b64decode(data["archiveB64"]), data

    # -- secrets + actions -----------------------------------------------------

    def list_secrets(self, name: str) -> list[str]:
        data = self.api.get(f"/envhub/environments/{name}/secrets")
        return data.get("keys", []) if isinstance(data, dict) else data

    def set_secret(self, name: str, key: str, value: str) -> None:
        self.api.put(f"/envhub/environments/{name}/secrets/{key}", json={"value": value})

    def delete_secret(self, name: str, key: str) -> None:
        self.api.delete(f"/envhub/environments/{name}/secrets/{key}")

    def actions(self, name: str) -> list[dict[str, Any]]:
        data = self.api.get(f"/envhub/environments/{name}/actions")
        return data.get("items", []) if isinstance(data, dict) else data

    def action_logs(self, name: str, action_id: str) -> list[str]:
        data = self.api.get(f"/envhub/environments/{name}/actions/{action_id}/logs")
        return data.get("logs", []) if isinstance(data, dict) else data

    def retry_action(self, name: str, action_id: str) -> dict[str, Any]:
        return self.api.post(
            f"/envhub/environments/{name}/actions/{action_id}/retry", idempotent_post=True
        )
