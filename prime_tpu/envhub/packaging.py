"""Environment packaging: templates, archives, content hashes, wheel builds.

The push pipeline (reference env.py:1039-1660): gitignore-filtered tar
archive + deterministic content hash (drift detection between local dir and
hub version, reference :365-409) + optional wheel build for pip installs.
"""

from __future__ import annotations

import fnmatch
import gzip
import hashlib
import io
import subprocess
import sys
import tarfile
from pathlib import Path

from prime_tpu.utils.compat import tomllib

DEFAULT_EXCLUDES = [
    ".prime",  # local hub-link state (provenance.py) — never ships or hashes
    ".git",
    "__pycache__",
    "*.pyc",
    ".venv",
    "venv",
    "dist",
    "build",
    "*.egg-info",
    ".pytest_cache",
    "outputs",
    ".env",
]

ENV_TOML_TEMPLATE = """\
[environment]
name = "{name}"
version = "0.1.0"
description = ""
tags = []

[tpu]
# TPU requirements for this environment (checked at install on a slice)
tpu_type = "v5e"
min_chips = 1

[eval]
dataset = "data/eval.jsonl"
max_new_tokens = 256
"""

PYPROJECT_TEMPLATE = """\
[build-system]
requires = ["setuptools>=68"]
build-backend = "setuptools.build_meta"

[project]
name = "{name}"
version = "0.1.0"
description = "prime environment: {name}"
requires-python = ">=3.10"
"""

MAIN_TEMPLATE = '''\
"""Environment entry point: load_environment() -> examples + scorer."""


def load_environment():
    return {{"name": "{name}"}}
'''


def _load_gitignore(env_dir: Path) -> list[str]:
    patterns = list(DEFAULT_EXCLUDES)
    gitignore = env_dir / ".gitignore"
    if gitignore.exists():
        for line in gitignore.read_text().splitlines():
            line = line.strip()
            if line and not line.startswith("#"):
                patterns.append(line.rstrip("/"))
    return patterns


def _excluded(rel_path: str, patterns: list[str]) -> bool:
    parts = rel_path.split("/")
    for pattern in patterns:
        if any(fnmatch.fnmatch(part, pattern) for part in parts):
            return True
        if fnmatch.fnmatch(rel_path, pattern):
            return True
    return False


def iter_env_files(env_dir: str | Path) -> list[Path]:
    import os

    env_dir = Path(env_dir)
    patterns = _load_gitignore(env_dir)
    files = []
    for dirpath, dirnames, filenames in os.walk(env_dir):
        rel_dir = Path(dirpath).relative_to(env_dir).as_posix()
        # prune excluded directories so .venv/.git trees are never walked
        dirnames[:] = sorted(
            d for d in dirnames
            if not _excluded(f"{rel_dir}/{d}" if rel_dir != "." else d, patterns)
        )
        for name in sorted(filenames):
            rel = f"{rel_dir}/{name}" if rel_dir != "." else name
            if not _excluded(rel, patterns):
                files.append(Path(dirpath) / name)
    files.sort()
    return files


def content_hash(env_dir: str | Path) -> str:
    """Deterministic hash of the (filtered) env contents — drift detection."""
    env_dir = Path(env_dir)
    digest = hashlib.sha256()
    for path in iter_env_files(env_dir):
        rel = path.relative_to(env_dir).as_posix()
        digest.update(rel.encode())
        digest.update(b"\0")
        digest.update(path.read_bytes())
        digest.update(b"\0")
    return digest.hexdigest()


def build_archive(env_dir: str | Path) -> bytes:
    """Deterministic tar.gz of the filtered env dir (mtime/uid zeroed).

    The gzip layer is opened explicitly with ``mtime=0``: ``tarfile``'s
    ``w:gz`` mode stamps the CURRENT time into the gzip header, so two
    builds of identical content straddling a second boundary would differ
    byte-for-byte (caught by the packaging-determinism property test)."""
    env_dir = Path(env_dir)
    buffer = io.BytesIO()
    with gzip.GzipFile(fileobj=buffer, mode="wb", compresslevel=6, mtime=0) as gz:
        with tarfile.open(fileobj=gz, mode="w") as tar:
            for path in iter_env_files(env_dir):
                rel = path.relative_to(env_dir).as_posix()
                info = tarfile.TarInfo(name=rel)
                data = path.read_bytes()
                info.size = len(data)
                info.mtime = 0
                info.uid = info.gid = 0
                info.uname = info.gname = ""
                tar.addfile(info, io.BytesIO(data))
    return buffer.getvalue()


def extract_archive(data: bytes, target_dir: str | Path) -> None:
    target_dir = Path(target_dir)
    target_dir.mkdir(parents=True, exist_ok=True)
    with tarfile.open(fileobj=io.BytesIO(data), mode="r:gz") as tar:
        tar.extractall(target_dir, filter="data")


def read_env_metadata(env_dir: str | Path) -> dict:
    """Parse env.toml (name/version/tpu requirements)."""
    env_toml = Path(env_dir) / "env.toml"
    if not env_toml.exists():
        raise FileNotFoundError(f"No env.toml in {env_dir} — run `prime env init` first")
    data = tomllib.loads(env_toml.read_text())
    env = data.get("environment", {})
    if not env.get("name"):
        raise ValueError("env.toml [environment] must set a name")
    return {
        "name": env["name"],
        "version": env.get("version", "0.1.0"),
        "description": env.get("description", ""),
        "tags": env.get("tags", []),
        "tpu": data.get("tpu", {}),
        "eval": data.get("eval", {}),
    }


def write_env_template(env_dir: str | Path, name: str) -> list[Path]:
    """`prime env init`: scaffold env.toml, pyproject.toml, main module."""
    env_dir = Path(env_dir)
    env_dir.mkdir(parents=True, exist_ok=True)
    module = name.replace("-", "_")
    written = []
    for rel, contents in [
        ("env.toml", ENV_TOML_TEMPLATE.format(name=name)),
        ("pyproject.toml", PYPROJECT_TEMPLATE.format(name=name)),
        (f"{module}.py", MAIN_TEMPLATE.format(name=name)),
    ]:
        path = env_dir / rel
        if not path.exists():
            path.write_text(contents)
            written.append(path)
    return written


def build_wheel(env_dir: str | Path, out_dir: str | Path | None = None) -> Path:
    """Build a wheel from the env's pyproject (for pip installs from the hub)."""
    env_dir = Path(env_dir)
    out = Path(out_dir) if out_dir else env_dir / "dist"
    result = subprocess.run(
        [sys.executable, "-m", "pip", "wheel", "--no-deps", "--no-build-isolation", "-w", str(out), str(env_dir)],
        capture_output=True,
        text=True,
    )
    if result.returncode != 0:
        raise RuntimeError(f"wheel build failed:\n{result.stderr[-2000:]}")
    wheels = sorted(out.glob("*.whl"), key=lambda p: p.stat().st_mtime)
    if not wheels:
        raise RuntimeError("wheel build produced no artifact")
    return wheels[-1]
