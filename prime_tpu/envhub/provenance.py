"""Release versioning + fork/upstream provenance for environments.

Reference behavior (commands/env.py:2010-2076 bump_version/bump_rc_version/
bump_post_version + :1073-1140 push --auto-bump/--rc/--post, :424
display_upstream_environment_info, utils/env_metadata.py): pushes can bump
the pyproject version in place first, and every push/pull records which hub
environment a local checkout tracks in ``.prime/env-metadata.json`` so later
pushes and evals can name their upstream.

TPU-repo shape: one module owns both concerns; the provenance record is a
single JSON file written atomically, and the version bumpers are pure
functions over PEP-440-ish strings.
"""

from __future__ import annotations

import json
import re
from datetime import datetime, timezone
from pathlib import Path

PROVENANCE_REL_PATH = Path(".prime") / "env-metadata.json"


# -- version bumping ----------------------------------------------------------


def bump_patch(version: str) -> str:
    """1.2.3 -> 1.2.4; pre/build suffixes on the patch are dropped
    (1.2.3rc1 -> 1.2.4); short versions grow a segment (1.2 -> 1.2.1)."""
    parts = version.split(".")
    if len(parts) >= 3:
        m = re.match(r"\d+", parts[2])
        if m is None:
            return f"{version}.1"
        return ".".join([*parts[:2], str(int(m.group()) + 1)])
    if len(parts) == 2:
        return f"{version}.1"
    return f"{version}.0.1"


def _bump_suffix(version: str, tag: str) -> str:
    m = re.match(rf"^(?P<base>.*?)(?:\.{tag}|{tag})(?P<num>\d+)$", version)
    if m:
        return f"{m.group('base')}.{tag}{int(m.group('num')) + 1}"
    base = re.sub(r"([+-].*)$", "", version)
    return f"{base}.{tag}0"


def bump_rc(version: str) -> str:
    """1.2.3 -> 1.2.3.rc0; 1.2.3.rc0 -> 1.2.3.rc1."""
    return _bump_suffix(version, "rc")


def bump_post(version: str) -> str:
    """1.2.3 -> 1.2.3.post0; 1.2.3.post0 -> 1.2.3.post1."""
    return _bump_suffix(version, "post")


def read_pyproject_version(env_dir: str | Path) -> str | None:
    """The [project] version in <env_dir>/pyproject.toml, or None."""
    from prime_tpu.utils.compat import tomllib

    path = Path(env_dir) / "pyproject.toml"
    try:
        data = tomllib.loads(path.read_text())
    except (OSError, tomllib.TOMLDecodeError):
        return None
    version = data.get("project", {}).get("version")
    return version if isinstance(version, str) else None


def read_env_toml_version(env_dir: str | Path) -> str | None:
    """The [environment] version in <env_dir>/env.toml (what push uploads)."""
    from prime_tpu.utils.compat import tomllib

    path = Path(env_dir) / "env.toml"
    try:
        data = tomllib.loads(path.read_text())
    except (OSError, tomllib.TOMLDecodeError):
        return None
    version = data.get("environment", {}).get("version")
    return version if isinstance(version, str) else None


def _rewrite_table_version(content: str, table: str, new_version: str) -> tuple[str, bool]:
    """Replace the ``version =`` line INSIDE ``[table]`` only — a version key
    in an unrelated earlier table (e.g. [tool.*]) must never be touched."""
    lines = content.splitlines(keepends=True)
    in_table = False
    for i, line in enumerate(lines):
        stripped = line.strip()
        if stripped.startswith("["):
            in_table = stripped == f"[{table}]"
            continue
        if in_table:
            replaced, n = re.subn(
                r'^(\s*version\s*=\s*)["\'][^"\']*["\']',
                rf'\g<1>"{new_version}"',
                line,
                count=1,
            )
            if n:
                lines[i] = replaced
                return "".join(lines), True
    return content, False


def bumped_version(env_dir: str | Path, mode: str) -> tuple[str, str]:
    """Apply one bump mode ('patch' | 'rc' | 'post') to the checkout.

    Both version carriers stay in sync: env.toml's [environment] version is
    what `env push` uploads, pyproject's [project] version is what the wheel
    build bakes in (a pyproject with no literal version line — dynamic
    versioning — is left alone). Returns (old, new); ValueError when no
    version line was found to rewrite."""
    env_dir = Path(env_dir)
    current = read_env_toml_version(env_dir) or read_pyproject_version(env_dir)
    if not current:
        raise ValueError(f"no version in {env_dir}/env.toml or pyproject.toml to bump")
    new = {"patch": bump_patch, "rc": bump_rc, "post": bump_post}[mode](current)
    rewritten = 0
    for name, table in (("env.toml", "environment"), ("pyproject.toml", "project")):
        path = env_dir / name
        if not path.exists():
            continue
        updated, changed = _rewrite_table_version(path.read_text(), table, new)
        if changed:
            path.write_text(updated)
            rewritten += 1
    if rewritten == 0:
        raise ValueError(
            f"no [environment]/[project] version line in {env_dir} to rewrite"
        )
    return current, new


# -- fork/upstream provenance -------------------------------------------------


def read_provenance(env_dir: str | Path) -> dict | None:
    """The checkout's hub-link record, or None when it was never linked."""
    path = Path(env_dir) / PROVENANCE_REL_PATH
    try:
        data = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError):
        return None
    return data if isinstance(data, dict) else None


def write_provenance(env_dir: str | Path, **fields) -> dict:
    """Merge ``fields`` into the checkout's record (created on demand);
    stamps ``updatedAt``. Returns the merged record."""
    path = Path(env_dir) / PROVENANCE_REL_PATH
    record = read_provenance(env_dir) or {}
    record.update({k: v for k, v in fields.items() if v is not None})
    record["updatedAt"] = datetime.now(timezone.utc).isoformat(timespec="seconds")
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_suffix(".tmp")
    tmp.write_text(json.dumps(record, indent=2, sort_keys=True) + "\n")
    tmp.replace(path)
    return record


def upstream_display(record: dict | None) -> str | None:
    """'owner/name' when the record names its upstream environment."""
    if not record:
        return None
    name = record.get("name")
    if not name:
        return None
    owner = record.get("owner")
    return f"{owner}/{name}" if owner else str(name)
