"""Environment execution protocol: resolve → prepare → import → run.

The reference's eval architecture shells out to the `verifiers` framework
(reference verifiers_bridge.py:724 `_prepare_single_environment`, :944
`run_eval_passthrough`, verifiers_plugin.py:100): an env reference is resolved
(local dir vs hub slug, with content-hash drift detection :365-409), installed
if needed, then executed as a subprocess that drives an OpenAI endpoint.

TPU-native redesign: environments are imported **in-process** and their
dataset + scorer drive the native JAX generator directly — no subprocess, no
HTTP round-trip per rollout; the generator batches prompts straight onto the
chip. The env contract is the `load_environment()` entry point the packaging
template scaffolds (envhub/packaging.py):

    def load_environment() -> dict:
        return {
            "name": "my-env",
            "examples": [{"prompt": ..., "answer": ...}, ...],
            # optional:
            "score": lambda completion, answer: float reward in [0, 1],
            "max_new_tokens": 256,
            "temperature": 0.0,
        }

(an object with .examples / .score attributes works too).
"""

from __future__ import annotations

import importlib.util
import sys
from dataclasses import dataclass
from pathlib import Path
from typing import Any, Callable

from prime_tpu.envhub.local import installs_dir, read_registry, save_registry
from prime_tpu.envhub.packaging import content_hash, extract_archive, read_env_metadata


# labels `prime eval run` treats as built-in datasets, never env refs — a hub
# env with one of these names cannot shadow the built-in behavior
BUILTIN_ENVS = frozenset({"gsm8k", "arith"})


class EnvResolutionError(RuntimeError):
    pass


class EnvProtocolError(RuntimeError):
    pass


@dataclass
class ResolvedEnv:
    name: str
    env_dir: Path
    source: str                      # local | installed | hub
    version: str | None = None
    drift: str | None = None         # human-readable drift warning, if any
    metadata: dict | None = None     # parsed env.toml


@dataclass
class LoadedEnvironment:
    name: str
    examples: list[dict]                       # [{"prompt":..., "answer":...}]
    scorer: Callable[[str, str], float] | None
    defaults: dict                             # eval defaults (max_new_tokens, ...)


def install_from_hub(hub_client, name: str, version: str | None = None) -> dict:
    """Pull an env from the hub into the local store and register it.

    Mirrors the reference's install-from-hub with pull-and-build fallback
    (reference env.py:2431, :3069): the wheel is built locally from the pulled
    source and pip-installed so the env's module is importable package-style;
    a failed wheel build degrades to path-import-only (the execution protocol
    imports by path regardless).
    """
    import shutil

    archive, info = hub_client.pull(name, version=version)
    target = installs_dir() / name
    # clean install: stale files from a previous version must not survive
    shutil.rmtree(target, ignore_errors=True)
    extract_archive(archive, target)
    entry = {
        "version": info["version"],
        "path": str(target),
        "contentHash": info.get("contentHash"),
    }
    wheel_error = _pip_install_env(target)
    entry["pipInstalled"] = wheel_error is None
    if wheel_error is not None:
        entry["installNote"] = wheel_error
    registry = read_registry()
    registry[name] = entry
    save_registry(registry)
    return entry | {"name": name}


def env_site_dir() -> Path:
    """Site dir for pip-installed env packages (~/.prime/envs/_site).

    A dedicated --target dir rather than the live site-packages: installs
    stay inside the prime store (uninstall = rm), never mutate the user's
    Python environment, and the execution protocol adds it to sys.path when
    importing — same importability, no global side effects.
    """
    return installs_dir() / "_site"


def _pip_install_env(env_dir: Path) -> str | None:
    """Build the env's wheel and pip-install it into the env site dir.
    Returns None on success, else a short reason (import-by-path still works
    without it)."""
    import subprocess

    from prime_tpu.envhub.packaging import build_wheel

    if not (env_dir / "pyproject.toml").exists():
        return "no pyproject.toml — path-import only"
    try:
        wheel = build_wheel(env_dir)
    except RuntimeError as e:
        return f"wheel build failed: {e}"
    proc = subprocess.run(
        [
            sys.executable, "-m", "pip", "install", "--no-deps", "--upgrade",
            "--target", str(env_site_dir()), str(wheel),
        ],
        capture_output=True,
        text=True,
    )
    if proc.returncode != 0:
        return f"pip install failed: {proc.stderr.strip()[-300:]}"
    return None


def resolve_environment(
    env_ref: str,
    hub_client=None,
    install_missing: bool = True,
) -> ResolvedEnv:
    """Resolve an env reference the way the reference CLI does: a local
    directory beats an installed env beats a hub slug (installed on demand)."""
    # 1. local directory containing env.toml
    local = Path(env_ref)
    if (local / "env.toml").exists():
        metadata = read_env_metadata(local)
        resolved = ResolvedEnv(
            name=metadata["name"], env_dir=local.resolve(), source="local", metadata=metadata
        )
        if hub_client is not None:
            resolved.drift = _local_drift(local, metadata["name"], hub_client)
        return resolved

    # 2. installed env store
    registry = read_registry()
    if env_ref in registry:
        entry = registry[env_ref]
        env_dir = Path(entry["path"])
        if not env_dir.exists():
            raise EnvResolutionError(
                f"{env_ref} is registered but {env_dir} is missing — reinstall with "
                f"`prime env install {env_ref}`"
            )
        drift = None
        if hub_client is not None:
            drift = _installed_drift(env_ref, entry, hub_client)
        metadata = _try_metadata(env_dir)
        return ResolvedEnv(
            name=env_ref,
            env_dir=env_dir,
            source="installed",
            version=entry.get("version"),
            drift=drift,
            metadata=metadata,
        )

    # 3. hub slug → install on demand
    if hub_client is not None and install_missing:
        from prime_tpu.core.exceptions import APIError

        try:
            entry = install_from_hub(hub_client, env_ref)
        except APIError as e:
            raise EnvResolutionError(
                f"{env_ref!r} is not a local env dir, not installed, and the hub "
                f"lookup failed: {e}"
            ) from None
        metadata = _try_metadata(Path(entry["path"]))
        return ResolvedEnv(
            name=env_ref,
            env_dir=Path(entry["path"]),
            source="hub",
            version=entry.get("version"),
            metadata=metadata,
        )
    raise EnvResolutionError(
        f"{env_ref!r} is not a local env dir and is not installed "
        "(no hub client available to install it)"
    )


def _try_metadata(env_dir: Path) -> dict | None:
    try:
        return read_env_metadata(env_dir)
    except (FileNotFoundError, ValueError):
        return None


def _local_drift(env_dir: Path, name: str, hub_client) -> str | None:
    """Local dir vs hub content hash (reference verifiers_bridge.py:365-409)."""
    from prime_tpu.core.exceptions import APIError

    try:
        hub = hub_client.get(name)
    except APIError:
        return None
    hub_hash = hub.get("contentHash")
    if hub_hash and hub_hash != content_hash(env_dir):
        return (
            f"local {name}/ differs from the hub version "
            f"({hub.get('latestVersion', '?')}) — running LOCAL code; "
            f"`prime env push` to sync"
        )
    return None


def _installed_drift(name: str, entry: dict, hub_client) -> str | None:
    from prime_tpu.core.exceptions import APIError

    try:
        hub = hub_client.get(name)
    except APIError:
        return None
    hub_hash = hub.get("contentHash")
    if hub_hash and entry.get("contentHash") and hub_hash != entry["contentHash"]:
        return (
            f"installed {name}@{entry.get('version', '?')} is stale vs hub "
            f"{hub.get('latestVersion', '?')} — `prime env install {name}` to update"
        )
    return None


def _find_module_file(env_dir: Path, name: str) -> Path:
    module = name.replace("-", "_")
    candidates = [
        env_dir / f"{module}.py",
        env_dir / module / "__init__.py",
        env_dir / "main.py",
    ]
    for candidate in candidates:
        if candidate.exists():
            return candidate
    raise EnvProtocolError(
        f"No entry module for env {name!r}: expected one of "
        f"{[str(c.relative_to(env_dir)) for c in candidates]} under {env_dir}"
    )


def load_environment(resolved: ResolvedEnv) -> LoadedEnvironment:
    """Import the env's module and call its ``load_environment()``."""
    site = env_site_dir()
    if site.exists() and str(site) not in sys.path:
        sys.path.append(str(site))  # pip-installed env deps become importable
    module_file = _find_module_file(resolved.env_dir, resolved.name)
    module_name = f"prime_env_{resolved.name.replace('-', '_')}"
    spec = importlib.util.spec_from_file_location(module_name, module_file)
    if spec is None or spec.loader is None:
        raise EnvProtocolError(f"Cannot import {module_file}")
    module = importlib.util.module_from_spec(spec)
    # registered so the env's own relative imports/dataclasses resolve
    sys.modules[module_name] = module
    try:
        spec.loader.exec_module(module)
    except Exception as e:
        raise EnvProtocolError(f"Importing {module_file} failed: {e}") from e
    loader = getattr(module, "load_environment", None)
    if loader is None:
        raise EnvProtocolError(
            f"{module_file} defines no load_environment() — not a prime environment"
        )
    try:
        env_obj = loader()
    except Exception as e:
        raise EnvProtocolError(f"{resolved.name}.load_environment() raised: {e}") from e
    return _normalize(env_obj, resolved)


def _normalize(env_obj: Any, resolved: ResolvedEnv) -> LoadedEnvironment:
    def pick(key: str, default=None):
        if isinstance(env_obj, dict):
            return env_obj.get(key, default)
        return getattr(env_obj, key, default)

    examples = pick("examples")
    if not examples:
        raise EnvProtocolError(
            f"{resolved.name}.load_environment() returned no examples "
            "(need a non-empty 'examples' list of {prompt, answer} records)"
        )
    # gsm8k-style records are accepted: 'question' doubles as the prompt
    examples = [
        {**e, "prompt": e.get("prompt", e.get("question"))} for e in examples
    ]
    bad = next((e for e in examples if e.get("prompt") is None or "answer" not in e), None)
    if bad is not None:
        raise EnvProtocolError(
            f"{resolved.name} example missing prompt/answer keys: {bad!r}"
        )
    scorer = pick("score")
    if scorer is not None and not callable(scorer):
        raise EnvProtocolError(f"{resolved.name} 'score' must be callable")
    defaults = {}
    eval_meta = (resolved.metadata or {}).get("eval", {})
    for key in ("max_new_tokens", "temperature"):
        value = pick(key, eval_meta.get(key))
        if value is not None:
            defaults[key] = value
    return LoadedEnvironment(
        name=pick("name", resolved.name),
        examples=list(examples),
        scorer=scorer,
        defaults=defaults,
    )
