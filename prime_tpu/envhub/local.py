"""Local installed-environment registry (shared by env CLI and Lab)."""

from __future__ import annotations

import json
from pathlib import Path


def installs_dir() -> Path:
    from prime_tpu.core.config import Config

    return Config().config_dir / "envs"


def read_registry() -> dict:
    path = installs_dir() / "installed.json"
    if path.exists():
        try:
            data = json.loads(path.read_text())
            return data if isinstance(data, dict) else {}
        except json.JSONDecodeError:
            return {}
    return {}


def save_registry(registry: dict) -> None:
    installs_dir().mkdir(parents=True, exist_ok=True)
    (installs_dir() / "installed.json").write_text(json.dumps(registry, indent=2))
