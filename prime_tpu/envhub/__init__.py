"""Environments Hub: package, version, and distribute eval/RL environments.

Reference surface: prime_cli/commands/env.py (push = wheel build + archive +
content hash + upload, env.py:1039-1660; install = pip from hub wheel with
private pull-and-build fallback :3069). TPU-native delta: environment
metadata declares TPU requirements (``tpu_type``, ``min_chips``) so installs
can check the target slice.
"""

from prime_tpu.envhub.packaging import (
    build_archive,
    content_hash,
    read_env_metadata,
    write_env_template,
)
from prime_tpu.envhub.client import EnvHubClient

__all__ = [
    "EnvHubClient",
    "build_archive",
    "content_hash",
    "read_env_metadata",
    "write_env_template",
]
