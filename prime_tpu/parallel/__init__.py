"""TPU parallelism: slice topology modeling, device meshes, sharding rules.

``topology`` is pure Python (no JAX import) so the platform-client layers can
use slice math without pulling in the compute stack. JAX-dependent modules
(mesh, sharding, ring attention) import lazily.
"""

from prime_tpu.parallel.topology import (
    SliceSpec,
    TpuGeneration,
    list_slice_names,
    parse_slice,
)

__all__ = ["SliceSpec", "TpuGeneration", "list_slice_names", "parse_slice"]
