"""Device mesh construction for TPU slices.

Axis conventions (used by sharding.py and the trainer):
- ``dp``   — pure data parallel (gradients all-reduced)
- ``fsdp`` — data parallel with parameter sharding (ZeRO-3 style; XLA turns
  the annotations into reduce-scatter/all-gather over ICI)
- ``tp``   — tensor parallel (megatron-style head/ff sharding)
- ``sp``   — sequence parallel (ring attention, prime_tpu.parallel.ring_attention)

``mesh_for_slice`` maps a provisioned TPU slice (SliceSpec) to a mesh whose
axis order puts tp innermost so tensor-parallel collectives ride the
fastest ICI dimension.
"""

from __future__ import annotations

import math

import numpy as np

from prime_tpu.parallel.topology import SliceSpec, parse_slice


def make_mesh(axes: dict[str, int] | None = None, devices=None):
    """Build a jax.sharding.Mesh with named axes.

    ``axes`` maps axis name -> size; sizes must multiply to the device count.
    Default: all devices on a single ``dp`` axis.
    """
    import jax
    from jax.sharding import Mesh

    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if axes is None:
        axes = {"dp": n}
    total = math.prod(axes.values())
    if total != n:
        raise ValueError(
            f"Mesh axes {axes} multiply to {total}, but {n} devices are available"
        )
    device_array = np.asarray(devices).reshape(*axes.values())
    return Mesh(device_array, tuple(axes))


def mesh_for_slice(
    slice_name: str | SliceSpec,
    tensor_parallel: int | None = None,
    fsdp: int | None = None,
    expert_parallel: int | str | None = None,
    n_experts: int | None = None,
    sequence_parallel: int | None = None,
    devices=None,
):
    """Derive a (dp, fsdp[, sp][, ep], tp) mesh for a TPU slice.

    Default policy: tp = min(chips, 8 aligned to the slice's minor ICI dim),
    fsdp = remaining chips, dp = 1. ``expert_parallel`` carves an ep axis out
    of the fsdp factor for MoE models (tp stays innermost on the fastest ICI
    dim); pass ``"auto"`` with ``n_experts`` to take gcd(non-tp factor,
    n_experts). ``sequence_parallel`` carves an sp axis for long-context
    work (ring-attention training, slot-sharded KV caches). Multi-slice DCN
    data parallelism belongs on an outer ``dp`` axis (see
    prime_tpu.parallel.distributed).
    """
    import jax
    import math as _math

    spec = parse_slice(slice_name) if isinstance(slice_name, str) else slice_name
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if tensor_parallel is None:
        minor = min(int(d) for d in spec.topology.split("x") if int(d) > 1) if spec.chips > 1 else 1
        tensor_parallel = min(8, minor if minor > 1 else 1, n)
        while n % tensor_parallel:
            tensor_parallel //= 2
    remaining = n // tensor_parallel
    sp = None
    if sequence_parallel and sequence_parallel > 1:
        if expert_parallel:
            raise ValueError("sequence_parallel and expert_parallel are mutually exclusive")
        if remaining % sequence_parallel:
            raise ValueError(
                f"sequence_parallel={sequence_parallel} must divide the "
                f"non-tp factor {remaining}"
            )
        sp = sequence_parallel
        remaining //= sp
        if fsdp is None:
            fsdp = remaining
        if remaining % fsdp:
            raise ValueError(f"fsdp={fsdp} must divide the non-tp/sp factor {remaining}")
        return make_mesh(
            {"dp": remaining // fsdp, "fsdp": fsdp, "sp": sp, "tp": tensor_parallel},
            devices,
        )
    if expert_parallel == "auto":
        if not n_experts:
            raise ValueError("expert_parallel='auto' needs n_experts")
        ep = _math.gcd(remaining, n_experts)
        expert_parallel = ep if ep > 1 else None
    if expert_parallel:
        if remaining % expert_parallel:
            raise ValueError(
                f"expert_parallel={expert_parallel} must divide the non-tp factor {remaining}"
            )
        if fsdp is None:
            fsdp = remaining // expert_parallel
        if remaining % (fsdp * expert_parallel):
            raise ValueError(
                f"fsdp={fsdp} * expert_parallel={expert_parallel} must divide "
                f"the non-tp factor {remaining}"
            )
        dp = remaining // (fsdp * expert_parallel)
        return make_mesh(
            {"dp": dp, "fsdp": fsdp, "ep": expert_parallel, "tp": tensor_parallel}, devices
        )
    if fsdp is None:
        fsdp = remaining
    if remaining % fsdp:
        raise ValueError(f"fsdp={fsdp} must divide the non-tp factor {remaining}")
    dp = n // (fsdp * tensor_parallel)
    return make_mesh({"dp": dp, "fsdp": fsdp, "tp": tensor_parallel}, devices)
