"""Long-context decode: KV cache sharded along its slot axis over ``sp``.

Ring attention (ring_attention.py) covers long-context PREFILL: the sequence
is sharded over sp and KV blocks rotate around the ring. This module covers
the matching DECODE step: once a cache is longer than one chip's HBM, its
slot axis lives sharded over sp, and each decode step runs flash-softmax
locally per shard followed by a two-phase combine — the online-softmax merge
lifted to the mesh level:

    global_max  = pmax(local_max)
    scale_i     = exp(local_max_i - global_max)
    out         = psum(scale_i * local_acc) / psum(scale_i * local_sum)

One pmax + two psums per step over ICI, independent of context length; the
HBM traffic (the decode bottleneck) stays perfectly sharded.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from prime_tpu.parallel.compat import shard_map

NEG_INF = -1e30


def sp_decode_attention(
    q: jnp.ndarray,              # (B, H, 1, D) replicated over sp
    k_cache: jnp.ndarray,        # (B, KH, D, C) with C sharded over sp
    v_cache: jnp.ndarray,        # (B, KH, D, C)
    cache_lengths: jnp.ndarray,  # (B,) GLOBAL valid lengths
    mesh,
    sm_scale: float | None = None,
    k_scale: jnp.ndarray | None = None,  # (B, KH, 1, C) int8-cache dequant
    v_scale: jnp.ndarray | None = None,  # scales, sharded over sp with C
) -> jnp.ndarray:
    """One decode step against a sequence-sharded cache. Returns (B, H, 1, D).

    int8 caches shard cleanly: the per-slot dequant scales live with their
    slots on each shard and fold into the local score/value einsums exactly
    as in the single-device quantized path — the combine is unchanged."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    shards = mesh.shape["sp"]
    capacity = k_cache.shape[3]
    if capacity % shards:
        raise ValueError(f"cache capacity {capacity} must divide over sp={shards}")
    local_c = capacity // shards
    quantized = k_scale is not None
    if quantized != (v_scale is not None):
        raise ValueError("k_scale and v_scale go together")
    slot_spec = P(None, None, None, "sp")
    scale_in = (
        (k_scale, v_scale)
        if quantized
        # dummy replicated ones keep ONE shard_map signature; `quantized`
        # gates their use statically
        else (jnp.ones((1, 1, 1, 1), jnp.float32),) * 2
    )
    scale_spec = slot_spec if quantized else P()

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), slot_spec, slot_spec, scale_spec, scale_spec, P()),
        out_specs=P(),
    )
    def step(q_full, k_local, v_local, ks_local, vs_local, lengths):
        batch, heads, _, head_dim = q_full.shape
        kv_heads = k_local.shape[1]
        group = heads // kv_heads
        shard_index = jax.lax.axis_index("sp")

        qg = (q_full.reshape(batch, kv_heads, group, head_dim).astype(jnp.float32)) * sm_scale
        scores = jnp.einsum(
            "bkgd,bkdc->bkgc", qg, k_local.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        if quantized:
            scores = scores * ks_local  # (B, KH, 1, C_local) broadcasts over G
        # this shard owns global slots [shard_index*local_c, ...+local_c)
        slots = shard_index * local_c + jnp.arange(local_c)
        valid = slots[None, None, None, :] < lengths[:, None, None, None]
        scores = jnp.where(valid, scores, NEG_INF)

        local_max = jnp.max(scores, axis=-1, keepdims=True)          # (B,KH,G,1)
        p = jnp.exp(scores - local_max) * valid
        if quantized:
            p = p * vs_local
        local_sum = jnp.sum(jnp.exp(scores - local_max) * valid, axis=-1, keepdims=True)
        local_acc = jnp.einsum(
            "bkgc,bkdc->bkgd", p, v_local.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )

        global_max = jax.lax.pmax(local_max, "sp")
        scale = jnp.exp(local_max - global_max)
        total_sum = jax.lax.psum(local_sum * scale, "sp")
        total_acc = jax.lax.psum(local_acc * scale, "sp")
        out = total_acc / jnp.maximum(total_sum, 1e-30)
        return out.reshape(batch, heads, 1, head_dim).astype(q_full.dtype)

    return step(q, k_cache, v_cache, *scale_in, cache_lengths)
