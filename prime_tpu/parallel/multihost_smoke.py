"""Multi-host connectivity smoke: prove jax.distributed actually works.

Run the SAME command on every worker of a slice (the SPMD contract —
reference launches its NCCL ranks the same way, e.g. its torchrun-shaped
entrypoints; here the fan-out is ``prime pods connect --all-workers``):

    python -m prime_tpu.parallel.multihost_smoke \
        --coordinator <worker0>:8476 --num-processes N --process-id $I

Each process initializes the distributed runtime via
``initialize_multihost`` (prime_tpu/parallel/distributed.py), then proves
the pooled device set is real with three checks that each REQUIRE
cross-process communication:

1. ``psum`` of ones over a global mesh — result must equal the GLOBAL
   device count, which no process can produce locally.
2. ``all_gather`` of process-stamped shards — every process must observe
   every other process's stamp.
3. A dp/tp-sharded matmul whose replicated scalar output must match a
   single-host numpy reference — the XLA partitioner inserts the
   cross-host collectives implicitly from shardings, the same path the
   real training step uses.

Each process prints one ``MULTIHOST_SMOKE_OK {json}`` line on success and
exits nonzero on any failure. In CI this runs as two CPU processes
(tests/test_multihost.py) — multi-host semantics without multi-host
hardware; on a real v5e-16+ slice the identical command validates DCN.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from prime_tpu.parallel.compat import shard_map


def run_smoke(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> dict:
    """Initialize the distributed runtime and run the three cross-process
    checks. Returns the result record (also asserted internally)."""
    from prime_tpu.parallel.distributed import initialize_multihost

    initialize_multihost(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import NamedSharding, PartitionSpec as P

    from prime_tpu.parallel.mesh import make_mesh

    n_global = jax.device_count()
    n_local = jax.local_device_count()
    n_proc = jax.process_count()
    assert n_proc == (num_processes or n_proc), (
        f"process_count {n_proc} != requested {num_processes}"
    )
    assert n_global == n_local * n_proc, (n_global, n_local, n_proc)

    mesh = make_mesh({"dp": n_global})

    # 1. psum over every device: only correct if the collective spans hosts
    ones = jax.device_put(
        jnp.ones((n_global,)), NamedSharding(mesh, P("dp"))
    )
    total = jax.jit(
        shard_map(
            lambda x: jax.lax.psum(jnp.sum(x), "dp"),
            mesh=mesh, in_specs=P("dp"), out_specs=P(),
        )
    )(ones)
    assert float(total) == float(n_global), (float(total), n_global)

    # 2. all_gather of process-stamped shards: device i carries value
    # 1000*process_of(device i) + i; the gathered vector must contain every
    # process's stamp on every process
    stamps = np.asarray(
        [1000 * d.process_index + i for i, d in enumerate(mesh.devices.ravel())],
        dtype=np.float32,
    )
    stamped = jax.device_put(jnp.asarray(stamps), NamedSharding(mesh, P("dp")))
    gathered = jax.jit(
        shard_map(
            lambda x: jax.lax.all_gather(x, "dp", tiled=True),
            mesh=mesh, in_specs=P("dp"), out_specs=P(),
            # the gathered result IS replicated, but the varying-axes checker
            # can't statically infer that for all_gather output
            check_vma=False,
        )
    )(stamped)
    seen_procs = sorted({int(v) // 1000 for v in np.asarray(gathered)})
    assert seen_procs == list(range(n_proc)), (seen_procs, n_proc)

    # 3. sharded matmul: dp-sharded activations x tp-sharded weights with a
    # replicated scalar out — the partitioner must insert the cross-host
    # collectives itself, exactly as in the real train/serve steps
    tp = n_local
    mesh2 = make_mesh({"dp": n_global // tp, "tp": tp})
    key = jax.random.PRNGKey(0)
    x_host = jax.random.normal(key, (8 * (n_global // tp), 64))
    w_host = jax.random.normal(jax.random.PRNGKey(1), (64, 16 * tp))
    x = jax.device_put(x_host, NamedSharding(mesh2, P("dp", None)))
    w = jax.device_put(w_host, NamedSharding(mesh2, P(None, "tp")))
    out = jax.jit(
        lambda a, b: jnp.sum(a @ b),
        out_shardings=NamedSharding(mesh2, P()),
    )(x, w)
    ref = float(np.sum(np.asarray(x_host) @ np.asarray(w_host)))
    got = float(out)
    assert abs(got - ref) <= 1e-2 + 1e-4 * abs(ref), (got, ref)

    return {
        "process_id": jax.process_index(),
        "process_count": n_proc,
        "global_devices": n_global,
        "local_devices": n_local,
        "psum": float(total),
        "procs_seen_in_gather": seen_procs,
        "sharded_matmul_ok": True,
    }


def main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--coordinator", default=None,
                        help="host:port of process 0 (omit on Cloud TPU VMs)")
    parser.add_argument("--num-processes", type=int, default=None)
    parser.add_argument("--process-id", type=int, default=None)
    parser.add_argument(
        "--devices-per-process", type=int, default=None,
        help="virtual CPU devices per process (CI only; must be set before "
        "jax import, so main() sets XLA_FLAGS/JAX_PLATFORMS itself)",
    )
    args = parser.parse_args(argv)
    if args.devices_per_process:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={args.devices_per_process}"
        )
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    record = run_smoke(
        coordinator_address=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
    )
    print("MULTIHOST_SMOKE_OK " + json.dumps(record), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
