"""Pipeline parallelism: GPipe microbatch schedule over a ``pp`` mesh axis.

TPU-first pipelining (scaling-book recipe): the stacked (L, ...) layer
parameters are sharded on their leading axis over ``pp`` — each device holds
a contiguous stage of L/P layers — and activations hop stage-to-stage with
``lax.ppermute`` inside one ``shard_map``. The schedule is the classic GPipe
fill/drain loop: with M microbatches and P stages, M + P - 1 ticks, bubble
fraction (P-1)/(M+P-1). Everything is a single compiled program: the tick
loop is a ``lax.fori_loop``, microbatch selection is a dynamic index, and
stage activity is masking (idle stages compute on garbage that is never
collected — the standard static-shape trade).

Embedding/unembedding run replicated outside the pipelined region (cheap at
the scales where pp matters less than the layer stack; a production variant
folds them into the first/last stages). Gradients flow through ppermute and
the final psum, so the same function backpropagates for training.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from prime_tpu.models.config import ModelConfig
from prime_tpu.ops.norms import rms_norm
from prime_tpu.ops.rope import rope_frequencies
from prime_tpu.parallel.compat import pcast, shard_map


def pipeline_param_specs(config: ModelConfig) -> dict:
    """Like sharding.param_specs but stages the layer stack over pp."""
    if config.first_k_dense:
        raise ValueError(
            "pipeline parallelism does not stage DeepSeek dense-prefix "
            "models (first_k_dense > 0): the two stacks would need separate "
            "pp layouts"
        )
    if config.is_moe:
        mlp_spec = {
            "router": P("pp", None, None),
            "w_gate": P("pp", None, None, None),
            "w_up": P("pp", None, None, None),
            "w_down": P("pp", None, None, None),
        }
        if config.moe_bias:  # GPT-OSS biases stage with their projections
            mlp_spec |= {
                "router_bias": P("pp", None),
                "b_gate": P("pp", None, None),
                "b_up": P("pp", None, None),
                "b_down": P("pp", None, None),
            }
        if config.moe_score_bias:  # DeepSeek-V3 balance bias
            mlp_spec |= {"score_bias": P("pp", None)}
        if config.n_shared_experts:  # DeepSeekMoE shared expert (dense MLP)
            mlp_spec |= {
                "w_shared_gate": P("pp", None, None),
                "w_shared_up": P("pp", None, None),
                "w_shared_down": P("pp", None, None),
            }
    else:
        mlp_spec = {
            "w_gate": P("pp", None, None),
            "w_up": P("pp", None, None),
            "w_down": P("pp", None, None),
        }
    if config.mla:
        attn_spec = {
            "wkv_a": P("pp", None, None),
            "kv_a_norm": P("pp", None),
            "wkv_b": P("pp", None, None),
            "wo": P("pp", None, None),
        }
        if config.q_lora_rank is not None:
            attn_spec |= {
                "wq_a": P("pp", None, None),
                "q_a_norm": P("pp", None),
                "wq_b": P("pp", None, None),
            }
        else:
            attn_spec["wq"] = P("pp", None, None)
    else:
        attn_spec = {
            "wq": P("pp", None, None),
            "wk": P("pp", None, None),
            "wv": P("pp", None, None),
            "wo": P("pp", None, None),
        }
    layer_spec = {
        **attn_spec,
        **mlp_spec,
    }
    if config.pre_norms:
        layer_spec |= {"attn_norm": P("pp", None), "mlp_norm": P("pp", None)}
    if config.attn_bias:
        layer_spec |= {"bq": P("pp", None), "bk": P("pp", None), "bv": P("pp", None)}
    if config.attn_out_bias:
        layer_spec |= {"bo": P("pp", None)}
    if config.qk_norm:
        layer_spec |= {"q_norm": P("pp", None), "k_norm": P("pp", None)}
    if config.attn_sinks:
        layer_spec |= {"sinks": P("pp", None)}
    if config.qk_norm_full:
        layer_spec |= {"q_norm_full": P("pp", None), "k_norm_full": P("pp", None)}
    if config.post_norms:
        layer_spec |= {"attn_post_norm": P("pp", None), "mlp_post_norm": P("pp", None)}
    specs = {
        "embed": P(None, None),
        "layers": layer_spec,
        "final_norm": P(None),
    }
    if not config.tie_embeddings:
        specs["lm_head"] = P(None, None)
    return specs


def _stage_forward(
    layers_local, sliding_local, x, positions, rope_tables, rope_tables_local,
    config: ModelConfig,
):
    """Run this device's contiguous stage of layers (scan, no cache). The
    per-layer sliding flags ride the scan exactly like in forward() — they
    were computed GLOBALLY and sharded over pp with the layer stack, so an
    alternating-window schedule stays aligned across stages."""
    from prime_tpu.models.llama import _attention_block, _mlp_block

    def layer_fn(carry, scanned):
        x, aux_sum = carry
        lp, sliding = scanned
        if config.mla:
            from prime_tpu.models.mla import mla_attention_block

            x, _, _, _, _ = mla_attention_block(
                x, lp, positions, rope_tables, config, None, None, None, False, "xla"
            )
        else:
            x, _, _, _, _ = _attention_block(
                x, lp, positions, rope_tables, config, None, None, None, False, "xla",
                sliding=sliding, rope_tables_local=rope_tables_local,
            )
        x, aux = _mlp_block(x, lp, config)
        return (x, aux_sum + aux), None

    # runs inside run_pipeline's shard_map: the zero init must carry the same
    # pp-varying marker the scanned layer params give the aux output
    aux_zero = pcast(jnp.zeros((), jnp.float32), ("pp",), to="varying")
    (x, aux_total), _ = jax.lax.scan(
        layer_fn, (x, aux_zero), (layers_local, sliding_local)
    )
    return x, aux_total


def pipeline_forward(
    params,
    tokens: jnp.ndarray,       # (B, S) with B divisible by n_microbatches
    config: ModelConfig,
    mesh,
    n_microbatches: int,
    return_aux: bool = False,
) -> jnp.ndarray:
    """Pipelined training forward. Returns logits (B, S, V) fp32 (plus the
    microbatch-averaged MoE load-balance aux when ``return_aux``)."""
    stages = mesh.shape["pp"]
    if config.n_layers % stages:
        raise ValueError(f"n_layers={config.n_layers} must divide into pp={stages} stages")
    batch, seq = tokens.shape
    if batch % n_microbatches:
        raise ValueError(f"batch {batch} not divisible by {n_microbatches} microbatches")
    micro = batch // n_microbatches

    x = params["embed"][tokens]                       # (B, S, D) replicated
    if config.scale_embed:
        x = x * jnp.asarray(config.d_model**0.5, dtype=x.dtype)
    x_mb = x.reshape(n_microbatches, micro, seq, x.shape[-1])
    positions = jnp.broadcast_to(jnp.arange(seq, dtype=jnp.int32)[None, :], (micro, seq))
    rope_tables = rope_frequencies(
        # MLA ropes only the shared qk_rope sub-head (mirrors llama.forward)
        config.qk_rope_head_dim if config.mla else config.head_dim,
        max(seq, config.max_seq_len), config.rope_theta,
        # must match forward()'s rope math exactly (incl. the round-4
        # families: non-truncated yarn, LongRoPE, partial rotary; the
        # no-cache path selects LongRoPE factors by seq)
        scale=config.rope_scale, llama3=config.rope_llama3, yarn=config.rope_yarn,
        yarn_truncate=config.rope_yarn_truncate, longrope=config.rope_longrope,
        longrope_select=seq, partial=config.partial_rotary,
    )
    rope_tables_local = (
        rope_frequencies(config.head_dim, max(seq, config.max_seq_len), config.rope_local_theta)
        if config.rope_local_theta is not None
        else None
    )
    from prime_tpu.models.llama import sliding_layer_flags

    sliding_flags = sliding_layer_flags(config)  # (L,), stages over pp below

    layer_specs = pipeline_param_specs(config)["layers"]

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(layer_specs, P("pp"), P()),
        out_specs=(P(), P()),
    )
    def run_pipeline(layers_local, sliding_local, x_mb):
        stage_index = jax.lax.axis_index("pp")
        perm = [(i, i + 1) for i in range(stages - 1)]  # forward shift, no wraparound

        def tick(t, carry):
            state, outs, aux_acc = carry
            mb_in = jnp.clip(t, 0, n_microbatches - 1)
            fresh = jax.lax.dynamic_index_in_dim(x_mb, mb_in, axis=0, keepdims=False)
            x_in = jnp.where(stage_index == 0, fresh, state)
            y, aux = _stage_forward(
                layers_local, sliding_local, x_in, positions, rope_tables,
                rope_tables_local, config,
            )
            # this stage processes microbatch t - stage_index at tick t; aux
            # from bubble ticks (garbage inputs outside that range) must not
            # pollute the MoE load-balance signal
            mb_here = t - stage_index
            real = (mb_here >= 0) & (mb_here < n_microbatches)
            aux_acc = aux_acc + jnp.where(real, aux, 0.0)
            # the last stage finishes microbatch t-(P-1) at tick t
            mb_out = t - (stages - 1)
            collect = (stage_index == stages - 1) & (mb_out >= 0) & (mb_out < n_microbatches)
            slot = jnp.clip(mb_out, 0, n_microbatches - 1)
            updated = jax.lax.dynamic_update_index_in_dim(outs, y, slot, axis=0)
            outs = jnp.where(collect, updated, outs)
            if stages > 1:
                state = jax.lax.ppermute(y, "pp", perm)
            else:
                state = y
            return state, outs, aux_acc

        # mark the zero carries as pp-varying so the loop carry types match
        # the ppermute/masked outputs (jax's manual-axes varying tracking)
        state0 = pcast(jnp.zeros_like(x_mb[0]), ("pp",), to="varying")
        outs0 = pcast(jnp.zeros_like(x_mb), ("pp",), to="varying")
        aux0 = pcast(jnp.zeros((), jnp.float32), ("pp",), to="varying")
        _, outs, aux_acc = jax.lax.fori_loop(
            0, n_microbatches + stages - 1, tick, (state0, outs0, aux0)
        )
        # only the last stage holds real outputs; psum broadcasts them to all
        # (aux sums every stage's layers — the same sum-over-layers forward()
        # returns — averaged over microbatches)
        logits_all = jax.lax.psum(jnp.where(stage_index == stages - 1, outs, 0.0), "pp")
        aux_all = jax.lax.psum(aux_acc, "pp") / n_microbatches
        return logits_all, aux_all

    hidden, aux_total = run_pipeline(params["layers"], sliding_flags, x_mb)  # (M, mb, S, D)
    hidden = hidden.reshape(batch, seq, -1)
    hidden = rms_norm(
        hidden, params["final_norm"], config.rms_eps, plus_one=config.norm_plus_one
    )
    head = params["embed"].T if config.tie_embeddings else params["lm_head"]
    from prime_tpu.ops.attention import _apply_softcap

    logits = _apply_softcap((hidden @ head).astype(jnp.float32), config.final_softcap)
    return (logits, aux_total) if return_aux else logits


def make_pipeline_train_step(
    config: ModelConfig,
    optimizer,
    mesh,
    n_microbatches: int,
    aux_weight: float = 0.01,   # MoE load-balance loss weight (Switch default)
):
    """Jitted pipelined train step (params staged over pp via
    shard_pipeline_params). Same contract as trainer.make_train_step."""
    if config.mla:
        from prime_tpu.models.mla import validate_mla_config

        # the stage forward calls the MLA block directly — the same loud
        # rejection forward() applies must fire here, or pipeline training
        # would silently run different attention math than serving
        validate_mla_config(config)
    from prime_tpu.train.trainer import TrainState, apply_gradients, cross_entropy_loss

    def loss_fn(params, tokens, targets, mask):
        if config.is_moe:
            logits, aux = pipeline_forward(
                params, tokens, config, mesh, n_microbatches, return_aux=True
            )
            return cross_entropy_loss(logits, targets, mask) + aux_weight * aux
        logits = pipeline_forward(params, tokens, config, mesh, n_microbatches)
        return cross_entropy_loss(logits, targets, mask)

    def train_step(state: TrainState, tokens, targets, mask):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, tokens, targets, mask)
        new_state, grad_norm = apply_gradients(state, grads, optimizer)
        return new_state, {"loss": loss, "grad_norm": grad_norm}

    return jax.jit(train_step, donate_argnums=(0,))


def shard_pipeline_params(params, mesh, config: ModelConfig):
    """Place params for the pipeline: layer stack staged over pp, rest
    replicated."""
    from jax.sharding import NamedSharding

    shardings = jax.tree.map(
        lambda spec: NamedSharding(mesh, spec),
        pipeline_param_specs(config),
        is_leaf=lambda x: isinstance(x, P),
    )
    return jax.device_put(params, shardings)
