"""Sharding rules for the Llama param/activation pytrees.

Megatron-style tensor parallelism + ZeRO-3 fsdp, expressed as PartitionSpecs
over the (dp, fsdp, tp) mesh from prime_tpu.parallel.mesh. XLA inserts the
collectives (all-gather for fsdp params, psum for tp partials) — nothing here
issues communication explicitly.

Layout choices (scaling-book recipe):
- attention: wq/wk/wv shard the *head* output dim on tp, wo shards its input
  dim on tp → one psum per attention block;
- mlp: w_gate/w_up shard d_ff on tp, w_down shards d_ff on tp → one psum;
- fsdp shards the other (d_model / vocab) dim of every large matrix;
- norms are replicated (tiny);
- batch is sharded over (dp, fsdp) jointly — fsdp is also a data axis.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from prime_tpu.models.config import ModelConfig


def param_specs(config: ModelConfig) -> dict[str, Any]:
    if config.is_moe:
        # experts ride the ep axis; within an expert the same megatron layout
        mlp_specs = {
            "router": P(None, None, None),  # tiny + fp32: replicate
            "w_gate": P(None, "ep", "fsdp", "tp"),
            "w_up": P(None, "ep", "fsdp", "tp"),
            "w_down": P(None, "ep", "tp", "fsdp"),
        }
        if config.moe_bias:  # GPT-OSS: biases land with their projections
            mlp_specs |= {
                "router_bias": P(None, None),
                "b_gate": P(None, "ep", "tp"),
                "b_up": P(None, "ep", "tp"),
                "b_down": P(None, "ep", "fsdp"),
            }
        if config.moe_score_bias:
            mlp_specs["score_bias"] = P(None, None)  # tiny fp32: replicate
        if config.n_shared_experts:
            # the shared expert is a dense MLP: megatron layout, no ep axis
            mlp_specs |= {
                "w_shared_gate": P(None, "fsdp", "tp"),
                "w_shared_up": P(None, "fsdp", "tp"),
                "w_shared_down": P(None, "tp", "fsdp"),
            }
    else:
        mlp_specs = {
            "w_gate": P(None, "fsdp", "tp"),
            "w_up": P(None, "fsdp", "tp"),
            "w_down": P(None, "tp", "fsdp"),
        }
    attn_bias_specs: dict[str, Any] = {}
    if config.attn_bias:
        # bias vectors live on the projection output dim — same tp split as
        # their matrices' output columns
        attn_bias_specs = {"bq": P(None, "tp"), "bk": P(None, "tp"), "bv": P(None, "tp")}
    if config.attn_out_bias:
        attn_bias_specs["bo"] = P(None, "fsdp")  # d_model dim, like wo's output
    if config.qk_norm:
        # (L, head_dim) weights shared across heads: replicate
        attn_bias_specs |= {"q_norm": P(None, None), "k_norm": P(None, None)}
    if config.attn_sinks:
        # (L, H) per-head logits: the head axis rides tp like the q heads
        # they normalize (each device needs only its own heads' sinks)
        attn_bias_specs["sinks"] = P(None, "tp")
    if config.qk_norm_full:
        # (L, H*hd) on the projection output dim — same tp split as the
        # matrices' output columns so the norm weight lands with its slice
        attn_bias_specs |= {"q_norm_full": P(None, "tp"), "k_norm_full": P(None, "tp")}
    if config.post_norms:
        attn_bias_specs |= {
            "attn_post_norm": P(None, None),
            "mlp_post_norm": P(None, None),
        }
    pre_norm_specs = (
        {"attn_norm": P(None, None), "mlp_norm": P(None, None)}
        if config.pre_norms
        else {}
    )
    if config.mla:
        # MLA: query heads and wkv_b's per-head output columns ride tp
        # (h-major flat layout splits whole heads when h % tp == 0); the
        # shared latent projections are head-free and ride fsdp only
        attn_specs: dict[str, Any] = {
            "wkv_a": P(None, "fsdp", None),
            "kv_a_norm": P(None, None),
            "wkv_b": P(None, None, "tp"),
            "wo": P(None, "tp", "fsdp"),
        }
        if config.q_lora_rank is not None:
            attn_specs |= {
                "wq_a": P(None, "fsdp", None),
                "q_a_norm": P(None, None),
                "wq_b": P(None, None, "tp"),
            }
        else:
            attn_specs["wq"] = P(None, "fsdp", "tp")
    else:
        attn_specs = {
            "wq": P(None, "fsdp", "tp"),
            "wk": P(None, "fsdp", "tp"),
            "wv": P(None, "fsdp", "tp"),
            "wo": P(None, "tp", "fsdp"),
        }
    specs: dict[str, Any] = {
        "embed": P("tp", "fsdp"),              # (V, D) vocab on tp, d_model on fsdp
        "layers": {
            **attn_specs,
            **pre_norm_specs,
            **attn_bias_specs,
            **mlp_specs,
        },
        "final_norm": P(None),
    }
    if config.first_k_dense:
        # DeepSeek dense-prefix stack: same attention/norm layout, dense MLP
        specs["dense_layers"] = {
            **attn_specs,
            **pre_norm_specs,
            **attn_bias_specs,
            "w_gate": P(None, "fsdp", "tp"),
            "w_up": P(None, "fsdp", "tp"),
            "w_down": P(None, "tp", "fsdp"),
        }
    if not config.tie_embeddings:
        specs["lm_head"] = P("fsdp", "tp")
    return specs


def batch_spec() -> P:
    return P(("dp", "fsdp"), None)


def cp_batch_spec() -> P:
    """Context-parallel training batches: sequence sharded over sp (ring
    attention rotates the KV blocks; everything elementwise stays local)."""
    return P(("dp", "fsdp"), "sp")


def ring_qkv_axes(mesh, kv_heads: int):
    """(batch_axis, head_axis) for ring attention on ``mesh`` — the ONE
    owner of the axis-name policy (model code must not re-hardcode it).
    Batch rides the data axes; heads ride tp when present (per-head math
    shards cleanly under megatron layout). A tp axis that can't divide the
    kv heads is an error rather than silent replication of every head's
    attention on every tp device."""
    batch = tuple(a for a in ("dp", "fsdp") if a in mesh.shape) or None
    tp = mesh.shape.get("tp", 1)
    if tp > 1 and kv_heads % tp:
        raise ValueError(
            f"ring attention: tp={tp} must divide n_kv_heads={kv_heads}"
        )
    return batch, ("tp" if tp > 1 else None)


def lengths_spec() -> P:
    return P(("dp", "fsdp"))


def cache_spec() -> P:
    """KV cache (L, B, KH, hd, C): batch on the data axes, kv-heads on tp.

    Pinning this matters for serving: without a constraint XLA may replicate
    the zeros-initialised cache, which for an 8B model at long context is the
    difference between fitting v5e HBM and OOM.
    """
    return P(None, ("dp", "fsdp"), "tp", None, None)


def cache_spec_for(config, sp: bool = False) -> P:
    """The cache spec a model's KV cache layout admits: MLA caches have ONE
    kv 'head' (the shared latent), so the head axis must stay replicated —
    putting tp there would demand 1 % tp == 0. Non-MLA picks the standard
    (sp_)cache_spec. Callers still prune against their mesh."""
    base = sp_cache_spec() if sp else cache_spec()
    if getattr(config, "mla", False):
        return P(base[0], base[1], None, *base[3:])
    return base


def serving_cache_spec(config, mesh) -> P:
    """THE serving KV-cache spec for ``mesh``: ``cache_spec_for`` (MLA keeps
    its single-latent head axis replicated; an sp axis shards the slot
    dimension for long-context serving) pruned to the axes the mesh actually
    has. One owner for the derivation the engine, serve_model, and the eval
    runner all need — a change to the MLA/sp rules lands in every consumer."""
    has_sp = mesh.shape.get("sp", 1) > 1
    return prune_spec(cache_spec_for(config, sp=has_sp), mesh)


def sp_cache_spec() -> P:
    """KV cache (L, B, KH, hd, C) with the SLOT axis sharded over sp: a
    long-context cache larger than one chip's HBM spreads across the
    slice. Pass as ``generate(..., cache_spec=sp_cache_spec())`` under a
    mesh with an sp axis — GSPMD inserts the slot-axis collectives for
    the decode reads/writes (the hand-optimized per-step combine is
    long_context.sp_decode_attention)."""
    return P(None, ("dp", "fsdp"), "tp", None, "sp")


def logits_spec() -> P:
    return P(("dp", "fsdp"), None, "tp")


def prune_spec(spec: P, mesh) -> P:
    """Drop axis names the mesh doesn't have (e.g. 'ep' on a (dp,fsdp,tp)
    serving mesh): those dims fall back to replicated instead of erroring."""
    axes = set(mesh.axis_names)

    def keep(element):
        if element is None:
            return None
        if isinstance(element, tuple):
            kept = tuple(a for a in element if a in axes)
            return kept if len(kept) > 1 else (kept[0] if kept else None)
        return element if element in axes else None

    return P(*(keep(element) for element in spec))


def param_shardings(mesh, config: ModelConfig):
    return jax.tree.map(
        lambda spec: NamedSharding(mesh, prune_spec(spec, mesh)),
        param_specs(config),
        is_leaf=lambda x: isinstance(x, P),
    )


def shard_params(params, mesh, config: ModelConfig):
    """Place a param pytree onto the mesh (device_put with NamedShardings)."""
    return jax.device_put(params, param_shardings(mesh, config))


def shard_batch(batch, mesh, spec: P | None = None):
    """Place a (B, S) batch: data axes on B by default; pass
    ``cp_batch_spec()`` to also shard S over sp (context parallelism).
    Unknown axes prune to replicated so one spec serves any mesh."""
    return jax.device_put(batch, NamedSharding(mesh, prune_spec(spec or batch_spec(), mesh)))
