"""jax API compatibility for the parallel layer.

``shard_map`` graduated from ``jax.experimental.shard_map`` to the top-level
``jax.shard_map`` after 0.4.x with the same call surface (f, mesh=, in_specs=,
out_specs=). The repo targets the jax_graft toolchain (top-level name); thin
test containers run 0.4.x — import it from here so every shard_map-wrapped
path (ring attention, sp decode, pipeline, multihost smoke) lowers under
both builds instead of failing on the attribute.
"""

from __future__ import annotations

import jax

try:
    shard_map = jax.shard_map
except AttributeError:  # jax 0.4.x

    from jax.experimental.shard_map import (  # type: ignore[import-not-found]
        shard_map as _experimental_shard_map,
    )

    def shard_map(f, **kwargs):  # type: ignore[no-redef]
        # the replication-check knob was renamed check_rep -> check_vma when
        # shard_map graduated. The callers here are written for the new vma
        # type system (jax.lax.pcast marks varying values); 0.4.x's check_rep
        # predates vma and false-positives on them (e.g. the pipeline's
        # psum'd aux scalar), so replication checking is OFF on this build.
        kwargs.pop("check_vma", None)
        kwargs["check_rep"] = False
        return _experimental_shard_map(f, **kwargs)


def enter_mesh(mesh):
    """Context manager making ``mesh`` the ambient mesh for compiled calls
    (bare-``PartitionSpec`` ``with_sharding_constraint`` sites resolve
    against it). The toolchain spells this ``jax.set_mesh``; 0.4.x predates
    it but a ``Mesh`` is itself a context manager with the same ambient
    effect, so every dispatch site that wraps itself in ``enter_mesh`` runs
    sharded on both builds instead of AttributeError-ing on the old one."""
    try:
        return jax.set_mesh(mesh)
    except AttributeError:  # jax 0.4.x: Mesh.__enter__ sets the ambient mesh
        return mesh


try:
    pcast = jax.lax.pcast
except AttributeError:  # jax 0.4.x

    def pcast(x, axes, to=None):  # type: ignore[no-redef]
        """0.4.x has no varying-axis (vma) type system: every shard_map here
        runs with replication checking off on that build (check_rep=False via
        the shim above), so the cast is data-wise an identity."""
        del axes, to
        return x


__all__ = ["enter_mesh", "pcast", "shard_map"]
