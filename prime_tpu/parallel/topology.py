"""TPU slice topology: the TPU-native replacement for GPU-type metadata.

The reference models compute as ``gpu_type/gpu_count/socket/interconnect``
(prime_cli/api/availability.py:53-83). Here the first-class unit is a **TPU
slice**: a named accelerator like ``v5e-16`` that expands to chips, hosts,
an ICI mesh topology (e.g. ``4x4``), and — for multi-slice jobs — a DCN pool.
This module is pure Python (no JAX) so every platform layer can do slice math;
`prime_tpu.parallel.mesh` maps these specs onto `jax.sharding.Mesh` axes.

Ground truth per generation (public Cloud TPU system architecture):

- **v4**: 3D torus, 4 chips/host, 2 TensorCores/chip, suffix counts *cores*
  (``v4-8`` = 4 chips = 1 host).
- **v5e**: 2D torus, up to 8 chips/host, 1 core/chip, suffix counts *chips*
  (``v5e-8`` = 8 chips = 1 host; ``v5e-256`` = 256 chips = 32 hosts).
- **v5p**: 3D torus, 4 chips/host, 2 cores/chip, suffix counts *cores*
  (``v5p-8`` = 4 chips = 1 host).
- **v6e**: 2D torus, same shape rules as v5e.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from enum import Enum


class TpuGeneration(str, Enum):
    V4 = "v4"
    V5E = "v5e"
    V5P = "v5p"
    V6E = "v6e"

    @property
    def cores_per_chip(self) -> int:
        return 1 if self in (TpuGeneration.V5E, TpuGeneration.V6E) else 2

    @property
    def chips_per_host(self) -> int:
        return 8 if self in (TpuGeneration.V5E, TpuGeneration.V6E) else 4

    @property
    def suffix_counts_cores(self) -> bool:
        """v4/v5p slice names count TensorCores; v5e/v6e count chips."""
        return self in (TpuGeneration.V4, TpuGeneration.V5P)

    @property
    def torus_rank(self) -> int:
        return 2 if self in (TpuGeneration.V5E, TpuGeneration.V6E) else 3

    @property
    def hbm_gib_per_chip(self) -> int:
        return {"v4": 32, "v5e": 16, "v5p": 95, "v6e": 32}[self.value]

    @property
    def bf16_tflops_per_chip(self) -> float:
        return {"v4": 275.0, "v5e": 197.0, "v5p": 459.0, "v6e": 918.0}[self.value]


def _factor_2d(chips: int) -> tuple[int, int]:
    """Most-square 2D power-of-two grid, x <= y."""
    x = 2 ** (int(math.log2(chips)) // 2)
    return x, chips // x


def _factor_3d(chips: int) -> tuple[int, int, int]:
    """Most-cubic 3D power-of-two grid, x <= y <= z."""
    exp = int(math.log2(chips))
    a = exp // 3
    rem = exp - 3 * a
    dims = [a, a, a]
    for i in range(rem):
        dims[2 - i] += 1
    return tuple(2**d for d in dims)  # type: ignore[return-value]


@dataclass(frozen=True)
class SliceSpec:
    """A concrete TPU slice: the unit `prime pods create` provisions."""

    name: str                 # e.g. "v5e-16"
    generation: TpuGeneration
    chips: int
    cores: int
    hosts: int
    topology: str             # ICI mesh, e.g. "4x4" or "2x2x2"
    multi_host: bool

    @property
    def hbm_gib(self) -> int:
        return self.chips * self.generation.hbm_gib_per_chip

    @property
    def bf16_tflops(self) -> float:
        return self.chips * self.generation.bf16_tflops_per_chip

    @property
    def ici_link_count(self) -> int:
        """Bidirectional ICI links in the (possibly wrapped) torus."""
        dims = [int(d) for d in self.topology.split("x")]
        links = 0
        for i, d in enumerate(dims):
            others = 1
            for j, o in enumerate(dims):
                if j != i:
                    others *= o
            # a dimension of size d contributes d-1 links per line, or d when
            # the torus wraps (only closed for full-size dims >= 4 in practice;
            # we model the unwrapped mesh, which is the conservative count)
            links += (d - 1) * others
        return links

    def to_metadata(self) -> dict:
        """Wire-format slice metadata (what the control plane returns)."""
        return {
            "name": self.name,
            "tpu_type": self.generation.value,
            "chips": self.chips,
            "cores": self.cores,
            "hosts": self.hosts,
            "ici_topology": self.topology,
            "multi_host": self.multi_host,
            "hbm_gib": self.hbm_gib,
            "bf16_tflops": self.bf16_tflops,
        }


# Largest slice per generation, in chips (full-pod sizes from public docs:
# v4 pod = 4096 chips, v5e pod = 256 chips, v5p pod = 8960 chips, v6e = 256).
_MAX_CHIPS = {
    TpuGeneration.V4: 4096,
    TpuGeneration.V5E: 256,
    TpuGeneration.V5P: 8960,
    TpuGeneration.V6E: 256,
}


def parse_slice(name: str) -> SliceSpec:
    """Parse an accelerator name like ``v5e-16`` into a full :class:`SliceSpec`.

    Raises ``ValueError`` with an actionable message for unknown generations,
    malformed names, non-power-of-two counts, and out-of-range sizes.
    """
    name = name.strip().lower()
    if "-" not in name:
        raise ValueError(
            f"Malformed TPU slice name {name!r}: expected '<generation>-<count>' like 'v5e-8'"
        )
    gen_str, _, count_str = name.partition("-")
    try:
        gen = TpuGeneration(gen_str)
    except ValueError:
        valid = ", ".join(g.value for g in TpuGeneration)
        raise ValueError(f"Unknown TPU generation {gen_str!r}: expected one of {valid}") from None
    try:
        count = int(count_str)
    except ValueError:
        raise ValueError(f"Malformed TPU slice name {name!r}: {count_str!r} is not a number") from None
    if count <= 0 or (count & (count - 1)) != 0:
        raise ValueError(f"Invalid slice size {count} in {name!r}: must be a power of two")

    if gen.suffix_counts_cores:
        # v4/v5p rent whole boards (4 chips): the smallest slice is <gen>-8.
        if count < gen.cores_per_chip * 4:
            raise ValueError(
                f"Invalid slice size {count} in {name!r}: {gen.value} slices count cores "
                f"({gen.cores_per_chip}/chip), minimum is {gen.value}-{gen.cores_per_chip * 4}"
            )
        chips = count // gen.cores_per_chip
    else:
        chips = count
    cores = chips * gen.cores_per_chip
    if chips > _MAX_CHIPS[gen]:
        raise ValueError(
            f"Slice {name!r} exceeds the largest {gen.value} pod ({_MAX_CHIPS[gen]} chips)"
        )

    hosts = max(1, math.ceil(chips / gen.chips_per_host))
    if gen.torus_rank == 2:
        x, y = _factor_2d(chips)
        topology = f"{x}x{y}"
    else:
        x, y, z = _factor_3d(chips)
        topology = f"{x}x{y}x{z}"
    return SliceSpec(
        name=f"{gen.value}-{count}",
        generation=gen,
        chips=chips,
        cores=cores,
        hosts=hosts,
        topology=topology,
        multi_host=hosts > 1,
    )


def list_slice_names(generation: TpuGeneration | str | None = None) -> list[str]:
    """Enumerate valid slice names (the catalog `prime availability` serves)."""
    gens = [TpuGeneration(generation)] if generation else list(TpuGeneration)
    out: list[str] = []
    for gen in gens:
        chips = 1
        while chips <= _MAX_CHIPS[gen]:
            if gen.suffix_counts_cores:
                if chips >= 4:  # v4/v5p minimum rentable slice is one board
                    out.append(f"{gen.value}-{chips * gen.cores_per_chip}")
            else:
                out.append(f"{gen.value}-{chips}")
            chips *= 2
    return out
