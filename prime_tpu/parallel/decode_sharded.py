"""Multi-chip flash decode: the pallas kernel under shard_map.

A ``pallas_call`` is opaque to the SPMD partitioner, so under a multi-device
mesh the jitted serve path falls back to XLA decode (ops/attention.py). This
module provides the building block that removes that limitation: the decode
step wrapped in ``shard_map`` with the serving layout's specs — batch over
the data axes, kv-heads over tp — so each device runs the flash-decode
kernel on exactly its local cache shard and no communication is needed (the
head-dim psum happens later in the attention output projection, as usual for
megatron attention).

Constraint: the batch shard and kv-head shard must be non-empty on every
device (B divisible by dp*fsdp, KH divisible by tp) — the same divisibility
the serving path already enforces.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from prime_tpu.parallel.compat import shard_map


def flash_decode_sharded(
    q: jnp.ndarray,              # (B, H, 1, D)
    k_cache: jnp.ndarray,        # (B, KH, D, C) feature-major
    v_cache: jnp.ndarray,        # (B, KH, D, C)
    cache_lengths: jnp.ndarray,  # (B,)
    mesh,
    sm_scale: float | None = None,
    softcap: float = 0.0,
    window: int = 0,
    sliding: jnp.ndarray | None = None,
    sinks: jnp.ndarray | None = None,  # (H,) per-head sink logits (GPT-OSS)
    interpret: bool = False,
) -> jnp.ndarray:
    """Per-shard pallas flash decode over a (dp, fsdp, tp[, ...]) mesh.

    The Gemma/GPT-OSS variants shard cleanly: softcap and the window are
    per-score/per-slot (no cross-shard state), and sinks split over tp with
    the heads they normalize."""
    from prime_tpu.ops.pallas_attention import flash_decode
    from prime_tpu.parallel import sharding

    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5

    # one source of truth for the serving layout: prune the canonical specs
    # down to the axes this mesh actually has
    q_spec = sharding.prune_spec(P(("dp", "fsdp"), "tp", None, None), mesh)
    kv_spec = q_spec
    lengths_spec = sharding.prune_spec(sharding.lengths_spec(), mesh)
    sinks_spec = sharding.prune_spec(P("tp"), mesh)
    if sinks is None:
        # dummy replicated zeros keep ONE shard_map signature; use_sinks
        # stays False inside flash_decode via the has_sinks closure below
        sinks_in = jnp.zeros((q.shape[1],), jnp.float32)
    else:
        sinks_in = sinks.astype(jnp.float32)
    has_sinks = sinks is not None

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(q_spec, kv_spec, kv_spec, lengths_spec, sinks_spec),
        out_specs=q_spec,
        # pallas_call's out ShapeDtypeStruct carries no varying-axes metadata
        check_vma=False,
    )
    def local_decode(q_local, k_local, v_local, lengths_local, sinks_local):
        return flash_decode(
            q_local, k_local, v_local, lengths_local, sm_scale=sm_scale,
            softcap=softcap, window=window, sliding=sliding,
            sinks=sinks_local if has_sinks else None, interpret=interpret,
        )

    return local_decode(q, k_cache, v_cache, cache_lengths, sinks_in)
