"""Ring attention: causal self-attention with the sequence sharded over ICI.

Long-context first-class citizen: sequences larger than one chip's HBM are
sharded along an ``sp`` mesh axis. Each device holds a local (B, H, S/P, D)
block of q/k/v; KV blocks rotate around the ring via ``lax.ppermute`` while
every device folds each visiting block into an online-softmax accumulator
(the same math as the pallas flash kernel, lifted to the inter-chip level).
P-1 rotations fully overlap compute with ICI transfers under XLA's async
collective scheduling.

Causality across the ring: device i owns global positions
[i*S_local, (i+1)*S_local). A visiting KV block from source device j is
- fully visible if j < i,
- causally masked within the block if j == i,
- fully masked if j > i (the where-mask zeroes it; its transfer cost is the
  price of the symmetric schedule).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from prime_tpu.parallel.compat import shard_map

NEG_INF = -1e30


def _ring_attention_local(
    q: jnp.ndarray,  # (B, H, S_local, D) — this device's block
    k: jnp.ndarray,  # (B, KH, S_local, D)
    v: jnp.ndarray,
    sinks: jnp.ndarray,  # (H,) per-head sink logits (zeros when unused)
    axis_name: str,
    sm_scale: float,
    window: int = 0,
    hops: int | None = None,  # ring rotations (host-static; None = P-1)
    softcap: float = 0.0,
    use_sinks: bool = False,
):
    axis_size = jax.lax.psum(1, axis_name)
    my_index = jax.lax.axis_index(axis_name)
    batch, heads, s_local, head_dim = q.shape
    kv_heads = k.shape[1]
    group = heads // kv_heads

    # GQA: keep k/v at (B, KH, S_local, D) through the ring — each ppermute
    # then moves 1/group of the repeated-layout bytes over ICI — and fold with
    # q grouped as (B, KH, G, S_local, D) so the einsum broadcasts over G.
    q32 = (q.astype(jnp.float32) * sm_scale).reshape(batch, kv_heads, group, s_local, head_dim)
    q_pos = my_index * s_local + jnp.arange(s_local)  # global positions of my queries

    m = jnp.full((batch, kv_heads, group, s_local, 1), NEG_INF, dtype=jnp.float32)
    l = jnp.zeros((batch, kv_heads, group, s_local, 1), dtype=jnp.float32)
    acc = jnp.zeros((batch, kv_heads, group, s_local, head_dim), dtype=jnp.float32)

    def fold(carry, kv_block, source_index):
        m_prev, l_prev, acc_prev = carry
        k_blk, v_blk = kv_block
        scores = jnp.einsum(
            "bhgqd,bhkd->bhgqk", q32, k_blk.astype(jnp.float32), preferred_element_type=jnp.float32
        )
        # the canonical softcap (cap-before-mask invariant lives there)
        from prime_tpu.ops.attention import _apply_softcap

        scores = _apply_softcap(scores, softcap)
        kv_pos = source_index * s_local + jnp.arange(s_local)
        visible = kv_pos[None, :] <= q_pos[:, None]  # (S_local, S_local) global causal mask
        if window:
            # sliding layer: the key must also be within `window` of the
            # query (delta < window, ops.attention._window_ok semantics)
            visible &= q_pos[:, None] - kv_pos[None, :] < window
        scores = jnp.where(visible[None, None, None], scores, NEG_INF)

        m_new = jnp.maximum(m_prev, jnp.max(scores, axis=-1, keepdims=True))
        p = jnp.exp(scores - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc_prev * alpha + jnp.einsum(
            "bhgqk,bhkd->bhgqd", p, v_blk.astype(jnp.float32), preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    # step 0: my own block; then rotate kv around the ring `hops` times
    # (host-static — the loop lowers to a fixed-length scan, not a dynamic
    # while; ring_hops computes the sliding-layer cap)
    carry = fold((m, l, acc), (k, v), my_index)
    perm = [(s, (s + 1) % axis_size) for s in range(axis_size)]

    def ring_step(step, state):
        carry, (k_cur, v_cur) = state
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        # after `step` rotations, I hold the block originally on device my_index - step
        source = (my_index - step + axis_size) % axis_size
        carry = fold(carry, (k_nxt, v_nxt), source)
        return carry, (k_nxt, v_nxt)

    last = (1 + hops) if hops is not None else axis_size
    (m, l, acc), _ = jax.lax.fori_loop(
        1, last, lambda s, st: ring_step(s, st), (carry, (k, v))
    )
    if use_sinks:
        # GPT-OSS attention sinks: one denominator adjustment after all
        # folds (the sink joins every query's normalization, no value) —
        # same algebra as ops.pallas_attention._finalize_attention
        sink = sinks.astype(jnp.float32).reshape(1, kv_heads, group, 1, 1)
        m_final = jnp.maximum(m, sink)
        rescale = jnp.exp(m - m_final)
        denom = l * rescale + jnp.exp(sink - m_final)
        out = (acc * rescale / jnp.maximum(denom, 1e-30))
    else:
        out = acc / jnp.maximum(l, 1e-30)
    return out.reshape(batch, heads, s_local, head_dim).astype(q.dtype)


def ring_self_attention(
    q: jnp.ndarray,  # (B, H, S, D) with S sharded on `seq_axis`
    k: jnp.ndarray,  # (B, KH, S, D)
    v: jnp.ndarray,
    mesh,
    seq_axis: str = "sp",
    sm_scale: float | None = None,
    window: int = 0,
    softcap: float = 0.0,
    sinks: jnp.ndarray | None = None,  # (H,) per-head sink logits
    batch_axis=None,  # mesh axis (or tuple) sharding the batch dim
    head_axis=None,   # mesh axis sharding the head dims (megatron tp)
) -> jnp.ndarray:
    """Causal ring attention over a mesh sequence axis (full-array API).

    ``window`` > 0 makes it a sliding layer: the causal mask adds the
    window band AND the ring stops after ``ring_hops(...)`` rotations —
    the KV blocks beyond the band are never transferred, so a
    Gemma/Mistral-style windowed layer costs O(window) ICI traffic per
    device instead of a full rotation. ``softcap``/``sinks`` carry the
    Gemma2/GPT-OSS score math. ``batch_axis``/``head_axis`` let the batch
    ride data axes and the heads ride tp (per-head math shards cleanly),
    so context parallelism composes with dp/fsdp/tp instead of silently
    replicating over them."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    shards = mesh.shape[seq_axis]
    hops = ring_hops(window, q.shape[2] // shards, shards)
    use_sinks = sinks is not None
    sinks_in = (
        sinks.astype(jnp.float32) if use_sinks else jnp.zeros((q.shape[1],), jnp.float32)
    )
    spec = P(batch_axis, head_axis, seq_axis, None)
    fn = shard_map(
        functools.partial(
            _ring_attention_local, axis_name=seq_axis, sm_scale=sm_scale,
            window=window, hops=hops, softcap=softcap, use_sinks=use_sinks,
        ),
        mesh=mesh,
        in_specs=(spec, spec, spec, P(head_axis)),
        out_specs=spec,
    )
    return fn(q, k, v, sinks_in)


def ring_hops(window: int, s_local: int, axis_size: int) -> int:
    """Ring rotations a layer needs. Global layers make the full P-1; a
    sliding layer's earliest query (global i*S_local) sees back to
    q - window + 1, exactly ceil((window-1)/S_local) hops upstream — every
    earlier block is fully masked and never transferred (a window within
    one shard span costs exactly one hop)."""
    if not window:
        return axis_size - 1
    return min(axis_size - 1, -(-(window - 1) // s_local))
