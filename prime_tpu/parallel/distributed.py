"""Multi-host / multi-slice distributed initialization.

The TPU-native replacement for the reference's NCCL/MPI-shaped backend
(SURVEY.md §2.10): on a multi-host slice every worker runs the same program;
``jax.distributed.initialize`` wires them over DCN, after which the global
device set spans all hosts and XLA collectives ride ICI within a slice and
DCN across slices. `prime pods connect --all-workers` is the launch fan-out.
"""

from __future__ import annotations


from prime_tpu.core.config import env_flag
from prime_tpu.parallel.topology import SliceSpec, parse_slice


def initialize_multihost(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
    initialization_timeout: float | None = None,
) -> None:
    """Initialize jax.distributed for a multi-host slice.

    On Cloud TPU VMs all three arguments are discovered from the metadata
    server automatically; explicit values are for DCN-pooled multi-slice jobs
    (coordinator = worker 0 of slice 0) or for tests.
    ``initialization_timeout`` bounds the coordinator handshake so a worker
    whose peers never arrive FAILS instead of hanging (failure detection at
    launch; exercised by tests/test_multihost.py).
    """
    import jax

    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    if initialization_timeout is not None:
        kwargs["initialization_timeout"] = initialization_timeout
    jax.distributed.initialize(**kwargs)


def multislice_mesh_axes(slice_name: str | SliceSpec, num_slices: int) -> dict[str, int]:
    """Axis sizes for a DCN-pooled multi-slice job: ``dp`` spans slices over
    DCN (gradient all-reduce is DCN-tolerant), fsdp/tp stay inside each
    slice's ICI (latency-sensitive collectives never cross DCN)."""
    spec = parse_slice(slice_name) if isinstance(slice_name, str) else slice_name
    tp = min(8, spec.chips)
    while spec.chips % tp:
        tp //= 2
    return {"dp": num_slices, "fsdp": spec.chips // tp, "tp": tp}


def worker_env(worker_index: int, coordinator_host: str, num_workers: int) -> dict[str, str]:
    """Environment to export on each TPU VM worker before launching the job
    (used by the pods SPMD fan-out)."""
    return {
        "PRIME_WORKER_INDEX": str(worker_index),
        "PRIME_NUM_WORKERS": str(num_workers),
        "PRIME_COORDINATOR": f"{coordinator_host}:8476",
        **({"TPU_STDERR_LOG_LEVEL": "0"} if env_flag("PRIME_DEBUG", False) else {}),
    }
