"""Dual-mode command output: rich tables, ``--plain`` text, ``--output json``.

Capability parity with the reference's PlainTyper/PrimeConsole
(prime_cli/utils/plain.py:17-37): every command renders human tables by
default, tab-separated plain text for scripts/AI agents, or machine JSON.
The ``--plain`` help note explicitly tells AI agents to prefer it.
"""

from __future__ import annotations

import functools
import json
from typing import Any, Callable, Sequence

import click
from rich.console import Console
from rich.table import Table

PLAIN_HELP = "Plain text output (recommended for scripts and AI agents)."
OUTPUT_HELP = "Output format: table (default) or json."


class Renderer:
    """Renders command results in the selected mode."""

    def __init__(self, plain: bool = False, output: str = "table") -> None:
        self.plain = plain
        self.output = output
        self.console = Console()

    @property
    def is_json(self) -> bool:
        return self.output == "json"

    def json(self, payload: Any) -> None:
        click.echo(json.dumps(payload, indent=2, default=str))

    def table(
        self,
        columns: Sequence[str],
        rows: Sequence[Sequence[Any]],
        *,
        title: str | None = None,
        json_rows: Any = None,
    ) -> None:
        if self.is_json:
            if json_rows is not None:
                self.json(json_rows)
            else:
                self.json([dict(zip(columns, row)) for row in rows])
            return
        if self.plain:
            click.echo("\t".join(str(c) for c in columns))
            for row in rows:
                click.echo("\t".join("" if v is None else str(v) for v in row))
            return
        table = Table(title=title)
        for col in columns:
            table.add_column(str(col))
        for row in rows:
            table.add_row(*("" if v is None else str(v) for v in row))
        self.console.print(table)

    def detail(self, pairs: dict[str, Any], *, title: str | None = None, json_obj: Any = None) -> None:
        if self.is_json:
            self.json(json_obj if json_obj is not None else pairs)
            return
        if self.plain:
            for k, v in pairs.items():
                click.echo(f"{k}\t{'' if v is None else v}")
            return
        table = Table(title=title, show_header=False)
        table.add_column("field", style="bold")
        table.add_column("value")
        for k, v in pairs.items():
            table.add_row(str(k), "" if v is None else str(v))
        self.console.print(table)

    def message(self, text: str, *, err: bool = False) -> None:
        if self.is_json:
            return  # JSON mode emits only the payload
        click.echo(text, err=err)

    def error(self, text: str) -> None:
        if self.is_json:
            click.echo(json.dumps({"error": text}), err=False)
        else:
            click.echo(f"Error: {text}", err=True)


def output_options(fn: Callable) -> Callable:
    """Attach ``--plain`` / ``--output`` and inject a Renderer as ``render``."""

    @click.option("--plain", is_flag=True, default=False, help=PLAIN_HELP)
    @click.option(
        "--output",
        "output",
        type=click.Choice(["table", "json"]),
        default="table",
        help=OUTPUT_HELP,
    )
    def wrapper(*args: Any, plain: bool, output: str, **kwargs: Any) -> Any:
        return fn(*args, render=Renderer(plain=plain, output=output), **kwargs)

    functools.update_wrapper(wrapper, fn, assigned=("__name__", "__doc__"), updated=())
    return wrapper


def flag_is_default(param: str) -> bool:
    """True when ``param`` was not given explicitly on the command line —
    used to let env-declared defaults beat CLI defaults but never beat the
    user's own flags."""
    from click.core import ParameterSource

    ctx = click.get_current_context()
    return ctx.get_parameter_source(param) == ParameterSource.DEFAULT
