"""`.env` parsing with ``${VAR}`` expansion (reference: utils/env_vars.py:145).

``collect_env_vars`` merges explicit KEY=VALUE pairs over a .env file over
the process environment, restricted to an allowlist when given — full-FT
dispatch only forwards WANDB_API_KEY/HF_TOKEN (reference commands/rl.py:985).
"""

from __future__ import annotations

import os
import re
from pathlib import Path

_VAR_RE = re.compile(r"\$\{([A-Za-z_][A-Za-z0-9_]*)\}")

FULL_FT_ALLOWED_KEYS = {"WANDB_API_KEY", "HF_TOKEN"}


def parse_dotenv(path: str | Path) -> dict[str, str]:
    """Parse a .env file: KEY=VALUE lines, quotes stripped, ${VAR} expanded
    against previously-defined keys then the process environment."""
    result: dict[str, str] = {}
    path = Path(path)
    if not path.exists():
        return result
    for line in path.read_text().splitlines():
        line = line.strip()
        if not line or line.startswith("#") or "=" not in line:
            continue
        key, _, value = line.partition("=")
        key = key.strip()
        value = value.strip()
        if len(value) >= 2 and value[0] == value[-1] and value[0] in "\"'":
            value = value[1:-1]
        value = _VAR_RE.sub(lambda m: result.get(m.group(1), os.environ.get(m.group(1), "")), value)
        result[key] = value
    return result


def collect_env_vars(
    explicit: dict[str, str] | None = None,
    dotenv_path: str | Path = ".env",
    allowed: set[str] | None = None,
) -> dict[str, str]:
    """explicit > .env > os.environ, filtered to `allowed` when given."""
    # os.environ is always the lowest layer; with no allowlist, seed from the
    # keys the upper layers mention (a full environ dump would leak secrets)
    dotenv = parse_dotenv(dotenv_path)
    keys = allowed if allowed is not None else set(dotenv) | set(explicit or {})
    merged = {key: os.environ[key] for key in keys if key in os.environ}
    merged.update(dotenv)
    if explicit:
        merged.update(explicit)
    return {k: v for k, v in merged.items() if k in keys}
