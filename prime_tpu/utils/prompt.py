"""Interactive pickers (reference: utils/prompt.py, 202 LoC).

One consistent selection UX for every wizard: numbered rows with aligned
columns, a default choice, and `--yes` short-circuiting. Built on click's
prompt machinery so CliRunner-driven tests can feed selections via stdin.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import click


def pick(
    title: str,
    rows: Sequence[Any],
    *,
    describe: Callable[[Any], str] = str,
    default: int | None = 1,
    assume_default: bool = False,
    prompt: str = "Select",
) -> Any:
    """Numbered picker: print rows, return the chosen one.

    ``default`` is 1-based; ``assume_default=True`` (e.g. from --yes) skips
    interaction entirely. Raises click.ClickException for an empty row list.
    """
    if not rows:
        raise click.ClickException(f"{title}: nothing to select from")
    if len(rows) == 1 or (assume_default and default is not None):
        return rows[(default or 1) - 1]
    click.echo(f"{title}:")
    width = len(str(len(rows)))
    for index, row in enumerate(rows, 1):
        click.echo(f"  {index:>{width}}. {describe(row)}")
    choice = click.prompt(prompt, type=click.IntRange(1, len(rows)), default=default)
    return rows[choice - 1]


def pick_value(
    title: str,
    value: Any | None,
    choices: Sequence[Any],
    *,
    describe: Callable[[Any], str] = str,
    default: int | None = 1,
    assume_default: bool = False,
) -> Any:
    """Return ``value`` if already provided (flag given), else pick one."""
    if value is not None:
        return value
    return pick(title, choices, describe=describe, default=default, assume_default=assume_default)


def prompt_int(
    label: str, default: int, *, minimum: int = 1, maximum: int | None = None,
    assume_default: bool = False,
) -> int:
    if assume_default:
        return default
    return click.prompt(label, type=click.IntRange(minimum, maximum), default=default)


def confirm(message: str, *, default: bool = True, assume_yes: bool = False) -> bool:
    if assume_yes:
        return True
    return click.confirm(message, default=default)
