"""Short-ID display + prefix resolution (reference: helper/short_id.py:6).

Long backend IDs (``offer_1234abcd``, ``pod_9f3a1c2b``) display as their first
8 significant characters; user-typed prefixes resolve back to the unique full
ID, with ambiguity and miss errors that name the candidates.
"""

from __future__ import annotations

SHORT_LEN = 8


def shorten(full_id: str) -> str:
    if "_" in full_id:
        prefix, _, rest = full_id.partition("_")
        return f"{prefix}_{rest[:SHORT_LEN]}" if len(rest) > SHORT_LEN else full_id
    return full_id[:SHORT_LEN] if len(full_id) > SHORT_LEN else full_id


def resolve(prefix: str, candidates: list[str]) -> str:
    """Resolve a (possibly short) ID against known candidates."""
    if prefix in candidates:
        return prefix
    matches = [c for c in candidates if c.startswith(prefix)]
    if len(matches) == 1:
        return matches[0]
    if not matches:
        raise ValueError(f"No ID matches {prefix!r}")
    sample = ", ".join(sorted(matches)[:5])
    raise ValueError(f"Ambiguous ID {prefix!r}: matches {sample}")
