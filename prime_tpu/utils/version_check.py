"""PyPI version check with 24h cache (reference: utils/version_check.py:12-16).

Runs before subcommands; network failures and zero-egress environments are
silent (a version nag must never break the CLI).
"""

from __future__ import annotations

import json
import time
from pathlib import Path

from prime_tpu.core.config import env_str

CACHE_TTL_S = 24 * 3600
PYPI_URL = "https://pypi.org/pypi/prime-tpu/json"


def _cache_path() -> Path:
    env_dir = env_str("PRIME_CONFIG_DIR")
    base = Path(env_dir) if env_dir else Path.home() / ".prime"
    return base / "version_check.json"


def _is_newer(candidate: str, current: str) -> bool:
    try:
        from packaging.version import Version

        return Version(candidate) > Version(current)
    except Exception:
        return False  # unparseable versions never nag


def _write_cache(cache: Path, latest: str | None) -> None:
    try:
        cache.parent.mkdir(parents=True, exist_ok=True)
        cache.write_text(json.dumps({"latest": latest, "checkedAt": time.time()}))
    except OSError:
        pass


def check_for_update(current_version: str, timeout_s: float = 2.0) -> str | None:
    """Return the newer PyPI version string, or None. Never raises."""
    cache = _cache_path()
    try:
        cached = json.loads(cache.read_text())
        if time.time() - cached.get("checkedAt", 0) < CACHE_TTL_S:
            latest = cached.get("latest")
            return latest if latest and _is_newer(latest, current_version) else None
    except (OSError, json.JSONDecodeError):
        pass
    try:
        import httpx

        response = httpx.get(PYPI_URL, timeout=timeout_s)
        response.raise_for_status()
        latest = response.json()["info"]["version"]
    except Exception:
        # cache the failure too: offline machines must not pay the
        # timeout on every invocation (bounded to once per TTL)
        _write_cache(cache, None)
        return None
    _write_cache(cache, latest)
    return latest if _is_newer(latest, current_version) else None
