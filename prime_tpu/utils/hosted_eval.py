"""Hosted eval config + status (reference: utils/hosted_eval.py:12-121)."""

from __future__ import annotations

from pydantic import BaseModel, ConfigDict, Field


class EvalStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    COMPLETED = "COMPLETED"
    FAILED = "FAILED"
    CANCELLED = "CANCELLED"

    TERMINAL = {COMPLETED, FAILED, CANCELLED}


class HostedEvalConfig(BaseModel):
    model_config = ConfigDict(populate_by_name=True)

    env: str
    model: str
    limit: int | None = None
    batch_size: int = Field(default=8, alias="batchSize")
    max_new_tokens: int = Field(default=256, alias="maxNewTokens")
    temperature: float = 0.0
    tpu_type: str = Field(default="v5e-8", alias="tpuType")
