"""Stdlib compatibility shims.

The repo targets the jax_graft toolchain (Python 3.11+), but thin test
containers may run 3.10, where ``tomllib`` does not exist. Importing TOML
parsing through this module keeps every importer importable everywhere:

- Python >= 3.11: the stdlib ``tomllib``;
- 3.10 with the ``tomli`` backport installed: ``tomli`` (identical API);
- neither: a placeholder that defers the ``ModuleNotFoundError`` to the
  first actual parse, so importing a module that MIGHT parse TOML never
  breaks test collection — only code paths that really parse raise, with an
  actionable message. Tests gate on ``TOMLLIB_AVAILABLE`` (or
  tests/_markers ``get_tomllib()`` / ``requires_tomllib``) and skip visibly.
"""

from __future__ import annotations

_have_parser = True
try:
    import tomllib  # type: ignore[import-not-found]  # Python >= 3.11
except ModuleNotFoundError:  # pragma: no cover — depends on the interpreter
    try:
        import tomli as tomllib  # type: ignore[import-not-found, no-redef]
    except ModuleNotFoundError:

        class _MissingTomllib:
            """Defer-to-first-use stand-in for the tomllib module."""

            class TOMLDecodeError(Exception):
                """Matches the real API for ``except`` clauses; never raised
                here — there is no parser to raise it."""

            def __getattr__(self, name: str):
                raise ModuleNotFoundError(
                    "TOML parsing needs Python >= 3.11 (stdlib tomllib) or "
                    "the tomli backport; neither is available in this "
                    "environment"
                )

        tomllib = _MissingTomllib()  # type: ignore[assignment]
        _have_parser = False

TOMLLIB_AVAILABLE = _have_parser
