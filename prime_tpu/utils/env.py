"""``PRIME_*`` environment-knob readers — stdlib-only implementation.

This is a dependency-free leaf so the obs layer (which must stay importable
without pydantic/httpx) can read its knobs directly. The *canonical* import
surface for product code is ``prime_tpu.core.config`` which re-exports these
four names — the knob-registry checker in ``prime_tpu/analysis`` enforces
that every ``PRIME_*`` read goes through them, has a row in the
docs/architecture.md "Environment knobs" table, and agrees with its paired
CLI flag default (see docs/analysis.md).

Semantics are deliberately uniform: unset -> default; junk never raises
(a malformed knob on a production replica must degrade to the default with
a warning, not take the process down at import or construction time).
"""

from __future__ import annotations

import os
import warnings

_FALSE_WORDS = ("", "0", "false", "off", "no")


def env_str(name: str, default: str = "") -> str:
    """String knob: the raw value, or ``default`` when unset."""
    raw = os.environ.get(name)
    return default if raw is None else raw


def env_flag(name: str, default: bool) -> bool:
    """Boolean knob: unset -> default; otherwise anything outside
    {"", "0", "false", "off", "no"} (case-insensitive) is true."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() not in _FALSE_WORDS


def env_int(name: str, default: int) -> int:
    """Integer knob: unset or blank -> default; junk warns and defaults."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        warnings.warn(
            f"{name}={raw!r} is not an integer; using the default of {default}",
            stacklevel=2,
        )
        return default


def env_float(name: str, default: float) -> float:
    """Float knob: unset or blank -> default; junk warns and defaults."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        warnings.warn(
            f"{name}={raw!r} is not a number; using the default of {default}",
            stacklevel=2,
        )
        return default
