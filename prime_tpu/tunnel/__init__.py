"""prime-tpu tunnel SDK: expose local ports via managed frp tunnels.

Reference: prime_tunnel (SURVEY.md §2.5) — register with the backend, write
an frpc TOML config, spawn the frpc data plane, parse its log stream for
connect/fail, poll the registration.
"""

from prime_tpu.tunnel.tunnel import AsyncTunnel, Tunnel, TunnelError

__all__ = ["AsyncTunnel", "Tunnel", "TunnelError"]
