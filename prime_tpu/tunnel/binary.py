"""frpc binary manager (reference: prime_tunnel/binary.py:15-155).

Downloads the pinned frp release per-platform with SHA256 verification into a
cache dir. Zero-egress environments point PRIME_FRPC_PATH at an existing
binary instead — the download is attempted only when no override or cached
copy exists.
"""

from __future__ import annotations

import hashlib
import os
import platform
import tarfile
import tempfile
from pathlib import Path

FRPC_VERSION = "0.66.0"
# sha256 of the release tarballs (fatedier/frp v0.66.0)
FRPC_CHECKSUMS = {
    "linux_amd64": "d73b4d8dd3a5ce352354b6a9b47da3a5a6a268137ba0728ceba1864dcc4e4e4c",
    "linux_arm64": "e9e73fcbf15c9fb9aa7e1e90826de5fddfbee125661c0dd0de7469aa5b38ab25",
    "darwin_amd64": "3fa0e2e3834aa08eac1737dca9002bbd5a08e5bba5826e5e8bcb4b9013ef1a0e",
    "darwin_arm64": "92dd6d23449e61e2e174168add13c0a1df894e5b5e0e1a0d8350c8169f5a989e",
}
RELEASE_URL = "https://github.com/fatedier/frp/releases/download/v{v}/frp_{v}_{plat}.tar.gz"


class FrpcUnavailable(RuntimeError):
    pass


def _platform_key() -> str:
    system = platform.system().lower()
    machine = platform.machine().lower()
    arch = {"x86_64": "amd64", "amd64": "amd64", "arm64": "arm64", "aarch64": "arm64"}.get(machine)
    if system not in ("linux", "darwin") or arch is None:
        raise FrpcUnavailable(f"No frpc build for {system}/{machine}")
    return f"{system}_{arch}"


def cache_dir() -> Path:
    env_dir = os.environ.get("PRIME_CONFIG_DIR")
    base = Path(env_dir) if env_dir else Path.home() / ".prime"
    return base / "bin"


def get_frpc_path(download: bool = True) -> Path:
    """Resolve the frpc binary: override > cache > (optional) download."""
    override = os.environ.get("PRIME_FRPC_PATH")
    if override:
        path = Path(override)
        if not path.exists():
            raise FrpcUnavailable(f"PRIME_FRPC_PATH={override} does not exist")
        return path
    cached = cache_dir() / f"frpc-{FRPC_VERSION}"
    if cached.exists():
        return cached
    if not download:
        raise FrpcUnavailable("frpc not cached and download disabled")
    return _download_frpc(cached)


def _download_frpc(target: Path) -> Path:
    import httpx

    plat = _platform_key()
    expected = FRPC_CHECKSUMS.get(plat)
    if expected is None:
        raise FrpcUnavailable(f"No pinned checksum for platform {plat}")
    url = RELEASE_URL.format(v=FRPC_VERSION, plat=plat)
    try:
        response = httpx.get(url, follow_redirects=True, timeout=120.0)
        response.raise_for_status()
    except httpx.HTTPError as e:
        raise FrpcUnavailable(
            f"Could not download frpc from {url}: {e}. "
            "Set PRIME_FRPC_PATH to an existing frpc binary."
        ) from e
    data = response.content
    digest = hashlib.sha256(data).hexdigest()
    if digest != expected:
        raise FrpcUnavailable(
            f"frpc download checksum mismatch for {plat}: got {digest}, expected {expected}"
        )
    with tempfile.TemporaryDirectory() as tmp:
        archive = Path(tmp) / "frp.tar.gz"
        archive.write_bytes(data)
        with tarfile.open(archive) as tar:
            member = next((m for m in tar.getmembers() if m.name.endswith("/frpc")), None)
            if member is None:
                raise FrpcUnavailable(
                    f"frp release archive has no frpc binary (layout changed?): {url}"
                )
            tar.extract(member, tmp, filter="data")
            extracted = Path(tmp) / member.name
            target.parent.mkdir(parents=True, exist_ok=True)
            target.write_bytes(extracted.read_bytes())
            target.chmod(0o755)
    return target
