"""frpc binary manager (reference: prime_tunnel/binary.py:15-155).

Downloads the pinned frp release per-platform with SHA256 verification into a
cache dir. Zero-egress environments point PRIME_FRPC_PATH at an existing
binary instead — the download is attempted only when no override or cached
copy exists.
"""

from __future__ import annotations

import hashlib
import platform
import tarfile
import tempfile
from pathlib import Path

from prime_tpu.core.config import env_str

FRPC_VERSION = "0.66.0"
# sha256 of the published fatedier/frp v0.66.0 release tarballs. These are
# the upstream artifact digests; re-validate with scripts/verify_frpc_pins.py
# (needs network) whenever FRPC_VERSION is bumped.
FRPC_CHECKSUMS = {
    "linux_amd64": "317a17a7adac2e6bed2d7a83dc077da91ced0d110e1636373ece8ae5ac8b578b",
    "linux_arm64": "196ddaa51b716c2e99aeb2916b0a2bf55bb317494c4acdcefab36c383de950ba",
    "darwin_amd64": "9558d55a9d8bc40e22018379ea645251f803f9e2d69e7a7a2fd1588f98f8ef43",
    "darwin_arm64": "eb24c3c172a20056d83379496500b92600a992f68e8ae2e27d128ce1f36d7a92",
}
RELEASE_URL = "https://github.com/fatedier/frp/releases/download/v{v}/frp_{v}_{plat}.tar.gz"


class FrpcUnavailable(RuntimeError):
    pass


def _platform_key() -> str:
    system = platform.system().lower()
    machine = platform.machine().lower()
    arch = {"x86_64": "amd64", "amd64": "amd64", "arm64": "arm64", "aarch64": "arm64"}.get(machine)
    if system not in ("linux", "darwin") or arch is None:
        raise FrpcUnavailable(f"No frpc build for {system}/{machine}")
    return f"{system}_{arch}"


def cache_dir() -> Path:
    env_dir = env_str("PRIME_CONFIG_DIR")
    base = Path(env_dir) if env_dir else Path.home() / ".prime"
    return base / "bin"


def get_frpc_path(download: bool = True) -> Path:
    """Resolve the frpc binary: override > cache > (optional) download."""
    override = env_str("PRIME_FRPC_PATH")
    if override:
        path = Path(override)
        if not path.exists():
            raise FrpcUnavailable(f"PRIME_FRPC_PATH={override} does not exist")
        return path
    cached = cache_dir() / f"frpc-{FRPC_VERSION}"
    if cached.exists():
        return cached
    if not download:
        raise FrpcUnavailable("frpc not cached and download disabled")
    return _download_frpc(cached)


def _download_frpc(target: Path) -> Path:
    import httpx

    plat = _platform_key()
    expected = FRPC_CHECKSUMS.get(plat)
    if expected is None:
        raise FrpcUnavailable(f"No pinned checksum for platform {plat}")
    url = RELEASE_URL.format(v=FRPC_VERSION, plat=plat)
    try:
        response = httpx.get(url, follow_redirects=True, timeout=120.0)
        response.raise_for_status()
    except httpx.HTTPError as e:
        raise FrpcUnavailable(
            f"Could not download frpc from {url}: {e}. "
            "Set PRIME_FRPC_PATH to an existing frpc binary."
        ) from e
    data = response.content
    digest = hashlib.sha256(data).hexdigest()
    if digest != expected:
        raise FrpcUnavailable(
            f"frpc download checksum mismatch for {plat}: got {digest}, expected {expected}"
        )
    with tempfile.TemporaryDirectory() as tmp:
        archive = Path(tmp) / "frp.tar.gz"
        archive.write_bytes(data)
        with tarfile.open(archive) as tar:
            member = next((m for m in tar.getmembers() if m.name.endswith("/frpc")), None)
            if member is None:
                raise FrpcUnavailable(
                    f"frp release archive has no frpc binary (layout changed?): {url}"
                )
            tar.extract(member, tmp, filter="data")
            extracted = Path(tmp) / member.name
            target.parent.mkdir(parents=True, exist_ok=True)
            target.write_bytes(extracted.read_bytes())
            target.chmod(0o755)
    return target
