"""Tunnel lifecycle (reference: prime_tunnel/tunnel.py:59-498).

start(): register with the backend → write frpc TOML → spawn frpc → a reader
thread parses its log stream until success/error/timeout → poll registration.
stop(): delete the registration, terminate the process, clean the config.

``Tunnel`` (sync) and ``AsyncTunnel`` share a :class:`_TunnelOps` core that
owns all process-local machinery (config file, frpc subprocess, log reader);
only the control-plane calls and the wait primitive differ. Neither class
inherits from the other, so a function typed against one cannot receive the
other with silently-changed sync/async semantics.
"""

from __future__ import annotations

import os
import re
import subprocess
import tempfile
import threading
import time
from pathlib import Path
from typing import Any

from prime_tpu.core.client import APIClient
from prime_tpu.tunnel.binary import get_frpc_path

_LOG_SUCCESS_RE = re.compile(r"start proxy success|start tunnel success", re.IGNORECASE)
_LOG_ERROR_RE = re.compile(r"(start error|login to server failed|proxy .* start error|connect to server error)(.*)", re.IGNORECASE)

START_TIMEOUT_S = 30.0


class TunnelError(RuntimeError):
    pass


class _TunnelOps:
    """Sync process-local machinery shared by Tunnel and AsyncTunnel.

    Everything here is synchronous and fast (file writes, Popen, poll): the
    async wrapper only needs to push the final blocking process reap off the
    event loop.
    """

    def __init__(
        self,
        local_port: int,
        basic_auth: tuple[str, str] | None,
        frpc_path: str | Path | None,
    ) -> None:
        self.local_port = local_port
        self.basic_auth = basic_auth
        self._frpc_path = Path(frpc_path) if frpc_path else None
        self.registration: dict[str, Any] | None = None
        self.process: subprocess.Popen | None = None
        self._config_path: Path | None = None
        self._connected = threading.Event()
        self._error: str | None = None

    # -- launch steps (each may raise; caller owns rollback) -----------------

    def resolve_binary(self) -> Path:
        return self._frpc_path or get_frpc_path()

    def write_config(self, registration: dict[str, Any]) -> None:
        self.registration = registration
        lines = [
            f'serverAddr = "{registration["serverHost"]}"',
            f"serverPort = {registration['serverPort']}",
            f'auth.token = "{registration["frpToken"]}"',
            "",
            "[[proxies]]",
            f'name = "{registration["tunnelId"]}"',
            'type = "http"',
            f"localPort = {self.local_port}",
            f'customDomains = ["{registration["hostname"]}"]',
        ]
        if self.basic_auth:
            user, password = self.basic_auth
            lines += [f'httpUser = "{user}"', f'httpPassword = "{password}"']
        fd, path = tempfile.mkstemp(prefix="frpc-", suffix=".toml")
        os.close(fd)
        Path(path).write_text("\n".join(lines) + "\n")
        self._config_path = Path(path)

    def spawn(self, frpc: Path) -> None:
        self.process = subprocess.Popen(
            [str(frpc), "-c", str(self._config_path)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        threading.Thread(target=self._read_logs, daemon=True).start()

    def poll_step(self) -> str | None:
        """One wait-loop iteration: 'connected', an error string, or None."""
        if self._error:
            return f"frpc failed: {self._error}"
        if self._connected.is_set():
            return "connected"
        if self.process is not None and self.process.poll() is not None:
            return f"frpc exited with code {self.process.returncode}"
        return None

    # -- teardown ------------------------------------------------------------

    def terminate_process(self) -> None:
        if self.process and self.process.poll() is None:
            self.process.terminate()

    def reap_process(self) -> None:
        """Blocking: wait for the terminated process, kill on timeout."""
        if self.process and self.process.poll() is None:
            try:
                self.process.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.process.kill()

    def cleanup_config(self) -> None:
        if self._config_path and self._config_path.exists():
            self._config_path.unlink(missing_ok=True)

    def _read_logs(self) -> None:
        assert self.process is not None and self.process.stdout is not None
        for line in self.process.stdout:
            if _LOG_SUCCESS_RE.search(line):
                self._connected.set()
            match = _LOG_ERROR_RE.search(line)
            if match:
                self._error = line.strip()


class Tunnel:
    """Expose a local port through a managed frp tunnel."""

    def __init__(
        self,
        local_port: int,
        client: APIClient | None = None,
        basic_auth: tuple[str, str] | None = None,
        frpc_path: str | Path | None = None,
    ) -> None:
        self.api = client or APIClient()
        self._ops = _TunnelOps(local_port, basic_auth, frpc_path)

    @property
    def local_port(self) -> int:
        return self._ops.local_port

    @property
    def registration(self) -> dict[str, Any] | None:
        return self._ops.registration

    @property
    def process(self) -> subprocess.Popen | None:
        return self._ops.process

    @property
    def url(self) -> str | None:
        return self._ops.registration.get("url") if self._ops.registration else None

    # -- lifecycle -----------------------------------------------------------

    def start(self, timeout_s: float = START_TIMEOUT_S) -> str:
        """Register, launch frpc, wait for the proxy to come up. Returns URL."""
        ops = self._ops
        frpc = ops.resolve_binary()
        registration = self.api.post(
            "/tunnels", json={"localPort": ops.local_port}, idempotent_post=True
        )
        # past this point the server-side registration exists: any failure —
        # config write, spawn, frpc error, timeout — must roll it back
        try:
            ops.write_config(registration)
            ops.spawn(frpc)
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                state = ops.poll_step()
                if state == "connected":
                    return registration["url"]
                if state is not None:
                    raise TunnelError(state)
                time.sleep(0.1)
            raise TunnelError(f"Tunnel did not connect within {timeout_s}s")
        except BaseException:
            self.stop()
            raise

    def status(self) -> dict[str, Any]:
        if not self._ops.registration:
            return {"status": "NOT_STARTED"}
        remote = self.api.get(f"/tunnels/{self._ops.registration['tunnelId']}")
        remote["processAlive"] = self.process is not None and self.process.poll() is None
        return remote

    def stop(self) -> None:
        ops = self._ops
        if ops.registration:
            try:
                self.api.delete(f"/tunnels/{ops.registration['tunnelId']}")
            except Exception:
                pass
        ops.terminate_process()
        ops.reap_process()
        ops.cleanup_config()

    def __enter__(self) -> "Tunnel":
        self.start()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.stop()


class AsyncTunnel:
    """Async tunnel: same :class:`_TunnelOps` machinery, async control-plane
    calls, blocking process reap pushed off the event loop."""

    def __init__(
        self,
        local_port: int,
        client: Any = None,
        basic_auth: tuple[str, str] | None = None,
        frpc_path: str | Path | None = None,
    ) -> None:
        from prime_tpu.core.client import AsyncAPIClient

        self.api = client or AsyncAPIClient()
        self._ops = _TunnelOps(local_port, basic_auth, frpc_path)

    @property
    def local_port(self) -> int:
        return self._ops.local_port

    @property
    def registration(self) -> dict[str, Any] | None:
        return self._ops.registration

    @property
    def process(self) -> subprocess.Popen | None:
        return self._ops.process

    @property
    def url(self) -> str | None:
        return self._ops.registration.get("url") if self._ops.registration else None

    async def start(self, timeout_s: float = START_TIMEOUT_S) -> str:
        import anyio

        ops = self._ops
        frpc = ops.resolve_binary()
        registration = await self.api.post(
            "/tunnels", json={"localPort": ops.local_port}, idempotent_post=True
        )
        try:
            ops.write_config(registration)
            ops.spawn(frpc)
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                state = ops.poll_step()
                if state == "connected":
                    return registration["url"]
                if state is not None:
                    raise TunnelError(state)
                await anyio.sleep(0.05)
            raise TunnelError(f"Tunnel did not connect within {timeout_s}s")
        except BaseException:
            await self.stop()
            raise

    async def status(self) -> dict[str, Any]:
        if not self._ops.registration:
            return {"status": "NOT_STARTED"}
        remote = await self.api.get(f"/tunnels/{self._ops.registration['tunnelId']}")
        remote["processAlive"] = self.process is not None and self.process.poll() is None
        return remote

    async def stop(self) -> None:
        import anyio

        ops = self._ops
        if ops.registration:
            try:
                await self.api.delete(f"/tunnels/{ops.registration['tunnelId']}")
            except Exception:
                pass
        ops.terminate_process()
        # off the event loop: a hung frpc must not stall other tasks
        await anyio.to_thread.run_sync(ops.reap_process)
        ops.cleanup_config()

    async def __aenter__(self) -> "AsyncTunnel":
        await self.start()
        return self

    async def __aexit__(self, *exc: Any) -> None:
        await self.stop()
