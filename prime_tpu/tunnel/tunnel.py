"""Tunnel lifecycle (reference: prime_tunnel/tunnel.py:59-498).

start(): register with the backend → write frpc TOML → spawn frpc → a reader
thread parses its log stream until success/error/timeout → poll registration.
stop(): delete the registration, terminate the process, clean the config.
"""

from __future__ import annotations

import re
import subprocess
import tempfile
import threading
import time
from pathlib import Path
from typing import Any

from prime_tpu.core.client import APIClient
from prime_tpu.tunnel.binary import get_frpc_path

_LOG_SUCCESS_RE = re.compile(r"start proxy success|start tunnel success", re.IGNORECASE)
_LOG_ERROR_RE = re.compile(r"(start error|login to server failed|proxy .* start error|connect to server error)(.*)", re.IGNORECASE)

START_TIMEOUT_S = 30.0


class TunnelError(RuntimeError):
    pass


class Tunnel:
    """Expose a local port through a managed frp tunnel."""

    def __init__(
        self,
        local_port: int,
        client: APIClient | None = None,
        basic_auth: tuple[str, str] | None = None,
        frpc_path: str | Path | None = None,
    ) -> None:
        self.local_port = local_port
        self.api = client or APIClient()
        self.basic_auth = basic_auth
        self._frpc_path = Path(frpc_path) if frpc_path else None
        self.registration: dict[str, Any] | None = None
        self.process: subprocess.Popen | None = None
        self._config_path: Path | None = None
        self._connected = threading.Event()
        self._error: str | None = None

    @property
    def url(self) -> str | None:
        return self.registration.get("url") if self.registration else None

    # -- lifecycle -----------------------------------------------------------

    def start(self, timeout_s: float = START_TIMEOUT_S) -> str:
        """Register, launch frpc, wait for the proxy to come up. Returns URL."""
        frpc = self._frpc_path or get_frpc_path()
        self.registration = self.api.post(
            "/tunnels", json={"localPort": self.local_port}, idempotent_post=True
        )
        self._config_path = self._write_config(self.registration)
        try:
            self.process = subprocess.Popen(
                [str(frpc), "-c", str(self._config_path)],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        except OSError:
            self.stop()  # don't leak the server-side registration or the token file
            raise
        reader = threading.Thread(target=self._read_logs, daemon=True)
        reader.start()

        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._error:
                self.stop()
                raise TunnelError(f"frpc failed: {self._error}")
            if self._connected.is_set():
                return self.registration["url"]
            if self.process.poll() is not None:
                self.stop()
                raise TunnelError(f"frpc exited with code {self.process.returncode}")
            time.sleep(0.1)
        self.stop()
        raise TunnelError(f"Tunnel did not connect within {timeout_s}s")

    def status(self) -> dict[str, Any]:
        if not self.registration:
            return {"status": "NOT_STARTED"}
        remote = self.api.get(f"/tunnels/{self.registration['tunnelId']}")
        remote["processAlive"] = self.process is not None and self.process.poll() is None
        return remote

    def stop(self) -> None:
        if self.registration:
            try:
                self.api.delete(f"/tunnels/{self.registration['tunnelId']}")
            except Exception:
                pass
        if self.process and self.process.poll() is None:
            self.process.terminate()
            try:
                self.process.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.process.kill()
        if self._config_path and self._config_path.exists():
            self._config_path.unlink(missing_ok=True)

    def __enter__(self) -> "Tunnel":
        self.start()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    # -- internals -----------------------------------------------------------

    def _write_config(self, registration: dict[str, Any]) -> Path:
        lines = [
            f'serverAddr = "{registration["serverHost"]}"',
            f"serverPort = {registration['serverPort']}",
            f'auth.token = "{registration["frpToken"]}"',
            "",
            "[[proxies]]",
            f'name = "{registration["tunnelId"]}"',
            'type = "http"',
            f"localPort = {self.local_port}",
            f'customDomains = ["{registration["hostname"]}"]',
        ]
        if self.basic_auth:
            user, password = self.basic_auth
            lines += [f'httpUser = "{user}"', f'httpPassword = "{password}"']
        fd, path = tempfile.mkstemp(prefix="frpc-", suffix=".toml")
        Path(path).write_text("\n".join(lines) + "\n")
        import os

        os.close(fd)
        return Path(path)

    def _read_logs(self) -> None:
        assert self.process is not None and self.process.stdout is not None
        for line in self.process.stdout:
            if _LOG_SUCCESS_RE.search(line):
                self._connected.set()
            match = _LOG_ERROR_RE.search(line)
            if match:
                self._error = line.strip()


class AsyncTunnel(Tunnel):
    """Async tunnel: same process machinery as :class:`Tunnel` (thread-based
    frpc log reader), async control-plane calls, blocking waits pushed off the
    event loop via anyio.to_thread."""

    def __init__(
        self,
        local_port: int,
        client=None,
        basic_auth: tuple[str, str] | None = None,
        frpc_path: str | Path | None = None,
    ) -> None:
        from prime_tpu.core.client import AsyncAPIClient

        super().__init__(local_port, client=object(), basic_auth=basic_auth, frpc_path=frpc_path)
        self.api = client or AsyncAPIClient()

    async def start(self, timeout_s: float = START_TIMEOUT_S) -> str:  # type: ignore[override]
        import anyio

        frpc = self._frpc_path or get_frpc_path()
        self.registration = await self.api.post(
            "/tunnels", json={"localPort": self.local_port}, idempotent_post=True
        )
        self._config_path = self._write_config(self.registration)
        try:
            self.process = subprocess.Popen(
                [str(frpc), "-c", str(self._config_path)],
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        except OSError:
            await self.stop()
            raise
        threading.Thread(target=self._read_logs, daemon=True).start()

        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._error:
                await self.stop()
                raise TunnelError(f"frpc failed: {self._error}")
            if self._connected.is_set():
                return self.registration["url"]
            if self.process.poll() is not None:
                await self.stop()
                raise TunnelError(f"frpc exited with code {self.process.returncode}")
            await anyio.sleep(0.05)
        await self.stop()
        raise TunnelError(f"Tunnel did not connect within {timeout_s}s")

    async def status(self) -> dict[str, Any]:  # type: ignore[override]
        if not self.registration:
            return {"status": "NOT_STARTED"}
        remote = await self.api.get(f"/tunnels/{self.registration['tunnelId']}")
        remote["processAlive"] = self.process is not None and self.process.poll() is None
        return remote

    async def stop(self) -> None:  # type: ignore[override]
        import anyio

        if self.registration:
            try:
                await self.api.delete(f"/tunnels/{self.registration['tunnelId']}")
            except Exception:
                pass
        if self.process and self.process.poll() is None:
            self.process.terminate()

            def wait_reap() -> None:
                try:
                    self.process.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    self.process.kill()

            # off the event loop: a hung frpc must not stall other tasks
            await anyio.to_thread.run_sync(wait_reap)
        if self._config_path and self._config_path.exists():
            self._config_path.unlink(missing_ok=True)

    async def __aenter__(self) -> "AsyncTunnel":  # type: ignore[override]
        await self.start()
        return self

    async def __aexit__(self, *exc: Any) -> None:  # type: ignore[override]
        await self.stop()
