"""`prime lab mcp` — a minimal stdio MCP server forwarding Lab tools.

Reference: prime_cli/lab_mcp.py:19-23 (stdio server bridging Lab widget
tools). Speaks newline-delimited JSON-RPC 2.0: ``initialize``,
``tools/list``, ``tools/call``. Tools are read-only views over the same data
layer the shell uses, plus the hygiene preflight — an agent connected over
MCP sees exactly what the TUI shows.
"""

from __future__ import annotations

import json
import sys
from typing import Any, Callable, TextIO

PROTOCOL_VERSION = "2024-11-05"
SERVER_INFO = {"name": "prime-lab", "version": "1.0"}


def _tool(name: str, description: str, properties: dict | None = None) -> dict:
    return {
        "name": name,
        "description": description,
        "inputSchema": {
            "type": "object",
            "properties": properties or {},
        },
    }


def build_tools(workspace: str = ".") -> dict[str, tuple[dict, Callable[[dict], Any]]]:
    """name -> (tool schema, handler(arguments) -> JSON-able result)."""
    from prime_tpu.lab.data import LabDataSource

    def snapshot(args: dict) -> Any:
        source = LabDataSource(workspace)
        snap = source.refresh() if args.get("refresh") else source.snapshot()
        return {
            "localEvalRuns": snap.local_eval_runs,
            "installedEnvs": snap.installed_envs,
            "platform": snap.platform,
            "freshness": snap.freshness,
            "errors": snap.errors,
        }

    def eval_runs(args: dict) -> Any:
        return LabDataSource(workspace).scan_local_eval_runs()

    def launch_cards(args: dict) -> Any:
        from prime_tpu.lab.tui.launch import scan_cards

        return [
            {"name": c.name, "kind": c.kind, "file": c.path.name}
            for c in scan_cards(workspace)
        ]

    def hygiene(args: dict) -> Any:
        from prime_tpu.lab.hygiene import check_workspace

        return [f.as_dict() for f in check_workspace(workspace)]

    def training_runs(args: dict) -> Any:
        rows = LabDataSource(workspace).scan_local_training_runs()
        # metrics arrays can be thousands of rows; agents get the summary +
        # the last row, and can chart via the lab_widget_show_chart tool
        out = []
        for row in rows:
            metrics = row.get("metrics") or []
            out.append(
                {k: v for k, v in row.items() if k != "metrics"}
                | {"lastMetrics": metrics[-1] if metrics else {}, "numRows": len(metrics)}
            )
        return out

    def eval_samples(args: dict) -> Any:
        from pathlib import Path

        from prime_tpu.lab.data import read_jsonl

        run_id = str(args.get("runId", ""))
        limit = int(args.get("limit", 50) or 50)
        rows = LabDataSource(workspace).scan_local_eval_runs()
        if not run_id and rows:
            # no runId means "the run of interest" = the NEWEST, not whichever
            # sorts first alphabetically
            rows = [max(rows, key=lambda r: Path(r["dir"]).stat().st_mtime)]
        for row in rows:
            if not run_id or row["runId"] == run_id:
                return read_jsonl(Path(row["dir"]) / "results.jsonl")[:limit]
        return {"error": f"no local run {run_id!r}"}

    def widget_handler(name: str) -> Callable[[dict], Any]:
        """Widget tool calls from MCP agents land in the workspace widget
        journal (.prime-lab/widgets.jsonl); the shell's chat screen renders
        the same contract natively when the agent speaks a chat dialect."""

        def handle(args: dict) -> Any:
            # the typed model repairs what it can (the journal gets the
            # NORMALIZED payload) and reports why when it can't — the agent
            # sees which repairs were applied and can correct next call
            from prime_tpu.lab.widget_model import WidgetValidationError, normalize_widget_call

            try:
                normalized = normalize_widget_call(name, args)
            except WidgetValidationError as e:
                return {"status": "invalid", "error": str(e)}
            from pathlib import Path

            journal = Path(workspace) / ".prime-lab" / "widgets.jsonl"
            journal.parent.mkdir(parents=True, exist_ok=True)
            with open(journal, "a") as f:
                f.write(json.dumps({"name": name, "args": normalized.args}) + "\n")
            result: dict[str, Any] = {"status": "rendered", "widget": name}
            if normalized.repairs:
                result["repairs"] = list(normalized.repairs)
            return result

        return handle

    from prime_tpu.lab.widgets import WIDGET_TOOLS

    widget_entries = {
        f"lab_widget_{tool.name}": (
            {
                "name": f"lab_widget_{tool.name}",
                "description": tool.description,
                "inputSchema": {
                    "type": "object",
                    "properties": tool.properties,
                    "required": list(tool.required),
                },
            },
            widget_handler(tool.name),
        )
        for tool in WIDGET_TOOLS
    }

    return {
        **widget_entries,
        "lab_training_runs": (
            _tool("lab_training_runs", "Local training runs: last metrics row + counts."),
            training_runs,
        ),
        "lab_eval_samples": (
            _tool(
                "lab_eval_samples",
                "Per-sample records (prompt/completion/reward) of a local eval run.",
                {"runId": {"type": "string"}, "limit": {"type": "integer"}},
            ),
            eval_samples,
        ),
        "lab_snapshot": (
            _tool(
                "lab_snapshot",
                "Full Lab snapshot: local eval runs, installed envs, platform sections.",
                {"refresh": {"type": "boolean", "description": "Hydrate from the platform first."}},
            ),
            snapshot,
        ),
        "lab_eval_runs": (
            _tool("lab_eval_runs", "Local eval run directories with metrics."),
            eval_runs,
        ),
        "lab_launch_cards": (
            _tool("lab_launch_cards", "Launch config cards under .prime-lab/launch/."),
            launch_cards,
        ),
        "lab_hygiene": (
            _tool("lab_hygiene", "Workspace hygiene findings (secrets, outputs, large files)."),
            hygiene,
        ),
    }


def handle_request(request: dict, tools: dict) -> dict | None:
    """One JSON-RPC request -> response dict (None for notifications)."""
    request_id = request.get("id")
    method = request.get("method")

    def ok(result: Any) -> dict:
        return {"jsonrpc": "2.0", "id": request_id, "result": result}

    def err(code: int, message: str) -> dict:
        return {"jsonrpc": "2.0", "id": request_id, "error": {"code": code, "message": message}}

    if method == "initialize":
        return ok(
            {
                "protocolVersion": PROTOCOL_VERSION,
                "serverInfo": SERVER_INFO,
                "capabilities": {"tools": {}},
            }
        )
    if method == "notifications/initialized":
        return None
    if method == "tools/list":
        return ok({"tools": [schema for schema, _ in tools.values()]})
    if method == "tools/call":
        params = request.get("params")
        if not isinstance(params, dict):
            return err(-32602, "params must be an object")
        name = params.get("name")
        if name not in tools:
            return err(-32602, f"unknown tool {name!r}")
        _, handler = tools[name]
        arguments = params.get("arguments")
        try:
            result = handler(arguments if isinstance(arguments, dict) else {})
            text = json.dumps(result)  # serialization failures are tool errors too
        except Exception as e:  # noqa: BLE001 — tool errors go back over the wire
            return ok({"content": [{"type": "text", "text": f"error: {e}"}], "isError": True})
        return ok({"content": [{"type": "text", "text": text}]})
    if request_id is None:
        return None  # unknown notification: ignore
    return err(-32601, f"method {method!r} not found")


def serve(workspace: str = ".", stdin: TextIO | None = None, stdout: TextIO | None = None) -> None:
    """Blocking stdio loop: one JSON-RPC message per line."""
    stdin = stdin or sys.stdin
    stdout = stdout or sys.stdout
    tools = build_tools(workspace)
    for line in stdin:
        line = line.strip()
        if not line:
            continue
        try:
            request = json.loads(line)
        except json.JSONDecodeError:
            response: dict | None = {
                "jsonrpc": "2.0", "id": None,
                "error": {"code": -32700, "message": "parse error"},
            }
        else:
            if isinstance(request, dict):
                response = handle_request(request, tools)
            else:
                # scalars and JSON-RPC batch arrays: reject, don't crash
                response = {
                    "jsonrpc": "2.0", "id": None,
                    "error": {"code": -32600, "message": "request must be an object"},
                }
        if response is not None:
            stdout.write(json.dumps(response) + "\n")
            stdout.flush()
