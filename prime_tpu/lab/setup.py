"""Lab workspace bootstrap (reference: lab_setup.py:44-50, 1,878 LoC).

The reference downloads skills + config templates from a GitHub repo at a
pinned ref and writes agent-native surface files. This environment is
zero-egress, so the equivalent content ships **bundled**: canonical skill
documents and an agent guide live in this module, and setup materializes
them into the workspace plus one surface file per agent flavor
(CLAUDE.md / AGENTS.md / .cursor rules).

Surface files are written idempotently between marker comments: user content
outside the markers is never touched, and re-running setup refreshes only the
generated block (the reference achieves the same with its pinned-ref
re-sync).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path

MARKER_BEGIN = "<!-- prime-lab:begin generated -->"
MARKER_END = "<!-- prime-lab:end generated -->"

LAB_TOML = """\
[lab]
version = 1
sections = ["evals", "training", "environments", "pods", "sandboxes"]
"""

AGENT_GUIDE = """\
## Prime Lab workspace

This workspace is managed with the `prime` CLI (TPU compute platform).

- Always pass `--plain` (or `--output json`) to `prime` commands: tables are
  for humans, plain/json output is stable for tooling.
- Evals: `prime eval run <env> -m <model> --plain` runs locally on the TPU;
  add `--slice v5e-8` to shard a large model over the slice. Results land in
  `outputs/evals/<env>--<model>/<run>/` (metadata.json + results.jsonl) and
  push to the hub unless `--no-push`.
- Environments: `prime env init <name>` scaffolds; `prime env push` uploads;
  `prime env install <name>` makes a hub env runnable; an environment is a
  module exposing `load_environment()` -> examples + scorer.
- Training: `prime train <config.toml>` submits a hosted run; follow with
  `prime train logs <id> -f`.
- Compute: `prime pods create` provisions TPU slices, `prime sandbox create`
  gives a JAX/libtpu sandbox, `prime tunnel start <port>` exposes local ports.
- Never commit `outputs/`, `.prime-lab/cache/`, or `.env` — setup keeps them
  gitignored; run `prime lab hygiene` before pushing.
"""

SKILLS: dict[str, str] = {
    "running-evals.md": """\
# Skill: running evals

1. Resolve the environment: local dir with env.toml > installed > hub slug.
2. `prime eval run <env> -m <model> -n <limit> --plain` (add `--no-push` for
   scratch runs; `--slice v5e-8 --tp 4` for sharded models).
3. Inspect with `prime eval view --plain` (newest run) and push later with
   `prime eval push`.
""",
    "publishing-environments.md": """\
# Skill: publishing environments

1. `prime env init my-env && cd my-env` — edit `load_environment()` to return
   {"examples": [{"prompt", "answer"}...], "score": fn}.
2. `prime env inspect . --plain` must report loadEnvironment=ok.
3. `prime env push --dir . --plain`; verify with `prime env actions list`.
""",
    "tpu-debugging.md": """\
# Skill: TPU debugging

- `prime pods status <id> --plain` and `prime pods connect <id>` for slices.
- Sandboxes: `prime sandbox run <id> -- python -c "import jax; print(jax.devices())"`.
- Multi-host slices expose one ssh target per worker; the same binary must
  run on every worker (`prime pods connect --all-workers`).
""",
    "training-locally.md": """\
# Skill: local training

1. SFT: `prime train local --model tiny-test --steps 100 --plain` (add
   `--lora r=8` for adapters, `--resume` to continue from a checkpoint).
2. GRPO: `prime train local-rl <env> --model <m> --steps 50 --plain`; the
   env's `load_environment()` supplies prompts + the reward scorer.
3. Metrics land in metrics.jsonl (charted by `prime lab`); checkpoints are
   orbax dirs under the run dir. `--profile` captures a jax.profiler trace.
""",
    "serving-models.md": """\
# Skill: serving models

1. `prime serve <model-or-checkpoint> --plain` starts the OpenAI-compatible
   endpoint; `--continuous` enables slot-based continuous batching with
   chunked prefill + prefix KV reuse.
2. Quantization: `--weight-quant` (int8 W8A16, fastest single-chip),
   `--kv-quant` (int8 KV cache). Speculative: `--speculative` (greedy: exact
   tokens; sampled: exact distribution; composes with --kv-quant).
3. Sharded: `--slice v5e-8 [--tp N]` shards over the slice mesh; MoE models
   carve an expert-parallel axis automatically.
""",
    "agent-widgets.md": """\
# Skill: Lab widget tools

Agents connected over MCP (`prime lab mcp`) or a chat dialect (codex /
letta / acp) can call native Lab widgets instead of printing text walls:
`choose` (picker), `show_table`, `show_chart` (sparkline), `launch_run`
(proposal card), `show_patch` (diff). Calls are validated against the
declared JSON schema; malformed calls render as widget errors, never crash.
""",
    "distributed-slices.md": """\
# Skill: distributed TPU slices

- Mesh policy: `--slice v5e-8` derives (dp, fsdp, tp); override with `--tp`.
- Long context: ring-attention sequence parallelism shards 16-32k prompts
  over the `sp` axis; chunked prefill keeps attention memory O(S*C).
- Multi-host: `jax.distributed` over DCN initializes from the pod metadata;
  collectives ride ICI within a slice.
""",
}

# Bump when SKILLS content changes: setup auto-refreshes bundled skills whose
# on-disk content still matches the PREVIOUS bundle (i.e. not locally edited).
SKILLS_VERSION = 3  # bump on ANY bundled skill content change (sync is version-keyed)

# agent flavor -> (guide surface path, MCP registration path or None).
# The guide rides the marked generated block; the MCP file registers
# `prime lab mcp` so the agent sees the Lab tools (reference lab_setup.py's
# multi-agent surface matrix role).
AGENT_SURFACES: dict[str, tuple[str, str | None]] = {
    "claude": ("CLAUDE.md", ".mcp.json"),
    "codex": ("AGENTS.md", None),
    "cursor": (".cursor/rules/prime-lab.mdc", ".cursor/mcp.json"),
    "gemini": ("GEMINI.md", None),
    "windsurf": (".windsurf/rules/prime-lab.md", None),
}

MCP_SERVER_ENTRY = {
    "command": "prime",
    "args": ["lab", "mcp"],
}

GITIGNORE_ENTRIES = ["outputs/", ".prime-lab/cache/", ".env"]

AGENTS_JSON_TEMPLATE = """\
{
  "_example": {"name": "my-agent", "dialect": "simple",
               "command": "python -u my_agent.py",
               "_dialects": "simple | acp | codex | letta"},
  "agents": []
}
"""


@dataclass
class SetupReport:
    created: list[str] = field(default_factory=list)
    updated: list[str] = field(default_factory=list)
    unchanged: list[str] = field(default_factory=list)
    skipped: list[str] = field(default_factory=list)   # locally-modified skills
    hygiene: list[dict] = field(default_factory=list)  # preflight findings

    def as_dict(self) -> dict:
        return {
            "created": self.created,
            "updated": self.updated,
            "unchanged": self.unchanged,
            "skipped": self.skipped,
            "hygiene": self.hygiene,
        }


def _write_generated_block(path: Path, body: str, report: SetupReport) -> None:
    """Create or refresh the marked generated block, preserving user text."""
    block = f"{MARKER_BEGIN}\n{body.rstrip()}\n{MARKER_END}\n"
    if not path.exists():
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(block)
        report.created.append(str(path))
        return
    text = path.read_text()
    if MARKER_BEGIN in text and MARKER_END in text:
        head, _, rest = text.partition(MARKER_BEGIN)
        _, _, tail = rest.partition(MARKER_END)
        new_text = head + block.rstrip("\n") + tail
    else:
        # surface exists but was never generated: append our block at the end
        new_text = text.rstrip("\n") + "\n\n" + block
    if new_text == text:
        report.unchanged.append(str(path))
    else:
        path.write_text(new_text)
        report.updated.append(str(path))


def _write_once(path: Path, content: str, report: SetupReport, force: bool = False) -> None:
    if path.exists() and not force:
        report.unchanged.append(str(path))
        return
    path.parent.mkdir(parents=True, exist_ok=True)
    existed = path.exists()
    path.write_text(content)
    (report.updated if existed else report.created).append(str(path))


def _sync_skills(ws: Path, report: SetupReport, force: bool) -> None:
    """Versioned skill-bundle sync (reference lab_setup.py's pinned-ref
    re-sync role). A manifest records the bundle version + per-file content
    hash at write time; on version bump, files still matching their RECORDED
    hash (never locally edited) refresh automatically, edited files are kept
    and reported as skipped. ``force`` overwrites everything."""
    import hashlib
    import json

    skills_dir = ws / ".prime-lab" / "skills"
    manifest_path = skills_dir / "MANIFEST.json"
    manifest: dict = {}
    if manifest_path.exists():
        try:
            loaded = json.loads(manifest_path.read_text())
            if isinstance(loaded, dict):
                manifest = loaded
        except (OSError, json.JSONDecodeError):
            manifest = {}
    recorded_version = manifest.get("version", 0)
    if isinstance(recorded_version, int) and recorded_version > SKILLS_VERSION and not force:
        # downgrade guard: a NEWER bundle (written by a newer CLI, possibly
        # committed by a teammate) must not be reverted by an older CLI — the
        # whole sync is skipped, manifest untouched
        report.skipped.append(
            f"{skills_dir} (bundle v{recorded_version} is newer than this CLI's "
            f"v{SKILLS_VERSION}; upgrade prime-tpu or pass --force-skills)"
        )
        return
    recorded_hashes = manifest.get("files", {})
    if not isinstance(recorded_hashes, dict):
        recorded_hashes = {}
    digest = lambda text: hashlib.sha256(text.encode()).hexdigest()  # noqa: E731

    for name, content in SKILLS.items():
        path = skills_dir / name
        if not path.exists() or force:
            _write_once(path, content, report, force=force)
            continue
        on_disk = path.read_text()
        if on_disk == content:
            report.unchanged.append(str(path))
        elif recorded_hashes.get(name) == digest(on_disk):
            # pristine copy of an older bundle: safe to refresh
            path.write_text(content)
            report.updated.append(str(path))
        else:
            report.skipped.append(f"{path} (locally modified; --force-skills to overwrite)")

    new_manifest = {
        "version": SKILLS_VERSION,
        "files": {name: digest(content) for name, content in SKILLS.items()},
    }
    serialized = json.dumps(new_manifest, indent=2) + "\n"
    if not manifest_path.exists() or manifest_path.read_text() != serialized:
        manifest_path.parent.mkdir(parents=True, exist_ok=True)
        existed = manifest_path.exists()
        manifest_path.write_text(serialized)
        (report.updated if existed else report.created).append(str(manifest_path))


def _register_mcp(ws: Path, mcp_path: str, report: SetupReport) -> None:
    """Merge the prime-lab MCP server into the agent's MCP config (additive:
    other servers in the file are preserved)."""
    import json

    path = ws / mcp_path
    config: dict = {}
    if path.exists():
        try:
            loaded = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError):
            report.skipped.append(f"{path} (unparseable; not touching it)")
            return
        if not isinstance(loaded, dict):
            # valid JSON but not an object: overwriting would destroy it
            report.skipped.append(f"{path} (not a JSON object; not touching it)")
            return
        config = loaded
    servers = config.setdefault("mcpServers", {})
    if not isinstance(servers, dict):
        report.skipped.append(f"{path} (mcpServers is not an object; not touching it)")
        return
    if servers.get("prime-lab") == MCP_SERVER_ENTRY:
        report.unchanged.append(str(path))
        return
    servers["prime-lab"] = MCP_SERVER_ENTRY
    existed = path.exists()
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(config, indent=2) + "\n")
    (report.updated if existed else report.created).append(str(path))


def setup_workspace(
    workspace: str | Path = ".",
    agents: tuple[str, ...] = ("claude", "codex"),
    force_skills: bool = False,
) -> SetupReport:
    """Materialize the Lab workspace in one pass: config, launch dir,
    versioned skill bundle, agent-surface matrix (guide block + MCP
    registration per flavor), chat-agent config, gitignore hygiene, and a
    hygiene preflight. Idempotent; returns what changed."""
    ws = Path(workspace)
    ws.mkdir(parents=True, exist_ok=True)
    report = SetupReport()

    _write_once(ws / ".prime-lab" / "lab.toml", LAB_TOML, report)
    _write_once(ws / ".prime-lab" / "agents.json", AGENTS_JSON_TEMPLATE, report)
    launch = ws / ".prime-lab" / "launch"
    if not launch.exists():
        launch.mkdir(parents=True)
        report.created.append(str(launch))

    _sync_skills(ws, report, force=force_skills)

    unknown = [a for a in agents if a not in AGENT_SURFACES]
    if unknown:
        raise ValueError(f"unknown agent flavor(s) {unknown}; choose from {sorted(AGENT_SURFACES)}")
    for agent in agents:
        surface, mcp_path = AGENT_SURFACES[agent]
        _write_generated_block(ws / surface, AGENT_GUIDE, report)
        if mcp_path:
            _register_mcp(ws, mcp_path, report)

    gitignore = ws / ".gitignore"
    existed = gitignore.exists()
    if append_gitignore(ws, GITIGNORE_ENTRIES):
        (report.updated if existed else report.created).append(str(gitignore))

    # hygiene preflight in the same pass: setup ends with a verdict on the
    # workspace, not just files written
    try:
        from prime_tpu.lab.hygiene import check_workspace

        report.hygiene = [f.as_dict() for f in check_workspace(ws)]
    except Exception as e:  # noqa: BLE001 - hygiene must not fail setup
        report.hygiene = [{"severity": "error", "code": "hygiene-crashed", "message": str(e)}]

    return report


def append_gitignore(workspace: str | Path, entries: list[str]) -> list[str]:
    """Append missing entries to the workspace .gitignore (additive only).
    Shared by setup and hygiene --fix. Returns the entries actually added."""
    gitignore = Path(workspace) / ".gitignore"
    text = gitignore.read_text() if gitignore.exists() else ""
    existing = text.splitlines()
    additions = [e for e in dict.fromkeys(entries) if e and e not in existing]
    if additions:
        with open(gitignore, "a") as f:
            if text and not text.endswith("\n"):
                f.write("\n")  # don't glue onto an unterminated last line
            for entry in additions:
                f.write(entry + "\n")
    return additions
