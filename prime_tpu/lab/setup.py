"""Lab workspace bootstrap (reference: lab_setup.py:44-50, 1,878 LoC).

The reference downloads skills + config templates from a GitHub repo at a
pinned ref and writes agent-native surface files. This environment is
zero-egress, so the equivalent content ships **bundled**: canonical skill
documents and an agent guide live in this module, and setup materializes
them into the workspace plus one surface file per agent flavor
(CLAUDE.md / AGENTS.md / .cursor rules).

Surface files are written idempotently between marker comments: user content
outside the markers is never touched, and re-running setup refreshes only the
generated block (the reference achieves the same with its pinned-ref
re-sync).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path

MARKER_BEGIN = "<!-- prime-lab:begin generated -->"
MARKER_END = "<!-- prime-lab:end generated -->"

LAB_TOML = """\
[lab]
version = 1
sections = ["evals", "training", "environments", "pods", "sandboxes"]
"""

AGENT_GUIDE = """\
## Prime Lab workspace

This workspace is managed with the `prime` CLI (TPU compute platform).

- Always pass `--plain` (or `--output json`) to `prime` commands: tables are
  for humans, plain/json output is stable for tooling.
- Evals: `prime eval run <env> -m <model> --plain` runs locally on the TPU;
  add `--slice v5e-8` to shard a large model over the slice. Results land in
  `outputs/evals/<env>--<model>/<run>/` (metadata.json + results.jsonl) and
  push to the hub unless `--no-push`.
- Environments: `prime env init <name>` scaffolds; `prime env push` uploads;
  `prime env install <name>` makes a hub env runnable; an environment is a
  module exposing `load_environment()` -> examples + scorer.
- Training: `prime train <config.toml>` submits a hosted run; follow with
  `prime train logs <id> -f`.
- Compute: `prime pods create` provisions TPU slices, `prime sandbox create`
  gives a JAX/libtpu sandbox, `prime tunnel start <port>` exposes local ports.
- Never commit `outputs/`, `.prime-lab/cache/`, or `.env` — setup keeps them
  gitignored; run `prime lab hygiene` before pushing.
"""

SKILLS: dict[str, str] = {
    "running-evals.md": """\
# Skill: running evals

1. Resolve the environment: local dir with env.toml > installed > hub slug.
2. `prime eval run <env> -m <model> -n <limit> --plain` (add `--no-push` for
   scratch runs; `--slice v5e-8 --tp 4` for sharded models).
3. Inspect with `prime eval view --plain` (newest run) and push later with
   `prime eval push`.
""",
    "publishing-environments.md": """\
# Skill: publishing environments

1. `prime env init my-env && cd my-env` — edit `load_environment()` to return
   {"examples": [{"prompt", "answer"}...], "score": fn}.
2. `prime env inspect . --plain` must report loadEnvironment=ok.
3. `prime env push --dir . --plain`; verify with `prime env actions list`.
""",
    "tpu-debugging.md": """\
# Skill: TPU debugging

- `prime pods status <id> --plain` and `prime pods connect <id>` for slices.
- Sandboxes: `prime sandbox run <id> -- python -c "import jax; print(jax.devices())"`.
- Multi-host slices expose one ssh target per worker; the same binary must
  run on every worker (`prime pods connect --all-workers`).
""",
}

# agent flavor -> surface path (relative to workspace)
AGENT_SURFACES: dict[str, str] = {
    "claude": "CLAUDE.md",
    "codex": "AGENTS.md",
    "cursor": ".cursor/rules/prime-lab.mdc",
}

GITIGNORE_ENTRIES = ["outputs/", ".prime-lab/cache/", ".env"]


@dataclass
class SetupReport:
    created: list[str] = field(default_factory=list)
    updated: list[str] = field(default_factory=list)
    unchanged: list[str] = field(default_factory=list)

    def as_dict(self) -> dict:
        return {"created": self.created, "updated": self.updated, "unchanged": self.unchanged}


def _write_generated_block(path: Path, body: str, report: SetupReport) -> None:
    """Create or refresh the marked generated block, preserving user text."""
    block = f"{MARKER_BEGIN}\n{body.rstrip()}\n{MARKER_END}\n"
    if not path.exists():
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(block)
        report.created.append(str(path))
        return
    text = path.read_text()
    if MARKER_BEGIN in text and MARKER_END in text:
        head, _, rest = text.partition(MARKER_BEGIN)
        _, _, tail = rest.partition(MARKER_END)
        new_text = head + block.rstrip("\n") + tail
    else:
        # surface exists but was never generated: append our block at the end
        new_text = text.rstrip("\n") + "\n\n" + block
    if new_text == text:
        report.unchanged.append(str(path))
    else:
        path.write_text(new_text)
        report.updated.append(str(path))


def _write_once(path: Path, content: str, report: SetupReport, force: bool = False) -> None:
    if path.exists() and not force:
        report.unchanged.append(str(path))
        return
    path.parent.mkdir(parents=True, exist_ok=True)
    existed = path.exists()
    path.write_text(content)
    (report.updated if existed else report.created).append(str(path))


def setup_workspace(
    workspace: str | Path = ".",
    agents: tuple[str, ...] = ("claude", "codex"),
    force_skills: bool = False,
) -> SetupReport:
    """Materialize the Lab workspace: config, launch dir, skills, agent
    surfaces, gitignore hygiene. Idempotent; returns what changed."""
    ws = Path(workspace)
    ws.mkdir(parents=True, exist_ok=True)
    report = SetupReport()

    _write_once(ws / ".prime-lab" / "lab.toml", LAB_TOML, report)
    launch = ws / ".prime-lab" / "launch"
    if not launch.exists():
        launch.mkdir(parents=True)
        report.created.append(str(launch))

    for name, content in SKILLS.items():
        _write_once(ws / ".prime-lab" / "skills" / name, content, report, force=force_skills)

    unknown = [a for a in agents if a not in AGENT_SURFACES]
    if unknown:
        raise ValueError(f"unknown agent flavor(s) {unknown}; choose from {sorted(AGENT_SURFACES)}")
    for agent in agents:
        _write_generated_block(ws / AGENT_SURFACES[agent], AGENT_GUIDE, report)

    gitignore = ws / ".gitignore"
    existed = gitignore.exists()
    if append_gitignore(ws, GITIGNORE_ENTRIES):
        (report.updated if existed else report.created).append(str(gitignore))

    return report


def append_gitignore(workspace: str | Path, entries: list[str]) -> list[str]:
    """Append missing entries to the workspace .gitignore (additive only).
    Shared by setup and hygiene --fix. Returns the entries actually added."""
    gitignore = Path(workspace) / ".gitignore"
    text = gitignore.read_text() if gitignore.exists() else ""
    existing = text.splitlines()
    additions = [e for e in dict.fromkeys(entries) if e and e not in existing]
    if additions:
        with open(gitignore, "a") as f:
            if text and not text.endswith("\n"):
                f.write("\n")  # don't glue onto an unterminated last line
            for entry in additions:
                f.write(entry + "\n")
    return additions
