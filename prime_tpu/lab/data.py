"""Lab snapshot assembly (reference: prime_lab_app/data.py LabDataSource:54).

Local-first: ``snapshot()`` returns instantly from the local workspace scan +
disk cache; ``refresh()`` hydrates platform sections through the real clients
and re-caches. Sections: evals (hub + local outputs/evals runs), training
runs, environments (hub + installed), pods, sandboxes.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

from prime_tpu.lab.cache import LabCache

PLATFORM_SECTIONS = ("evals", "training", "environments", "pods", "sandboxes")


def read_jsonl(path: Path) -> list[dict[str, Any]]:
    """Tolerant JSONL read: skip blank and unparseable lines (a mid-append
    tail line must not discard the parsed rows). Shared by the data source
    and the detail screens."""
    rows: list[dict[str, Any]] = []
    try:
        text = path.read_text()
    except OSError:
        return rows
    for line in text.splitlines():
        if not line.strip():
            continue
        try:
            loaded = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(loaded, dict):
            rows.append(loaded)
    return rows


@dataclass
class LabSnapshot:
    local_eval_runs: list[dict[str, Any]] = field(default_factory=list)
    local_training_runs: list[dict[str, Any]] = field(default_factory=list)
    installed_envs: dict[str, Any] = field(default_factory=dict)
    platform: dict[str, Any] = field(default_factory=dict)      # section -> rows
    freshness: dict[str, bool] = field(default_factory=dict)    # section -> fresh?
    errors: dict[str, str] = field(default_factory=dict)        # section -> fetch error


class LabDataSource:
    def __init__(self, workspace: str | Path = ".", api_client=None, cache: LabCache | None = None) -> None:
        self.workspace = Path(workspace)
        self.cache = cache or LabCache(workspace)
        self._api = api_client
        self._metrics_cache: dict[str, tuple[tuple[int, int], list[dict[str, Any]]]] = {}

    # -- local scans (no network, always fresh) ------------------------------

    def scan_local_eval_runs(self) -> list[dict[str, Any]]:
        runs = []
        base = self.workspace / "outputs" / "evals"
        if not base.exists():
            return runs
        for env_model_dir in sorted(base.iterdir()):
            if not env_model_dir.is_dir() or "--" not in env_model_dir.name:
                continue
            env, _, model = env_model_dir.name.partition("--")
            for run_dir in sorted(env_model_dir.iterdir()):
                metadata_path = run_dir / "metadata.json"
                if not metadata_path.exists():
                    continue
                try:
                    metadata = json.loads(metadata_path.read_text())
                except json.JSONDecodeError:
                    continue
                if not isinstance(metadata, dict):
                    continue
                metrics = metadata.get("metrics")
                metrics = metrics if isinstance(metrics, dict) else {}
                runs.append(
                    {
                        "env": env,
                        "model": model,
                        "runId": run_dir.name,
                        "accuracy": metrics.get("accuracy"),
                        "samples": metrics.get("num_samples"),
                        "dir": str(run_dir),
                    }
                )
        return runs

    def scan_installed_envs(self) -> dict[str, Any]:
        from prime_tpu.envhub.local import read_registry

        return read_registry()

    def scan_local_training_runs(self) -> list[dict[str, Any]]:
        """Local training runs = dirs holding a metrics.jsonl (train_loop's
        output): outputs/train/<run>/ plus the workspace root. Parsed rows are
        cached on (mtime, size) — the TUI rescans every idle tick and a long
        run's file must not be re-parsed each time."""
        runs = []
        candidates = [self.workspace]
        train_base = self.workspace / "outputs" / "train"
        if train_base.exists():
            candidates += sorted(p for p in train_base.iterdir() if p.is_dir())
        for run_dir in candidates:
            path = run_dir / "metrics.jsonl"
            if not path.exists():
                continue
            try:
                stat = path.stat()
                stamp = (stat.st_mtime_ns, stat.st_size)
                cached = self._metrics_cache.get(str(path))
                if cached and cached[0] == stamp:
                    rows = cached[1]
                else:
                    rows = read_jsonl(path)
                    self._metrics_cache[str(path)] = (stamp, rows)
            except OSError:
                continue
            if not rows:
                continue
            last = rows[-1]
            runs.append(
                {
                    "run": run_dir.name if run_dir != self.workspace else "(workspace)",
                    "steps": last.get("step", len(rows) - 1),
                    "loss": last.get("loss"),
                    "tokPerSec": last.get("tokens_per_sec"),
                    "dir": str(run_dir),
                    "metrics": rows,
                }
            )
        return runs

    # -- snapshot ------------------------------------------------------------

    def snapshot(self) -> LabSnapshot:
        """Instant: local scans + whatever the cache holds (possibly stale)."""
        snap = LabSnapshot(
            local_eval_runs=self.scan_local_eval_runs(),
            local_training_runs=self.scan_local_training_runs(),
            installed_envs=self.scan_installed_envs(),
        )
        for section in PLATFORM_SECTIONS:
            rows, fresh = self.cache.get(section)
            snap.platform[section] = rows or []
            snap.freshness[section] = fresh
        return snap

    def refresh(self, sections: tuple[str, ...] = PLATFORM_SECTIONS) -> LabSnapshot:
        """Hydrate platform sections through the real clients, then snapshot.

        A dead section must not take down the others, but failures are
        recorded in snapshot.errors so callers can tell "empty" from "broken".
        Incoming rows are merged against the cached ones (progressive
        loading, reference snapshots.py:8 role): a list endpoint returning a
        lighter row shape must not wipe richer fields a previous fetch (or a
        detail hydration) already cached for the same id.
        """
        if self._api is None:
            import prime_tpu.commands._deps as deps

            self._api = deps.build_client()
        fetchers = {
            "evals": self._fetch_evals,
            "training": self._fetch_training,
            "environments": self._fetch_environments,
            "pods": self._fetch_pods,
            "sandboxes": self._fetch_sandboxes,
        }
        errors: dict[str, str] = {}
        for section in sections:
            # the whole fetch→merge→cache pipeline stays inside the guard: a
            # corrupt cache file or unwritable cache dir is a per-section
            # failure too, not a reason to abort the other sections
            try:
                incoming = fetchers[section]()
                previous, _ = self.cache.get(section)
                self.cache.put(section, merge_rows(previous or [], incoming))
            except Exception as e:
                errors[section] = str(e)
        snap = self.snapshot()
        snap.errors = errors
        return snap

    def _fetch_evals(self) -> list[dict[str, Any]]:
        from prime_tpu.evals import EvalsClient

        return [e.model_dump(by_alias=True) for e in EvalsClient(self._api).list_evaluations()]

    def _fetch_training(self) -> list[dict[str, Any]]:
        from prime_tpu.api.rl import RLClient

        return [r.model_dump(by_alias=True) for r in RLClient(self._api).list_runs()]

    def _fetch_environments(self) -> list[dict[str, Any]]:
        from prime_tpu.envhub import EnvHubClient

        return EnvHubClient(self._api).list()

    def _fetch_pods(self) -> list[dict[str, Any]]:
        from prime_tpu.api.pods import PodsClient

        return [p.model_dump(by_alias=True) for p in PodsClient(self._api).list()]

    def _fetch_sandboxes(self) -> list[dict[str, Any]]:
        from prime_tpu.sandboxes.client import SandboxClient

        client = SandboxClient(client=self._api)
        return [s.model_dump(by_alias=True) for s in client.list(limit=50)]


_ROW_ID_KEYS = ("id", "evalId", "runId", "podId", "sandboxId", "name")


def _row_id(row: dict[str, Any]) -> str | None:
    for key in _ROW_ID_KEYS:
        value = row.get(key)
        if value:
            return f"{key}={value}"
    return None


def merge_rows(
    previous: list[dict[str, Any]], incoming: list[dict[str, Any]]
) -> list[dict[str, Any]]:
    """Progressive-loading merge (reference snapshots.py:8 merge_snapshot_rows
    role). The incoming list is authoritative for ORDER and MEMBERSHIP (a row
    the backend no longer returns is gone — deletions must propagate); for a
    row present in both, incoming NON-None values win per field. An incoming
    explicit None never clobbers a cached value: the fetchers dump pydantic
    models without exclude_none, so a lighter list response emits its
    unpopulated optional fields as None — exactly the fields a richer earlier
    fetch may have filled. Rows without any recognizable id pass through."""
    by_id: dict[str, dict[str, Any]] = {}
    for row in previous:
        if isinstance(row, dict):
            row_id = _row_id(row)
            if row_id is not None:
                by_id[row_id] = row
    merged: list[dict[str, Any]] = []
    for row in incoming:
        old = by_id.get(_row_id(row)) if isinstance(row, dict) else None
        if old is None:
            merged.append(row)
            continue
        combined = dict(old)
        for key, value in row.items():
            if value is None and combined.get(key) is not None:
                continue
            combined[key] = value
        merged.append(combined)
    return merged
