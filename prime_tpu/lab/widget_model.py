"""Typed widget-payload model: normalize/repair/reject agent widget calls.

Reference role: prime_lab_app/agent_widget_model.py:1-1168 + agent_cards.py
:1-536 — the layer between raw agent tool-call JSON and the TUI. Agents emit
malformed payloads constantly (numbers as strings, null holes, scalar where
an array belongs, 10k-row tables); the previous shallow check
(widgets.validate_widget_call) only gated types, so anything past it was
rendered best-effort. This module gives every widget a typed contract:

- **repair** what is safely repairable — coerce numeric strings, stringify
  scalar options, drop null/empty/non-finite entries, dedupe, cap sizes —
  and RECORD each repair so the TUI can show "repaired: ..." instead of
  silently rendering something the agent didn't say;
- **reject** what isn't — unknown tool, missing required keys, payloads
  empty after repair — with a reason string the chat renders as an error
  widget (never a crash, never a silent misrender);
- **round-trip state**: the stamps the chat screen writes back into a
  rendered widget's args (``selected``, ``saved_card``) survive
  re-normalization, so re-rendering a transcript keeps interaction state;
- **card lifecycle**: a normalized ``launch_run`` payload converts to a
  typed launch-card payload (kind mapped onto the card taxonomy, numerics
  actually numeric) so the card on disk — and the TOML the user edits —
  has real types, not stringly-typed leftovers.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any

MAX_OPTIONS = 24
MAX_ROWS = 100
MAX_POINTS = 512
MAX_PATCH_LINES = 400
# launch-config fields that must be numeric on the card; agents routinely
# send them as strings ("limit": "64")
INT_CONFIG_FIELDS = ("limit", "batch_size", "max_new_tokens", "epochs", "draft_len", "seed")
FLOAT_CONFIG_FIELDS = ("temperature", "learning_rate", "top_p", "beta", "clip_eps")
# stamps the chat screen writes back into rendered args; normalization must
# carry them through unchanged (widget state round-trip)
STATE_KEYS = ("selected", "saved_card")


class WidgetValidationError(Exception):
    """The payload is unusable even after repair; the message says why."""


@dataclass
class NormalizedWidget:
    name: str
    args: dict[str, Any]
    repairs: tuple[str, ...] = ()

    def with_state_from(self, raw_args: dict[str, Any]) -> "NormalizedWidget":
        for key in STATE_KEYS:
            if isinstance(raw_args, dict) and key in raw_args:
                self.args[key] = raw_args[key]
        return self


def _coerce_number(value: Any) -> float | int | None:
    """A number, a numeric string, or None; NaN/inf count as unusable."""
    if isinstance(value, bool):
        return None
    if isinstance(value, (int, float)):
        return value if math.isfinite(value) else None
    if isinstance(value, str):
        text = value.strip()
        try:
            number = int(text)
        except ValueError:
            try:
                number = float(text)
            except ValueError:
                return None
        return number if math.isfinite(number) else None
    return None


def _title(args: dict[str, Any], repairs: list[str]) -> dict[str, Any]:
    out: dict[str, Any] = {}
    if "title" in args and args["title"] is not None:
        if isinstance(args["title"], str):
            out["title"] = args["title"]
        else:
            out["title"] = str(args["title"])
            repairs.append("title coerced to string")
    return out


def _normalize_choose(args: dict[str, Any], repairs: list[str]) -> dict[str, Any]:
    raw = args.get("options")
    if not isinstance(raw, list):
        raise WidgetValidationError("choose: options must be an array of strings")
    options: list[str] = []
    for item in raw:
        if item is None:
            repairs.append("dropped null option")
            continue
        text = item if isinstance(item, str) else str(item)
        if not isinstance(item, str):
            repairs.append(f"option {text[:20]!r} coerced to string")
        text = text.strip()
        if not text:
            repairs.append("dropped empty option")
            continue
        if text in options:
            repairs.append(f"dropped duplicate option {text[:20]!r}")
            continue
        options.append(text)
    if not options:
        raise WidgetValidationError("choose: no usable options after repair")
    if len(options) > MAX_OPTIONS:
        repairs.append(f"options capped at {MAX_OPTIONS} (got {len(options)})")
        options = options[:MAX_OPTIONS]
    return {**_title(args, repairs), "options": options}


def _normalize_table(args: dict[str, Any], repairs: list[str]) -> dict[str, Any]:
    raw = args.get("rows")
    if not isinstance(raw, list):
        raise WidgetValidationError("show_table: rows must be an array of objects")
    rows = []
    for row in raw:
        if isinstance(row, dict):
            rows.append({str(k): v for k, v in row.items()})
        else:
            repairs.append(f"dropped non-object row {str(row)[:20]!r}")
    if not rows:
        raise WidgetValidationError("show_table: no object rows after repair")
    if len(rows) > MAX_ROWS:
        repairs.append(f"rows capped at {MAX_ROWS} (got {len(rows)})")
        rows = rows[:MAX_ROWS]
    return {**_title(args, repairs), "rows": rows}


def _normalize_chart(args: dict[str, Any], repairs: list[str]) -> dict[str, Any]:
    raw = args.get("values")
    if not isinstance(raw, list):
        raise WidgetValidationError("show_chart: values must be an array of numbers")
    values: list[float | int] = []
    for item in raw:
        number = _coerce_number(item)
        if number is None:
            repairs.append(f"dropped non-numeric value {str(item)[:20]!r}")
            continue
        if not isinstance(item, (int, float)) or isinstance(item, bool):
            repairs.append(f"value {number} coerced from {type(item).__name__}")
        values.append(number)
    if not values:
        raise WidgetValidationError("show_chart: no numeric values after repair")
    if len(values) > MAX_POINTS:
        repairs.append(f"values downsampled to {MAX_POINTS} points (got {len(values)})")
        step = len(values) / MAX_POINTS
        values = [values[int(i * step)] for i in range(MAX_POINTS)]
    return {**_title(args, repairs), "values": values}


def _normalize_launch(args: dict[str, Any], repairs: list[str]) -> dict[str, Any]:
    kind = args.get("kind")
    if not isinstance(kind, str) or kind not in ("eval", "training"):
        raise WidgetValidationError(
            f"launch_run: kind must be 'eval' or 'training', got {str(kind)[:20]!r}"
        )
    raw = args.get("config")
    if not isinstance(raw, dict):
        raise WidgetValidationError("launch_run: config must be an object")
    config: dict[str, Any] = {}
    for key, value in raw.items():
        key = str(key)
        if value is None:
            repairs.append(f"dropped null config field {key!r}")
            continue
        if key in INT_CONFIG_FIELDS:
            number = _coerce_number(value)
            if number is None:
                repairs.append(f"dropped non-numeric {key!r}={str(value)[:20]!r}")
                continue
            if not isinstance(value, int) or isinstance(value, bool):
                repairs.append(f"{key} coerced to int")
            config[key] = int(number)
        elif key in FLOAT_CONFIG_FIELDS:
            number = _coerce_number(value)
            if number is None:
                repairs.append(f"dropped non-numeric {key!r}={str(value)[:20]!r}")
                continue
            if isinstance(value, str):
                repairs.append(f"{key} coerced to float")
            config[key] = float(number)
        elif isinstance(value, (str, int, float, bool)):
            config[key] = value
        else:
            repairs.append(f"dropped non-scalar config field {key!r}")
    if not config:
        raise WidgetValidationError("launch_run: no usable config fields after repair")
    return {"kind": kind, "config": config}


def _normalize_patch(args: dict[str, Any], repairs: list[str]) -> dict[str, Any]:
    raw = args.get("patch")
    if raw is None:
        raise WidgetValidationError("show_patch: patch is required")
    text = raw if isinstance(raw, str) else str(raw)
    if not isinstance(raw, str):
        repairs.append("patch coerced to string")
    if not text.strip():
        raise WidgetValidationError("show_patch: patch is empty")
    lines = text.splitlines()
    if len(lines) > MAX_PATCH_LINES:
        repairs.append(f"patch truncated to {MAX_PATCH_LINES} lines (got {len(lines)})")
        text = "\n".join(lines[:MAX_PATCH_LINES])
    return {**_title(args, repairs), "patch": text}


_NORMALIZERS = {
    "choose": _normalize_choose,
    "show_table": _normalize_table,
    "show_chart": _normalize_chart,
    "launch_run": _normalize_launch,
    "show_patch": _normalize_patch,
}


def normalize_widget_call(name: str, args: Any) -> NormalizedWidget:
    """Typed repair-or-reject for one widget call.

    Returns the normalized payload with a record of every repair applied, or
    raises :class:`WidgetValidationError` with a reason the TUI can render.
    Interaction stamps (``selected``/``saved_card``) round-trip untouched.
    """
    normalizer = _NORMALIZERS.get(name)
    if normalizer is None:
        raise WidgetValidationError(f"unknown widget tool {name!r}")
    if not isinstance(args, dict):
        raise WidgetValidationError(f"{name}: args must be an object")
    repairs: list[str] = []
    normalized = normalizer(args, repairs)
    return NormalizedWidget(name=name, args=normalized, repairs=tuple(repairs)).with_state_from(
        args
    )


def launch_card_payload(normalized: NormalizedWidget) -> tuple[str, dict[str, Any]]:
    """Card-lifecycle step: map a normalized launch_run onto the launch-card
    taxonomy (train|eval) with typed values, ready for editor.new_card /
    launch.save_card."""
    if normalized.name != "launch_run":
        raise WidgetValidationError(f"not a launch proposal: {normalized.name!r}")
    kind = {"training": "train"}.get(normalized.args["kind"], normalized.args["kind"])
    return kind, dict(normalized.args["config"])
