"""Typed widget-payload model: normalize/repair/reject agent widget calls.

Reference role: prime_lab_app/agent_widget_model.py:1-1168 + agent_cards.py
:1-536 — the layer between raw agent tool-call JSON and the TUI. Agents emit
malformed payloads constantly (numbers as strings, null holes, scalar where
an array belongs, 10k-row tables); the previous shallow check
(widgets.validate_widget_call) only gated types, so anything past it was
rendered best-effort. This module gives every widget a typed contract:

- **repair** what is safely repairable — coerce numeric strings, stringify
  scalar options, drop null/empty/non-finite entries, dedupe, cap sizes —
  and RECORD each repair so the TUI can show "repaired: ..." instead of
  silently rendering something the agent didn't say;
- **reject** what isn't — unknown tool, missing required keys, payloads
  empty after repair — with a reason string the chat renders as an error
  widget (never a crash, never a silent misrender);
- **round-trip state**: the stamps the chat screen writes back into a
  rendered widget's args (``selected``, ``saved_card``) survive
  re-normalization, so re-rendering a transcript keeps interaction state;
- **card lifecycle**: a normalized ``launch_run`` payload converts to a
  typed launch-card payload (kind mapped onto the card taxonomy, numerics
  actually numeric) so the card on disk — and the TOML the user edits —
  has real types, not stringly-typed leftovers.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Any

MAX_OPTIONS = 24
MAX_ROWS = 100
MAX_POINTS = 512
MAX_PATCH_LINES = 400
# launch-config fields that must be numeric on the card; agents routinely
# send them as strings ("limit": "64")
INT_CONFIG_FIELDS = ("limit", "batch_size", "max_new_tokens", "epochs", "draft_len", "seed")
FLOAT_CONFIG_FIELDS = ("temperature", "learning_rate", "top_p", "beta", "clip_eps")
# stamps the chat screen writes back into rendered args; normalization must
# carry them through unchanged (widget state round-trip). ``form_values``
# holds the user's form edits, ``form_errors`` the last typed-parse failures
# (prefixed: a bare "values" stamp would collide with show_chart's payload).
STATE_KEYS = ("selected", "saved_card", "command", "form_values", "form_errors")

FORM_KINDS = ("eval", "rl", "gepa")
FORM_INT_FIELDS = ("rollouts_per_example", "max_steps", "seq_len")


class WidgetValidationError(Exception):
    """The payload is unusable even after repair; the message says why."""


@dataclass
class NormalizedWidget:
    name: str
    args: dict[str, Any]
    repairs: tuple[str, ...] = ()

    def with_state_from(self, raw_args: dict[str, Any]) -> "NormalizedWidget":
        for key in STATE_KEYS:
            if isinstance(raw_args, dict) and key in raw_args:
                self.args[key] = raw_args[key]
        return self


def _coerce_number(value: Any) -> float | int | None:
    """A number, a numeric string, or None; NaN/inf count as unusable."""
    if isinstance(value, bool):
        return None
    if isinstance(value, (int, float)):
        return value if math.isfinite(value) else None
    if isinstance(value, str):
        text = value.strip()
        try:
            number = int(text)
        except ValueError:
            try:
                number = float(text)
            except ValueError:
                return None
        return number if math.isfinite(number) else None
    return None


def _title(args: dict[str, Any], repairs: list[str]) -> dict[str, Any]:
    out: dict[str, Any] = {}
    if "title" in args and args["title"] is not None:
        if isinstance(args["title"], str):
            out["title"] = args["title"]
        else:
            out["title"] = str(args["title"])
            repairs.append("title coerced to string")
    return out


def _normalize_choose(args: dict[str, Any], repairs: list[str]) -> dict[str, Any]:
    raw = args.get("options")
    if not isinstance(raw, list):
        raise WidgetValidationError("choose: options must be an array of strings")
    options: list[str] = []
    for item in raw:
        if item is None:
            repairs.append("dropped null option")
            continue
        text = item if isinstance(item, str) else str(item)
        if not isinstance(item, str):
            repairs.append(f"option {text[:20]!r} coerced to string")
        text = text.strip()
        if not text:
            repairs.append("dropped empty option")
            continue
        if text in options:
            repairs.append(f"dropped duplicate option {text[:20]!r}")
            continue
        options.append(text)
    if not options:
        raise WidgetValidationError("choose: no usable options after repair")
    if len(options) > MAX_OPTIONS:
        repairs.append(f"options capped at {MAX_OPTIONS} (got {len(options)})")
        options = options[:MAX_OPTIONS]
    return {**_title(args, repairs), "options": options}


def _normalize_table(args: dict[str, Any], repairs: list[str]) -> dict[str, Any]:
    raw = args.get("rows")
    if not isinstance(raw, list):
        raise WidgetValidationError("show_table: rows must be an array of objects")
    rows = []
    for row in raw:
        if isinstance(row, dict):
            rows.append({str(k): v for k, v in row.items()})
        else:
            repairs.append(f"dropped non-object row {str(row)[:20]!r}")
    if not rows:
        raise WidgetValidationError("show_table: no object rows after repair")
    if len(rows) > MAX_ROWS:
        repairs.append(f"rows capped at {MAX_ROWS} (got {len(rows)})")
        rows = rows[:MAX_ROWS]
    return {**_title(args, repairs), "rows": rows}


def _normalize_chart(args: dict[str, Any], repairs: list[str]) -> dict[str, Any]:
    raw = args.get("values")
    if not isinstance(raw, list):
        raise WidgetValidationError("show_chart: values must be an array of numbers")
    values: list[float | int] = []
    for item in raw:
        number = _coerce_number(item)
        if number is None:
            repairs.append(f"dropped non-numeric value {str(item)[:20]!r}")
            continue
        if not isinstance(item, (int, float)) or isinstance(item, bool):
            repairs.append(f"value {number} coerced from {type(item).__name__}")
        values.append(number)
    if not values:
        raise WidgetValidationError("show_chart: no numeric values after repair")
    if len(values) > MAX_POINTS:
        repairs.append(f"values downsampled to {MAX_POINTS} points (got {len(values)})")
        step = len(values) / MAX_POINTS
        values = [values[int(i * step)] for i in range(MAX_POINTS)]
    return {**_title(args, repairs), "values": values}


def _normalize_launch(args: dict[str, Any], repairs: list[str]) -> dict[str, Any]:
    kind = args.get("kind")
    if not isinstance(kind, str) or kind not in ("eval", "training"):
        raise WidgetValidationError(
            f"launch_run: kind must be 'eval' or 'training', got {str(kind)[:20]!r}"
        )
    raw = args.get("config")
    if not isinstance(raw, dict):
        raise WidgetValidationError("launch_run: config must be an object")
    config: dict[str, Any] = {}
    for key, value in raw.items():
        key = str(key)
        if value is None:
            repairs.append(f"dropped null config field {key!r}")
            continue
        if key in INT_CONFIG_FIELDS:
            number = _coerce_number(value)
            if number is None:
                repairs.append(f"dropped non-numeric {key!r}={str(value)[:20]!r}")
                continue
            if not isinstance(value, int) or isinstance(value, bool):
                repairs.append(f"{key} coerced to int")
            config[key] = int(number)
        elif key in FLOAT_CONFIG_FIELDS:
            number = _coerce_number(value)
            if number is None:
                repairs.append(f"dropped non-numeric {key!r}={str(value)[:20]!r}")
                continue
            if isinstance(value, str):
                repairs.append(f"{key} coerced to float")
            config[key] = float(number)
        elif isinstance(value, (str, int, float, bool)):
            config[key] = value
        else:
            repairs.append(f"dropped non-scalar config field {key!r}")
    if not config:
        raise WidgetValidationError("launch_run: no usable config fields after repair")
    return {"kind": kind, "config": config}


def _normalize_patch(args: dict[str, Any], repairs: list[str]) -> dict[str, Any]:
    raw = args.get("patch")
    if raw is None:
        raise WidgetValidationError("show_patch: patch is required")
    text = raw if isinstance(raw, str) else str(raw)
    if not isinstance(raw, str):
        repairs.append("patch coerced to string")
    if not text.strip():
        raise WidgetValidationError("show_patch: patch is empty")
    lines = text.splitlines()
    if len(lines) > MAX_PATCH_LINES:
        repairs.append(f"patch truncated to {MAX_PATCH_LINES} lines (got {len(lines)})")
        text = "\n".join(lines[:MAX_PATCH_LINES])
    return {**_title(args, repairs), "patch": text}


def _normalize_form(args: dict[str, Any], repairs: list[str]) -> dict[str, Any]:
    """configure_run: an editable run form (reference run_launcher/
    config_editor widget kinds). kind picks the field schedule; env seeds the
    environment field; config overrides the per-kind defaults."""
    kind = args.get("kind")
    if isinstance(kind, str):
        kind = {"training": "rl", "train": "rl"}.get(kind.strip(), kind.strip())
        if kind != args.get("kind"):
            repairs.append(f"kind {args.get('kind')!r} mapped to {kind!r}")
    if kind not in FORM_KINDS:
        raise WidgetValidationError(
            f"configure_run: kind must be one of {sorted(FORM_KINDS)}, "
            f"got {str(args.get('kind'))[:20]!r}"
        )
    out: dict[str, Any] = {**_title(args, repairs), "kind": kind}
    env = args.get("env")
    if env is not None:
        if not isinstance(env, str):
            env = str(env)
            repairs.append("env coerced to string")
        if env.strip():
            out["env"] = env.strip()
    raw = args.get("config")
    if raw is not None and not isinstance(raw, dict):
        repairs.append("dropped non-object config")
        raw = None
    if isinstance(raw, dict):
        config: dict[str, Any] = {}
        for key, value in raw.items():
            key = str(key)
            if value is None:
                repairs.append(f"dropped null config field {key!r}")
                continue
            if key in INT_CONFIG_FIELDS or key in FORM_INT_FIELDS:
                number = _coerce_number(value)
                if number is None:
                    repairs.append(f"dropped non-numeric {key!r}={str(value)[:20]!r}")
                    continue
                config[key] = int(number)
            elif isinstance(value, (str, int, float, bool)):
                config[key] = value
            else:
                repairs.append(f"dropped non-scalar config field {key!r}")
        if config:
            out["config"] = config
    return out


_NORMALIZERS = {
    "choose": _normalize_choose,
    "show_table": _normalize_table,
    "show_chart": _normalize_chart,
    "launch_run": _normalize_launch,
    "show_patch": _normalize_patch,
    "configure_run": _normalize_form,
}


def normalize_widget_call(name: str, args: Any) -> NormalizedWidget:
    """Typed repair-or-reject for one widget call.

    Returns the normalized payload with a record of every repair applied, or
    raises :class:`WidgetValidationError` with a reason the TUI can render.
    Interaction stamps (``selected``/``saved_card``) round-trip untouched.
    """
    normalizer = _NORMALIZERS.get(name)
    if normalizer is None:
        raise WidgetValidationError(f"unknown widget tool {name!r}")
    if not isinstance(args, dict):
        raise WidgetValidationError(f"{name}: args must be an object")
    repairs: list[str] = []
    normalized = normalizer(args, repairs)
    return NormalizedWidget(name=name, args=normalized, repairs=tuple(repairs)).with_state_from(
        args
    )


# -- typed run form (reference agent_widget_model.py field-spec layer) --------


@dataclass(frozen=True)
class FieldSpec:
    """One editable field of a run form (reference AgentWidgetFieldSpec)."""

    name: str
    label: str
    value: str
    input_type: str = "text"  # "text" | "integer"
    disabled: bool = False
    widget: str = "input"  # "input" | "select"
    options: tuple[tuple[str, str], ...] = ()  # (label, value)


@dataclass(frozen=True)
class ActionSpec:
    """One action a form exposes (reference AgentWidgetActionSpec)."""

    name: str
    label: str
    variant: str = "default"


@dataclass(frozen=True)
class FormModel:
    """Logical run-configuration form, independent of the rendering skin.

    ``extras`` are agent-proposed config fields outside the editable
    schedule (e.g. temperature, seed): not editable, but visible in the
    render and carried onto the launched card — a proposal must not behave
    differently between launch_run and configure_run."""

    kind: str
    title: str
    fields: tuple[FieldSpec, ...]
    actions: tuple[ActionSpec, ...]
    extras: tuple[tuple[str, Any], ...] = ()


# (name, label, input_type, default, disabled) per form kind — defaults
# mirror the reference's seeded values, renamed to this repo's config
# vocabulary (limit/max_new_tokens, not num_examples/max_tokens)
_FORM_SCHEDULES: dict[str, tuple[tuple[str, str, str, str, bool], ...]] = {
    "eval": (
        ("env", "Environment", "text", "", False),
        ("model", "Model", "text", "", False),
        ("limit", "Examples", "integer", "50", False),
        ("rollouts_per_example", "Rollouts per example", "integer", "3", False),
        ("max_new_tokens", "Max new tokens", "integer", "1024", False),
        ("max_concurrent", "Max concurrent", "text", "auto", False),
    ),
    "rl": (
        ("env", "Environment", "text", "", False),
        ("model", "Model", "text", "", False),
        ("max_steps", "Steps", "integer", "100", False),
        ("rollouts_per_example", "Rollouts per example", "integer", "8", False),
        ("batch_size", "Rollouts per batch", "integer", "256", False),
        ("max_new_tokens", "Max new tokens", "integer", "8192", False),
        ("seq_len", "Seq len", "integer", "", True),
    ),
    "gepa": (
        ("env", "Environment", "text", "", False),
        ("model", "Model", "text", "", False),
    ),
}

_FORM_TITLES = {"eval": "Evaluate", "rl": "Train", "gepa": "Optimize"}


# render_widget repaints every transcript widget on every keystroke; without
# a cache each frame would re-read configs/endpoints.toml and every env.toml
# (TUI render hot path). A short TTL keeps edits visible within a beat.
_OPTIONS_CACHE: dict[tuple[str, ...], tuple[float, Any]] = {}
_OPTIONS_TTL_S = 2.0


def _cached(key: tuple[str, ...], compute):
    import time

    now = time.monotonic()
    hit = _OPTIONS_CACHE.get(key)
    if hit is not None and now - hit[0] < _OPTIONS_TTL_S:
        return hit[1]
    value = compute()
    _OPTIONS_CACHE[key] = (now, value)
    return value


def model_options(workspace: Any = None, kind: str = "eval") -> tuple[tuple[str, str], ...]:
    """(label, value) model choices: local presets plus the workspace's
    configs/endpoints.toml aliases (reference _widget_model_options — there
    the options come from the training API / endpoint registry; here the
    preset table IS the trainable set, and aliases are serving endpoints, so
    rl forms list presets only)."""
    return _cached(
        ("models", str(workspace), "rl" if kind == "rl" else "other"),
        lambda: _model_options_uncached(workspace, kind),
    )


def _model_options_uncached(workspace: Any, kind: str) -> tuple[tuple[str, str], ...]:
    from prime_tpu.models.config import MODEL_PRESETS

    options: list[tuple[str, str]] = [(name, name) for name in sorted(MODEL_PRESETS)]
    if kind != "rl" and workspace is not None:
        from prime_tpu.utils.compat import tomllib
        from pathlib import Path

        path = Path(workspace) / "configs" / "endpoints.toml"
        try:
            table = tomllib.loads(path.read_text())
        except (OSError, tomllib.TOMLDecodeError):
            table = {}
        for alias, entry in sorted(table.items()):
            if isinstance(entry, dict) and isinstance(entry.get("model"), str):
                options.append((f"{alias} (endpoint)", alias))
    return tuple(options)


def environment_options(workspace: Any = None) -> tuple[str, ...]:
    """Local environment checkouts: <workspace>/environments/*/env.toml plus
    the workspace root itself (reference _widget_local_environment_names)."""
    if workspace is None:
        return ()
    return _cached(("envs", str(workspace)), lambda: _environment_options_uncached(workspace))


def _environment_options_uncached(workspace: Any) -> tuple[str, ...]:
    from prime_tpu.utils.compat import tomllib
    from pathlib import Path

    names: list[str] = []

    def name_of(env_dir: Path) -> str | None:
        try:
            data = tomllib.loads((env_dir / "env.toml").read_text())
        except (OSError, tomllib.TOMLDecodeError):
            return None
        name = data.get("environment", {}).get("name")
        return name if isinstance(name, str) and name else None

    root = Path(workspace)
    envs_dir = root / "environments"
    if envs_dir.is_dir():
        for child in sorted(envs_dir.iterdir()):
            if (child / "env.toml").exists():
                found = name_of(child)
                if found and found not in names:
                    names.append(found)
    if (root / "env.toml").exists():
        found = name_of(root)
        if found and found not in names:
            names.append(found)
    return tuple(names)


def build_form_model(normalized: NormalizedWidget, workspace: Any = None) -> FormModel:
    """Normalized configure_run args -> renderable form: per-kind field
    schedule with seeded defaults, agent config + user edits layered on top,
    model/environment selects populated from the workspace."""
    if normalized.name != "configure_run":
        raise WidgetValidationError(f"not a run form: {normalized.name!r}")
    kind = normalized.args["kind"]
    layered: dict[str, str] = {}
    for source in (normalized.args.get("config"), normalized.args.get("form_values")):
        if isinstance(source, dict):
            layered.update({str(k): str(v) for k, v in source.items()})
    if normalized.args.get("env") and "env" not in layered:
        layered["env"] = str(normalized.args["env"])

    models = model_options(workspace, kind)
    envs = environment_options(workspace)
    fields: list[FieldSpec] = []
    for name, label, input_type, default, disabled in _FORM_SCHEDULES[kind]:
        value = layered.get(name, default)
        if not value and disabled:
            continue  # a disabled field with no value carries no information
        widget = "input"
        options: tuple[tuple[str, str], ...] = ()
        if name == "model" and models:
            option_values = {v for _, v in models}
            if value and value not in option_values:
                models = ((value, value), *models)  # keep the agent's pick
            elif not value:
                value = models[0][1]
            widget, options = "select", models
        elif name == "env" and envs:
            env_opts = tuple((n, n) for n in envs)
            if value and value not in envs:
                env_opts = ((value, value), *env_opts)
            elif not value:
                value = envs[0]
            widget, options = "select", env_opts
        fields.append(
            FieldSpec(
                name=name, label=label, value=str(value), input_type=input_type,
                disabled=disabled, widget=widget, options=options,
            )
        )
    env_value = next((f.value for f in fields if f.name == "env"), "")
    env_label = (env_value or "run").rsplit("/", 1)[-1]
    title = normalized.args.get("title") or f"{_FORM_TITLES[kind]} {env_label}"
    actions = (ActionSpec("launch", "Launch", "primary"), ActionSpec("stop", "Stop"))
    schedule_names = {name for name, *_ in _FORM_SCHEDULES[kind]}
    config = normalized.args.get("config") or {}
    extras = tuple(
        (key, value) for key, value in config.items() if key not in schedule_names
    )
    return FormModel(
        kind=kind, title=title, fields=tuple(fields), actions=actions, extras=extras
    )


def parse_form_values(form: FormModel) -> tuple[dict[str, Any], list[str]]:
    """Typed parse of the form's current values: integer fields must parse
    (errors collected per field, reference parse_optional_int), 'auto' and
    blanks drop out, everything else passes as the string the user typed."""
    config: dict[str, Any] = {}
    errors: list[str] = []
    for spec in form.fields:
        value = spec.value.strip()
        if not value or value == "auto":
            continue
        if spec.input_type == "integer":
            try:
                config[spec.name] = int(value)
            except ValueError:
                errors.append(f"{spec.label}: {value!r} is not an integer")
        else:
            config[spec.name] = value
    return config, errors


def form_launch_payload(form: FormModel) -> tuple[str, dict[str, Any]]:
    """Map a parsed form onto the launch-card taxonomy (eval|train); raises
    with the collected field errors when the values don't parse."""
    config, errors = parse_form_values(form)
    if errors:
        raise WidgetValidationError("; ".join(errors))
    if not config.get("env"):
        raise WidgetValidationError("Environment is required")
    kind = {"rl": "train"}.get(form.kind, form.kind)
    if kind == "gepa":
        raise WidgetValidationError("gepa forms launch via the command line")
    # field values win over extras on key collision (can't happen today —
    # extras are by construction outside the schedule — but cheap insurance)
    return kind, {**dict(form.extras), **config}


def form_command_text(form: FormModel) -> str:
    """The CLI equivalent of the form (reference widget_command_text) — what
    the user could paste in a shell instead of arming a card."""
    config, _errors = parse_form_values(form)
    env = config.get("env", "<env>")
    model = config.get("model", "")
    if form.kind == "eval":
        parts = [f"prime eval run {env}"]
        if model:
            parts.append(f"-m {model}")
        if "limit" in config:
            parts.append(f"-n {config['limit']}")
        if "max_new_tokens" in config:
            parts.append(f"--max-new-tokens {config['max_new_tokens']}")
        return " ".join(parts)
    if form.kind == "rl":
        parts = [f"prime train request --env {env}"]
        if model:
            parts.append(f"--model {model}")
        if "max_steps" in config:
            parts.append(f"--steps {config['max_steps']}")
        return " ".join(parts)
    parts = [f"prime gepa run {env}"]
    if model:
        parts.append(f"-m {model}")
    return " ".join(parts)


def launch_card_payload(normalized: NormalizedWidget) -> tuple[str, dict[str, Any]]:
    """Card-lifecycle step: map a normalized launch_run onto the launch-card
    taxonomy (train|eval) with typed values, ready for editor.new_card /
    launch.save_card."""
    if normalized.name != "launch_run":
        raise WidgetValidationError(f"not a launch proposal: {normalized.name!r}")
    kind = {"training": "train"}.get(normalized.args["kind"], normalized.args["kind"])
    return kind, dict(normalized.args["config"])
