"""Lazy access + aggregate statistics for local eval run records.

The eval runner writes one JSON object per line to ``results.jsonl``
(`prime_tpu/evals/runner.py`); a long run can hold tens of thousands of
samples, so the Lab shell must not slurp the whole file to show one of them.
``IndexedJsonl`` keeps a byte-offset index and a bounded parsed-row cache:
random access costs one seek + one json.loads, memory stays O(cache), and a
row written while the shell is open is picked up by a later ``refresh()``.

``run_overview`` computes the aggregate view (reward distribution, pass rate,
per-metric summaries) in ONE streaming pass without retaining rows.

Reference roles: prime_lab_app/eval_records.py:109 (LazyRunResults) and
eval_records.py:55 (RunOverviewStats/MetricSummary) — redesigned around a
bounded cache + streaming aggregation instead of an unbounded dict cache.
"""

from __future__ import annotations

import json
import math
from collections import OrderedDict
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Iterator


class IndexedJsonl:
    """Offset-indexed random access over a .jsonl file.

    ``get(i)`` seeks to the i-th line and parses it; parsed rows live in an
    LRU cache capped at ``cache_rows``. ``len()`` forces a full offset scan
    (cheap: readline only, no parsing). A malformed line yields ``{}`` so one
    torn write cannot take down the browser.
    """

    def __init__(self, path: str | Path, cache_rows: int = 256) -> None:
        self.path = Path(path)
        self._offsets: list[int] = []
        self._scanned = 0  # bytes consumed by the offset scan so far
        self._eof = False
        self._cache: OrderedDict[int, dict[str, Any]] = OrderedDict()
        self._cache_rows = cache_rows

    # -- offset index ----------------------------------------------------------

    def _scan_to(self, index: int | None) -> None:
        """Extend the offset index to cover ``index`` (None = to EOF)."""
        if self._eof or (index is not None and index < len(self._offsets)):
            return
        try:
            with self.path.open("rb") as fh:
                fh.seek(self._scanned)
                while index is None or len(self._offsets) <= index:
                    pos = fh.tell()
                    line = fh.readline()
                    if not line:
                        self._eof = True
                        break
                    if not line.endswith(b"\n"):
                        # torn final line: a writer is mid-append. Do not
                        # index it; a later refresh() re-reads from here.
                        break
                    self._offsets.append(pos)
                    self._scanned = fh.tell()
        except OSError:
            self._eof = True

    def refresh(self) -> None:
        """Pick up rows appended since the last scan (live runs)."""
        self._eof = False

    def __len__(self) -> int:
        self._scan_to(None)
        return len(self._offsets)

    def count_so_far(self) -> int:
        """Rows indexed without forcing a full scan."""
        return len(self._offsets)

    # -- row access ------------------------------------------------------------

    def get(self, index: int) -> dict[str, Any]:
        if index in self._cache:
            self._cache.move_to_end(index)
            return self._cache[index]
        self._scan_to(index)
        if not 0 <= index < len(self._offsets):
            return {}
        try:
            with self.path.open("rb") as fh:
                fh.seek(self._offsets[index])
                raw = fh.readline()
        except OSError:
            return {}
        try:
            row = json.loads(raw)
        except (json.JSONDecodeError, UnicodeDecodeError):
            row = {}
        row = row if isinstance(row, dict) else {}
        self._cache[index] = row
        if len(self._cache) > self._cache_rows:
            self._cache.popitem(last=False)
        return row

    def __getitem__(self, index: int) -> dict[str, Any]:
        return self.get(index)

    def __iter__(self) -> Iterator[dict[str, Any]]:
        """Stream rows WITHOUT populating the cache (aggregation path).

        Capped at the indexed row count so iteration and ``get``/``len`` always
        agree: rows appended after the index froze (_eof) stay invisible to
        BOTH until ``refresh()`` — no phantom rows in filtered views.
        """
        self._scan_to(None)
        count = len(self._offsets)
        try:
            with self.path.open("rb") as fh:
                for _ in range(count):
                    raw = fh.readline()
                    try:
                        row = json.loads(raw)
                    except (json.JSONDecodeError, UnicodeDecodeError):
                        row = {}
                    yield row if isinstance(row, dict) else {}
        except OSError:
            return

    def column(self, key: str) -> list[Any]:
        """One field across all rows, streamed (no row cache pollution)."""
        return [row.get(key) for row in self]


@dataclass(frozen=True)
class MetricSummary:
    name: str
    count: int
    mean: float
    minimum: float
    maximum: float


@dataclass
class RunOverview:
    """Aggregates for one local eval run, computed in a single pass."""

    n_samples: int = 0
    rewards: list[float] = field(default_factory=list)
    pass_rate: float | None = None
    metrics: list[MetricSummary] = field(default_factory=list)

    @property
    def mean_reward(self) -> float | None:
        return sum(self.rewards) / len(self.rewards) if self.rewards else None

    def reward_histogram(self, bins: int = 10) -> list[int]:
        """Counts per equal-width bin over [min, max] (empty → [])."""
        if not self.rewards:
            return []
        lo, hi = min(self.rewards), max(self.rewards)
        counts = [0] * bins
        span = hi - lo
        for value in self.rewards:
            if span <= 0:
                counts[0] += 1
            else:
                counts[min(int((value - lo) / span * bins), bins - 1)] += 1
        return counts


# fields that are per-sample bookkeeping, not scoreable metrics
@dataclass(frozen=True)
class SampleFlip:
    """One sample whose correctness changed between two runs."""

    key: str                  # prompt (or sample id) identifying the sample
    direction: str            # "improvement" | "regression"
    completion_a: str
    completion_b: str
    answer: str


@dataclass
class RunComparison:
    """A vs B deltas for two local eval runs (reference eval compare role)."""

    metrics: list[tuple[str, Any, Any, float | None]]  # (name, a, b, delta)
    shared: int = 0
    only_a: int = 0
    only_b: int = 0
    flips: list[SampleFlip] = field(default_factory=list)
    duplicates: int = 0  # multi-rollout rows beyond each key's first

    @property
    def regressions(self) -> int:
        return sum(1 for f in self.flips if f.direction == "regression")

    @property
    def improvements(self) -> int:
        return sum(1 for f in self.flips if f.direction == "improvement")


def _sample_key(row: dict[str, Any]) -> str | None:
    # explicit None checks: sample_id 0 and an empty-string prompt are real keys
    for field_name in ("prompt", "sample_id", "sampleId"):
        value = row.get(field_name)
        if value is not None:
            return str(value)
    return None


def compare_runs(dir_a: str | Path, dir_b: str | Path) -> RunComparison:
    """Compare two runs' metadata metrics and per-sample correctness,
    matching samples by prompt (sample id fallback).

    Streaming-first: the index pass keeps only key → (correct, row index)
    per run (no completions in memory); the handful of flipped rows are
    fetched afterwards through the lazy reader. Samples missing a
    ``correct`` field in EITHER run are excluded from flip accounting (an
    env that scores rewards only must not read as 100% regressions).
    Duplicate keys (multi-rollout runs) keep the FIRST occurrence —
    deterministic, and counted in ``duplicates`` so the screen can say so.
    """
    dir_a, dir_b = Path(dir_a), Path(dir_b)

    def metadata_metrics(run_dir: Path) -> dict[str, Any]:
        try:
            loaded = json.loads((run_dir / "metadata.json").read_text())
        except (OSError, json.JSONDecodeError):
            return {}
        metrics = loaded.get("metrics") if isinstance(loaded, dict) else None
        return metrics if isinstance(metrics, dict) else {}

    def index_run(records: IndexedJsonl) -> tuple[dict[str, tuple[bool | None, int]], int]:
        out: dict[str, tuple[bool | None, int]] = {}
        duplicates = 0
        for position, row in enumerate(records):
            key = _sample_key(row)
            if key is None:
                continue
            if key in out:
                duplicates += 1
                continue  # first occurrence wins, deterministically
            correct = bool(row["correct"]) if "correct" in row else None
            out[key] = (correct, position)
        return out, duplicates

    metrics_a = metadata_metrics(dir_a)
    metrics_b = metadata_metrics(dir_b)
    metric_rows: list[tuple[str, Any, Any, float | None]] = []
    for name in sorted(set(metrics_a) | set(metrics_b)):
        a, b = metrics_a.get(name), metrics_b.get(name)
        numeric = isinstance(a, (int, float)) and isinstance(b, (int, float))
        if isinstance(a, (int, float)) or isinstance(b, (int, float)):
            metric_rows.append((name, a, b, float(b - a) if numeric else None))

    records_a = IndexedJsonl(dir_a / "results.jsonl")
    records_b = IndexedJsonl(dir_b / "results.jsonl")
    index_a, dup_a = index_run(records_a)
    index_b, dup_b = index_run(records_b)
    shared_keys = set(index_a) & set(index_b)
    flips: list[SampleFlip] = []
    for key in sorted(shared_keys):
        ok_a, pos_a = index_a[key]
        ok_b, pos_b = index_b[key]
        if ok_a is None or ok_b is None or ok_a == ok_b:
            continue
        row_a, row_b = records_a.get(pos_a), records_b.get(pos_b)
        flips.append(
            SampleFlip(
                key=key,
                direction="improvement" if ok_b else "regression",
                completion_a=str(row_a.get("completion", "")),
                completion_b=str(row_b.get("completion", "")),
                answer=str(row_a.get("answer", row_b.get("answer", ""))),
            )
        )
    return RunComparison(
        metrics=metric_rows,
        shared=len(shared_keys),
        only_a=len(set(index_a) - shared_keys),
        only_b=len(set(index_b) - shared_keys),
        flips=flips,
        duplicates=dup_a + dup_b,
    )


_NON_METRIC_KEYS = {"prompt", "completion", "answer", "sample_index", "tokens"}


def run_overview(records: IndexedJsonl | str | Path) -> RunOverview:
    """Stream ``results.jsonl`` once and aggregate.

    ``reward`` feeds the distribution; ``correct`` feeds pass rate; every
    OTHER numeric field becomes a MetricSummary (so custom env metrics —
    format rewards, tool-call counts — show up without schema knowledge).
    """
    if not isinstance(records, IndexedJsonl):
        records = IndexedJsonl(records)
    overview = RunOverview()
    n_correct = 0
    n_flagged = 0
    sums: dict[str, tuple[int, float, float, float]] = {}
    for row in records:
        overview.n_samples += 1
        reward = row.get("reward")
        if isinstance(reward, (int, float)) and math.isfinite(reward):
            overview.rewards.append(float(reward))
        if "correct" in row:
            n_flagged += 1
            n_correct += bool(row["correct"])
        for key, value in row.items():
            if key in _NON_METRIC_KEYS or key == "reward" or key == "correct":
                continue
            if isinstance(value, bool) or not isinstance(value, (int, float)):
                continue
            if not math.isfinite(value):
                continue
            count, total, lo, hi = sums.get(key, (0, 0.0, float("inf"), float("-inf")))
            sums[key] = (count + 1, total + value, min(lo, value), max(hi, value))
    if n_flagged:
        overview.pass_rate = n_correct / n_flagged
    overview.metrics = [
        MetricSummary(name=k, count=c, mean=t / c, minimum=lo, maximum=hi)
        for k, (c, t, lo, hi) in sorted(sums.items())
    ]
    return overview
