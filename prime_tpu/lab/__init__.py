"""Lab workspace: local-first data layer for the Lab surfaces.

Reference architecture (prime_lab_app, SURVEY.md §2.8) separates the Textual
shell from the data machinery; this package carries the data machinery —
disk caches (cache.py) and snapshot assembly (data.py: local workspace scan +
cached platform rows + on-demand hydration). The interactive TUI shell is an
optional future layer; `prime lab view` renders a one-shot snapshot today.
"""

from prime_tpu.lab.cache import LabCache
from prime_tpu.lab.data import LabDataSource, LabSnapshot

__all__ = ["LabCache", "LabDataSource", "LabSnapshot"]
