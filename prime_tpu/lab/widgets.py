"""Lab widget tool contract: native TUI surfaces agents can drive.

Reference role: prime_lab_app/agent_widgets.py:38 ``LAB_WIDGET_TOOLS`` +
agent_widget_model.py — a fixed table of tools every chat dialect advertises
(Codex ``dynamicTools``, Letta ``register_external_tools``, the MCP bridge's
tool list); when the agent calls one, the TUI renders a native widget instead
of text. This stack keeps the table small and declarative: each spec is pure
data, ``render_widget`` maps a call onto rich renderables, and the chat
screen owns any interactive follow-up (choice selection).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any


@dataclass(frozen=True)
class WidgetToolSpec:
    name: str
    description: str
    properties: dict[str, Any] = field(default_factory=dict)
    required: tuple[str, ...] = ()


WIDGET_TOOLS: tuple[WidgetToolSpec, ...] = (
    WidgetToolSpec(
        name="choose",
        description="Present options as a native picker; the user selects one.",
        properties={
            "title": {"type": "string"},
            "options": {"type": "array", "items": {"type": "string"}},
        },
        required=("options",),
    ),
    WidgetToolSpec(
        name="show_table",
        description="Render rows as a native table (columns inferred from keys).",
        properties={
            "title": {"type": "string"},
            "rows": {"type": "array", "items": {"type": "object"}},
        },
        required=("rows",),
    ),
    WidgetToolSpec(
        name="show_chart",
        description="Render a numeric series as a native sparkline chart.",
        properties={
            "title": {"type": "string"},
            "values": {"type": "array", "items": {"type": "number"}},
        },
        required=("values",),
    ),
    WidgetToolSpec(
        name="launch_run",
        description=(
            "Propose launching a hosted eval or training run with an explicit "
            "config; the user confirms in the launch section."
        ),
        properties={
            "kind": {"type": "string", "enum": ["eval", "training"]},
            "config": {"type": "object"},
        },
        required=("kind", "config"),
    ),
    WidgetToolSpec(
        name="show_patch",
        description="Render a unified diff with syntax-aware +/- coloring.",
        properties={
            "title": {"type": "string"},
            "patch": {"type": "string"},
        },
        required=("patch",),
    ),
    WidgetToolSpec(
        name="configure_run",
        description=(
            "Open an editable run-configuration form (eval, rl, or gepa) "
            "seeded with your proposed values; the user edits fields with "
            "name=value and launches or stops it."
        ),
        properties={
            "title": {"type": "string"},
            "kind": {"type": "string", "enum": ["eval", "rl", "gepa"]},
            "env": {"type": "string"},
            "config": {"type": "object"},
        },
        required=("kind",),
    ),
)

_BY_NAME = {tool.name: tool for tool in WIDGET_TOOLS}


def widget_tool_specs() -> list[dict[str, Any]]:
    """Codex ``dynamicTools`` shape (JSON-schema parameters)."""
    return [
        {
            "name": tool.name,
            "description": tool.description,
            "parameters": {
                "type": "object",
                "properties": tool.properties,
                "required": list(tool.required),
                "additionalProperties": False,
            },
        }
        for tool in WIDGET_TOOLS
    ]


def letta_external_tools() -> list[dict[str, Any]]:
    """Letta ``register_external_tools`` shape (label + parameters)."""
    return [
        {
            "name": tool.name,
            "label": f"Lab {tool.name.replace('_', ' ')}",
            "description": tool.description,
            "parameters": {
                "type": "object",
                "properties": tool.properties,
                "required": list(tool.required),
                "additionalProperties": False,
            },
        }
        for tool in WIDGET_TOOLS
    ]


def validate_widget_call(name: str, args: dict[str, Any]) -> str | None:
    """None when the call is usable (possibly after repair), else a reason.

    Thin shim over the typed widget model — ONE validation contract
    (widget_model.normalize_widget_call) decides; a second shallower
    checker here would invite callers onto the weaker path the round-4
    model replaced."""
    from prime_tpu.lab.widget_model import WidgetValidationError, normalize_widget_call

    try:
        normalize_widget_call(name, args)
    except WidgetValidationError as e:
        return str(e)
    return None


def render_widget(
    name: str, args: dict[str, Any], cursor: int | None = None, workspace: Any = None
):
    """One rich renderable per widget call (pure; no app state beyond the
    optional ``cursor`` for a pending choice and the ``selected`` /
    ``saved_card`` stamps the chat screen writes back into ``args``).

    Payloads go through the typed widget model first
    (widget_model.normalize_widget_call): repairable damage is fixed and
    surfaced in the panel subtitle, unusable payloads render as an explicit
    error panel — never a crash, never a silent misrender."""
    from rich.panel import Panel
    from rich.table import Table
    from rich.text import Text

    from prime_tpu.lab.widget_model import WidgetValidationError, normalize_widget_call

    try:
        normalized = normalize_widget_call(name, args)
    except WidgetValidationError as e:
        return Panel(Text(str(e), style="red"), title="widget error", border_style="red")
    args = normalized.args
    subtitle = (
        f"repaired: {'; '.join(normalized.repairs[:3])}"
        + ("; …" if len(normalized.repairs) > 3 else "")
        if normalized.repairs
        else None
    )

    def panel(*a, **kw):
        return Panel(*a, subtitle=subtitle, subtitle_align="left", **kw)

    title = str(args.get("title", "")) or name
    if name == "choose":
        selected = args.get("selected")
        body = Table.grid(padding=(0, 1))
        for index, option in enumerate(args["options"], 1):
            text = str(option)
            if selected is not None:
                marker = "✓" if text == selected else " "
                style = "green" if text == selected else "dim"
            elif cursor is not None:
                marker = "▸" if index - 1 == cursor else " "
                style = "reverse" if index - 1 == cursor else ""
            else:
                marker, style = "", ""
            body.add_row(
                Text(f"{marker}{index}.", style="bold"), Text(text, style=style or None)
            )
        border = "dim" if selected is not None else "cyan"
        return panel(body, title=f"choose: {title}", border_style=border)
    if name == "show_table":
        rows = [r for r in args["rows"] if isinstance(r, dict)]
        columns: list[str] = []
        for row in rows:
            for key in row:
                if key not in columns:
                    columns.append(key)
        table = Table(expand=True, pad_edge=False)
        for column in columns[:6]:
            table.add_column(str(column), overflow="ellipsis", no_wrap=True)
        for row in rows[:20]:
            table.add_row(*[str(row.get(c, "—")) for c in columns[:6]])
        return panel(table, title=title, border_style="cyan")
    if name == "show_chart":
        from prime_tpu.lab.tui.charts import sparkline

        values = [v for v in args["values"] if isinstance(v, (int, float))]
        line = sparkline(values, width=48) if values else "(no numeric values)"
        caption = f"{values[0]:.4g} → {values[-1]:.4g}" if values else ""
        return panel(
            Text(f"{line}  {caption}", no_wrap=True, overflow="crop"),
            title=title,
            border_style="cyan",
        )
    if name == "launch_run":
        body = Table.grid(padding=(0, 1))
        body.add_row(Text("kind", style="dim"), Text(str(args.get("kind"))))
        for key, value in (args.get("config") or {}).items():
            body.add_row(Text(str(key), style="dim"), Text(str(value)[:60]))
        saved = args.get("saved_card")
        if saved:
            body.add_row(Text("card", style="green"), Text(str(saved), style="green"))
        return panel(
            body,
            title="launch proposal"
            + (" (card written)" if saved else " (confirm in the launch section)"),
            border_style="dim" if saved else "yellow",
        )
    if name == "configure_run":
        from prime_tpu.lab.widget_model import build_form_model

        form = build_form_model(normalized, workspace)
        body = Table.grid(padding=(0, 1))
        for spec in form.fields:
            marker = "▾" if spec.widget == "select" else " "
            style = "dim" if spec.disabled else None
            body.add_row(
                Text(spec.label, style="dim"),
                Text(f"{spec.value or '—'} {marker}".rstrip(), style=style),
            )
        for key, value in form.extras:
            # agent-proposed fields outside the editable schedule: shown, and
            # carried onto the launched card
            body.add_row(Text(str(key), style="dim"), Text(str(value)[:60], style="dim"))
        for error in args.get("form_errors") or ():
            body.add_row(Text("!", style="red"), Text(str(error), style="red"))
        saved = args.get("saved_card")
        command = args.get("command")
        if saved:
            body.add_row(Text("card", style="green"), Text(str(saved), style="green"))
        if command:
            body.add_row(Text("command", style="green"), Text(str(command), style="green"))
        hint = (
            "card written"
            if saved
            else "command sent"
            if command
            else "edit: name=value · enter: launch · stop: discard"
        )
        return panel(
            body,
            title=f"{form.title} ({hint})",
            border_style="dim" if saved else "yellow",
        )
    # show_patch
    text = Text()
    for line in str(args["patch"]).splitlines()[:40]:
        style = "green" if line.startswith("+") else "red" if line.startswith("-") else None
        text.append(line + "\n", style=style)
    return panel(text, title=title, border_style="cyan")
