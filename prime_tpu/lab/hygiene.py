"""Workspace hygiene preflights (reference: lab_hygiene.py, 321 LoC).

Checks a Lab workspace for the accidents that leak data or bloat repos:
secrets on disk that git would pick up, eval outputs / caches inside the
repo, oversized files, and a missing workspace config. One filesystem walk
plus one batched ``git check-ignore --stdin`` call, so the preflight stays
fast on workspaces with thousands of output files. Findings carry a severity
and, where safe, an auto-fix (a gitignore append); ``apply_fixes`` only ever
adds ignore rules — it never deletes or rewrites user files.
"""

from __future__ import annotations

import fnmatch
import subprocess
from dataclasses import dataclass
from pathlib import Path

SECRET_PATTERNS = ("*.pem", "*.key", "id_rsa", "id_ed25519", "credentials*.json", ".env")
LARGE_FILE_MB = 50
GENERATED_DIRS = (("outputs", "unignored-outputs"), (".prime-lab/cache", "unignored-cache"))


@dataclass
class Finding:
    severity: str          # error | warn | info
    code: str
    message: str
    fix_entry: str | None = None   # gitignore line that resolves it, if any

    def as_dict(self) -> dict:
        return {
            "severity": self.severity,
            "code": self.code,
            "message": self.message,
            "fix": self.fix_entry,
        }


def _in_git_repo(workspace: Path) -> bool:
    try:
        proc = subprocess.run(
            ["git", "rev-parse", "--is-inside-work-tree"],
            cwd=workspace,
            capture_output=True,
            timeout=10,
        )
    except (OSError, subprocess.TimeoutExpired):
        return False
    return proc.returncode == 0


def _batch_ignored(workspace: Path, rels: list[str]) -> set[str]:
    """One `git check-ignore -z --stdin` call: returns the subset git ignores.
    NUL separation on both sides — without -z git C-quotes non-ASCII paths on
    stdout and they would never match the raw strings we compare against."""
    if not rels:
        return set()
    try:
        proc = subprocess.run(
            ["git", "check-ignore", "-z", "--stdin"],
            cwd=workspace,
            input="\0".join(rels) + "\0",
            text=True,
            capture_output=True,
            timeout=30,
        )
    except (OSError, subprocess.TimeoutExpired):
        return set()
    return {p for p in proc.stdout.split("\0") if p}


def _escape_gitignore(path: str) -> str:
    """Escape glob metacharacters so a literal path works as an ignore rule."""
    escaped = path.replace("\\", "\\\\")
    for ch in ("[", "]", "*", "?"):
        escaped = escaped.replace(ch, "\\" + ch)
    if escaped.startswith(("#", "!")):
        escaped = "\\" + escaped
    return escaped


def check_workspace(workspace: str | Path = ".") -> list[Finding]:
    ws = Path(workspace)
    if not ws.is_dir():
        raise FileNotFoundError(f"workspace {ws} does not exist")
    findings: list[Finding] = []

    if not (ws / ".prime-lab" / "lab.toml").exists():
        findings.append(
            Finding("info", "no-lab-config", "no .prime-lab/lab.toml — run `prime lab setup`")
        )

    if not _in_git_repo(ws):
        findings.append(
            Finding("info", "no-git", "workspace is not a git repository; skipping git checks")
        )
        return findings

    # single walk with .git pruned BEFORE descent (never enumerate objects/)
    import os

    secrets: list[str] = []
    large: list[tuple[str, float]] = []
    for dirpath, dirnames, filenames in os.walk(ws):
        dirnames[:] = sorted(d for d in dirnames if d != ".git")
        for name in sorted(filenames):
            path = Path(dirpath) / name
            rel = path.relative_to(ws).as_posix()
            if any(fnmatch.fnmatch(name, pattern) for pattern in SECRET_PATTERNS):
                secrets.append(rel)
            try:
                size_mb = path.stat().st_size / (1024 * 1024)
            except OSError:
                continue
            if size_mb >= LARGE_FILE_MB:
                large.append((rel, size_mb))

    dir_rels = [rel for rel, _ in GENERATED_DIRS if (ws / rel).exists()]
    ignored = _batch_ignored(ws, secrets + [rel for rel, _ in large] + dir_rels)

    for rel in secrets:
        if rel not in ignored:
            findings.append(
                Finding(
                    "error",
                    "unignored-secret",
                    f"{rel} looks like a secret and is not gitignored",
                    fix_entry=_escape_gitignore(rel)
                    if "/" not in rel
                    else f"**/{_escape_gitignore(Path(rel).name)}",
                )
            )

    for rel, code in GENERATED_DIRS:
        if (ws / rel).exists() and rel not in ignored:
            findings.append(
                Finding("warn", code, f"{rel}/ exists and is not gitignored", fix_entry=rel + "/")
            )

    for rel, size_mb in large:
        if rel not in ignored:
            findings.append(
                Finding(
                    "warn",
                    "large-file",
                    f"{rel} is {size_mb:.0f} MB and not gitignored",
                    fix_entry=_escape_gitignore(rel),
                )
            )

    return findings


def apply_fixes(workspace: str | Path, findings: list[Finding]) -> list[str]:
    """Append the fixable findings' ignore entries to .gitignore. Returns the
    entries added. Additive only — never rewrites existing content."""
    from prime_tpu.lab.setup import append_gitignore

    return append_gitignore(workspace, [f.fix_entry for f in findings if f.fix_entry])


# `prime lab register-github` (reference commands/lab.py:106-113) drops a CI
# workflow that runs the hygiene preflight on every push/PR, so a workspace
# that leaks secrets or tracks generated outputs fails CI, not just the
# local doctor. The workflow installs this package and runs the same
# `prime lab hygiene` the shell's setup screen uses.
GITHUB_WORKFLOW_RELPATH = Path(".github") / "workflows" / "prime-lab-hygiene.yml"
GITHUB_WORKFLOW_YAML = """\
name: prime-lab-hygiene

on:
  pull_request:
  push:
    branches: [main]

jobs:
  hygiene:
    runs-on: ubuntu-latest
    steps:
      - uses: actions/checkout@v4
      - uses: actions/setup-python@v5
        with:
          python-version: "3.12"
      - name: Install prime
        run: pip install prime-tpu
      - name: Lab workspace hygiene
        run: prime lab hygiene --plain
"""


def write_github_workflow(workspace: str | Path = ".") -> Path:
    """Write the hygiene CI workflow into the workspace; returns its path."""
    path = Path(workspace).expanduser().resolve() / GITHUB_WORKFLOW_RELPATH
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(GITHUB_WORKFLOW_YAML, encoding="utf-8")
    return path
