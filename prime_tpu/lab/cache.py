"""Disk row/detail caches for Lab sections (reference: prime_lab_app/cache.py).

Section rows are cached as JSON under ``.prime-lab/cache/`` with a freshness
timestamp: the TUI/data layer shows cached rows instantly and hydrates in the
background; a TTL marks rows stale without deleting them (stale data beats a
spinner).
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Any

DEFAULT_TTL_S = 300.0


class LabCache:
    def __init__(self, workspace: str | Path = ".", ttl_s: float = DEFAULT_TTL_S) -> None:
        self.directory = Path(workspace) / ".prime-lab" / "cache"
        self.ttl_s = ttl_s

    def _path(self, section: str) -> Path:
        safe = section.replace("/", "_")
        return self.directory / f"{safe}.json"

    def put(self, section: str, rows: Any) -> None:
        self.directory.mkdir(parents=True, exist_ok=True)
        self._path(section).write_text(json.dumps({"savedAt": time.time(), "rows": rows}, default=str))

    def get(self, section: str) -> tuple[Any | None, bool]:
        """Return (rows, fresh). rows is None when never cached."""
        path = self._path(section)
        if not path.exists():
            return None, False
        try:
            data = json.loads(path.read_text())
        except json.JSONDecodeError:
            return None, False
        saved_at = data.get("savedAt", 0) if isinstance(data, dict) else None
        if not isinstance(saved_at, (int, float)):
            return None, False  # foreign/corrupt cache file — treat as a miss
        fresh = time.time() - saved_at < self.ttl_s
        return data.get("rows"), fresh

    def invalidate(self, section: str | None = None) -> None:
        if section is not None:
            self._path(section).unlink(missing_ok=True)
            return
        if self.directory.exists():
            for path in self.directory.glob("*.json"):
                path.unlink(missing_ok=True)
