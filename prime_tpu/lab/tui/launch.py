"""Launch runner: config cards -> platform jobs (reference launch_runner.py).

A config card is a TOML file under ``<workspace>/.prime-lab/launch/``:

    [launch]
    kind = "train" | "eval"
    name = "sweep-lr3e4"          # optional display name

    [train]                       # kind=train: hosted-training TOML payload
    model = "llama3-8b"
    env = "arith-rl"
    ...

    [eval]                        # kind=eval: hosted eval config
    env = "arith-rl"
    model = "llama3-8b"
    tpu_type = "v5e-8"

The Lab shell lists cards in the launch section; launching submits through
the same clients the CLI uses and reports the created run id.
"""

from __future__ import annotations

from dataclasses import dataclass
from pathlib import Path
from typing import Any

from prime_tpu.utils.compat import tomllib


class LaunchError(RuntimeError):
    pass


@dataclass
class LaunchCard:
    path: Path
    kind: str
    name: str
    payload: dict[str, Any]


def launch_dir(workspace: str | Path = ".") -> Path:
    return Path(workspace) / ".prime-lab" / "launch"


def scan_cards(workspace: str | Path = ".") -> list[LaunchCard]:
    cards = []
    base = launch_dir(workspace)
    if not base.exists():
        return cards
    for path in sorted(base.glob("*.toml")):
        try:
            data = tomllib.loads(path.read_text())
        except (OSError, tomllib.TOMLDecodeError):
            continue
        launch = data.get("launch", {})
        kind = launch.get("kind")
        if kind not in ("train", "eval"):
            continue
        cards.append(
            LaunchCard(
                path=path,
                kind=kind,
                name=launch.get("name", path.stem),
                payload=data.get(kind, {}),
            )
        )
    return cards


def format_toml(card: LaunchCard) -> str:
    """Serialize a card back to TOML (reference toml_format.py role). Flat
    scalar payloads only — exactly what scan_cards accepts."""

    def literal(value: Any) -> str:
        if isinstance(value, bool):
            return "true" if value else "false"
        if isinstance(value, (int, float)):
            return str(value)
        text = str(value).replace("\\", "\\\\").replace('"', '\\"')
        return f'"{text}"'

    def bare(key: str) -> str:
        # non-bare keys are quoted so they stay FLAT on reparse (an unquoted
        # dotted key would nest and corrupt the scalar payload contract)
        if key and key.replace("_", "").replace("-", "").isalnum():
            return key
        return literal(key)

    lines = ["[launch]", f'kind = "{card.kind}"', f"name = {literal(card.name)}", ""]
    lines.append(f"[{card.kind}]")
    for key, value in card.payload.items():
        lines.append(f"{bare(key)} = {literal(value)}")
    return "\n".join(lines) + "\n"


def save_card(card: LaunchCard) -> None:
    """Write the card to its path; a reparse failure means a bug in
    format_toml, surfaced as LaunchError rather than a corrupt card."""
    text = format_toml(card)
    try:
        reparsed = tomllib.loads(text)
    except tomllib.TOMLDecodeError as e:  # pragma: no cover - formatter bug
        raise LaunchError(f"card would not reparse: {e}") from e
    if reparsed.get("launch", {}).get("kind") != card.kind:
        raise LaunchError("card would lose its kind on reparse")  # pragma: no cover
    if reparsed.get(card.kind) != card.payload:
        raise LaunchError("card payload would not round-trip")
    card.path.parent.mkdir(parents=True, exist_ok=True)
    card.path.write_text(text)


def parse_field_value(text: str) -> Any:
    """Editor input -> typed TOML value (int / float / bool / string)."""
    stripped = text.strip()
    if stripped.lower() in ("true", "false"):
        return stripped.lower() == "true"
    for cast in (int, float):
        try:
            return cast(stripped)
        except ValueError:
            continue
    return stripped


def launch_card(card: LaunchCard, api_client) -> dict[str, Any]:
    """Submit a card through the platform clients. Returns {id, kind, status}."""
    if not card.payload:
        raise LaunchError(f"{card.path.name} has no [{card.kind}] payload")
    if card.kind == "train":
        from prime_tpu.api.rl import RLClient

        run = RLClient(api_client).create_run(card.payload)
        return {"id": run.run_id, "kind": "train", "status": run.status}
    if card.kind == "eval":
        from prime_tpu.evals import EvalsClient

        run = EvalsClient(api_client).create_hosted(card.payload)
        return {"id": run["hostedId"], "kind": "eval", "status": run["status"]}
    raise LaunchError(f"unknown card kind {card.kind!r}")
