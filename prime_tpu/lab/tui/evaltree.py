"""Grouped eval-run browser: env → model → run tree with aggregates
(reference prime_lab_app/evaluation_browser.py:35 evaluation_index +
eval_screen tree panel role).

Opened with ``t`` from the local-runs section. The tree is a pure state
machine over the flat run rows the data layer already scans: nodes carry an
indent level and collapse state; group nodes aggregate run count and mean
accuracy; enter on a run drills into the same EvalRunOverview screen the
flat list uses (via the shell's child handoff).

Keys: j/k move · enter/space collapse-toggle a group, enter opens a run ·
g/G first/last · esc back.
"""

from __future__ import annotations

from typing import Any

from prime_tpu.lab.tui.detail import DetailScreen, load_local_eval_detail


def build_tree(runs: list[dict[str, Any]]) -> list[dict[str, Any]]:
    """Flat run rows → ordered node list. Node: {"level": 0|1|2, "label",
    "key", "row"? (runs only), "count", "accuracy" (group mean over runs
    that report one)}. Envs and models sort lexically; runs newest-first by
    runId (run dirs are timestamped names in the results contract)."""
    index: dict[str, dict[str, list[dict[str, Any]]]] = {}
    for run in runs:
        env = str(run.get("env", "?"))
        model = str(run.get("model", "?"))
        index.setdefault(env, {}).setdefault(model, []).append(run)

    def mean_accuracy(items: list[dict[str, Any]]) -> float | None:
        values = [r["accuracy"] for r in items if isinstance(r.get("accuracy"), (int, float))]
        return sum(values) / len(values) if values else None

    nodes: list[dict[str, Any]] = []
    for env in sorted(index):
        env_runs = [r for models in index[env].values() for r in models]
        nodes.append(
            {
                "level": 0,
                "key": env,
                "label": env,
                "count": len(env_runs),
                "accuracy": mean_accuracy(env_runs),
            }
        )
        for model in sorted(index[env]):
            model_runs = index[env][model]
            nodes.append(
                {
                    "level": 1,
                    "key": f"{env}/{model}",
                    "label": model,
                    "count": len(model_runs),
                    "accuracy": mean_accuracy(model_runs),
                }
            )
            for run in sorted(model_runs, key=lambda r: str(r.get("runId", "")), reverse=True):
                nodes.append(
                    {
                        "level": 2,
                        "key": f"{env}/{model}/{run.get('runId', '?')}",
                        "label": str(run.get("runId", "?")),
                        "count": 1,
                        "accuracy": run.get("accuracy"),
                        "row": run,
                    }
                )
    return nodes


class EvalTreeScreen(DetailScreen):
    def __init__(self, runs: list[dict[str, Any]]) -> None:
        self.title = "eval runs by env/model"
        self.nodes = build_tree(runs)
        self.cursor = 0
        self.collapsed: set[str] = set()
        self.child: DetailScreen | None = None

    # -- visibility ------------------------------------------------------------

    def visible(self) -> list[int]:
        """Indices of nodes whose ancestors are all expanded."""
        out: list[int] = []
        hidden_below: int | None = None  # level under which nodes are hidden
        for index, node in enumerate(self.nodes):
            level = node["level"]
            if hidden_below is not None:
                if level > hidden_below:
                    continue
                hidden_below = None
            out.append(index)
            if level < 2 and node["key"] in self.collapsed:
                hidden_below = level
        return out

    def current(self) -> dict[str, Any] | None:
        vis = self.visible()
        if not vis:
            return None
        if self.cursor not in vis:
            self.cursor = vis[0]
        return self.nodes[self.cursor]

    def _step(self, delta: int) -> None:
        vis = self.visible()
        if not vis:
            return
        if self.cursor not in vis:
            self.cursor = vis[0]
            return
        pos = vis.index(self.cursor)
        self.cursor = vis[max(0, min(pos + delta, len(vis) - 1))]

    # -- keys ------------------------------------------------------------------

    def on_key(self, key: str) -> str | None:
        node = self.current()
        if key in ("j", "down"):
            self._step(+1)
        elif key in ("k", "up"):
            self._step(-1)
        elif key == "g":
            vis = self.visible()
            if vis:
                self.cursor = vis[0]
        elif key == "G":
            vis = self.visible()
            if vis:
                self.cursor = vis[-1]
        elif key in ("enter", " ", "space"):
            if node is None:
                return None
            if node["level"] < 2:
                if node["key"] in self.collapsed:
                    self.collapsed.discard(node["key"])
                    return f"expanded {node['label']}"
                self.collapsed.add(node["key"])
                return f"collapsed {node['label']}"
            if key == "enter":
                try:
                    self.child = load_local_eval_detail(node["row"])
                except Exception as e:  # noqa: BLE001 - drill-down must not kill the tree
                    return f"open failed: {e}"[:120]
        else:
            return super().on_key(key)
        return None

    # -- render ----------------------------------------------------------------

    def render(self):
        from rich.console import Group
        from rich.text import Text

        if not self.nodes:
            return Text("(no local eval runs)", style="dim")
        parts: list[Any] = []
        for index in self.visible():
            node = self.nodes[index]
            selected = index == self.cursor
            level = node["level"]
            if level < 2:
                marker = "▸" if node["key"] in self.collapsed else "▾"
                label = f"{'  ' * level}{marker} {node['label']}"
                extra = f"  {node['count']} run(s)"
            else:
                label = f"    {node['label']}"
                extra = ""
            accuracy = node.get("accuracy")
            if isinstance(accuracy, (int, float)):
                extra += f"  acc={accuracy:.1%}"
            style = "reverse" if selected else ("bold" if level == 0 else "")
            parts.append(
                Text(label + extra, style=style or None, no_wrap=True, overflow="ellipsis")
            )
        parts.append(Text(""))
        parts.append(Text("j/k move · enter open/toggle · space toggle · esc back", style="dim"))
        return Group(*parts)
