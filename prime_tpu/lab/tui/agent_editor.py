"""Agent config editor: add/edit chat agents from inside the shell
(reference prime_lab_app/agent_cards.py agent-config role — there a Textual
card widget; here a field editor over ``.prime-lab/agents.json``, the file
``load_agents_config`` reads and ``lab setup`` templates).

Fields: name · dialect (enter cycles through the runtime's dialect table
instead of free text — a typo'd dialect would only fail at spawn time) ·
command (free text, shlex-split at spawn).

Keys: j/k move · enter edit value (dialect: cycle) · s save · d delete this
agent from the config · esc back.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any

from prime_tpu.lab.tui.detail import CLOSE, DetailScreen


def _dialects() -> tuple[str, ...]:
    """The runtime's own dialect table — the cycle UI exists so a config can
    only name a dialect the runtime will actually accept at spawn."""
    from prime_tpu.lab.agents import DIALECTS

    return tuple(sorted(DIALECTS))


def _config_path(workspace) -> Path:
    return Path(workspace) / ".prime-lab" / "agents.json"


def load_raw_agents(workspace) -> list[dict[str, Any]]:
    """The agents.json rows verbatim (unlike load_agents_config, which
    normalizes + drops incomplete rows — the editor must see those too)."""
    path = _config_path(workspace)
    try:
        loaded = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError):
        return []
    rows = loaded.get("agents") if isinstance(loaded, dict) else loaded
    return [dict(r) for r in rows if isinstance(r, dict)] if isinstance(rows, list) else []


def save_agents(workspace, agents: list[dict[str, Any]]) -> None:
    path = _config_path(workspace)
    path.parent.mkdir(parents=True, exist_ok=True)
    existing: dict[str, Any] = {}
    try:
        loaded = json.loads(path.read_text())
        if isinstance(loaded, dict):
            existing = loaded  # keep unknown top-level keys (_example, notes)
    except (OSError, json.JSONDecodeError):
        pass
    existing["agents"] = agents
    path.write_text(json.dumps(existing, indent=2) + "\n")


class AgentConfigEditor(DetailScreen):
    FIELDS = ("name", "dialect", "command")

    def __init__(self, workspace, agent_name: str | None = None) -> None:
        self.workspace = workspace
        self.agents = load_raw_agents(workspace)
        self.index: int | None = None
        if agent_name is not None:
            for i, row in enumerate(self.agents):
                if str(row.get("name")) == agent_name:
                    self.index = i
                    break
        if self.index is None and agent_name and agent_name.startswith("agent-"):
            # a nameless row is listed as its synthesized "agent-<i>" label
            # (chat.load_agents_config) — resolve it back to the row rather
            # than appending a duplicate
            suffix = agent_name.rsplit("-", 1)[1]
            if suffix.isdigit():
                position = int(suffix)
                if position < len(self.agents) and not self.agents[position].get("name"):
                    self.index = position
        if self.index is None:
            self.agents.append({"name": agent_name or "new-agent", "dialect": "acp", "command": ""})
            self.index = len(self.agents) - 1
            self.dirty = True
        else:
            self.dirty = False
        self.entry = self.agents[self.index]
        self.title = f"agent: {self.entry.get('name', '?')}"
        self.cursor = 0
        self.input: str | None = None
        self.message = ""

    # the shell's 'q'-quits guard keys off this attribute name
    @property
    def search_input(self) -> str | None:
        return self.input

    def save(self) -> str:
        if not str(self.entry.get("command", "")).strip():
            return "command is required (the agent subprocess to spawn)"
        try:
            save_agents(self.workspace, self.agents)
        except OSError as e:
            return f"save failed: {e}"
        self.dirty = False
        self.title = f"agent: {self.entry.get('name', '?')}"
        return f"saved {self.entry.get('name')}"

    def on_key(self, key: str) -> str | None:
        if self.input is not None:
            if key == "enter":
                field = self.FIELDS[self.cursor]
                self.entry[field] = self.input.strip()
                self.input = None
                self.dirty = True
                return f"{field} set"
            if key == "escape":
                self.input = None
                return "cancelled"
            if key == "backspace":
                self.input = self.input[:-1]
            elif len(key) == 1 and key.isprintable():
                self.input += key
            return None
        if key in ("j", "down"):
            self.cursor = min(self.cursor + 1, len(self.FIELDS) - 1)
        elif key in ("k", "up"):
            self.cursor = max(0, self.cursor - 1)
        elif key == "enter":
            field = self.FIELDS[self.cursor]
            if field == "dialect":
                dialects = _dialects()
                current = str(self.entry.get("dialect", ""))
                pos = dialects.index(current) if current in dialects else -1
                self.entry["dialect"] = dialects[(pos + 1) % len(dialects)]
                self.dirty = True
                return f"dialect: {self.entry['dialect']}"
            self.input = str(self.entry.get(field, ""))
        elif key == "s":
            self.message = self.save()
            return self.message
        elif key == "d":
            name = self.agents[self.index].get("name", "?")
            del self.agents[self.index]
            try:
                save_agents(self.workspace, self.agents)
            except OSError as e:
                return f"delete failed: {e}"
            self.message = f"deleted {name}"
            return CLOSE
        else:
            return super().on_key(key)
        return None

    def render(self):
        from rich.console import Group
        from rich.table import Table
        from rich.text import Text

        grid = Table.grid(padding=(0, 2))
        for index, field in enumerate(self.FIELDS):
            selected = index == self.cursor
            if selected and self.input is not None:
                value = Text(f"{self.input}▌", style="bold reverse")
            else:
                shown = str(self.entry.get(field, ""))
                if field == "dialect":
                    shown += "  (enter cycles)"
                value = Text(shown, style="reverse" if selected else "")
            grid.add_row(Text(field, style="bold" if selected else "dim"), value)
        parts: list[Any] = [grid, Text("")]
        if self.dirty:
            parts.append(Text("unsaved changes", style="yellow"))
        if self.message:
            parts.append(Text(self.message, style="cyan"))
        parts.append(Text("enter edit/cycle · s save · d delete · esc back", style="dim"))
        return Group(*parts)
