"""Run-comparison screen: metric deltas + per-sample correctness flips
(the `prime eval compare` CLI surface, in-shell — reference eval_screen
comparison role).

Opened from the local-runs section: `x` marks the selected run as the
baseline (A), `x` on a second run opens this screen comparing A → B.

Keys: j/k move over flips · f cycle filter (all → regressions →
improvements) · enter expand/collapse the selected flip's completions ·
esc back.
"""

from __future__ import annotations

from typing import Any

from prime_tpu.lab.tui.detail import DetailScreen, _wrap

_FILTERS = ("all", "regressions", "improvements")


class RunCompareScreen(DetailScreen):
    def __init__(self, label_a: str, label_b: str, comparison) -> None:
        self.title = f"compare: {label_a} → {label_b}"
        self.label_a = label_a
        self.label_b = label_b
        self.comparison = comparison
        self.cursor = 0
        self.filter_mode = "all"
        self.expanded = False

    def visible(self) -> list[int]:
        flips = self.comparison.flips
        if self.filter_mode == "all":
            return list(range(len(flips)))
        want = "regression" if self.filter_mode == "regressions" else "improvement"
        return [i for i, f in enumerate(flips) if f.direction == want]

    def on_key(self, key: str) -> str | None:
        vis = self.visible()
        if key in ("j", "down"):
            if vis:
                pos = vis.index(self.cursor) if self.cursor in vis else -1
                self.cursor = vis[min(pos + 1, len(vis) - 1)]
                self.expanded = False
        elif key in ("k", "up"):
            if vis:
                pos = vis.index(self.cursor) if self.cursor in vis else 1
                self.cursor = vis[max(pos - 1, 0)]
                self.expanded = False
        elif key == "f":
            position = _FILTERS.index(self.filter_mode)
            self.filter_mode = _FILTERS[(position + 1) % len(_FILTERS)]
            fresh = self.visible()
            if fresh:
                self.cursor = fresh[0]
            self.expanded = False
            return f"filter: {self.filter_mode} ({len(fresh)} flips)"
        elif key == "enter":
            self.expanded = not self.expanded
        else:
            return super().on_key(key)
        return None

    def render(self):
        from rich.console import Group
        from rich.table import Table
        from rich.text import Text

        comparison = self.comparison
        parts: list[Any] = []
        head = Table.grid(padding=(0, 2))
        head.add_row(
            Text("shared samples", style="dim"), Text(str(comparison.shared)),
            Text("only A / only B", style="dim"),
            Text(f"{comparison.only_a} / {comparison.only_b}"),
        )
        head.add_row(
            Text("improvements", style="dim"),
            Text(str(comparison.improvements), style="green"),
            Text("regressions", style="dim"),
            Text(str(comparison.regressions), style="red"),
        )
        parts.append(head)

        if comparison.metrics:
            grid = Table.grid(padding=(0, 2))
            grid.add_row(*(Text(h, style="bold dim") for h in ("metric", "A", "B", "Δ")))
            for name, a, b, delta in comparison.metrics:
                style = "" if delta in (None, 0) else ("green" if delta > 0 else "red")
                grid.add_row(
                    Text(name),
                    Text(f"{a:.4g}" if isinstance(a, (int, float)) else "—", style="dim"),
                    Text(f"{b:.4g}" if isinstance(b, (int, float)) else "—", style="dim"),
                    Text(f"{delta:+.4g}" if delta is not None else "—", style=style or None),
                )
            parts.append(Text(""))
            parts.append(grid)

        if comparison.duplicates:
            parts.append(
                Text(
                    f"(multi-rollout runs: first rollout per prompt compared, "
                    f"{comparison.duplicates} later rollout(s) ignored)",
                    style="dim",
                )
            )
        vis = self.visible()
        parts.append(Text(""))
        if not vis:
            parts.append(Text(f"(no {self.filter_mode} flips)", style="dim"))
        # window around the cursor so j/k can reach every flip
        window = 14
        start = 0
        if self.cursor in vis:
            position = vis.index(self.cursor)
            start = max(0, min(position - window // 2, len(vis) - window))
        if start:
            parts.append(Text(f"… {start} earlier flips", style="dim"))
        for index in vis[start : start + window]:
            flip = comparison.flips[index]
            selected = index == self.cursor
            marker = "↑" if flip.direction == "improvement" else "↓"
            color = "green" if flip.direction == "improvement" else "red"
            parts.append(
                Text(
                    f"{marker} {flip.key[:70]}",
                    style=f"reverse {color}" if selected else color,
                    no_wrap=True,
                    overflow="ellipsis",
                )
            )
            if selected and self.expanded:
                body = Text()
                for label, text in (
                    (f"A ({self.label_a})", flip.completion_a),
                    (f"B ({self.label_b})", flip.completion_b),
                    ("answer", flip.answer),
                ):
                    body.append(f"  {label}:\n", style="bold dim")
                    for line in _wrap(text, width=70)[:6]:
                        body.append(f"    {line}\n")
                parts.append(body)
        if len(vis) > start + window:
            parts.append(Text(f"… {len(vis) - start - window} more flips", style="dim"))
        parts.append(Text(""))
        parts.append(Text("j/k move · f filter · enter expand · esc back", style="dim"))
        return Group(*parts)
