"""Terminal driver for the Lab TUI: raw-mode keys + rich.Live rendering.

``run_interactive`` owns the tty; ``render_text`` renders any app frame to a
plain string (tests and snapshots drive the app exclusively through it).
"""

from __future__ import annotations

import select
import sys
from typing import Any, Protocol


class TuiApp(Protocol):
    quit: bool

    def render(self) -> Any: ...
    def on_key(self, key: str) -> None: ...
    def tick(self) -> None: ...


def render_text(app: TuiApp, width: int = 120, height: int = 40) -> str:
    """Render one frame to plain text (headless — no tty required)."""
    from rich.console import Console

    console = Console(width=width, height=height, force_terminal=False)
    with console.capture() as capture:
        console.print(app.render())
    return capture.get()


def run_interactive(app: TuiApp, tick_interval_s: float = 2.0) -> None:
    """Run the app against the real terminal until it quits."""
    import termios
    import tty

    from rich.console import Console
    from rich.live import Live

    from prime_tpu.lab.tui.keys import decode_keys

    if not sys.stdin.isatty():
        raise RuntimeError("prime lab needs an interactive terminal (try `prime lab view`)")

    stdin_fd = sys.stdin.fileno()
    saved_attrs = termios.tcgetattr(stdin_fd)
    console = Console()
    try:
        tty.setcbreak(stdin_fd)
        with Live(app.render(), console=console, screen=True, auto_refresh=False) as live:
            while not app.quit:
                # a busy screen (streaming agent turn) renders at 4 Hz so
                # chunks appear as they arrive, not in tick-sized jumps
                interval = tick_interval_s
                top = getattr(app, "screens", None)
                if top and getattr(top[-1], "busy", False):
                    interval = 0.25
                ready, _, _ = select.select([stdin_fd], [], [], interval)
                if ready:
                    import os

                    data = os.read(stdin_fd, 64)
                    for key in decode_keys(data):
                        if key == "ctrl+c":
                            return
                        app.on_key(key)
                        if app.quit:
                            break
                else:
                    app.tick()
                live.update(app.render(), refresh=True)
    finally:
        termios.tcsetattr(stdin_fd, termios.TCSADRAIN, saved_attrs)
