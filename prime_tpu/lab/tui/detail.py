"""Drill-down detail screens for the Lab shell (VERDICT r2 #3).

Reference roles: prime_lab_app/eval_screen.py:1 (per-sample rollout browser
with search/filter), training_screen.py:100 (charts + config + log tabs),
env inspection depth from commands/env.py. Same design rule as the shell:
every screen is a pure state machine — ``on_key`` mutates state and returns a
status string (or CLOSE), ``render`` produces a rich renderable — so all
navigation is testable headlessly.

Screens are pushed onto ``PrimeLabApp.screens`` by enter on a row; escape /
backspace pops. Data comes from the run dir (local rows) or the platform
clients (hub rows), fetched once at push time and on explicit refresh — a
detail screen must never block the render loop on the network.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Callable

CLOSE = "__close__"

_PAGE = 16  # text-window lines per scroll page


def _wrap(text: str, width: int = 76) -> list[str]:
    lines: list[str] = []
    for raw in str(text).splitlines() or [""]:
        while len(raw) > width:
            lines.append(raw[:width])
            raw = raw[width:]
        lines.append(raw)
    return lines


def _media_placeholder(kind: str, part: dict[str, Any]) -> str | None:
    """Non-text content parts render as explicit placeholders instead of
    vanishing (reference eval_render.py media handling): a multimodal turn
    must say what it carried even though a terminal can't show it."""
    if kind in ("image_url", "input_image", "image"):
        url = part.get("image_url")
        if isinstance(url, dict):
            url = url.get("url", "")
        url = str(url or part.get("url") or "")
        if url.startswith("data:"):
            return f"[image: inline data, {len(url)} bytes]"
        return f"[image: {url[:60]}]" if url else "[image]"
    if kind in ("input_audio", "audio"):
        audio = part.get("input_audio")
        fmt = audio.get("format", "") if isinstance(audio, dict) else ""
        return f"[audio: {fmt}]" if fmt else "[audio]"
    if kind in ("file", "input_file", "attachment"):
        name = str(
            part.get("filename") or part.get("file_name") or part.get("name") or ""
        )
        return f"[file: {name[:60]}]" if name else "[file]"
    return None


def _content_text(content: Any) -> str:
    """Chat-message content → text. Handles the OpenAI part-list shape
    ([{"type": "text", "text": ...}, ...]) alongside plain strings, surfaces
    reasoning-part content (thinking models) inline, and renders image/
    audio/file parts as placeholders rather than dropping them."""
    if isinstance(content, list):
        parts = []
        for part in content:
            if isinstance(part, dict):
                kind = str(part.get("type", ""))
                text = str(part.get("text", part.get("content", "")))
                if not text and kind in ("reasoning", "thinking"):
                    text = str(part.get(kind, ""))
                if text and kind in ("reasoning", "thinking"):
                    text = f"[reasoning] {text}"
                if not text and kind not in ("", "text", "reasoning", "thinking"):
                    # kinds handled above stay empty when their text is empty
                    placeholder = _media_placeholder(kind, part)
                    text = placeholder or f"[{kind}]"  # unknown parts never vanish
                parts.append(text)
            else:
                parts.append(str(part))
        return "\n".join(p for p in parts if p)
    return str(content)


def _tool_call_lines(tool_calls: Any) -> list[str]:
    """One line per tool call: name(args) [-> id]. Tolerates both the OpenAI
    function-call shape ({"function": {"name", "arguments"}}) and flat
    {"name", "arguments"} records (reference eval_render.tool_call_parts)."""
    lines: list[str] = []
    if not isinstance(tool_calls, list):
        return lines
    for call in tool_calls:
        if not isinstance(call, dict):
            lines.append(str(call))
            continue
        fn = call.get("function") if isinstance(call.get("function"), dict) else call
        name = str(fn.get("name", "?"))
        args = fn.get("arguments", "")
        if isinstance(args, dict):
            import json as _json

            args = _json.dumps(args, sort_keys=True)
        args = str(args)
        if len(args) > 200:
            args = args[:200] + "…"
        call_id = call.get("id") or call.get("tool_call_id")
        lines.append(f"{name}({args})" + (f" -> {call_id}" if call_id else ""))
    return lines


def sample_sections(sample: dict[str, Any]) -> list[tuple[str, str]]:
    """(label, text) sections for one eval sample. Chat rollouts (a
    ``messages`` list — multi-turn envs, hub samples) render one section per
    role turn, including tool calls, tool results, and reasoning content;
    flat rows render PROMPT/COMPLETION/ANSWER. Token usage and env state
    get their own sections when the record carries them (reference
    eval_render.py rollout-history / build_usage_text / build_state_text
    roles)."""
    sections: list[tuple[str, str]] = []
    messages = sample.get("messages")
    if isinstance(messages, list) and messages:
        # call-id -> tool name across ALL turns, so a tool reply three turns
        # after its call still names the tool it answers (multi-turn chains)
        call_names: dict[str, str] = {}
        for message in messages:
            if isinstance(message, dict) and isinstance(message.get("tool_calls"), list):
                for call in message["tool_calls"]:
                    if isinstance(call, dict):
                        fn = call.get("function") if isinstance(call.get("function"), dict) else call
                        call_id = str(call.get("id") or call.get("tool_call_id") or "")
                        if call_id:
                            call_names[call_id] = str(fn.get("name", "?"))
        for message in messages:
            if isinstance(message, dict):
                role = str(message.get("role", "?")).upper()
                body = _content_text(message.get("content", ""))
                reasoning = message.get("reasoning") or message.get("reasoning_content")
                if reasoning:
                    prefix = f"[reasoning] {reasoning}"
                    body = f"{prefix}\n{body}" if body else prefix
                refusal = message.get("refusal")
                if refusal:
                    line = f"[refusal] {refusal}"
                    body = f"{line}\n{body}" if body else line
                # assistant tool calls render as call lines; tool replies
                # label with the calling tool's NAME (id as fallback) so a
                # multi-turn chain reads call -> result top-down
                calls = _tool_call_lines(message.get("tool_calls"))
                if calls:
                    body = "\n".join(
                        ([body] if body else []) + [f"⚒ {line}" for line in calls]
                    )
                if role == "TOOL" and message.get("tool_call_id"):
                    call_id = str(message["tool_call_id"])
                    name = call_names.get(call_id)
                    role = f"TOOL {name} ({call_id})" if name else f"TOOL {call_id} (unmatched)"
                if message.get("error"):
                    line = f"[error] {message['error']}"
                    body = f"{body}\n{line}" if body else line
                    role = f"{role} ⚠"
                sections.append((role, body))
            else:
                sections.append(("?", str(message)))
        # completion/answer still shown unless the completion IS the last turn
        completion = str(sample.get("completion", ""))
        if completion and (not sections or completion != sections[-1][1]):
            sections.append(("COMPLETION", completion))
        if sample.get("answer") not in (None, ""):
            sections.append(("ANSWER", str(sample["answer"])))
    else:
        for label, key in (
            ("PROMPT", "prompt"), ("COMPLETION", "completion"), ("ANSWER", "answer")
        ):
            sections.append((label, str(sample.get(key, ""))))
    # a failed rollout's record carries the harness error — render it as its
    # own red section, never buried in state
    error = sample.get("error") or sample.get("exception")
    if error:
        sections.append(("ERROR", str(error)))
    usage = sample.get("usage")
    if isinstance(usage, dict) and usage:
        sections.append(
            ("USAGE", "  ".join(f"{k}={usage[k]}" for k in sorted(usage)))
        )
    state = sample.get("state")
    if isinstance(state, dict) and state:
        import json as _json

        sections.append(("STATE", _json.dumps(state, sort_keys=True)[:500]))
    return sections


class DetailScreen:
    """Base: key routing shared by every detail screen."""

    title = "detail"

    def on_key(self, key: str) -> str | None:
        if key in ("escape", "backspace"):
            return CLOSE
        return None

    def render(self):  # pragma: no cover - overridden
        from rich.text import Text

        return Text("")


class EvalSampleBrowser(DetailScreen):
    """Per-sample prompt/completion/answer/reward browser with filter and
    search (reference eval_screen.py RolloutViewer:560 role).

    ``samples``: [{"prompt", "completion", "answer", "reward", "correct"}] —
    a list, or any lazy sequence with ``__len__``/``__getitem__``/``__iter__``
    (``evalrecords.IndexedJsonl`` for big local runs).
    Keys: n/→ next · p/← prev · g/G first/last · f cycle filter
    (all → correct → incorrect) · / incremental search (enter jumps to the
    next match, esc cancels) · j/k scroll long sample text · m toggle
    markdown/LaTeX rendering · esc back.
    """

    FILTERS = ("all", "correct", "incorrect")

    def __init__(self, title: str, samples, source: str = "") -> None:
        self.title = title
        self.samples = samples
        self.source = source
        self.idx = 0
        self.scroll = 0
        self.filter_mode = "all"
        self.search = ""
        self.search_input: str | None = None  # non-None = capturing keys
        self.rendered = False  # m: markdown/LaTeX translation of sample text
        self._flags: list[bool] | None = None  # per-row `correct`, one pass

    # -- sample selection ------------------------------------------------------

    def visible(self) -> list[int]:
        """Indices of samples passing the filter. Correctness flags are
        extracted in ONE streaming pass and cached — visible() runs on every
        keypress and render, and must stay O(n-bools) even when ``samples``
        is a lazily-parsed IndexedJsonl over a huge file."""
        if self.filter_mode == "all":
            return list(range(len(self.samples)))
        if self._flags is None:
            self._flags = [bool(s.get("correct")) for s in self.samples]
        want = self.filter_mode == "correct"
        return [i for i, flag in enumerate(self._flags) if flag == want]

    def current(self) -> dict[str, Any] | None:
        vis = self.visible()
        if not vis:
            return None
        if self.idx not in vis:
            self.idx, self.scroll = vis[0], 0
        return self.samples[self.idx]

    def _step(self, delta: int) -> None:
        vis = self.visible()
        if not vis:
            return
        if self.idx not in vis:
            # cursor was filtered out: re-snap to the first visible sample
            # (scroll reset like every other navigation, not mid-text)
            self.idx, self.scroll = vis[0], 0
            return
        pos = vis.index(self.idx)
        self.idx = vis[max(0, min(pos + delta, len(vis) - 1))]
        self.scroll = 0

    def _search_jump(self) -> str:
        if not self.search:
            return "empty search"
        needle = self.search.lower()
        vis = self.visible()
        if not vis:
            return "no samples"
        start = vis.index(self.idx) if self.idx in vis else 0
        order = vis[start + 1 :] + vis[: start + 1]  # wrap, current last
        for i in order:
            s = self.samples[i]
            hay = " ".join(text for _, text in sample_sections(s))
            if needle in hay.lower():
                self.idx = i
                self.scroll = 0
                return f"match at sample {i + 1}/{len(self.samples)}"
        return f"no match for {self.search!r}"

    def on_key(self, key: str) -> str | None:
        if self.search_input is not None:
            if key == "enter":
                self.search = self.search_input
                self.search_input = None
                return self._search_jump()
            if key == "escape":
                self.search_input = None
                return "search cancelled"
            if key == "backspace":
                self.search_input = self.search_input[:-1]
            elif len(key) == 1 and key.isprintable():
                self.search_input += key
            return f"search: {self.search_input}"
        if key in ("n", "right", "down"):
            self._step(+1)
        elif key in ("p", "left", "up"):
            self._step(-1)
        elif key == "g":
            vis = self.visible()
            if vis:
                self.idx, self.scroll = vis[0], 0
        elif key == "G":
            vis = self.visible()
            if vis:
                self.idx, self.scroll = vis[-1], 0
        elif key == "f":
            pos = self.FILTERS.index(self.filter_mode)
            self.filter_mode = self.FILTERS[(pos + 1) % len(self.FILTERS)]
            return f"filter: {self.filter_mode} ({len(self.visible())} samples)"
        elif key == "/":
            self.search_input = ""
            return "search: "
        elif key == "j":
            self.scroll += _PAGE // 2
        elif key == "k":
            self.scroll = max(0, self.scroll - _PAGE // 2)
        elif key == "m":
            self.rendered = not self.rendered
            self.scroll = 0
            return f"markdown rendering {'on' if self.rendered else 'off'}"
        else:
            return super().on_key(key)
        return None

    def render(self):
        from rich.console import Group
        from rich.table import Table
        from rich.text import Text

        sample = self.current()
        vis = self.visible()
        if sample is None:
            return Text(f"(no {self.filter_mode} samples)", style="dim")
        pos = vis.index(self.idx) + 1

        head = Table.grid(padding=(0, 1))
        reward = sample.get("reward")
        head.add_row(
            Text(f"sample {pos}/{len(vis)}", style="bold"),
            Text(f"filter={self.filter_mode}", style="dim"),
            Text(
                f"reward={reward:.3f}" if isinstance(reward, (int, float)) else "reward=—",
                style="green" if sample.get("correct") else "red",
            ),
            Text(f"search={self.search!r}" if self.search else "", style="dim"),
        )

        body_lines: list[tuple[str, str]] = []  # (style, line)
        for label, content in sample_sections(sample):
            header_style = (
                "bold red" if label.startswith("ERROR") or label.endswith("⚠") else "bold cyan"
            )
            body_lines.append((header_style, f"── {label} " + "─" * 40))
            if self.rendered:
                from prime_tpu.lab.tui.markdown import markdown_lines

                for style, line in markdown_lines(content):
                    for piece in _wrap(line):
                        body_lines.append((style, piece))
            else:
                for line in _wrap(content):
                    body_lines.append(("", line))
        window = body_lines[self.scroll : self.scroll + _PAGE]
        if self.scroll and not window:
            self.scroll = max(0, len(body_lines) - _PAGE)
            window = body_lines[self.scroll :]
        text = Text()
        for style, line in window:
            text.append(line + "\n", style=style or None)
        if len(body_lines) > self.scroll + _PAGE:
            text.append(f"… {len(body_lines) - self.scroll - _PAGE} more lines (j/k)", style="dim")
        footer = Text(
            "n/p sample · f filter · / search · j/k scroll · m markdown · esc back",
            style="dim",
        )
        if self.search_input is not None:
            footer = Text(f"search: {self.search_input}▌", style="bold")
        return Group(head, Text(""), text, Text(""), footer)


class EvalRunOverview(DetailScreen):
    """Aggregate view of one eval run BEFORE per-sample drill-down
    (reference eval_screen.py overview + eval_records.py:55 RunOverviewStats
    role): pass rate, reward distribution, per-metric summaries — streamed
    once from results.jsonl, no rows retained.

    Keys: enter/s open the sample browser · r re-stream (live runs) ·
    esc back.
    """

    def __init__(
        self,
        title: str,
        records,
        info: dict[str, Any] | None = None,
        source: str = "",
    ) -> None:
        from prime_tpu.lab.evalrecords import run_overview

        self.title = title
        self.records = records
        self.info = info or {}
        self.source = source
        self.overview = run_overview(records)
        self.child: DetailScreen | None = None

    def on_key(self, key: str) -> str | None:
        if key in ("enter", "s"):
            self.child = EvalSampleBrowser(
                title=self.title, samples=self.records, source=self.source
            )
            return None
        if key == "r":
            from prime_tpu.lab.evalrecords import run_overview

            refresh = getattr(self.records, "refresh", None)
            if refresh is not None:
                refresh()
            self.overview = run_overview(self.records)
            return f"reloaded: {self.overview.n_samples} samples"
        return super().on_key(key)

    def render(self):
        from rich.console import Group
        from rich.table import Table
        from rich.text import Text

        from prime_tpu.lab.tui.charts import BLOCKS

        ov = self.overview
        head = Table.grid(padding=(0, 2))
        for key in ("env", "model", "runId"):
            if self.info.get(key):
                head.add_row(Text(key, style="dim"), Text(str(self.info[key])))
        head.add_row(Text("samples", style="dim"), Text(str(ov.n_samples)))
        if ov.pass_rate is not None:
            head.add_row(
                Text("pass rate", style="dim"),
                Text(f"{ov.pass_rate:.1%}", style="green" if ov.pass_rate >= 0.5 else "red"),
            )
        if ov.mean_reward is not None:
            head.add_row(Text("mean reward", style="dim"), Text(f"{ov.mean_reward:.4f}"))

        parts: list[Any] = [head]
        hist = ov.reward_histogram(bins=12)
        if hist and ov.rewards:
            peak = max(hist)
            bars = "".join(
                BLOCKS[int(c / peak * (len(BLOCKS) - 1))] if peak else BLOCKS[0] for c in hist
            )
            lo, hi = min(ov.rewards), max(ov.rewards)
            parts.append(Text(""))
            parts.append(
                Text(f"reward dist  {lo:.2f} {bars} {hi:.2f}", style="cyan")
            )
        if ov.metrics:
            grid = Table.grid(padding=(0, 2))
            grid.add_row(*(Text(h, style="bold dim") for h in ("metric", "n", "mean", "min", "max")))
            for m in ov.metrics:
                grid.add_row(
                    Text(m.name),
                    Text(str(m.count), style="dim"),
                    Text(f"{m.mean:.4g}"),
                    Text(f"{m.minimum:.4g}", style="dim"),
                    Text(f"{m.maximum:.4g}", style="dim"),
                )
            parts.append(Text(""))
            parts.append(grid)
        parts.append(Text(""))
        parts.append(Text("enter samples · r reload · esc back", style="dim"))
        return Group(*parts)


class TrainingRunDetail(DetailScreen):
    """Charts + config + log tail for one training run (reference
    training_screen.py:100 role). Tabs: chart / config / logs.

    Keys: tab or h/l cycle tabs · c cycle charted metric · s toggle EMA
    smoothing · [ / ] zoom the step window out/in · j/k scroll logs ·
    r reload from source · esc back.
    """

    TABS = ("chart", "config", "logs")
    WINDOWS = (None, 512, 128, 32)  # [ and ] walk this zoom ladder

    def __init__(
        self,
        title: str,
        metrics: list[dict[str, Any]],
        config: dict[str, Any] | None = None,
        log_tail: Callable[[], list[str]] | None = None,
        reload: Callable[[], list[dict[str, Any]]] | None = None,
    ) -> None:
        self.title = title
        self.metrics = metrics
        self.config = config or {}
        self._log_tail = log_tail
        self._reload = reload
        self.tab = "chart"
        self.metric_idx = 0
        self.log_scroll = 0
        self._logs: list[str] | None = None
        self.smooth = False
        self.window_idx = 0  # index into WINDOWS

    def metric_keys(self) -> list[str]:
        from prime_tpu.lab.tui.charts import discover_metrics

        return discover_metrics(self.metrics)

    def logs(self) -> list[str]:
        if self._logs is None:
            self._logs = self._log_tail() if self._log_tail else []
        return self._logs

    def on_key(self, key: str) -> str | None:
        if key in ("tab", "l"):
            self.tab = self.TABS[(self.TABS.index(self.tab) + 1) % len(self.TABS)]
            return f"tab: {self.tab}"
        if key == "h":
            self.tab = self.TABS[(self.TABS.index(self.tab) - 1) % len(self.TABS)]
            return f"tab: {self.tab}"
        if key == "c" and self.tab == "chart":
            keys = self.metric_keys()
            if keys:
                self.metric_idx = (self.metric_idx + 1) % len(keys)
                return f"metric: {keys[self.metric_idx]}"
        if key == "s" and self.tab == "chart":
            self.smooth = not self.smooth
            return f"smoothing {'on' if self.smooth else 'off'}"
        if key in ("[", "]") and self.tab == "chart":
            delta = -1 if key == "[" else 1
            self.window_idx = max(0, min(self.window_idx + delta, len(self.WINDOWS) - 1))
            window = self.WINDOWS[self.window_idx]
            return f"window: {'all' if window is None else f'last {window}'}"
        if key == "j" and self.tab == "logs":
            self.log_scroll += _PAGE // 2
            return None
        if key == "k" and self.tab == "logs":
            self.log_scroll = max(0, self.log_scroll - _PAGE // 2)
            return None
        if key == "r":
            if self._reload:
                self.metrics = self._reload()
            self._logs = None
            return "reloaded"
        return super().on_key(key)

    def render(self):
        from rich.console import Group
        from rich.table import Table
        from rich.text import Text

        tabs = Text()
        for name in self.TABS:
            tabs.append(
                f" {name} ", style="reverse" if name == self.tab else "dim"
            )
        parts: list[Any] = [tabs, Text("")]

        if self.tab == "chart":
            from prime_tpu.lab.tui.charts import chart_panel, metric_chart

            keys = self.metric_keys()
            if not keys:
                parts.append(Text("(no numeric metrics)", style="dim"))
            else:
                self.metric_idx = min(self.metric_idx, len(keys) - 1)
                focused = keys[self.metric_idx]
                panel = chart_panel(
                    self.metrics,
                    focused,
                    width=64,
                    height=8,
                    smooth=self.smooth,
                    window=self.WINDOWS[self.window_idx],
                )
                for style, line in panel:
                    parts.append(Text(line, style=style or None, no_wrap=True, overflow="crop"))
                if panel:
                    parts.append(Text(""))
                for key in (k for k in keys if k != focused):
                    line = metric_chart(self.metrics, key, width=64)
                    if line:
                        parts.append(Text(line, no_wrap=True, overflow="crop"))
                last = self.metrics[-1] if self.metrics else {}
                parts.append(Text(""))
                parts.append(
                    Text(
                        " · ".join(
                            f"{k}={last[k]:.4g}" for k in keys if isinstance(last.get(k), (int, float))
                        ),
                        style="dim",
                    )
                )
        elif self.tab == "config":
            if not self.config:
                parts.append(Text("(no config recorded)", style="dim"))
            else:
                grid = Table.grid(padding=(0, 1))
                for key, value in sorted(self.config.items()):
                    rendered = (
                        json.dumps(value) if isinstance(value, (dict, list)) else str(value)
                    )
                    grid.add_row(Text(str(key), style="dim"), Text(rendered[:80]))
                parts.append(grid)
        else:
            lines = self.logs()
            if not lines:
                parts.append(Text("(no logs)", style="dim"))
            else:
                window = lines[self.log_scroll : self.log_scroll + _PAGE]
                if self.log_scroll and not window:
                    self.log_scroll = max(0, len(lines) - _PAGE)
                    window = lines[self.log_scroll :]
                text = Text()
                for line in window:
                    text.append(line[:100] + "\n")
                if len(lines) > self.log_scroll + _PAGE:
                    text.append(
                        f"… {len(lines) - self.log_scroll - _PAGE} more (j/k)", style="dim"
                    )
                parts.append(text)

        parts.append(Text(""))
        parts.append(
            Text(
                "tab/h/l tabs · c metric · s smooth · [/] window · j/k scroll · r reload · esc back",
                style="dim",
            )
        )
        return Group(*parts)


class EnvDetail(DetailScreen):
    """Versions + actions for one environment (reference env inspect /
    versions / actions depth). Cursor moves over the action list; enter
    fetches that action's logs inline.

    Keys: j/k move · enter action logs · r refresh · esc back.
    """

    def __init__(
        self,
        name: str,
        versions: list[dict[str, Any]],
        actions: list[dict[str, Any]],
        fetch_logs: Callable[[str], list[str]] | None = None,
        error: str = "",
    ) -> None:
        self.title = f"env: {name}"
        self.name = name
        self.versions = versions
        self.actions = actions
        self._fetch_logs = fetch_logs
        self.error = error
        self.cursor = 0
        self.logs: list[str] | None = None
        self.logs_for: str | None = None

    def on_key(self, key: str) -> str | None:
        if key in ("j", "down"):
            self.cursor = min(self.cursor + 1, max(len(self.actions) - 1, 0))
        elif key in ("k", "up"):
            self.cursor = max(0, self.cursor - 1)
        elif key == "enter" and self.actions:
            action = self.actions[min(self.cursor, len(self.actions) - 1)]
            action_id = str(action.get("id") or action.get("actionId") or "")
            if not action_id:
                return "action has no id"
            if self._fetch_logs is None:
                return "no log fetcher (offline)"
            try:
                self.logs = self._fetch_logs(action_id)
                self.logs_for = action_id
            except Exception as e:  # noqa: BLE001 - network surface
                return f"logs failed: {e}"
            return f"logs for {action_id}"
        else:
            return super().on_key(key)
        return None

    def render(self):
        from rich.console import Group
        from rich.table import Table
        from rich.text import Text

        parts: list[Any] = []
        if self.error:
            parts.append(Text(f"hub fetch failed: {self.error}", style="red"))
            parts.append(Text(""))
        versions = Table(title="versions", expand=True, pad_edge=False)
        for header in ("VERSION", "CREATED", "STATUS"):
            versions.add_column(header, overflow="ellipsis", no_wrap=True)
        for v in self.versions[:8]:
            versions.add_row(
                str(v.get("version", "—")),
                str(v.get("createdAt", v.get("created_at", "—"))),
                str(v.get("status", "—")),
            )
        if not self.versions:
            parts.append(Text("(no versions)", style="dim"))
        else:
            parts.append(versions)

        actions = Table(title="actions", expand=True, pad_edge=False)
        for header in ("ID", "KIND", "STATUS"):
            actions.add_column(header, overflow="ellipsis", no_wrap=True)
        for index, a in enumerate(self.actions[:12]):
            style = "reverse" if index == min(self.cursor, len(self.actions) - 1) else ""
            actions.add_row(
                str(a.get("id", a.get("actionId", "—"))),
                str(a.get("kind", a.get("type", "—"))),
                str(a.get("status", "—")),
                style=style,
            )
        if self.actions:
            parts.append(actions)
        else:
            parts.append(Text("(no actions)", style="dim"))

        if self.logs is not None:
            parts.append(Text(f"── logs: {self.logs_for} " + "─" * 30, style="bold cyan"))
            text = Text()
            for line in self.logs[-_PAGE:]:
                text.append(line[:100] + "\n")
            parts.append(text if self.logs else Text("(empty)", style="dim"))

        parts.append(Text("j/k move · enter action logs · esc back", style="dim"))
        return Group(*parts)


# -- constructors from app rows (data loading happens HERE, once) -------------


def load_local_eval_detail(row: dict[str, Any]) -> EvalRunOverview:
    """results.jsonl from a local run dir → overview screen (enter drills
    into the lazily-backed sample browser)."""
    from prime_tpu.lab.evalrecords import IndexedJsonl

    run_dir = Path(row.get("dir", ""))
    records = IndexedJsonl(run_dir / "results.jsonl")
    return EvalRunOverview(
        title=f"eval: {row.get('env', '?')}/{row.get('runId', '?')}",
        records=records,
        info=row,
        source=str(run_dir),
    )


def load_hub_eval_detail(row: dict[str, Any], api) -> EvalSampleBrowser:
    """Evals Hub samples for one evaluation → sample browser."""
    from prime_tpu.evals import EvalsClient

    eval_id = str(row.get("evalId", row.get("id", "")))
    samples: list[dict[str, Any]] = []
    error = ""
    try:
        fetched = EvalsClient(api).get_samples(eval_id, limit=200)
        samples = [s.model_dump(by_alias=True, exclude_none=True) for s in fetched]
    except Exception as e:  # noqa: BLE001 - network surface
        error = str(e)
    browser = EvalSampleBrowser(title=f"eval: {eval_id}", samples=samples, source="hub")
    if error:
        browser.title += f" (fetch failed: {error[:60]})"
    return browser


def load_local_training_detail(row: dict[str, Any]) -> TrainingRunDetail:
    """metrics.jsonl rows (+ config.json / train.log when present)."""
    run_dir = Path(row.get("dir", ""))
    config: dict[str, Any] = {}
    for name in ("config.json", "run_config.json"):
        path = run_dir / name
        if path.exists():
            try:
                loaded = json.loads(path.read_text())
                if isinstance(loaded, dict):
                    config = loaded
                    break
            except json.JSONDecodeError:
                pass

    def log_tail() -> list[str]:
        for name in ("train.log", "logs.txt"):
            path = run_dir / name
            if path.exists():
                return path.read_text().splitlines()[-400:]
        return []

    def reload() -> list[dict[str, Any]]:
        from prime_tpu.lab.data import read_jsonl

        return read_jsonl(run_dir / "metrics.jsonl") or row.get("metrics", [])

    return TrainingRunDetail(
        title=f"training: {row.get('run', run_dir.name)}",
        metrics=row.get("metrics", []),
        config=config,
        log_tail=log_tail,
        reload=reload,
    )


def load_platform_training_detail(row: dict[str, Any], api) -> TrainingRunDetail:
    """RL run detail via the platform clients: metrics history + logs."""
    from prime_tpu.api.rl import RLClient

    run_id = str(row.get("runId", row.get("id", "")))
    client = RLClient(api)
    metrics_rows: list[dict[str, Any]] = []
    config: dict[str, Any] = dict(row)
    try:
        fetched = client.metrics(run_id)
        # accept both {"history": [...]} and {metric: [values...]} shapes
        if isinstance(fetched.get("history"), list):
            metrics_rows = [r for r in fetched["history"] if isinstance(r, dict)]
        else:
            series = {
                k: v for k, v in fetched.items() if isinstance(v, list) and v
            }
            length = max((len(v) for v in series.values()), default=0)
            for i in range(length):
                metrics_rows.append(
                    {k: v[i] for k, v in series.items() if i < len(v) and isinstance(v[i], (int, float))}
                )
    except Exception as e:  # noqa: BLE001 - network surface
        config["metricsError"] = str(e)

    def log_tail() -> list[str]:
        try:
            items = client.get_logs(run_id, limit=200)
            return [
                str(item.get("message", item)) if isinstance(item, dict) else str(item)
                for item in items
            ]
        except Exception as e:  # noqa: BLE001
            return [f"(logs failed: {e})"]

    return TrainingRunDetail(
        title=f"training: {run_id}",
        metrics=metrics_rows,
        config=config,
        log_tail=log_tail,
    )


def load_env_detail(row: dict[str, Any], api, installed: dict[str, Any]) -> EnvDetail:
    """Hub versions/actions (when reachable) + local install state."""
    name = str(row.get("name", ""))
    versions: list[dict[str, Any]] = []
    actions: list[dict[str, Any]] = []
    fetch_logs = None
    error = ""
    if api is not None:
        from prime_tpu.envhub import EnvHubClient

        client = EnvHubClient(api)
        try:
            versions = client.versions(name)
            actions = client.actions(name)
            fetch_logs = lambda action_id: client.action_logs(name, action_id)  # noqa: E731
        except Exception as e:  # noqa: BLE001 - network surface
            error = str(e)
    local = installed.get(name)
    if isinstance(local, dict):
        versions = [
            {"version": local.get("version", "installed"), "status": "installed locally"}
        ] + versions
    return EnvDetail(name, versions, actions, fetch_logs=fetch_logs, error=error)
