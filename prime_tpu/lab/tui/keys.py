"""Terminal key decoding: raw stdin bytes -> symbolic key names.

Covers the keys the Lab shell binds (arrows, enter, tab, escape, printable
ASCII). Unrecognized escape sequences decode to None and are ignored.
"""

from __future__ import annotations

ESCAPE_SEQUENCES = {
    b"[A": "up",
    b"[B": "down",
    b"[C": "right",
    b"[D": "left",
    b"[H": "home",
    b"[F": "end",
    b"[5~": "pageup",
    b"[6~": "pagedown",
    b"[3~": "delete",
}


def decode_key(data: bytes) -> str | None:
    """Decode one key's worth of bytes (as read after a select() wakeup)."""
    keys = decode_keys(data)
    return keys[0] if keys else None


def decode_keys(data: bytes) -> list[str]:
    """Decode a buffer that may hold several coalesced keypresses (key
    auto-repeat batches reads: b'jjj', b'\\x1b[A\\x1b[A')."""
    keys: list[str] = []
    index = 0
    while index < len(data):
        byte = data[index : index + 1]
        if byte == b"\x1b":
            # longest escape sequence first
            matched = False
            for length in (3, 2):
                payload = data[index + 1 : index + 1 + length]
                if payload in ESCAPE_SEQUENCES:
                    keys.append(ESCAPE_SEQUENCES[payload])
                    index += 1 + length
                    matched = True
                    break
            if matched:
                continue
            if data[index + 1 : index + 2] == b"[":
                # unrecognized CSI sequence: swallow through its terminator
                # (an alphabetic final byte or '~') so its chars aren't typed
                index += 2
                while index < len(data):
                    final = data[index : index + 1]
                    index += 1
                    if final.isalpha() or final == b"~":
                        break
            else:
                keys.append("escape")
                index += 1
            continue
        if byte in (b"\r", b"\n"):
            keys.append("enter")
        elif byte == b"\t":
            keys.append("tab")
        elif byte in (b"\x7f", b"\x08"):
            keys.append("backspace")
        elif byte == b"\x03":
            keys.append("ctrl+c")
        elif byte == b"\x15":
            keys.append("ctrl+u")
        else:
            try:
                text = byte.decode()
            except UnicodeDecodeError:
                text = ""
            if text.isprintable():
                keys.append(text)
        index += 1
    return keys
