"""In-shell agent chat screen (VERDICT r2 #4).

Reference role: prime_lab_app agent chat + ``agent_widgets.py`` native widget
rendering. The screen is a state machine like every other detail screen; the
only thread is the turn worker (consuming ``AgentRuntime.prompt`` events into
the transcript), so renders never block on the agent process.

Transcript entries: {"role": "user"|"assistant"|"system", "text": str} or
{"role": "widget", "name": str, "args": dict}. Widget calls render natively
via lab/widgets.render_widget.

Actionable widgets (reference agent_widget_model.py role): the newest
un-answered ``choose`` or ``launch_run`` becomes *pending* — while the input
line is empty, ↑/↓ move the option cursor and enter acts (choose: the
selection is sent back to the agent as the next user message and stamped
into the widget; launch_run: the proposal is written as a launch card for
the launch section's arm/confirm flow — chat never launches directly).
Typing anything instead answers in free text, which also clears the pending
state on send.

Keys: printable chars type · enter send/act · backspace delete · esc clears
the input (or closes the screen when empty and idle) · ctrl+u clear line.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Iterator

from prime_tpu.lab.tui.detail import CLOSE, DetailScreen


class AgentChatScreen(DetailScreen):
    def __init__(
        self,
        name: str,
        runtime_factory: Callable[[], Any],
        transcript_limit: int = 200,
        workspace: str | None = None,
    ) -> None:
        self.title = f"agent: {name}"
        self.name = name
        self._factory = runtime_factory
        self._runtime: Any = None
        self.transcript: list[dict[str, Any]] = []
        self.input_buffer = ""
        self.busy = False
        self.error = ""
        self._worker: threading.Thread | None = None
        self._limit = transcript_limit
        self.workspace = workspace
        self.pending: dict[str, Any] | None = None  # newest actionable widget
        self.choice_cursor = 0
        # chat captures the keyboard (the shell's 'q'-quits guard keys off
        # this attribute, same as the sample browser's search field)
        self.search_input = ""

    # -- turn lifecycle --------------------------------------------------------

    def _ensure_runtime(self) -> Any:
        if self._runtime is None:
            self._runtime = self._factory()
            if hasattr(self._runtime, "start"):
                self._runtime.start()
        return self._runtime

    def send(self, text: str) -> None:
        if self.busy or not text.strip():
            return
        self.transcript.append({"role": "user", "text": text})
        self.busy = True
        self.error = ""
        self._worker = threading.Thread(target=self._run_turn, args=(text,), daemon=True)
        self._worker.start()

    def _run_turn(self, text: str) -> None:
        try:
            runtime = self._ensure_runtime()
            streaming: dict[str, Any] | None = None
            events: Iterator[Any] = runtime.prompt(text)
            for event in events:
                if event.kind == "chunk" and event.text:
                    if streaming is None:
                        streaming = {"role": "assistant", "text": ""}
                        self.transcript.append(streaming)
                    streaming["text"] += event.text
                elif event.kind == "widget" and event.widget:
                    streaming = None  # widget splits the assistant stream
                    entry = {
                        "role": "widget",
                        "name": event.widget.get("name", ""),
                        "args": event.widget.get("args", {}),
                    }
                    self.transcript.append(entry)
                    if entry["name"] in ("choose", "launch_run", "configure_run"):
                        self.pending = entry
                        self.choice_cursor = 0
            if len(self.transcript) > self._limit:
                del self.transcript[: len(self.transcript) - self._limit]
        except Exception as e:  # noqa: BLE001 - agent failures surface in-chat
            self.error = str(e)
            self.transcript.append({"role": "system", "text": f"error: {e}"})
        finally:
            self.busy = False

    def wait_idle(self, timeout_s: float = 10.0) -> bool:
        """Join the turn worker (tests + clean shutdown)."""
        worker = self._worker
        if worker is None:
            return True
        worker.join(timeout=timeout_s)
        return not worker.is_alive()

    def close(self) -> None:
        if self._runtime is not None and hasattr(self._runtime, "close"):
            try:
                self._runtime.close()
            except Exception:  # noqa: BLE001 - teardown must not raise
                pass
            self._runtime = None

    # -- keys ------------------------------------------------------------------

    # -- widget actions --------------------------------------------------------

    def _choice_options(self) -> list[str]:
        """The NORMALIZED options — the exact list render_widget displays.
        Selecting from the raw list would let the cursor act on an option
        the panel never showed (dropped nulls/dupes shift the indices)."""
        if self.pending is None or self.pending["name"] != "choose":
            return []
        from prime_tpu.lab.widget_model import WidgetValidationError, normalize_widget_call

        try:
            normalized = normalize_widget_call("choose", self.pending.get("args", {}))
        except WidgetValidationError:
            return []
        return list(normalized.args["options"])

    def _act_on_pending(self) -> str | None:
        pending = self.pending
        if pending is None:
            return None
        if pending["name"] == "choose":
            options = self._choice_options()
            if not options:
                self.pending = None
                return "choice widget has no options"
            index = min(self.choice_cursor, len(options) - 1)
            selected = options[index]
            pending["args"]["selected"] = selected  # stamps the transcript render
            self.pending = None
            # a blank option label would be dropped by send(); answer by
            # position so the agent always receives a reply
            self.send(selected if selected.strip() else f"option {index + 1}")
            return f"selected: {selected or f'option {index + 1}'}"
        if pending["name"] == "configure_run":
            return self._act_on_form(pending)
        # launch_run: hand the proposal to the launch section's arm/confirm
        # flow as a card on disk — chat never submits to the platform itself.
        # The typed widget model repairs/rejects the payload (numerics become
        # numeric on the card, junk fields are dropped with a record) so the
        # TOML the user arms has real types, not agent leftovers.
        args = pending.get("args", {})
        if self.workspace is None:
            return "no workspace for launch cards"
        from prime_tpu.lab.widget_model import (
            WidgetValidationError,
            launch_card_payload,
            normalize_widget_call,
        )

        try:
            normalized = normalize_widget_call("launch_run", args)
            kind, payload = launch_card_payload(normalized)
        except WidgetValidationError as e:
            # never substitute template defaults for a config the agent did
            # not propose — an armed card must contain only proposed values
            return f"unusable proposal: {e}"
        return self._write_launch_card(pending, kind, payload, "proposal")

    def _write_launch_card(
        self, pending: dict[str, Any], kind: str, payload: dict[str, Any], suffix: str
    ) -> str:
        """Shared card-write tail for launch_run proposals and configure_run
        forms: write the card, stamp the widget, clear the pending state."""
        try:
            from prime_tpu.lab.tui.editor import new_card
            from prime_tpu.lab.tui.launch import save_card

            card = new_card(self.workspace, kind=kind, name=f"{self.name}-{suffix}")
            card.payload = payload
            save_card(card)
        except Exception as e:  # noqa: BLE001 - a bad proposal must not kill chat
            return f"card write failed: {e}"
        pending["args"]["saved_card"] = card.path.name
        self.pending = None
        return f"launch card written: {card.path.name} (arm it in the launch section)"

    def _form_edit(self, text: str) -> str | None:
        """``name=value`` against a pending configure_run edits that field in
        place (stamped into args['values'] so the transcript re-render shows
        the edit); returns a status line, or None when the text is not a form
        edit and should go to the agent as a normal message."""
        pending = self.pending
        if pending is None or pending["name"] != "configure_run" or "=" not in text:
            return None
        from prime_tpu.lab.widget_model import (
            WidgetValidationError,
            build_form_model,
            normalize_widget_call,
        )

        name, _, value = text.partition("=")
        name, value = name.strip(), value.strip()
        try:
            normalized = normalize_widget_call("configure_run", pending.get("args", {}))
            form = build_form_model(normalized, self.workspace)
        except WidgetValidationError:
            return None
        field_names = {spec.name for spec in form.fields if not spec.disabled}
        if name not in field_names:
            return None  # not a field: treat as a chat message
        values = pending["args"].setdefault("form_values", {})
        values[name] = value
        pending["args"].pop("form_errors", None)  # edits invalidate stale errors
        return f"{name} = {value or '(cleared)'}"

    def _act_on_form(self, pending: dict[str, Any]) -> str | None:
        """Enter on a pending form: typed parse -> launch card (eval/train)
        or CLI command (gepa); parse failures stay on the form as errors."""
        from prime_tpu.lab.widget_model import (
            WidgetValidationError,
            build_form_model,
            form_command_text,
            form_launch_payload,
            normalize_widget_call,
        )

        args = pending.get("args", {})
        try:
            normalized = normalize_widget_call("configure_run", args)
            form = build_form_model(normalized, self.workspace)
        except WidgetValidationError as e:
            self.pending = None
            return f"unusable form: {e}"
        if form.kind == "gepa":
            # no launch card exists for gepa — stamp the CLI command (its own
            # key: a saved_card stamp would render "card written" for a card
            # that was never on disk)
            command = form_command_text(form)
            pending["args"]["command"] = command
            self.pending = None
            self.send(f"run it with: {command}")
            return command
        try:
            kind, payload = form_launch_payload(form)
        except WidgetValidationError as e:
            args["form_errors"] = [part.strip() for part in str(e).split(";")]
            return f"fix the form: {e}"
        if self.workspace is None:
            return "no workspace for launch cards"
        return self._write_launch_card(pending, kind, payload, "form")

    # -- keys ------------------------------------------------------------------

    def on_key(self, key: str) -> str | None:
        if key in ("up", "down") and not self.input_buffer and self._choice_options():
            delta = 1 if key == "down" else -1
            count = len(self._choice_options())
            self.choice_cursor = (self.choice_cursor + delta) % count
            return None
        if key == "enter":
            if not self.input_buffer.strip() and self.pending is not None and not self.busy:
                # blank input (including stray whitespace) acts on the widget
                self.input_buffer = ""
                return self._act_on_pending()
            if self.busy:
                # keep the typed text — a discarded message with no feedback
                # is worse than waiting
                return "turn still running — message kept in the input"
            text, self.input_buffer = self.input_buffer, ""
            stripped = text.strip()
            if stripped and self.pending is not None and self.pending["name"] == "configure_run":
                if stripped == "stop":  # the form's discard action
                    self.pending = None
                    return "form dismissed"
                edited = self._form_edit(stripped)
                if edited is not None:
                    return edited
            if stripped:
                self.pending = None  # a real free-text reply answers the widget
            self.send(text)
            return None
        if key == "backspace":
            self.input_buffer = self.input_buffer[:-1]
            return None
        if key == "ctrl+u":
            self.input_buffer = ""
            return None
        if key == "escape":
            if self.input_buffer:
                self.input_buffer = ""
                return None
            if self.busy:
                return "turn still running (esc again after it finishes)"
            self.close()
            return CLOSE
        if len(key) == 1 and key.isprintable():
            self.input_buffer += key
            return None
        return None

    # -- render ----------------------------------------------------------------

    def render(self):
        from rich.console import Group
        from rich.text import Text

        from prime_tpu.lab.widgets import render_widget

        parts: list[Any] = []
        for entry in self.transcript[-24:]:
            role = entry.get("role")
            if role == "widget":
                cursor = self.choice_cursor if entry is self.pending else None
                parts.append(
                    render_widget(
                        str(entry.get("name", "")), entry.get("args", {}),
                        cursor=cursor, workspace=self.workspace,
                    )
                )
                continue
            style = {"user": "bold", "assistant": "", "system": "red"}.get(role or "", "dim")
            prefix = {"user": "you", "assistant": self.name, "system": "sys"}.get(role or "", "?")
            parts.append(Text(f"{prefix}: {entry.get('text', '')}", style=style or None))
        if not self.transcript:
            parts.append(Text("(no messages — type and press enter)", style="dim"))
        parts.append(Text(""))
        status = "…thinking" if self.busy else ""
        parts.append(Text(f"> {self.input_buffer}▌ {status}", style="bold"))
        if self.pending is not None and not self.input_buffer:
            hint = (
                "↑/↓ pick · enter select (or type a reply)"
                if self.pending["name"] == "choose"
                else "enter writes the launch card (or type a reply)"
            )
            parts.append(Text(hint, style="yellow"))
        else:
            parts.append(Text("enter send · esc clear/back", style="dim"))
        return Group(*parts)


def load_agents_config(workspace) -> list[dict[str, Any]]:
    """Configured chat agents: ``.prime-lab/agents.json`` rows
    [{"name", "command", "dialect"}]. Missing file -> empty list."""
    import json
    from pathlib import Path

    path = Path(workspace) / ".prime-lab" / "agents.json"
    if not path.exists():
        return []
    try:
        loaded = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError):
        return []
    rows = loaded.get("agents") if isinstance(loaded, dict) else loaded
    if not isinstance(rows, list):
        return []
    return [
        {
            "name": str(row.get("name", f"agent-{i}")),
            "dialect": str(row.get("dialect", "acp")),
            "command": str(row.get("command", "")),
        }
        for i, row in enumerate(rows)
        if isinstance(row, dict) and row.get("command")
    ]


def open_agent_chat(row: dict[str, Any], workspace) -> AgentChatScreen:
    """Chat screen over a real AgentRuntime for one configured agent row."""
    import shlex

    from prime_tpu.lab.agents import AgentRuntime

    def factory() -> AgentRuntime:
        return AgentRuntime(
            shlex.split(row["command"]),
            dialect=row.get("dialect", "acp"),
            cwd=str(workspace),
        )

    return AgentChatScreen(row["name"], factory, workspace=str(workspace))
