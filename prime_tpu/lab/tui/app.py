"""The Lab shell: three panes (nav / selector / inspector) over LabDataSource.

Reference: prime_lab_app/app.py:179 ``PrimeLabView`` and
docs/lab-tui-design.md:38-44 (three-pane layout, section routing,
local-first data with background hydration). This implementation is a pure
state machine — ``on_key`` mutates state, ``render`` produces a rich
renderable — so the whole shell is testable without a terminal.

Key bindings: ↑/↓ or j/k move · tab/←/→ switch pane · 1-9 jump section ·
enter select (launch section: arm, then launch; data sections: drill into a
detail screen — eval overview → sample browser, training charts/config/logs,
env versions/actions) · e edit / n new launch card · S workspace setup +
doctor · r refresh section · R refresh all · g/G top/bottom · q quit (esc
pops a detail screen first).
"""

from __future__ import annotations

from pathlib import Path
from typing import Any

from prime_tpu.lab.data import LabDataSource, LabSnapshot
from prime_tpu.lab.tui.detail import (
    CLOSE,
    DetailScreen,
    load_env_detail,
    load_hub_eval_detail,
    load_local_eval_detail,
    load_local_training_detail,
    load_platform_training_detail,
)
from prime_tpu.lab.tui.launch import LaunchError, launch_card, scan_cards

# section key -> (title, [(column header, row dict key)...])
SECTION_SPECS: dict[str, tuple[str, list[tuple[str, str]]]] = {
    "local-runs": (
        "Local eval runs",
        [("ENV", "env"), ("MODEL", "model"), ("RUN", "runId"), ("ACC", "accuracy")],
    ),
    "local-training": (
        "Local training",
        [("RUN", "run"), ("STEPS", "steps"), ("LOSS", "loss"), ("TOK/S", "tokPerSec")],
    ),
    "evals": (
        "Evals Hub",
        [("ID", "evalId"), ("MODEL", "model"), ("STATUS", "status"), ("SAMPLES", "sampleCount")],
    ),
    "training": (
        "Training runs",
        [("ID", "runId"), ("NAME", "name"), ("STATUS", "status"), ("MODEL", "model")],
    ),
    "environments": (
        "Environments",
        [("NAME", "name"), ("LATEST", "latestVersion"), ("VISIBILITY", "visibility")],
    ),
    "pods": (
        "Pods",
        [("ID", "podId"), ("NAME", "name"), ("STATUS", "status"), ("TPU", "tpuType")],
    ),
    "sandboxes": (
        "Sandboxes",
        [("ID", "sandboxId"), ("STATUS", "status"), ("IMAGE", "dockerImage")],
    ),
    "launch": (
        "Launch cards",
        [("NAME", "name"), ("KIND", "kind"), ("FILE", "file")],
    ),
    "agents": (
        "Agent chat",
        [("NAME", "name"), ("DIALECT", "dialect"), ("COMMAND", "command")],
    ),
}
SECTIONS = tuple(SECTION_SPECS)
PLATFORM_KEYS = ("evals", "training", "environments", "pods", "sandboxes")


class PrimeLabApp:
    def __init__(
        self,
        data_source: LabDataSource | None = None,
        workspace: str | Path = ".",
        api_client=None,
    ) -> None:
        self.workspace = Path(workspace)
        self.data = data_source or LabDataSource(workspace, api_client=api_client)
        self._api = api_client
        self.snapshot: LabSnapshot = self.data.snapshot()
        self.section_idx = 0
        self.cursors: dict[str, int] = {key: 0 for key in SECTIONS}
        self.focus = "nav"  # nav | rows
        self.status = "r: refresh section · R: refresh all · q: quit"
        self.quit = False
        self.screens: list[DetailScreen] = []  # drill-down stack; top renders
        self._armed_launch: Path | None = None
        self._compare_base: dict[str, Any] | None = None  # `x` comparison baseline
        # launch cards are rescanned at most once per input event: render()
        # reads rows() several times per frame and must not re-glob each time
        self._launch_rows: list[dict[str, Any]] | None = None

    # -- state accessors -----------------------------------------------------

    @property
    def section(self) -> str:
        return SECTIONS[self.section_idx]

    def rows(self, section: str | None = None) -> list[dict[str, Any]]:
        section = section or self.section
        if section == "local-runs":
            return self.snapshot.local_eval_runs
        if section == "local-training":
            return self.snapshot.local_training_runs
        if section == "launch":
            if self._launch_rows is None:
                self._launch_rows = [
                    {"name": c.name, "kind": c.kind, "file": c.path.name, "path": str(c.path),
                     "payload": c.payload}
                    for c in scan_cards(self.workspace)
                ]
            return self._launch_rows
        if section == "agents":
            from prime_tpu.lab.tui.chat import load_agents_config

            return load_agents_config(self.workspace)
        return self.snapshot.platform.get(section, [])

    def selected_row(self) -> dict[str, Any] | None:
        rows = self.rows()
        if not rows:
            return None
        cursor = min(self.cursors[self.section], len(rows) - 1)
        return rows[cursor]

    # -- key handling ---------------------------------------------------------

    def on_key(self, key: str) -> None:
        self._launch_rows = None  # fresh scan per input event
        if self.screens:
            # the top detail screen owns the keyboard ('q' still quits from
            # anywhere unless a search input is capturing text)
            screen = self.screens[-1]
            if key == "q" and getattr(screen, "search_input", None) is None:
                self.quit = True
                return
            result = screen.on_key(key)
            child = getattr(screen, "child", None)
            if child is not None:
                # a screen may hand off a deeper screen (overview -> samples)
                screen.child = None
                self.screens.append(child)
                self.status = f"{child.title} · esc: back"
                return
            if result == CLOSE:
                self.screens.pop()
                self.status = "back"
            elif result:
                self.status = result
            return
        if key in ("q", "escape"):
            if self._armed_launch:
                self._armed_launch = None
                self.status = "launch disarmed"
            else:
                self.quit = True
        elif key in ("tab", "right", "left"):
            self.focus = "rows" if self.focus == "nav" else "nav"
        elif key in ("down", "j"):
            self._move(+1)
        elif key in ("up", "k"):
            self._move(-1)
        elif key == "g":
            self._jump(0)
        elif key == "G":
            self._jump(-1)
        elif key.isdigit() and key != "0" and int(key) <= len(SECTIONS):
            self.section_idx = int(key) - 1
            self.focus = "rows"
        elif key == "r":
            self.refresh_current()
        elif key == "R":
            self.refresh_all()
        elif key == "e" and self.section == "launch" and self.focus == "rows":
            self._open_card_editor()
        elif key == "n" and self.section == "launch":
            self._open_card_editor(new=True)
        elif key == "S":
            from prime_tpu.lab.tui.setup_screen import WorkspaceSetupScreen

            screen = WorkspaceSetupScreen(self.workspace)
            self.screens.append(screen)
            self.status = "lab setup · enter run · d doctor · esc back"
        elif key == "t" and self.section == "local-runs":
            from prime_tpu.lab.tui.evaltree import EvalTreeScreen

            tree = EvalTreeScreen(self.snapshot.local_eval_runs)
            self.screens.append(tree)
            self.status = "eval tree · enter open · esc back"
        elif key == "x" and self.section == "local-runs":
            self._mark_or_compare()
        elif key == "?":
            from prime_tpu.lab.tui.help import HelpScreen

            self.screens.append(HelpScreen())
            self.status = "keys · esc back"
        elif key in ("e", "n") and self.section == "agents":
            from prime_tpu.lab.tui.agent_editor import AgentConfigEditor

            row = self.selected_row() if key == "e" else None
            if key == "e" and row is None:
                return
            editor = AgentConfigEditor(
                self.workspace, agent_name=row["name"] if row else None
            )
            self.screens.append(editor)
            self.status = f"{editor.title} · s: save · esc: back"
        elif key == "enter":
            self._on_enter()

    def tick(self) -> None:
        """Idle callback from the driver: rescan local state only (cheap)."""
        self._launch_rows = None
        local = self.data.snapshot()
        self.snapshot.local_eval_runs = local.local_eval_runs
        self.snapshot.local_training_runs = local.local_training_runs
        self.snapshot.installed_envs = local.installed_envs

    def _move(self, delta: int) -> None:
        self._armed_launch = None
        if self.focus == "nav":
            self.section_idx = (self.section_idx + delta) % len(SECTIONS)
        else:
            rows = self.rows()
            if rows:
                cursor = self.cursors[self.section] + delta
                self.cursors[self.section] = max(0, min(cursor, len(rows) - 1))

    def _jump(self, where: int) -> None:
        rows = self.rows()
        if rows:
            self.cursors[self.section] = 0 if where == 0 else len(rows) - 1

    def _on_enter(self) -> None:
        if self.focus == "nav":
            self.focus = "rows"
            return
        if self.section != "launch":
            self._open_detail()
            return
        row = self.selected_row()
        if row is None:
            return
        card_path = Path(row["path"])
        if self._armed_launch != card_path:
            self._armed_launch = card_path
            self.status = f"press enter again to launch {row['name']} ({row['kind']})"
            return
        self._armed_launch = None
        self.status = self._do_launch(row)

    def _do_launch(self, row: dict[str, Any]) -> str:
        cards = {str(c.path): c for c in scan_cards(self.workspace)}
        card = cards.get(row["path"])
        if card is None:
            return f"card {row['file']} disappeared"
        api = self._api
        if api is None:
            import prime_tpu.commands._deps as deps

            api = self._api = deps.build_client()
        try:
            result = launch_card(card, api)
        except LaunchError as e:
            return f"launch failed: {e}"
        except Exception as e:
            return f"launch failed: {e}"
        return f"launched {result['kind']} {result['id']} ({result['status']})"

    # -- detail screens --------------------------------------------------------

    def _platform_api(self):
        """Client for hub-backed detail screens; None means offline (detail
        screens degrade to their local data rather than crashing)."""
        if self._api is None:
            try:
                import prime_tpu.commands._deps as deps

                self._api = deps.build_client()
            except Exception:  # noqa: BLE001 - missing config/offline
                return None
        return self._api

    def _open_detail(self) -> None:
        row = self.selected_row()
        if row is None:
            return
        section = self.section
        try:
            if section == "local-runs":
                screen = load_local_eval_detail(row)
            elif section == "evals":
                screen = load_hub_eval_detail(row, self._platform_api())
            elif section == "local-training":
                screen = load_local_training_detail(row)
            elif section == "training":
                screen = load_platform_training_detail(row, self._platform_api())
            elif section == "environments":
                screen = load_env_detail(
                    row, self._platform_api(), self.snapshot.installed_envs
                )
            elif section == "agents":
                from prime_tpu.lab.tui.chat import open_agent_chat

                screen = open_agent_chat(row, self.workspace)
            else:
                return
        except Exception as e:  # noqa: BLE001 - detail must not kill the shell
            self.status = f"detail failed: {e}"[:160]
            return
        self.screens.append(screen)
        self.status = f"{screen.title} · esc: back"

    def _mark_or_compare(self) -> None:
        """First `x` marks the selected run as the comparison baseline;
        a second `x` on a different run opens the A → B compare screen."""
        row = self.selected_row()
        if row is None:
            return
        base = self._compare_base
        if base is None or base.get("dir") == row.get("dir"):
            self._compare_base = row
            self.status = f"baseline: {row.get('runId', '?')} — press x on another run"
            return
        from prime_tpu.lab.evalrecords import compare_runs
        from prime_tpu.lab.tui.compare import RunCompareScreen

        try:
            comparison = compare_runs(base["dir"], row["dir"])
        except Exception as e:  # noqa: BLE001 - compare must not kill the shell
            self.status = f"compare failed: {e}"[:160]
            return
        self._compare_base = None
        self.screens.append(
            RunCompareScreen(
                str(base.get("runId", "A")), str(row.get("runId", "B")), comparison
            )
        )
        self.status = f"{self.screens[-1].title} · esc: back"

    def _open_card_editor(self, new: bool = False) -> None:
        from prime_tpu.lab.tui.editor import ConfigCardEditor, new_card

        if new:
            card = new_card(self.workspace)
        else:
            row = self.selected_row()
            if row is None:
                return
            cards = {str(c.path): c for c in scan_cards(self.workspace)}
            card = cards.get(row.get("path", ""))
            if card is None:
                self.status = "card disappeared"
                return
        self._armed_launch = None
        self.screens.append(ConfigCardEditor(card, api_factory=self._platform_api))
        self.status = f"editing {card.path.name} · s: save · esc: back"

    # -- refresh --------------------------------------------------------------

    def refresh_current(self) -> None:
        if self.section in PLATFORM_KEYS:
            self.snapshot = self.data.refresh((self.section,))
            self._report_refresh()
        else:
            self.tick()
            self.status = f"rescanned {self.section}"

    def refresh_all(self) -> None:
        self.snapshot = self.data.refresh()
        self._report_refresh()

    def _report_refresh(self) -> None:
        if self.snapshot.errors:
            broken = ", ".join(f"{k}: {v}" for k, v in self.snapshot.errors.items())
            self.status = f"refresh errors — {broken}"[:160]
        else:
            self.status = "refreshed"

    # -- rendering ------------------------------------------------------------

    def render(self):
        from rich.console import Group
        from rich.layout import Layout
        from rich.panel import Panel
        from rich.table import Table
        from rich.text import Text

        layout = Layout()
        layout.split_column(
            Layout(name="header", size=1),
            Layout(name="body"),
            Layout(name="footer", size=1),
        )
        if self.screens:
            # detail screen takes the whole body; header shows the crumb trail
            screen = self.screens[-1]
            crumbs = " › ".join(
                [SECTION_SPECS[self.section][0]] + [s.title for s in self.screens]
            )
            layout["header"].update(Text(f" PRIME LAB · {crumbs}", style="bold"))
            try:
                body = screen.render()
            except Exception as e:  # noqa: BLE001 — a broken screen must not kill the shell
                body = Text(f"render failed: {e}", style="red")
            layout["body"].update(Panel(body, title=screen.title, border_style="dim"))
            layout["footer"].update(Text(f" {self.status}", style="dim"))
            return layout
        layout["body"].split_row(
            Layout(name="nav", size=24),
            Layout(name="rows", ratio=2),
            Layout(name="inspector", ratio=1),
        )

        layout["header"].update(
            Text(f" PRIME LAB · {self.workspace.resolve().name}", style="bold")
        )

        nav = Table.grid(padding=(0, 1))
        for index, key in enumerate(SECTIONS):
            title = SECTION_SPECS[key][0]
            count = len(self.rows(key))
            marker = "▸" if index == self.section_idx else " "
            style = "reverse" if index == self.section_idx and self.focus == "nav" else (
                "bold" if index == self.section_idx else ""
            )
            stale = ""
            if key in PLATFORM_KEYS and not self.snapshot.freshness.get(key, False):
                stale = "*"
            nav.add_row(Text(f"{marker}{index + 1} {title} ({count}){stale}", style=style))
        layout["nav"].update(Panel(nav, title="sections", border_style="dim"))

        title, columns = SECTION_SPECS[self.section]
        table = Table(expand=True, pad_edge=False)
        for header, _ in columns:
            table.add_column(header, overflow="ellipsis", no_wrap=True)
        rows = self.rows()
        cursor = min(self.cursors[self.section], max(len(rows) - 1, 0))
        for index, row in enumerate(rows):
            style = "reverse" if index == cursor and self.focus == "rows" else ""
            table.add_row(
                *[_cell(row.get(key)) for _, key in columns],
                style=style,
            )
        if not rows:
            empty = Text("(empty)", style="dim")
            layout["rows"].update(Panel(empty, title=title, border_style="dim"))
        else:
            layout["rows"].update(Panel(table, title=title, border_style="dim"))

        detail = Table.grid(padding=(0, 1))
        selected = self.selected_row()
        if selected:
            for key, value in selected.items():
                if key in ("payload", "metrics"):
                    continue
                detail.add_row(Text(str(key), style="dim"), _cell(value))
        body = detail if selected else Text("(nothing selected)", style="dim")
        if selected and isinstance(selected.get("metrics"), list):
            # training run: sparkline charts under the key/value detail;
            # crop rather than wrap — a folded sparkline is unreadable
            from prime_tpu.lab.tui.charts import training_chart_lines

            chart = Text(
                "\n".join(training_chart_lines(selected["metrics"], width=14)),
                no_wrap=True,
                overflow="crop",
            )
            body = Group(detail, Text(""), chart)
        layout["inspector"].update(Panel(body, title="inspector", border_style="dim"))

        layout["footer"].update(Text(f" {self.status}", style="dim"))
        return layout


def _cell(value: Any):
    from rich.text import Text

    if value is None:
        return Text("—", style="dim")
    if isinstance(value, float):
        return Text(f"{value:.3f}")
    return Text(str(value))
