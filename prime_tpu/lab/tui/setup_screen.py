"""Workspace setup + doctor screen for the Lab shell (reference
prime_lab_app/setup_screens.py:38 SetupScreen, :197 AgentSyncScreen,
:294 DoctorScreen — collapsed into one pure state machine since this stack's
setup is synchronous file materialization, not a worker thread).

Opened with ``S`` from the shell. Three actions over ``lab/setup.py`` and
``lab/hygiene.py``:
- enter  run setup for the checked agent surfaces (skill bundle, guide
         blocks, MCP registration, gitignore) and show the change report
- d      doctor: hygiene preflight only, findings colored by severity
- x      apply the doctor's auto-fixes (gitignore entries)

Keys: j/k move over surfaces · space check/uncheck · f toggle force-skills
(overwrite locally-modified bundled skills) · esc back.
"""

from __future__ import annotations

from typing import Any

from prime_tpu.lab.tui.detail import DetailScreen


class WorkspaceSetupScreen(DetailScreen):
    def __init__(self, workspace) -> None:
        from prime_tpu.lab.setup import AGENT_SURFACES

        self.workspace = workspace
        self.title = "lab setup"
        self.surfaces = sorted(AGENT_SURFACES)
        self.checked = {name: name in ("claude", "codex") for name in self.surfaces}
        self.cursor = 0
        self.force_skills = False
        self.report: dict[str, Any] | None = None   # last setup report
        self.findings: list[dict[str, Any]] | None = None  # last doctor run
        self.message = ""

    # -- actions ---------------------------------------------------------------

    def run_setup(self) -> str:
        from prime_tpu.lab.setup import setup_workspace

        agents = tuple(name for name in self.surfaces if self.checked[name])
        if not agents:
            return "no surfaces checked (space toggles)"
        try:
            report = setup_workspace(
                self.workspace, agents=agents, force_skills=self.force_skills
            )
        except Exception as e:  # noqa: BLE001 - setup must not kill the shell
            return f"setup failed: {e}"
        self.report = report.as_dict()
        self.findings = self.report.get("hygiene") or []
        changed = len(self.report["created"]) + len(self.report["updated"])
        return f"setup ok: {changed} changed, {len(self.report['unchanged'])} unchanged"

    def run_doctor(self) -> str:
        from prime_tpu.lab.hygiene import check_workspace

        try:
            self.findings = [f.as_dict() for f in check_workspace(self.workspace)]
        except Exception as e:  # noqa: BLE001
            return f"doctor failed: {e}"
        if not self.findings:
            return "doctor: workspace clean"
        worst = max(self.findings, key=_severity_rank)
        return f"doctor: {len(self.findings)} finding(s), worst {worst['severity']}"

    def apply_fixes(self) -> str:
        from prime_tpu.lab.hygiene import apply_fixes, check_workspace

        try:
            findings = check_workspace(self.workspace)
            applied = apply_fixes(self.workspace, findings)
            self.findings = [f.as_dict() for f in check_workspace(self.workspace)]
        except Exception as e:  # noqa: BLE001
            return f"fixes failed: {e}"
        return f"applied {len(applied)} fix(es)" if applied else "nothing auto-fixable"

    # -- keys ------------------------------------------------------------------

    def on_key(self, key: str) -> str | None:
        if key in ("j", "down"):
            self.cursor = min(self.cursor + 1, len(self.surfaces) - 1)
        elif key in ("k", "up"):
            self.cursor = max(0, self.cursor - 1)
        elif key in (" ", "space"):
            name = self.surfaces[self.cursor]
            self.checked[name] = not self.checked[name]
            return f"{name}: {'on' if self.checked[name] else 'off'}"
        elif key == "f":
            self.force_skills = not self.force_skills
            return f"force-skills {'on' if self.force_skills else 'off'}"
        elif key == "enter":
            self.message = self.run_setup()
            return self.message
        elif key == "d":
            self.message = self.run_doctor()
            return self.message
        elif key == "x":
            self.message = self.apply_fixes()
            return self.message
        else:
            return super().on_key(key)
        return None

    # -- render ----------------------------------------------------------------

    def render(self):
        from rich.console import Group
        from rich.table import Table
        from rich.text import Text

        parts: list[Any] = []
        grid = Table.grid(padding=(0, 1))
        for index, name in enumerate(self.surfaces):
            selected = index == self.cursor
            box = "[x]" if self.checked[name] else "[ ]"
            grid.add_row(
                Text(box, style="green" if self.checked[name] else "dim"),
                Text(name, style="reverse" if selected else ""),
            )
        parts.append(grid)
        parts.append(
            Text(
                f"force-skills: {'on' if self.force_skills else 'off'}",
                style="yellow" if self.force_skills else "dim",
            )
        )

        if self.report is not None:
            parts.append(Text(""))
            summary = Table.grid(padding=(0, 2))
            for bucket in ("created", "updated", "unchanged", "skipped"):
                paths = self.report.get(bucket, [])
                if paths:
                    summary.add_row(
                        Text(bucket, style="bold"),
                        Text(", ".join(_short(p) for p in paths[:6]), style="dim"),
                    )
            parts.append(summary)

        if self.findings is not None:
            parts.append(Text(""))
            if not self.findings:
                parts.append(Text("hygiene: clean ✓", style="green"))
            for finding in self.findings:
                style = {"error": "red", "warn": "yellow"}.get(finding["severity"], "dim")
                fix = " (x fixes)" if finding.get("fix") else ""
                parts.append(
                    Text(f"{finding['severity']:>5} {finding['code']}: {finding['message']}{fix}", style=style)
                )

        if self.message:
            parts.append(Text(""))
            parts.append(Text(self.message, style="cyan"))
        parts.append(Text(""))
        parts.append(
            Text(
                "space check · f force · enter setup · d doctor · x fix · esc back",
                style="dim",
            )
        )
        return Group(*parts)


def _severity_rank(finding: dict[str, Any]) -> int:
    return {"info": 0, "warn": 1, "error": 2}.get(finding.get("severity", "info"), 0)


def _short(path: str) -> str:
    from pathlib import Path

    return Path(path).name or path
