"""Terminal metric charts for the Lab shell (reference: training_charts.py).

The reference renders textual-plot canvas charts inside its Textual app
(training_charts.py:35 LabPlotWidget, :440 _adaptive_ema); this stack draws
pure-text unicode charts with rich primitives so the same charts work in the
shell's inspector pane, the detail screens, and one-shot CLI output:

- ``sparkline``: one-row block strip (section tables, secondary metrics)
- ``block_chart``: multi-row column chart with y-axis labels (the focused
  metric in the training detail screen)
- ``ema`` / ``adaptive_retention``: smoothing overlay for noisy series
"""

from __future__ import annotations

BLOCKS = "▁▂▃▄▅▆▇█"


def _bucket(values: list[float], width: int) -> list[float]:
    """Downsample to ``width`` bucket means; keeps spikes from aliasing away
    and always lands the final bucket on the newest sample."""
    if len(values) <= width:
        return values
    size = len(values) / width
    out = []
    for i in range(width):
        start = int(i * size)
        end = len(values) if i == width - 1 else max(int((i + 1) * size), start + 1)
        chunk = values[start:end]
        out.append(sum(chunk) / len(chunk))
    return out


def sparkline(values: list[float], width: int = 48) -> str:
    """Downsample values to ``width`` buckets and render block characters."""
    import math as _math

    # drop NaN AND inf: a diverged-loss inf would poison the bucket means
    # and the span normalization (inf/inf -> NaN) however it's rescued
    clean = [float(v) for v in values if _math.isfinite(v)]
    if not clean:
        return ""
    clean = _bucket(clean, width)
    lo, hi = min(clean), max(clean)
    span = hi - lo
    if span <= 0:
        return BLOCKS[0] * len(clean)
    if span == float("inf"):
        # finite endpoints can still have an overflowing range (±1e308);
        # rescale into a finite span instead of dividing by inf -> NaN
        scale = max(abs(lo), abs(hi)) / 2.0
        clean = [v / scale for v in clean]
        lo, hi = min(clean), max(clean)
        span = hi - lo
    return "".join(BLOCKS[int((v - lo) / span * (len(BLOCKS) - 1))] for v in clean)


def ema(values: list[float], retention: float) -> list[float]:
    """Exponential moving average; ``retention`` in [0, 1) is the weight kept
    from the running average each step (0 = no smoothing)."""
    if not values:
        return []
    out = [values[0]]
    for value in values[1:]:
        out.append(retention * out[-1] + (1.0 - retention) * value)
    return out


def adaptive_retention(n: int) -> float:
    """Smoothing strength scaled to series length (reference
    training_charts.py:440 role): short series stay nearly raw, long noisy
    series get a half-life around n/16 points, capped at 0.98."""
    if n <= 8:
        return 0.0
    return min(0.98, 1.0 - 16.0 / n)


def block_chart(
    values: list[float],
    width: int = 60,
    height: int = 8,
) -> list[str]:
    """Multi-row unicode column chart. Row 0 is the TOP. Each column is one
    bucket; cells fill bottom-up with full blocks plus one partial block cap
    (1/8-cell resolution → height*8 distinct levels)."""
    clean = [float(v) for v in values if v == v]
    if not clean or height < 1:
        return []
    clean = _bucket(clean, width)
    lo, hi = min(clean), max(clean)
    span = hi - lo
    rows = [[" "] * len(clean) for _ in range(height)]
    for col, value in enumerate(clean):
        frac = 0.5 if span <= 0 else (value - lo) / span
        eighths = max(1, round(frac * height * 8))  # every column visible
        full, part = divmod(eighths, 8)
        for r in range(full):
            rows[height - 1 - r][col] = BLOCKS[7]
        if part and full < height:
            rows[height - 1 - full][col] = BLOCKS[part - 1]
    return ["".join(row) for row in rows]


def chart_panel(
    rows: list[dict],
    key: str,
    width: int = 60,
    height: int = 8,
    smooth: bool = False,
    window: int | None = None,
) -> list[tuple[str, str]]:
    """Full labeled chart for one metric as (style, line) tuples: title with
    last/min/max, y-axis gutter labels, the block chart, and an x-axis step
    range. ``window`` shows only the last N points; ``smooth`` overlays
    adaptive EMA (the stats line always reports RAW values)."""
    points = [
        (row.get("step", i), float(row[key]))
        for i, row in enumerate(rows)
        if isinstance(row.get(key), (int, float)) and row[key] == row[key]
    ]
    if window:
        points = points[-window:]
    if len(points) < 2:
        return []
    steps = [p[0] for p in points]
    raw = [p[1] for p in points]
    values = ema(raw, adaptive_retention(len(raw))) if smooth else raw
    lines: list[tuple[str, str]] = []
    tag = " (ema)" if smooth and adaptive_retention(len(raw)) > 0 else ""
    lines.append(
        (
            "bold",
            f"{key}{tag}  last={raw[-1]:.4g}  min={min(raw):.4g}  max={max(raw):.4g}",
        )
    )
    # bucket BEFORE computing axis labels so the gutter's hi/lo describe the
    # columns actually drawn (bucket means), not pre-bucket outliers the
    # chart cannot show; block_chart's own bucketing is then a no-op
    values = _bucket(values, width)
    chart_rows = block_chart(values, width=width, height=height)
    lo, hi = min(values), max(values)
    gutter = max(len(f"{hi:.3g}"), len(f"{lo:.3g}"))
    for i, row in enumerate(chart_rows):
        if i == 0:
            label = f"{hi:.3g}".rjust(gutter)
        elif i == len(chart_rows) - 1:
            label = f"{lo:.3g}".rjust(gutter)
        else:
            label = " " * gutter
        lines.append(("cyan", f"{label} {row}"))
    lines.append(("dim", " " * gutter + f" step {steps[0]} → {steps[-1]} ({len(points)} pts)"))
    return lines


def discover_metrics(rows: list[dict]) -> list[str]:
    """All numeric series keys, reward/loss-ish first (reference
    training_charts.py:470 _metric_sort_key role), bookkeeping excluded."""
    seen: list[str] = []
    for row in rows:
        for key, value in row.items():
            if key in seen or key in ("step", "epoch", "time", "ts", "timestamp"):
                continue
            if isinstance(value, bool) or not isinstance(value, (int, float)):
                continue
            seen.append(key)

    def rank(key: str) -> tuple[int, str]:
        lowered = key.lower()
        if "reward" in lowered or lowered == "loss":
            return (0, lowered)
        if "loss" in lowered or "acc" in lowered:
            return (1, lowered)
        return (2, lowered)

    return sorted(seen, key=rank)


def metric_chart(rows: list[dict], key: str, width: int = 48) -> str | None:
    """One labeled sparkline line for a metrics.jsonl-shaped row list."""
    values = [row[key] for row in rows if isinstance(row.get(key), (int, float))]
    if len(values) < 2:
        return None
    line = sparkline(values, width=width)
    return f"{key:>14} {line}  {values[0]:.4g} → {values[-1]:.4g}"


def training_chart_lines(rows: list[dict], width: int = 48) -> list[str]:
    """Charts for the standard training metrics present in the rows."""
    lines = []
    for key in ("loss", "grad_norm", "tokens_per_sec", "step_time_s"):
        line = metric_chart(rows, key, width=width)
        if line:
            lines.append(line)
    return lines
