"""Terminal metric charts for the Lab shell (reference: training_charts.py).

The reference renders textual-plot charts inside its Textual app; this stack
draws unicode sparklines + axis labels with rich primitives so the same
charts work in the shell's inspector pane and in one-shot CLI output.
"""

from __future__ import annotations

BLOCKS = "▁▂▃▄▅▆▇█"


def sparkline(values: list[float], width: int = 48) -> str:
    """Downsample values to ``width`` buckets and render block characters."""
    clean = [float(v) for v in values if v == v]  # drop NaN
    if not clean:
        return ""
    if len(clean) > width:
        # bucket means keep the shape without aliasing single spikes away
        bucket = len(clean) / width
        bucketed = []
        for i in range(width):
            start = int(i * bucket)
            # the final bucket always reaches the newest sample exactly
            end = len(clean) if i == width - 1 else max(int((i + 1) * bucket), start + 1)
            chunk = clean[start:end]
            bucketed.append(sum(chunk) / len(chunk))
        clean = bucketed
    lo, hi = min(clean), max(clean)
    span = hi - lo
    if span <= 0:
        return BLOCKS[0] * len(clean)
    return "".join(BLOCKS[int((v - lo) / span * (len(BLOCKS) - 1))] for v in clean)


def metric_chart(rows: list[dict], key: str, width: int = 48) -> str | None:
    """One labeled sparkline line for a metrics.jsonl-shaped row list."""
    values = [row[key] for row in rows if isinstance(row.get(key), (int, float))]
    if len(values) < 2:
        return None
    line = sparkline(values, width=width)
    return f"{key:>14} {line}  {values[0]:.4g} → {values[-1]:.4g}"


def training_chart_lines(rows: list[dict], width: int = 48) -> list[str]:
    """Charts for the standard training metrics present in the rows."""
    lines = []
    for key in ("loss", "grad_norm", "tokens_per_sec", "step_time_s"):
        line = metric_chart(rows, key, width=width)
        if line:
            lines.append(line)
    return lines
