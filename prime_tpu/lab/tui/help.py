"""Keybinding help overlay (`?` from the shell — reference app footer/help
role). Static reference grouped by context; the table lives here so it can
be asserted complete in tests when bindings change."""

from __future__ import annotations

from typing import Any

from prime_tpu.lab.tui.detail import DetailScreen

KEYBINDINGS: tuple[tuple[str, tuple[tuple[str, str], ...]], ...] = (
    (
        "Shell",
        (
            ("↑/↓ j/k", "move (nav pane cycles sections, rows pane moves the cursor)"),
            ("tab ←/→", "switch pane"),
            ("1-9", "jump to section"),
            ("enter", "drill into the selected row (launch: arm, enter again fires)"),
            ("r / R", "refresh section / refresh everything"),
            ("g / G", "first / last row"),
            ("S", "workspace setup + hygiene doctor"),
            ("?", "this help"),
            ("q", "quit (esc pops a screen first)"),
        ),
    ),
    (
        "Local eval runs",
        (
            ("enter", "run overview → per-sample browser"),
            ("t", "env → model → run tree with aggregates"),
            ("x", "mark comparison baseline; x on a second run compares A → B"),
        ),
    ),
    (
        "Sample browser",
        (
            ("n/p", "next / previous sample"),
            ("f", "filter all → correct → incorrect"),
            ("/", "incremental search across turns"),
            ("m", "markdown/LaTeX rendering"),
            ("j/k", "scroll the transcript"),
        ),
    ),
    (
        "Training run",
        (
            ("tab h/l", "chart / config / logs tabs"),
            ("c", "cycle charted metric"),
            ("s", "EMA smoothing"),
            ("[ / ]", "step-window zoom"),
        ),
    ),
    (
        "Launch cards",
        (
            ("e / n", "edit / new card (typed fields, TOML round-trip guard)"),
            ("enter", "arm, enter again launches"),
        ),
    ),
    (
        "Agents",
        (
            ("enter", "chat (widgets: ↑/↓ + enter answer a pending choice/launch)"),
            ("e / n", "edit / add an agent config"),
        ),
    ),
)


class HelpScreen(DetailScreen):
    title = "keys"

    def render(self):
        from rich.console import Group
        from rich.table import Table
        from rich.text import Text

        parts: list[Any] = []
        for section, rows in KEYBINDINGS:
            parts.append(Text(section, style="bold magenta"))
            grid = Table.grid(padding=(0, 2))
            for keys, description in rows:
                grid.add_row(Text(keys, style="bold"), Text(description, style="dim"))
            parts.append(grid)
            parts.append(Text(""))
        parts.append(Text("esc back", style="dim"))
        return Group(*parts)
