"""Config-card editor screen (reference config_screen.py / config_factory.py /
toml_format.py roles): edit a launch card's fields natively, save it back as
TOML, and launch without leaving the shell.

Pure state machine like every detail screen. Modes:
- browse: j/k move over fields · enter edit the selected value · a add field
  ("key=value") · d delete field · s save · L launch (saved card) · esc back
- input: printable chars type · enter commit · esc cancel

Values are typed on commit (int / float / bool / string via
launch.parse_field_value) so a TOML round-trip preserves types.
"""

from __future__ import annotations

from typing import Any, Callable

from prime_tpu.lab.tui.detail import CLOSE, DetailScreen
from prime_tpu.lab.tui.launch import (
    LaunchCard,
    LaunchError,
    launch_card,
    parse_field_value,
    save_card,
)


class ConfigCardEditor(DetailScreen):
    # pseudo-field key for the card's [launch].name — dotted so it can never
    # collide with a payload key (add rejects dotted keys; scan_cards payloads
    # are flat bare keys)
    NAME_FIELD = "launch.name"

    def __init__(self, card: LaunchCard, api_factory: Callable[[], Any] | None = None) -> None:
        self.card = card
        self.title = f"edit: {card.path.name}"
        self._api_factory = api_factory
        # ordered working copy; the name pseudo-field first, payload after
        self.fields: list[tuple[str, Any]] = [(self.NAME_FIELD, card.name)] + list(
            card.payload.items()
        )
        self.cursor = 0
        # a card that has never been written (new_card template) starts dirty
        # so the launch guard forces an explicit save first
        self.dirty = not card.path.exists()
        self.input: str | None = None   # non-None = capturing (also guards 'q')
        self.input_mode = ""            # "edit" | "add"
        self.message = ""

    # the shell's 'q'-quits guard keys off this attribute name
    @property
    def search_input(self) -> str | None:
        return self.input

    # -- field ops -------------------------------------------------------------

    def _commit_edit(self, text: str) -> str:
        key, _ = self.fields[self.cursor]
        value = parse_field_value(text) if key != self.NAME_FIELD else text.strip()
        self.fields[self.cursor] = (key, value)
        self.dirty = True
        return f"{key} = {value!r}"

    def _commit_add(self, text: str) -> str:
        key, sep, raw = text.partition("=")
        key = key.strip()
        if not sep or not key:
            return "add expects key=value"
        if not key.replace("_", "").replace("-", "").isalnum():
            # dotted/quoted keys would nest on TOML reparse and corrupt the
            # flat-scalar payload contract — reject at entry
            return f"key {key!r} must be bare (letters, digits, _ or -)"
        if any(k == key for k, _ in self.fields):
            return f"{key} already exists (edit it instead)"
        self.fields.append((key, parse_field_value(raw)))
        self.cursor = len(self.fields) - 1
        self.dirty = True
        return f"added {key}"

    def _sync_card(self) -> None:
        for key, value in self.fields:
            if key == self.NAME_FIELD:
                self.card.name = str(value)
        self.card.payload = {k: v for k, v in self.fields if k != self.NAME_FIELD}

    def save(self) -> str:
        self._sync_card()
        try:
            save_card(self.card)
        except (LaunchError, OSError) as e:
            return f"save failed: {e}"
        self.dirty = False
        return f"saved {self.card.path.name}"

    def launch(self) -> str:
        if self.dirty:
            return "unsaved changes — press s first"
        api = self._api_factory() if self._api_factory is not None else None
        if api is None:
            return "no platform client (offline)"
        self._sync_card()
        try:
            result = launch_card(self.card, api)
        except LaunchError as e:
            return f"launch failed: {e}"
        except Exception as e:  # noqa: BLE001 - network surface
            return f"launch failed: {e}"
        return f"launched {result['kind']} {result['id']} ({result['status']})"

    # -- keys ------------------------------------------------------------------

    def on_key(self, key: str) -> str | None:
        if self.input is not None:
            if key == "enter":
                text, self.input = self.input, None
                self.message = (
                    self._commit_edit(text) if self.input_mode == "edit" else self._commit_add(text)
                )
                return self.message
            if key == "escape":
                self.input = None
                return "cancelled"
            if key == "backspace":
                self.input = self.input[:-1]
            elif len(key) == 1 and key.isprintable():
                self.input += key
            return None
        if key in ("j", "down"):
            self.cursor = min(self.cursor + 1, len(self.fields) - 1)
        elif key in ("k", "up"):
            self.cursor = max(0, self.cursor - 1)
        elif key == "enter":
            _, value = self.fields[self.cursor]
            self.input, self.input_mode = str(value), "edit"
        elif key == "a":
            self.input, self.input_mode = "", "add"
            return "add field: key=value"
        elif key == "d":
            k, _ = self.fields[self.cursor]
            if k == self.NAME_FIELD:
                return "the name field cannot be deleted"
            del self.fields[self.cursor]
            self.cursor = min(self.cursor, len(self.fields) - 1)
            self.dirty = True
            return f"deleted {k}"
        elif key == "s":
            return self.save()
        elif key == "L":
            return self.launch()
        else:
            return super().on_key(key)
        return None

    # -- render ----------------------------------------------------------------

    def render(self):
        from rich.console import Group
        from rich.table import Table
        from rich.text import Text

        head = Text(
            f"[launch] kind={self.card.kind}" + ("  · unsaved changes" if self.dirty else ""),
            style="yellow" if self.dirty else "dim",
        )
        grid = Table.grid(padding=(0, 2))
        for index, (key, value) in enumerate(self.fields):
            selected = index == self.cursor
            if selected and self.input is not None:
                shown = Text(f"{self.input}▌", style="bold reverse")
            else:
                shown = Text(str(value), style="reverse" if selected else "")
            grid.add_row(Text(key, style="bold" if selected else "dim"), shown)
        footer = Text(
            "enter edit · a add · d delete · s save · L launch · esc back",
            style="dim",
        )
        parts: list[Any] = [head, Text(""), grid, Text("")]
        if self.message:
            parts.append(Text(self.message, style="cyan"))
        parts.append(footer)
        return Group(*parts)


def new_card(workspace, kind: str = "eval", name: str = "new-card") -> LaunchCard:
    """Fresh card with a sensible template payload (config_factory.py role).
    Not yet written to disk — the editor's save does that."""
    from pathlib import Path

    base = Path(workspace) / ".prime-lab" / "launch"
    stem = name
    counter = 1
    while (base / f"{stem}.toml").exists():
        counter += 1
        stem = f"{name}-{counter}"
    payload = (
        {"env": "gsm8k", "model": "llama3-8b", "tpu_type": "v5e-8"}
        if kind == "eval"
        else {"model": "llama3-8b", "env": "arith-rl", "steps": 100}
    )
    return LaunchCard(path=base / f"{stem}.toml", kind=kind, name=stem, payload=payload)
