"""Interactive Lab TUI (reference: prime_lab_app/, 40 modules).

The reference builds on the Textual framework; that is not a declarable
dependency here, so the shell is a small self-contained TUI stack on rich:
``driver`` owns the terminal (raw-mode keys + rich.Live), ``app`` is the
three-pane shell (nav / selector / inspector, reference
docs/lab-tui-design.md:38-44) over the local-first LabDataSource, and
``launch`` runs config cards (reference launch_runner.py).

Everything renders headlessly for tests: the app is a pure
state-machine (on_key) + renderable (render), and the driver is the only
tty-touching component.
"""

from prime_tpu.lab.tui.app import PrimeLabApp
from prime_tpu.lab.tui.driver import render_text, run_interactive

__all__ = ["PrimeLabApp", "render_text", "run_interactive"]
