"""Interactive Lab TUI (reference: prime_lab_app/, 40 modules).

The reference builds on the Textual framework; that is not a declarable
dependency here, so the shell is a small self-contained TUI stack on rich:
``driver`` owns the terminal (raw-mode keys + rich.Live), ``app`` is the
three-pane shell (nav / selector / inspector, reference
docs/lab-tui-design.md:38-44) over the local-first LabDataSource, and
``launch`` runs config cards (reference launch_runner.py).

Everything renders headlessly for tests: the app is a pure
state-machine (on_key) + renderable (render), and the driver is the only
tty-touching component.
"""

from prime_tpu.lab.tui.app import PrimeLabApp
from prime_tpu.lab.tui.driver import render_text, run_interactive

__all__ = ["PrimeLabApp", "open_shell", "render_text", "run_interactive"]


def open_shell(workspace: str = ".", api_client=None, section: str | None = None) -> None:
    """Launch the interactive shell, optionally focused on one section.

    The single CLI entry point shared by `prime lab` and `prime eval tui` —
    raises RuntimeError without a tty (callers map it to a CLI error).
    """
    from prime_tpu.lab.tui.app import SECTIONS

    app = PrimeLabApp(workspace=workspace, api_client=api_client)
    if section is not None:
        app.section_idx = SECTIONS.index(section)
        app.focus = "rows"
    run_interactive(app)
