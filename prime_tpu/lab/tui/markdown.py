"""Markdown + LaTeX-lite rendering for eval transcripts in the Lab shell.

Math-heavy envs (gsm8k, MATH) emit prompts/completions full of ``$\\frac{a}{b}$``
and ``\\[ ... \\]`` spans; raw LaTeX in a terminal pane is unreadable. The
reference renders these through markdown-it + a math plugin inside Textual
(prime_lab_app/eval_markdown.py:89-151); this stack has no markdown-it, so it
ships a small deterministic translator: LaTeX → plain unicode text, markdown
block structure → (style, line) tuples the detail screens already render.

Deliberately lossy-but-legible: unknown commands degrade to their argument
text, never to a parse error.
"""

from __future__ import annotations

import re

# single-token LaTeX commands with a direct unicode spelling
_SYMBOLS = {
    "times": "×", "cdot": "·", "div": "÷", "pm": "±", "le": "≤", "leq": "≤",
    "ge": "≥", "geq": "≥", "ne": "≠", "neq": "≠", "approx": "≈", "infty": "∞",
    "sum": "Σ", "prod": "Π", "int": "∫", "pi": "π", "alpha": "α", "beta": "β",
    "gamma": "γ", "delta": "δ", "epsilon": "ε", "theta": "θ", "lambda": "λ",
    "mu": "μ", "sigma": "σ", "phi": "φ", "omega": "ω", "rightarrow": "→",
    "to": "→", "leftarrow": "←", "Rightarrow": "⇒", "in": "∈", "subset": "⊂",
    "cup": "∪", "cap": "∩", "forall": "∀", "exists": "∃", "sqrt": "√",
    "angle": "∠", "degree": "°", "circ": "°", "percent": "%", "ldots": "…",
    "dots": "…", "cdots": "⋯", "quad": " ", "qquad": "  ", ",": " ", ";": " ",
    "!": "", "equiv": "≡", "propto": "∝", "partial": "∂", "nabla": "∇",
}

_SUPERSCRIPTS = str.maketrans("0123456789+-ni", "⁰¹²³⁴⁵⁶⁷⁸⁹⁺⁻ⁿⁱ")
_SUBSCRIPTS = str.maketrans("0123456789+-", "₀₁₂₃₄₅₆₇₈₉₊₋")


def _take_group(text: str, start: int) -> tuple[str, int]:
    """Return (content, index_after) of the {...} group at ``start`` (which
    must point at '{'), honoring nesting. No group → single char."""
    if start >= len(text):
        return "", start
    if text[start] != "{":
        return text[start], start + 1
    depth = 0
    for i in range(start, len(text)):
        if text[i] == "{":
            depth += 1
        elif text[i] == "}":
            depth -= 1
            if depth == 0:
                return text[start + 1 : i], i + 1
    return text[start + 1 :], len(text)  # unbalanced: rest of string


def latex_to_text(latex: str) -> str:
    """Translate a LaTeX math fragment to plain unicode text."""
    out: list[str] = []
    i = 0
    text = latex
    while i < len(text):
        ch = text[i]
        if ch == "\\":
            match = re.match(r"\\([a-zA-Z]+|.)", text[i:])
            if not match:
                i += 1
                continue
            command = match.group(1)
            i += match.end()
            if command == "frac":
                num, i = _take_group(text, i)
                den, i = _take_group(text, i)
                out.append(f"({latex_to_text(num)})/({latex_to_text(den)})")
            elif command == "sqrt":
                arg, i = _take_group(text, i)
                out.append(f"√({latex_to_text(arg)})")
            elif command in ("text", "mathrm", "mathbf", "mathit", "textbf", "operatorname", "boxed"):
                arg, i = _take_group(text, i)
                rendered = latex_to_text(arg)
                out.append(f"[{rendered}]" if command == "boxed" else rendered)
            elif command in ("left", "right", "big", "Big"):
                pass  # sizing only; the delimiter itself follows as a literal
            elif command in _SYMBOLS:
                out.append(_SYMBOLS[command])
            else:
                out.append(command)  # unknown command: degrade to its name
        elif ch == "^":
            arg, i = _take_group(text, i + 1)
            plain = latex_to_text(arg)
            if plain and all(c in "0123456789+-ni" for c in plain):
                out.append(plain.translate(_SUPERSCRIPTS))
            else:
                out.append(f"^({plain})")
        elif ch == "_":
            arg, i = _take_group(text, i + 1)
            plain = latex_to_text(arg)
            if plain and all(c in "0123456789+-" for c in plain):
                out.append(plain.translate(_SUBSCRIPTS))
            else:
                out.append(f"_({plain})")
        elif ch in "{}":
            i += 1
        else:
            out.append(ch)
            i += 1
    return "".join(out)


_MATH_SPANS = (
    re.compile(r"\$\$(.+?)\$\$", re.DOTALL),
    re.compile(r"\\\[(.+?)\\\]", re.DOTALL),
    re.compile(r"\\\((.+?)\\\)"),
    re.compile(r"\$([^$\n]+?)\$"),
)


def replace_math(text: str) -> str:
    """Replace every $..$/$$..$$/\\(..\\)/\\[..\\] span with its translation."""
    for pattern in _MATH_SPANS:
        text = pattern.sub(lambda m: latex_to_text(m.group(1).strip()), text)
    return text


_INLINE_BOLD = re.compile(r"\*\*(.+?)\*\*")
_INLINE_CODE = re.compile(r"`([^`]+)`")


def markdown_lines(text: str, math: bool = True) -> list[tuple[str, str]]:
    """Markdown → (style, line) tuples for the detail screens' text window.

    Handles: #-headers, fenced code blocks, bullets, blockquotes, bold/code
    marker stripping, math spans. Everything else passes through verbatim.
    """
    lines: list[tuple[str, str]] = []
    in_fence = False
    for raw in text.splitlines():
        stripped = raw.strip()
        if stripped.startswith("```"):
            in_fence = not in_fence
            tag = stripped[3:].strip()
            lines.append(("dim", f"┌─ {tag or 'code'}" if in_fence else "└─"))
            continue
        if in_fence:
            lines.append(("cyan", "│ " + raw))
            continue
        if math:
            raw = replace_math(raw)
        raw = _INLINE_BOLD.sub(lambda m: m.group(1), raw)
        raw = _INLINE_CODE.sub(lambda m: m.group(1), raw)
        header = re.match(r"^(#{1,6})\s+(.*)", raw)
        if header:
            lines.append(("bold magenta", header.group(2)))
        elif raw.lstrip().startswith(("- ", "* ")):
            indent = len(raw) - len(raw.lstrip())
            lines.append(("", " " * indent + "• " + raw.lstrip()[2:]))
        elif raw.lstrip().startswith("> "):
            lines.append(("dim italic", raw.lstrip()[2:]))
        else:
            lines.append(("", raw))
    return lines
