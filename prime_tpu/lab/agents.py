"""Agent runtime: subprocess chat over newline-delimited JSON-RPC stdio.

Reference: prime_lab_app/agent_runtime.py:66 — an embedded chat runtime that
owns one agent server process per workspace and speaks ACP / Codex
app-server / Letta dialects over stdio. This implementation keeps the same
architecture (spawn → initialize → prompt → streamed events → close) with a
dialect table mapping the four wire shapes onto one driver:

- ``acp``    — JSON-RPC 2.0: ``initialize`` → ``session/new`` →
  ``session/prompt``; streamed ``session/update`` notifications carry chunks.
- ``codex``  — Codex app-server JSON-RPC (agent_runtime.py:629): ``initialize``
  → ``thread/start`` → ``turn/start``; ``item/agentMessage/delta``
  notifications stream text, ``turn/completed`` ends the turn. Lab widget
  tools ride ``thread/start.dynamicTools``.
- ``letta``  — Letta bidirectional JSONL (agent_runtime.py:543): typed
  messages (``user`` / ``assistant`` / ``result`` / ``control_request``);
  the client auto-approves ``can_use_tool`` control requests and registers
  the widget tools via ``register_external_tools``.
- ``simple`` — bare JSONL turns: ``{"type": "prompt", ...}`` in,
  ``{"type": "chunk"|"done", ...}`` out (what our test agents speak, and a
  sane target for custom agents).

The stdout reader runs on a thread pushing events into a queue; callers
iterate :meth:`AgentRuntime.prompt` to stream a turn's chunks. Widget tool
calls surface as ``widget`` events carrying the parsed call (name + args) —
the TUI renders them natively (lab/widgets.py).
"""

from __future__ import annotations

import json
import queue
import subprocess
import threading
import time
from dataclasses import dataclass
from typing import Any, Iterator


class AgentError(RuntimeError):
    pass


@dataclass
class AgentEvent:
    kind: str          # chunk | done | error | log | widget
    text: str = ""
    raw: dict | None = None
    widget: dict | None = None   # {"name": ..., "args": {...}} for kind=widget


class _Dialect:
    """Wire-shape hooks; every method is pure message construction/parsing
    except ``auto_reply`` (protocol-mandated responses the reader thread
    writes back, e.g. Letta tool-permission grants)."""

    name = "simple"
    needs_handshake = False  # True: wait for session/thread id before prompts

    def __init__(self, cwd: str | None = None) -> None:
        self.cwd = cwd
        self.session_id: str | None = None

    def initialize_msgs(self) -> list[dict]:
        return []

    def prompt_msg(self, text: str, msg_id: int) -> dict:
        return {"type": "prompt", "id": msg_id, "text": text}

    def parse(self, msg: dict) -> AgentEvent | None:
        kind = msg.get("type")
        if kind == "chunk":
            return AgentEvent("chunk", text=str(msg.get("text", "")), raw=msg)
        if kind == "done":
            return AgentEvent("done", raw=msg)
        if kind == "error":
            return AgentEvent("error", text=str(msg.get("message", "")), raw=msg)
        if kind == "widget":
            return AgentEvent(
                "widget",
                raw=msg,
                widget={"name": str(msg.get("name", "")), "args": msg.get("args", {}) or {}},
            )
        return AgentEvent("log", raw=msg)

    def auto_reply(self, msg: dict) -> dict | None:
        """A message the client must answer on the wire (reader thread sends
        it before the event reaches the consumer)."""
        return None


class _AcpDialect(_Dialect):
    """ACP-flavored JSON-RPC 2.0 (initialize / session/new / session/prompt)."""

    name = "acp"
    needs_handshake = True

    def initialize_msgs(self) -> list[dict]:
        return [
            {"jsonrpc": "2.0", "id": 1, "method": "initialize",
             "params": {"protocolVersion": 1, "clientInfo": {"name": "prime-lab"}}},
            {"jsonrpc": "2.0", "id": 2, "method": "session/new", "params": {}},
        ]

    def prompt_msg(self, text: str, msg_id: int) -> dict:
        return {
            "jsonrpc": "2.0",
            "id": msg_id,
            "method": "session/prompt",
            "params": {"sessionId": self.session_id, "prompt": [{"type": "text", "text": text}]},
        }

    def parse(self, msg: dict) -> AgentEvent | None:
        if msg.get("method") == "session/update":
            update = msg.get("params", {}).get("update", {})
            if update.get("sessionUpdate") == "agent_message_chunk":
                content = update.get("content", {})
                return AgentEvent("chunk", text=str(content.get("text", "")), raw=msg)
            return AgentEvent("log", raw=msg)
        if "result" in msg:
            result = msg.get("result") or {}
            if isinstance(result, dict) and result.get("sessionId"):
                self.session_id = result["sessionId"]
                return AgentEvent("log", raw=msg)
            if isinstance(result, dict) and result.get("stopReason") is not None:
                return AgentEvent("done", raw=msg)
            return AgentEvent("log", raw=msg)
        if "error" in msg:
            return AgentEvent("error", text=str(msg["error"].get("message", "")), raw=msg)
        return AgentEvent("log", raw=msg)


class _CodexDialect(_Dialect):
    """Codex app-server JSON-RPC (reference agent_runtime.py:629-668,863-1012):
    ``initialize`` → ``thread/start`` (carrying the Lab widget tools as
    ``dynamicTools``) → per-prompt ``turn/start``. Streaming notifications:
    ``item/agentMessage/delta`` (text), ``item/tool/call`` (widget calls),
    ``turn/completed`` (turn end, possibly with an error)."""

    name = "codex"
    needs_handshake = True

    def initialize_msgs(self) -> list[dict]:
        from prime_tpu.lab.widgets import widget_tool_specs

        return [
            {"jsonrpc": "2.0", "id": 1, "method": "initialize",
             "params": {"clientInfo": {"name": "prime-lab"},
                        "capabilities": {"experimentalApi": True}}},
            {"jsonrpc": "2.0", "id": 2, "method": "thread/start",
             "params": {"cwd": self.cwd, "dynamicTools": widget_tool_specs()}},
        ]

    def prompt_msg(self, text: str, msg_id: int) -> dict:
        return {
            "jsonrpc": "2.0",
            "id": msg_id,
            "method": "turn/start",
            "params": {
                "threadId": self.session_id,
                "cwd": self.cwd,
                "input": [{"type": "text", "text": text}],
            },
        }

    def parse(self, msg: dict) -> AgentEvent | None:
        method = msg.get("method")
        params = msg.get("params", {}) if isinstance(msg.get("params"), dict) else {}
        if method == "item/agentMessage/delta":
            return AgentEvent("chunk", text=str(params.get("delta", "")), raw=msg)
        if method == "item/tool/call":
            return AgentEvent(
                "widget",
                raw=msg,
                widget={
                    "name": str(params.get("name", params.get("tool", ""))),
                    "args": params.get("arguments", params.get("args", {})) or {},
                },
            )
        if method == "turn/completed":
            turn = params.get("turn", {})
            error = turn.get("error") if isinstance(turn, dict) else None
            if isinstance(error, dict):
                return AgentEvent(
                    "error", text=str(error.get("message", "codex turn failed")), raw=msg
                )
            return AgentEvent("done", raw=msg)
        if "result" in msg:
            result = msg.get("result") or {}
            thread = result.get("thread") if isinstance(result, dict) else None
            if isinstance(thread, dict) and thread.get("id"):
                self.session_id = str(thread["id"])
            return AgentEvent("log", raw=msg)
        if "error" in msg:
            return AgentEvent("error", text=str(msg["error"].get("message", "")), raw=msg)
        return AgentEvent("log", raw=msg)

    def auto_reply(self, msg: dict) -> dict | None:
        # a tool call sent as a REQUEST (with an id) awaits a JSON-RPC result;
        # without an ack the server blocks on the call and the turn never
        # completes (same hazard the Letta path documents)
        if msg.get("method") == "item/tool/call" and msg.get("id") is not None:
            return {"jsonrpc": "2.0", "id": msg["id"], "result": {"status": "rendered"}}
        return None


class _LettaDialect(_Dialect):
    """Letta bidirectional JSONL (reference agent_runtime.py:543-560,727-800):
    typed messages, not JSON-RPC. The client registers the widget tools as
    external tools at startup and auto-approves ``can_use_tool`` requests;
    ``execute_external_tool`` requests surface as widget events (the TUI
    renders them) while the wire reply acknowledges receipt."""

    name = "letta"

    def initialize_msgs(self) -> list[dict]:
        from prime_tpu.lab.widgets import letta_external_tools

        return [
            {"type": "control_request", "request_id": "prime-lab-init",
             "request": {"subtype": "initialize"}},
            {"type": "control_request", "request_id": "prime-lab-tools",
             "request": {"subtype": "register_external_tools",
                         "tools": letta_external_tools()}},
        ]

    def prompt_msg(self, text: str, msg_id: int) -> dict:
        return {"type": "user", "message": {"role": "user", "content": text}}

    def parse(self, msg: dict) -> AgentEvent | None:
        kind = msg.get("type")
        if kind == "system":
            session = msg.get("session_id") or msg.get("sessionId")
            if session:
                self.session_id = str(session)
            return AgentEvent("log", raw=msg)
        if kind == "assistant":
            message = msg.get("message", {})
            content = message.get("content") if isinstance(message, dict) else None
            if isinstance(content, list):
                text = "".join(
                    str(part.get("text", ""))
                    for part in content
                    if isinstance(part, dict) and part.get("type") == "text"
                )
            else:
                text = str(content or "")
            return AgentEvent("chunk", text=text, raw=msg)
        if kind == "result":
            return AgentEvent("done", raw=msg)
        if kind == "error":
            return AgentEvent("error", text=str(msg.get("message", "")), raw=msg)
        if kind == "control_request":
            request = msg.get("request", {})
            if isinstance(request, dict) and request.get("subtype") == "execute_external_tool":
                return AgentEvent(
                    "widget",
                    raw=msg,
                    widget={
                        "name": str(request.get("tool_name", request.get("name", ""))),
                        "args": request.get("arguments", request.get("args", {})) or {},
                    },
                )
            return AgentEvent("log", raw=msg)
        return AgentEvent("log", raw=msg)

    def auto_reply(self, msg: dict) -> dict | None:
        if msg.get("type") != "control_request":
            return None
        request = msg.get("request", {})
        subtype = request.get("subtype") if isinstance(request, dict) else None
        if subtype == "can_use_tool":
            return {
                "type": "control_response",
                "request_id": str(msg.get("request_id", "")),
                "response": {"subtype": "success", "response": {"behavior": "allow"}},
            }
        if subtype == "execute_external_tool":
            # the widget event renders in the TUI; the wire gets an ack so the
            # agent's tool call resolves instead of hanging
            return {
                "type": "control_response",
                "request_id": str(msg.get("request_id", "")),
                "response": {"subtype": "success", "response": {"status": "rendered"}},
            }
        return None


DIALECTS = {
    "simple": _Dialect,
    "acp": _AcpDialect,
    "codex": _CodexDialect,
    "letta": _LettaDialect,
}


class AgentRuntime:
    """Owns one agent subprocess and streams chat turns over its stdio."""

    def __init__(
        self,
        command: list[str],
        dialect: str = "simple",
        cwd: str | None = None,
        env: dict[str, str] | None = None,
    ) -> None:
        if dialect not in DIALECTS:
            raise AgentError(f"unknown dialect {dialect!r}; choose from {sorted(DIALECTS)}")
        self.command = command
        self.dialect = DIALECTS[dialect](cwd=cwd)
        self._cwd = cwd
        self._env = env
        self.process: subprocess.Popen | None = None
        self._events: queue.Queue[AgentEvent | None] = queue.Queue()
        self._msg_id = 10
        # the reader thread writes auto-replies on the same stdin the prompt
        # thread writes turns on — unserialized writes can interleave frames
        self._stdin_lock = threading.Lock()

    # -- lifecycle -----------------------------------------------------------

    def start(self, timeout_s: float = 15.0) -> None:
        import os

        env = dict(os.environ)
        if self._env:
            env.update(self._env)
        try:
            self.process = subprocess.Popen(
                self.command,
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL,
                text=True,
                cwd=self._cwd,
                env=env,
            )
        except OSError as e:
            raise AgentError(f"could not spawn agent {self.command[0]!r}: {e}") from e
        threading.Thread(target=self._read_stdout, daemon=True).start()
        for msg in self.dialect.initialize_msgs():
            self._send(msg)
        # handshake dialects (acp: session id, codex: thread id) must not
        # accept prompts until the id arrives
        if self.dialect.needs_handshake:
            deadline = time.monotonic() + timeout_s
            while self.dialect.session_id is None:
                if time.monotonic() > deadline:
                    self.close()
                    raise AgentError("agent did not establish a session in time")
                if self.process.poll() is not None:
                    rc = self.process.returncode
                    self.close()  # release the pipes even though it exited
                    raise AgentError(f"agent exited during handshake (rc={rc})")
                time.sleep(0.02)

    def prompt(self, text: str, timeout_s: float = 120.0) -> Iterator[AgentEvent]:
        """Send one user turn; yield chunk + widget events until the turn
        completes."""
        if self.process is None or self.process.poll() is not None:
            raise AgentError("agent is not running")
        # drain leftovers from an abandoned/timed-out turn so this turn never
        # consumes a stale chunk or terminates on a stale done
        while True:
            try:
                if self._events.get_nowait() is None:
                    raise AgentError("agent closed its output stream")
            except queue.Empty:
                break
        self._msg_id += 1
        self._send(self.dialect.prompt_msg(text, self._msg_id))
        deadline = time.monotonic() + timeout_s
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise AgentError(f"agent turn timed out after {timeout_s}s")
            try:
                event = self._events.get(timeout=min(remaining, 0.5))
            except queue.Empty:
                if self.process.poll() is not None:
                    raise AgentError(
                        f"agent exited mid-turn (rc={self.process.returncode})"
                    ) from None
                continue
            if event is None:  # stdout closed
                raise AgentError("agent closed its output stream mid-turn")
            if event.kind == "error":
                raise AgentError(event.text or "agent error")
            if event.kind == "done":
                return
            if event.kind in ("chunk", "widget"):
                yield event

    def chat(self, text: str, timeout_s: float = 120.0) -> str:
        """Convenience: one turn, concatenated."""
        return "".join(e.text for e in self.prompt(text, timeout_s=timeout_s))

    def close(self) -> None:
        if self.process is None:
            return
        if self.process.stdin:
            try:
                self.process.stdin.close()
            except OSError:
                pass
        if self.process.poll() is None:
            self.process.terminate()
            try:
                self.process.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.process.kill()
                self.process.wait(timeout=5)  # reap: no zombie after kill
        else:
            self.process.wait()  # already exited: reap it

    def __enter__(self) -> "AgentRuntime":
        self.start()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    # -- internals -----------------------------------------------------------

    def _send(self, msg: dict) -> None:
        assert self.process is not None and self.process.stdin is not None
        try:
            with self._stdin_lock:
                self.process.stdin.write(json.dumps(msg) + "\n")
                self.process.stdin.flush()
        except (OSError, ValueError) as e:
            raise AgentError(f"agent stdin write failed: {e}") from e

    def _read_stdout(self) -> None:
        assert self.process is not None and self.process.stdout is not None
        try:
            for line in self.process.stdout:
                line = line.strip()
                if not line:
                    continue
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError:
                    self._events.put(AgentEvent("log", text=line))
                    continue
                if not isinstance(msg, dict):
                    # scalars / JSON-RPC batches: log, never crash the reader
                    self._events.put(AgentEvent("log", text=line))
                    continue
                reply = None
                try:
                    reply = self.dialect.auto_reply(msg)
                    event = self.dialect.parse(msg)
                except Exception as e:  # noqa: BLE001 — a bad message must not kill the reader
                    event = AgentEvent("error", text=f"unparseable agent message: {e}", raw=msg)
                if reply is not None:
                    try:
                        self._send(reply)
                    except AgentError:
                        pass  # process died; the sentinel below reports it
                if event is not None:
                    self._events.put(event)
        finally:
            # sentinel ALWAYS lands, or prompt() would block to full timeout
            self._events.put(None)
