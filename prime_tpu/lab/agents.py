"""Agent runtime: subprocess chat over newline-delimited JSON-RPC stdio.

Reference: prime_lab_app/agent_runtime.py:66 — an embedded chat runtime that
owns one agent server process per workspace and speaks ACP / Codex
app-server / Letta dialects over stdio. This implementation keeps the same
architecture (spawn → initialize → prompt → streamed events → close) with a
dialect table mapping the three wire shapes onto one driver:

- ``acp``    — JSON-RPC 2.0: ``initialize`` → ``session/new`` →
  ``session/prompt``; streamed ``session/update`` notifications carry chunks.
- ``simple`` — bare JSONL turns: ``{"type": "prompt", ...}`` in,
  ``{"type": "chunk"|"done", ...}`` out (what our test agents speak, and a
  sane target for custom agents).

The stdout reader runs on a thread pushing events into a queue; callers
iterate :meth:`AgentRuntime.prompt` to stream a turn's chunks.
"""

from __future__ import annotations

import json
import queue
import subprocess
import threading
import time
from dataclasses import dataclass
from typing import Any, Iterator


class AgentError(RuntimeError):
    pass


@dataclass
class AgentEvent:
    kind: str          # chunk | done | error | log
    text: str = ""
    raw: dict | None = None


class _Dialect:
    """Wire-shape hooks; every method is pure message construction/parsing."""

    name = "simple"

    def initialize_msgs(self) -> list[dict]:
        return []

    def prompt_msg(self, text: str, msg_id: int) -> dict:
        return {"type": "prompt", "id": msg_id, "text": text}

    def parse(self, msg: dict) -> AgentEvent | None:
        kind = msg.get("type")
        if kind == "chunk":
            return AgentEvent("chunk", text=str(msg.get("text", "")), raw=msg)
        if kind == "done":
            return AgentEvent("done", raw=msg)
        if kind == "error":
            return AgentEvent("error", text=str(msg.get("message", "")), raw=msg)
        return AgentEvent("log", raw=msg)


class _AcpDialect(_Dialect):
    """ACP-flavored JSON-RPC 2.0 (initialize / session/new / session/prompt)."""

    name = "acp"

    def __init__(self) -> None:
        self.session_id: str | None = None

    def initialize_msgs(self) -> list[dict]:
        return [
            {"jsonrpc": "2.0", "id": 1, "method": "initialize",
             "params": {"protocolVersion": 1, "clientInfo": {"name": "prime-lab"}}},
            {"jsonrpc": "2.0", "id": 2, "method": "session/new", "params": {}},
        ]

    def prompt_msg(self, text: str, msg_id: int) -> dict:
        return {
            "jsonrpc": "2.0",
            "id": msg_id,
            "method": "session/prompt",
            "params": {"sessionId": self.session_id, "prompt": [{"type": "text", "text": text}]},
        }

    def parse(self, msg: dict) -> AgentEvent | None:
        if msg.get("method") == "session/update":
            update = msg.get("params", {}).get("update", {})
            if update.get("sessionUpdate") == "agent_message_chunk":
                content = update.get("content", {})
                return AgentEvent("chunk", text=str(content.get("text", "")), raw=msg)
            return AgentEvent("log", raw=msg)
        if "result" in msg:
            result = msg.get("result") or {}
            if isinstance(result, dict) and result.get("sessionId"):
                self.session_id = result["sessionId"]
                return AgentEvent("log", raw=msg)
            if isinstance(result, dict) and result.get("stopReason") is not None:
                return AgentEvent("done", raw=msg)
            return AgentEvent("log", raw=msg)
        if "error" in msg:
            return AgentEvent("error", text=str(msg["error"].get("message", "")), raw=msg)
        return AgentEvent("log", raw=msg)


DIALECTS = {"simple": _Dialect, "acp": _AcpDialect}


class AgentRuntime:
    """Owns one agent subprocess and streams chat turns over its stdio."""

    def __init__(
        self,
        command: list[str],
        dialect: str = "simple",
        cwd: str | None = None,
        env: dict[str, str] | None = None,
    ) -> None:
        if dialect not in DIALECTS:
            raise AgentError(f"unknown dialect {dialect!r}; choose from {sorted(DIALECTS)}")
        self.command = command
        self.dialect = DIALECTS[dialect]()
        self._cwd = cwd
        self._env = env
        self.process: subprocess.Popen | None = None
        self._events: queue.Queue[AgentEvent | None] = queue.Queue()
        self._msg_id = 10

    # -- lifecycle -----------------------------------------------------------

    def start(self, timeout_s: float = 15.0) -> None:
        import os

        env = dict(os.environ)
        if self._env:
            env.update(self._env)
        try:
            self.process = subprocess.Popen(
                self.command,
                stdin=subprocess.PIPE,
                stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL,
                text=True,
                cwd=self._cwd,
                env=env,
            )
        except OSError as e:
            raise AgentError(f"could not spawn agent {self.command[0]!r}: {e}") from e
        threading.Thread(target=self._read_stdout, daemon=True).start()
        for msg in self.dialect.initialize_msgs():
            self._send(msg)
        # ACP: wait for the session id before accepting prompts
        if isinstance(self.dialect, _AcpDialect):
            deadline = time.monotonic() + timeout_s
            while self.dialect.session_id is None:
                if time.monotonic() > deadline:
                    self.close()
                    raise AgentError("agent did not establish a session in time")
                if self.process.poll() is not None:
                    rc = self.process.returncode
                    self.close()  # release the pipes even though it exited
                    raise AgentError(f"agent exited during handshake (rc={rc})")
                time.sleep(0.02)

    def prompt(self, text: str, timeout_s: float = 120.0) -> Iterator[AgentEvent]:
        """Send one user turn; yield chunk events until the turn completes."""
        if self.process is None or self.process.poll() is not None:
            raise AgentError("agent is not running")
        # drain leftovers from an abandoned/timed-out turn so this turn never
        # consumes a stale chunk or terminates on a stale done
        while True:
            try:
                if self._events.get_nowait() is None:
                    raise AgentError("agent closed its output stream")
            except queue.Empty:
                break
        self._msg_id += 1
        self._send(self.dialect.prompt_msg(text, self._msg_id))
        deadline = time.monotonic() + timeout_s
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise AgentError(f"agent turn timed out after {timeout_s}s")
            try:
                event = self._events.get(timeout=min(remaining, 0.5))
            except queue.Empty:
                if self.process.poll() is not None:
                    raise AgentError(
                        f"agent exited mid-turn (rc={self.process.returncode})"
                    ) from None
                continue
            if event is None:  # stdout closed
                raise AgentError("agent closed its output stream mid-turn")
            if event.kind == "error":
                raise AgentError(event.text or "agent error")
            if event.kind == "done":
                return
            if event.kind == "chunk":
                yield event

    def chat(self, text: str, timeout_s: float = 120.0) -> str:
        """Convenience: one turn, concatenated."""
        return "".join(e.text for e in self.prompt(text, timeout_s=timeout_s))

    def close(self) -> None:
        if self.process is None:
            return
        if self.process.stdin:
            try:
                self.process.stdin.close()
            except OSError:
                pass
        if self.process.poll() is None:
            self.process.terminate()
            try:
                self.process.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.process.kill()
                self.process.wait(timeout=5)  # reap: no zombie after kill
        else:
            self.process.wait()  # already exited: reap it

    def __enter__(self) -> "AgentRuntime":
        self.start()
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    # -- internals -----------------------------------------------------------

    def _send(self, msg: dict) -> None:
        assert self.process is not None and self.process.stdin is not None
        try:
            self.process.stdin.write(json.dumps(msg) + "\n")
            self.process.stdin.flush()
        except (OSError, ValueError) as e:
            raise AgentError(f"agent stdin write failed: {e}") from e

    def _read_stdout(self) -> None:
        assert self.process is not None and self.process.stdout is not None
        try:
            for line in self.process.stdout:
                line = line.strip()
                if not line:
                    continue
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError:
                    self._events.put(AgentEvent("log", text=line))
                    continue
                if not isinstance(msg, dict):
                    # scalars / JSON-RPC batches: log, never crash the reader
                    self._events.put(AgentEvent("log", text=line))
                    continue
                try:
                    event = self.dialect.parse(msg)
                except Exception as e:  # noqa: BLE001 — a bad message must not kill the reader
                    event = AgentEvent("error", text=f"unparseable agent message: {e}", raw=msg)
                if event is not None:
                    self._events.put(event)
        finally:
            # sentinel ALWAYS lands, or prompt() would block to full timeout
            self._events.put(None)
