"""`prime env` — Environments Hub workflow.

Reference surface (prime_cli/commands/env.py): init/build/push/pull/install/
uninstall/list/status/info/versions/delete + per-env secrets + actions.
TPU-native: installs check the env's declared TPU requirements against the
local device when JAX sees an accelerator.
"""

from __future__ import annotations

from pathlib import Path

import click

import prime_tpu.commands._deps as deps
from prime_tpu.core.client import APIClient
from prime_tpu.envhub import EnvHubClient
from prime_tpu.envhub.packaging import (
    build_archive,
    build_wheel,
    content_hash,
    extract_archive,
    read_env_metadata,
    write_env_template,
)
from prime_tpu.utils.render import Renderer, output_options


@click.group(name="env")
def env_group() -> None:
    """Package and distribute eval/RL environments."""


def build_hub_client() -> EnvHubClient:
    return EnvHubClient(APIClient(config=deps.build_config(), transport=deps.transport_override))


def load_resolved_environment(render: Renderer, resolved):
    """Drift-warn, execute ``load_environment()``, and announce the result —
    the shared tail of the environment execution protocol for every command
    that runs an env (`prime eval run`, `prime train local-rl`)."""
    from prime_tpu.envhub.execution import EnvProtocolError, load_environment

    if resolved.drift:
        click.echo(f"warning: {resolved.drift}", err=True)
    try:
        loaded = load_environment(resolved)
    except EnvProtocolError as e:
        raise click.ClickException(str(e)) from None
    render.message(
        f"Resolved env {loaded.name} ({resolved.source}"
        + (f"@{resolved.version}" if resolved.version else "")
        + f", {len(loaded.examples)} examples)"
    )
    return loaded


from prime_tpu.envhub.local import read_registry as _installed_registry, save_registry as _save_registry


@env_group.command("init")
@click.argument("name")
@click.option("--dir", "target", default=None, help="Target directory (default ./<name>).")
def init_cmd(name: str, target: str | None) -> None:
    """Scaffold a new environment (env.toml + pyproject + module)."""
    env_dir = Path(target or name)
    written = write_env_template(env_dir, name)
    for path in written:
        click.echo(f"  created {path}")
    click.echo(f"Environment '{name}' initialized in {env_dir}/")


@env_group.command("build")
@click.option("--dir", "env_dir", default=".", type=click.Path(exists=True))
@output_options
def build_cmd(render: Renderer, env_dir: str) -> None:
    """Build the env archive + wheel locally (no upload)."""
    try:
        metadata = read_env_metadata(env_dir)
    except (FileNotFoundError, ValueError) as e:
        raise click.ClickException(str(e)) from None
    archive = build_archive(env_dir)
    digest = content_hash(env_dir)
    payload = {
        "name": metadata["name"],
        "version": metadata["version"],
        "archiveBytes": len(archive),
        "contentHash": digest,
    }
    try:
        wheel = build_wheel(env_dir)
        payload["wheel"] = str(wheel)
    except RuntimeError as e:
        render.message(f"(wheel build skipped: {e})", err=True)
    if render.is_json:
        render.json(payload)
    else:
        render.detail(payload, title=f"Built {metadata['name']}")


@env_group.command("push")
@click.option("--dir", "env_dir", default=".", type=click.Path(exists=True))
@click.option("--visibility", type=click.Choice(["private", "public"]), default="private")
@click.option(
    "--auto-bump", is_flag=True,
    help="Bump the patch version before pushing (1.2.3 -> 1.2.4).",
)
@click.option(
    "--rc", is_flag=True,
    help="Bump or create an .rc pre-release before pushing (rc0 -> rc1).",
)
@click.option(
    "--post", is_flag=True,
    help="Bump or create a .post release before pushing (post0 -> post1).",
)
@output_options
def push_cmd(
    render: Renderer, env_dir: str, visibility: str,
    auto_bump: bool, rc: bool, post: bool,
) -> None:
    """Archive, hash, and upload the environment to the hub.

    --auto-bump/--rc/--post rewrite the env.toml + pyproject versions in
    place first (reference env.py:1073-1140); the checkout's upstream link
    (.prime/env-metadata.json) is shown before and updated after the push.
    """
    from prime_tpu.envhub.provenance import (
        bumped_version,
        read_provenance,
        upstream_display,
        write_provenance,
    )

    from prime_tpu.core.exceptions import APIError

    if sum((auto_bump, rc, post)) > 1:
        raise click.UsageError("--auto-bump, --rc, and --post are mutually exclusive")
    bump_mode = "patch" if auto_bump else "rc" if rc else "post" if post else None
    upstream = upstream_display(read_provenance(env_dir))
    if upstream:
        render.message(f"Using upstream environment {upstream}", err=True)
    # snapshot the version carriers so a failed push doesn't burn the bumped
    # number (re-running would skip it, leaving local one ahead of the hub)
    bump_snapshots: list[tuple[Path, str]] = []
    if bump_mode:
        for carrier in ("env.toml", "pyproject.toml"):
            path = Path(env_dir) / carrier
            if path.exists():
                bump_snapshots.append((path, path.read_text()))
        try:
            old, new = bumped_version(env_dir, bump_mode)
        except ValueError as e:
            raise click.ClickException(str(e)) from None
        render.message(f"Auto-bumping version: {old} -> {new}")
    try:
        result = build_hub_client().push(env_dir, visibility=visibility)
    except (FileNotFoundError, ValueError, APIError) as e:
        for path, content in bump_snapshots:
            path.write_text(content)
        if bump_snapshots:
            render.message("Push failed — version bump rolled back.", err=True)
        raise click.ClickException(str(e)) from None
    if not result.get("unchanged"):
        write_provenance(
            env_dir,
            name=result.get("name"),
            owner=result.get("owner"),
            version=result.get("latestVersion"),
            source="push",
        )
    if render.is_json:
        render.json(result)
    elif result.get("unchanged"):
        render.message(f"{result['name']} unchanged (content hash matches hub) — nothing to push.")
    else:
        render.message(f"Pushed {result['name']}@{result['latestVersion']} ({visibility}).")


@env_group.command("pull")
@click.argument("name")
@click.option("--version", default=None)
@click.option("--dir", "target", default=None, help="Extract here (default ./<name>).")
@output_options
def pull_cmd(render: Renderer, name: str, version: str | None, target: str | None) -> None:
    """Download an environment version and extract it locally."""
    archive, info = build_hub_client().pull(name, version=version)
    target_dir = Path(target or name)
    if target_dir.exists() and any(target_dir.iterdir()):
        raise click.ClickException(
            f"{target_dir}/ exists and is not empty — refusing to overwrite local files"
        )
    extract_archive(archive, target_dir)
    from prime_tpu.envhub.provenance import write_provenance

    # link the checkout to its upstream so later pushes/evals name it
    write_provenance(
        target_dir,
        name=name,
        owner=info.get("owner"),
        version=info.get("version"),
        source="pull",
    )
    render.message(f"Pulled {name}@{info['version']} -> {target_dir}/")
    if render.is_json:
        render.json({"name": name, "version": info["version"], "dir": str(target_dir)})


@env_group.command("install")
@click.argument("name")
@click.option("--version", default=None)
@output_options
def install_cmd(render: Renderer, name: str, version: str | None) -> None:
    """Install an environment from the hub: pull, build the wheel, pip-install
    it (pull-and-build, reference env.py:2431/:3069), register locally."""
    from prime_tpu.envhub.execution import install_from_hub

    entry = install_from_hub(build_hub_client(), name, version=version)
    target = Path(entry["path"])
    # TPU requirement check (best-effort; informative, not fatal)
    try:
        metadata = read_env_metadata(target)
        tpu_req = metadata.get("tpu", {})
        if tpu_req.get("tpu_type"):
            render.message(f"  env declares TPU requirement: {tpu_req}")
    except (FileNotFoundError, ValueError):
        pass
    if entry.get("installNote"):
        render.message(f"  note: {entry['installNote']}", err=True)
    render.message(
        f"Installed {name}@{entry['version']} -> {target}"
        + (" (pip package installed)" if entry.get("pipInstalled") else "")
    )
    if render.is_json:
        render.json(entry)


@env_group.command("inspect")
@click.argument("env_ref")
@output_options
def inspect_cmd(render: Renderer, env_ref: str) -> None:
    """Inspect an env (local dir, installed name, or hub slug): metadata,
    content hash, entry module, example count, drift vs the hub."""
    from prime_tpu.envhub.execution import (
        EnvProtocolError,
        EnvResolutionError,
        load_environment,
        resolve_environment,
    )
    from prime_tpu.envhub.packaging import content_hash as compute_hash, iter_env_files

    try:
        resolved = resolve_environment(env_ref, hub_client=build_hub_client(), install_missing=False)
    except EnvResolutionError as e:
        # not local and not installed — fall back to hub-side metadata only
        from prime_tpu.core.exceptions import APIError

        try:
            hub = build_hub_client().get(env_ref)
        except APIError:
            raise click.ClickException(str(e)) from None
        render.detail(
            {
                "name": hub.get("name", env_ref),
                "source": "hub (not installed)",
                "latestVersion": hub.get("latestVersion"),
                "visibility": hub.get("visibility"),
                "contentHash": hub.get("contentHash"),
                "tags": hub.get("tags", []),
                "tpu": hub.get("tpu", {}),
            },
            title=f"Environment {env_ref}",
        )
        return
    files = iter_env_files(resolved.env_dir)
    payload: dict = {
        "name": resolved.name,
        "source": resolved.source,
        "dir": str(resolved.env_dir),
        "version": resolved.version,
        "contentHash": compute_hash(resolved.env_dir),
        "files": len(files),
        "drift": resolved.drift,
    }
    if resolved.metadata:
        payload["tpu"] = resolved.metadata.get("tpu", {})
        payload["eval"] = resolved.metadata.get("eval", {})
    from prime_tpu.envhub.provenance import read_provenance, upstream_display

    provenance = read_provenance(resolved.env_dir)
    if provenance:
        payload["upstream"] = upstream_display(provenance)
        payload["upstreamVersion"] = provenance.get("version")
        payload["upstreamSource"] = provenance.get("source")
    try:
        loaded = load_environment(resolved)
        payload["examples"] = len(loaded.examples)
        payload["hasScorer"] = loaded.scorer is not None
        payload["loadEnvironment"] = "ok"
    except EnvProtocolError as e:
        payload["loadEnvironment"] = str(e)
    render.detail(payload, title=f"Environment {resolved.name}")


@env_group.command("uninstall")
@click.argument("name")
@output_options
def uninstall_cmd(render: Renderer, name: str) -> None:
    import shutil

    registry = _installed_registry()
    entry = registry.pop(name, None)
    if entry is None:
        raise click.ClickException(f"{name} is not installed")
    shutil.rmtree(entry["path"], ignore_errors=True)
    _save_registry(registry)
    render.message(f"Uninstalled {name}.")


@env_group.command("list")
@click.option("--installed", is_flag=True, help="Show locally installed envs instead of the hub.")
@output_options
def list_cmd(render: Renderer, installed: bool) -> None:
    if installed:
        registry = _installed_registry()
        render.table(
            ["NAME", "VERSION", "PATH"],
            [[name, e["version"], e["path"]] for name, e in sorted(registry.items())],
            title="Installed environments",
            json_rows=registry,
        )
        return
    envs = build_hub_client().list()
    render.table(
        ["NAME", "LATEST", "VISIBILITY", "TAGS", "DESCRIPTION"],
        [
            [e["name"], e.get("latestVersion", ""), e.get("visibility", ""), ",".join(e.get("tags", [])), e.get("description", "")]
            for e in envs
        ],
        title="Hub environments",
        json_rows=envs,
    )


@env_group.command("info")
@click.argument("name")
@output_options
def info_cmd(render: Renderer, name: str) -> None:
    env = build_hub_client().get(name)
    render.detail(env, title=f"Environment {name}")


@env_group.command("status")
@click.argument("name")
@output_options
def status_cmd(render: Renderer, name: str) -> None:
    render.detail(build_hub_client().status(name), title=f"Status {name}")


@env_group.command("versions")
@click.argument("name")
@output_options
def versions_cmd(render: Renderer, name: str) -> None:
    rows = build_hub_client().versions(name)
    render.table(["VERSION"], [[v["version"]] for v in rows], title=f"{name} versions", json_rows=rows)


@env_group.command("delete")
@click.argument("name")
@click.option("--version", default=None, help="Delete one version instead of the whole env.")
@click.option("--yes", "-y", is_flag=True)
@output_options
def delete_cmd(render: Renderer, name: str, version: str | None, yes: bool) -> None:
    label = f"{name}@{version}" if version else name
    if not yes and not click.confirm(f"Delete {label} from the hub?"):
        render.message("Aborted.")
        return
    client = build_hub_client()
    if version:
        client.delete_version(name, version)
    else:
        client.delete(name)
    render.message(f"Deleted {label}.")


@env_group.group("secrets")
def secrets_subgroup() -> None:
    """Per-environment secrets."""


@secrets_subgroup.command("list")
@click.argument("name")
@output_options
def env_secrets_list(render: Renderer, name: str) -> None:
    keys = build_hub_client().list_secrets(name)
    render.table(["KEY"], [[k] for k in keys], title=f"{name} secrets", json_rows=keys)


@secrets_subgroup.command("set")
@click.argument("name")
@click.argument("key")
@click.argument("value", required=False)
def env_secrets_set(name: str, key: str, value: str | None) -> None:
    if value is None:
        value = click.prompt(f"Value for {key}", hide_input=True)
    build_hub_client().set_secret(name, key, value)
    click.echo(f"Secret {key} set on {name}.")


@secrets_subgroup.command("delete")
@click.argument("name")
@click.argument("key")
def env_secrets_delete(name: str, key: str) -> None:
    build_hub_client().delete_secret(name, key)
    click.echo(f"Secret {key} deleted from {name}.")


@env_group.group("actions")
def actions_subgroup() -> None:
    """Hub-side actions on an environment (builds, pushes)."""


@actions_subgroup.command("list")
@click.argument("name")
@output_options
def actions_list_cmd(render: Renderer, name: str) -> None:
    rows = build_hub_client().actions(name)
    render.table(
        ["ID", "ACTION", "VERSION", "STATUS"],
        [
            [a.get("id", ""), a.get("action", ""), a.get("version", ""), a.get("status", "")]
            for a in rows
        ],
        title=f"{name} actions",
        json_rows=rows,
    )


@actions_subgroup.command("logs")
@click.argument("name")
@click.argument("action_id")
@output_options
def actions_logs_cmd(render: Renderer, name: str, action_id: str) -> None:
    logs = build_hub_client().action_logs(name, action_id)
    if render.is_json:
        render.json({"logs": logs})
    else:
        for line in logs:
            render.message(line)


@actions_subgroup.command("retry")
@click.argument("name")
@click.argument("action_id")
@output_options
def actions_retry_cmd(render: Renderer, name: str, action_id: str) -> None:
    result = build_hub_client().retry_action(name, action_id)
    render.message(f"Retried {action_id} -> {result.get('id')} ({result.get('status')}).")
    if render.is_json:
        render.json(result)
