"""`prime deployments` — LoRA adapter deploy/unload (reference: commands/deployments.py,
api/deployments.py:10-113: adapter list/deploy/unload, checkpoint→adapter)."""

from __future__ import annotations

import click

from prime_tpu.commands._deps import build_client
from prime_tpu.utils.render import Renderer, output_options
from prime_tpu.utils.short_id import shorten


@click.group(name="deployments")
def deployments_group() -> None:
    """Deploy trained adapters to the inference fleet."""


@deployments_group.command("list")
@output_options
def list_cmd(render: Renderer) -> None:
    data = build_client().get("/deployments/adapters")
    items = data.get("items", []) if isinstance(data, dict) else data
    render.table(
        ["ADAPTER", "BASE MODEL", "STATUS", "CHECKPOINT"],
        [
            [a.get("adapterId", ""), a.get("baseModel", ""), a.get("status", ""), shorten(a.get("checkpointId", "") or "")]
            for a in items
        ],
        title="Deployed adapters",
        json_rows=items,
    )


@deployments_group.command("base-models")
@output_options
def base_models_cmd(render: Renderer) -> None:
    """List base models adapters can be deployed onto."""
    data = build_client().get("/deployments/base-models")
    items = data.get("items", []) if isinstance(data, dict) else data
    render.table(["MODEL"], [[m] for m in items], title="Deployable base models", json_rows=items)


@deployments_group.command("deploy")
@click.option("--checkpoint", required=True, help="Checkpoint ID to deploy as an adapter.")
@click.option("--name", default=None)
@output_options
def deploy_cmd(render: Renderer, checkpoint: str, name: str | None) -> None:
    result = build_client().post(
        "/deployments/adapters",
        json={"checkpointId": checkpoint, **({"name": name} if name else {})},
        idempotent_post=True,
    )
    if render.is_json:
        render.json(result)
    else:
        render.message(f"Adapter {result.get('adapterId')} deploying ({result.get('status')}).")


@deployments_group.command("unload")
@click.argument("adapter_id")
@output_options
def unload_cmd(render: Renderer, adapter_id: str) -> None:
    build_client().delete(f"/deployments/adapters/{adapter_id}")
    render.message(f"Adapter {adapter_id} unloaded.")
