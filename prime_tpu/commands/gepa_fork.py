"""`prime gepa` + `prime fork` (reference: commands/gepa.py, fork.py).

``fork`` clones a hub environment under a new name (server-side copy).
``gepa`` is a passthrough to the GEPA prompt-optimizer when that optional
package is installed locally.
"""

from __future__ import annotations

import click

from prime_tpu.commands._deps import build_client
from prime_tpu.utils.render import Renderer, output_options


@click.command("fork")
@click.argument("source_env")
@click.argument("new_name")
@output_options
def fork(render: Renderer, source_env: str, new_name: str) -> None:
    """Fork a hub environment under a new name."""
    result = build_client().post(
        f"/envhub/environments/{source_env}/fork",
        json={"newName": new_name},
        idempotent_post=True,
    )
    if render.is_json:
        render.json(result)
    else:
        render.message(f"Forked {source_env} -> {result.get('name', new_name)}")


@click.command("gepa", context_settings={"ignore_unknown_options": True})
@click.argument("args", nargs=-1, type=click.UNPROCESSED)
def gepa(args: tuple[str, ...]) -> None:
    """Run the GEPA prompt optimizer (requires the optional `gepa` package)."""
    import importlib.util
    import subprocess
    import sys

    if importlib.util.find_spec("gepa") is None:
        raise click.ClickException(
            "GEPA is not installed: pip install gepa (then re-run `prime gepa ...`)"
        )
    raise SystemExit(subprocess.run([sys.executable, "-m", "gepa", *args]).returncode)
