"""`prime gepa` + `prime fork` (reference: commands/gepa.py, fork.py).

``fork`` clones a hub environment under a new name (server-side copy).
``gepa`` is a passthrough to the GEPA prompt-optimizer when that optional
package is installed locally.
"""

from __future__ import annotations

import click

from prime_tpu.commands._deps import build_client
from prime_tpu.utils.render import Renderer, output_options


@click.command("fork")
@click.argument("source_env")
@click.argument("new_name")
@output_options
def fork(render: Renderer, source_env: str, new_name: str) -> None:
    """Fork a hub environment under a new name."""
    result = build_client().post(
        f"/envhub/environments/{source_env}/fork",
        json={"newName": new_name},
        idempotent_post=True,
    )
    if render.is_json:
        render.json(result)
    else:
        render.message(f"Forked {source_env} -> {result.get('name', new_name)}")


class _DefaultRunGroup(click.Group):
    """`prime gepa wordle --max-calls 100` == `prime gepa run wordle ...`
    (reference commands/gepa.py DefaultCommandGroup)."""

    def resolve_command(self, ctx, args):
        if args and args[0] not in self.commands and args[0] not in ("--help", "-h"):
            args = ["run", *args]
        return super().resolve_command(ctx, args)

    def format_usage(self, ctx, formatter):
        formatter.write_usage(ctx.command_path, "run ENV_OR_CONFIG [ARGS]...")


@click.group("gepa", cls=_DefaultRunGroup, invoke_without_command=False)
def gepa() -> None:
    """Run GEPA prompt optimization (endpoint + key injected from config)."""


def _exec_gepa(run_target: str, args: list[str], env: dict[str, str]) -> None:
    """Exec the optional optimizer package — the ONLY step that needs it
    installed; everything before (injection, env resolution) runs without."""
    import importlib.util
    import subprocess
    import sys

    if importlib.util.find_spec("gepa") is None:
        raise click.ClickException(
            "GEPA is not installed: pip install gepa (then re-run `prime gepa ...`)"
        )
    raise SystemExit(
        subprocess.run(
            [sys.executable, "-m", "gepa", run_target, *args], env=env
        ).returncode
    )


@gepa.command(
    "run",
    context_settings={"ignore_unknown_options": True, "help_option_names": []},
)
@click.argument("environment_or_config", required=False)
@click.argument("args", nargs=-1, type=click.UNPROCESSED)
def gepa_run(environment_or_config: str | None, args: tuple[str, ...]) -> None:
    """Run optimization with local-first environment resolution.

    Injects the configured inference endpoint (-b) and API key
    (-k PRIME_API_KEY) unless overridden; resolves ENV_OR_CONFIG the same
    way `prime eval run` does (reference verifiers_bridge.py:1064).
    """
    from prime_tpu.evals.gepa_bridge import (
        GepaBridgeError,
        gepa_help_text,
        is_help_request,
        prepare_gepa_run,
    )

    passthrough = list(args)
    if is_help_request(environment_or_config or "", passthrough):
        click.echo(gepa_help_text())
        return
    if environment_or_config is None:
        raise click.UsageError(
            "Missing argument 'ENV_OR_CONFIG'. "
            "Example: prime gepa run wordle --max-calls 100"
        )
    if environment_or_config.startswith("-"):
        raise click.UsageError(
            "Environment/config must be the first argument. "
            "Example: prime gepa run wordle --max-calls 100"
        )

    from prime_tpu.commands._deps import build_config
    from prime_tpu.envhub.execution import EnvResolutionError
    from prime_tpu.evals.endpoints import EvalPreflightError

    try:
        invocation = prepare_gepa_run(
            environment_or_config, passthrough, build_config(),
            hub_client=_hub_client_or_none(),
        )
    except (GepaBridgeError, EnvResolutionError, EvalPreflightError, ValueError) as e:
        # ValueError: a local env dir with a malformed env.toml
        # (envhub.packaging.read_env_metadata) must fail as a CLI error too
        raise click.ClickException(str(e)) from None
    for warning in invocation.warnings:
        click.echo(f"Warning: {warning}", err=True)
    if invocation.resolved_env_name:
        click.echo(
            f"Environment: {invocation.resolved_env_name} "
            f"({invocation.resolved_source})",
            err=True,
        )
    _exec_gepa(invocation.run_target, invocation.args, invocation.env)


def _hub_client_or_none():
    """A hub client for on-demand env installs; None when the control plane
    is unreachable/unconfigured (local env dirs still resolve)."""
    try:
        from prime_tpu.commands.env import build_hub_client

        return build_hub_client()
    except Exception:  # noqa: BLE001 — resolution degrades to local-only
        return None
