"""`prime disks` — persistent disk CRUD (reference: prime_cli/commands/disks.py)."""

from __future__ import annotations

import click

from prime_tpu.api.disks import CreateDiskRequest, DisksClient
from prime_tpu.commands._deps import build_client
from prime_tpu.utils.render import Renderer, output_options
from prime_tpu.utils.short_id import resolve, shorten


@click.group(name="disks")
def disks_group() -> None:
    """Manage persistent disks."""


def _resolve(client: DisksClient, disk_id: str) -> str:
    try:
        return resolve(disk_id, [d.disk_id for d in client.list()])
    except ValueError as e:
        raise click.ClickException(str(e)) from None


@disks_group.command("list")
@output_options
def list_disks(render: Renderer) -> None:
    disks = DisksClient(build_client()).list()
    render.table(
        ["ID", "NAME", "SIZE GiB", "TYPE", "PROVIDER", "REGION", "STATUS", "ATTACHED TO"],
        [
            [
                shorten(d.disk_id),
                d.name,
                d.size_gib,
                d.disk_type,
                d.provider,
                d.region,
                d.status,
                shorten(d.attached_pod_id) if d.attached_pod_id else "",
            ]
            for d in disks
        ],
        title="Disks",
        json_rows=[d.model_dump(by_alias=True) for d in disks],
    )


@disks_group.command("create")
@click.option("--name", required=True)
@click.option("--size-gib", type=int, required=True)
@click.option("--disk-type", default="hyperdisk-balanced")
@click.option("--provider", default=None)
@click.option("--region", default=None)
@output_options
def create_disk(
    render: Renderer, name: str, size_gib: int, disk_type: str, provider: str | None, region: str | None
) -> None:
    disk = DisksClient(build_client()).create(
        CreateDiskRequest(name=name, size_gib=size_gib, disk_type=disk_type, provider=provider, region=region)
    )
    if render.is_json:
        render.json(disk.model_dump(by_alias=True))
    else:
        render.message(f"Disk {shorten(disk.disk_id)} ({disk.name}, {disk.size_gib} GiB) created: {disk.status}")


@disks_group.command("get")
@click.argument("disk_id")
@output_options
def get_disk(render: Renderer, disk_id: str) -> None:
    client = DisksClient(build_client())
    disk = client.get(_resolve(client, disk_id))
    render.detail(disk.model_dump(by_alias=True), title=f"Disk {shorten(disk.disk_id)}")


@disks_group.command("delete")
@click.argument("disk_id")
@click.option("--yes", "-y", is_flag=True)
@output_options
def delete_disk(render: Renderer, disk_id: str, yes: bool) -> None:
    client = DisksClient(build_client())
    full_id = _resolve(client, disk_id)
    if not yes and not click.confirm(f"Delete disk {shorten(full_id)}?"):
        render.message("Aborted.")
        return
    client.delete(full_id)
    render.message(f"Disk {shorten(full_id)} deleted.")
