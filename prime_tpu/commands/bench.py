"""`prime bench` — the perf trajectory and the loadgen harness from the CLI.

`delta` renders the committed BENCH_*.json rounds into the per-PR table
(stdlib-only — safe on machines without jax); `smoke` runs the CPU loadgen
fleet smoke and writes its SLO report + BENCH-schema record (docs/
benchmarking.md). The real TPU bench stays `python bench.py` — it manages
accelerator preflight and stray-process sweeps no CLI should hide.
"""

from __future__ import annotations

import json

import click


@click.group(name="bench")
def bench_group() -> None:
    """Benchmark trajectory tools (see docs/benchmarking.md)."""


@bench_group.command("delta")
@click.option("--root", default=".", help="Directory holding BENCH_*.json.")
@click.option("--pattern", default=None,
              help="Restrict to one round-file glob (default: BENCH_*.json "
                   "and MULTICHIP_*.json merged — multichip rounds render "
                   "their own mc-prefixed rows, never cross-backend deltas).")
@click.option("--output", "as_json", is_flag=False, flag_value="json", default=None,
              help="Set to 'json' for machine-readable output.")
@click.option("--min-rounds", type=int, default=2,
              help="Exit nonzero below this many parseable rounds.")
def bench_delta(root: str, pattern: str | None, as_json: str | None, min_rounds: int) -> None:
    """Render the per-PR perf delta table across committed bench rounds."""
    from prime_tpu.loadgen.perf_delta import (
        delta_json,
        delta_table,
        load_all_rounds,
        load_rounds,
    )

    rounds = load_rounds(root, pattern) if pattern else load_all_rounds(root)
    if as_json == "json":
        click.echo(json.dumps(delta_json(rounds), indent=2))
    else:
        click.echo(delta_table(rounds, min_rounds=min_rounds))
    if len(rounds) < min_rounds:
        raise SystemExit(1)


@bench_group.command("smoke")
@click.option("--output", default="loadgen-smoke", help="Artifact directory.")
@click.option("--scenario", default="smoke",
              help="Loadgen scenario name (prime_tpu.loadgen.SCENARIOS).")
@click.option("--seed", type=int, default=None,
              help="Schedule seed. Default: 0 (PRIME_LOADGEN_SEED).")
@click.option("--replicas", type=int, default=2, help="In-process fleet size.")
@click.option("--mesh", default=None, metavar="SPEC",
              help="Sharded-replica mesh spec (e.g. 'dp=1,fsdp=2,tp=2'): "
                   "each replica spans that mesh (MULTICHIP rounds).")
@click.option("--time-scale", type=float, default=1.0,
              help="Multiplier on scheduled arrival/cancel offsets.")
def bench_smoke(
    output: str, scenario: str, seed: int | None, replicas: int,
    mesh: str | None, time_scale: float
) -> None:
    """Run the CPU loadgen fleet smoke end to end (no TPU required)."""
    from prime_tpu.loadgen.smoke import run_smoke

    outcome = run_smoke(
        output, scenario=scenario, seed=seed, replicas=replicas, mesh=mesh,
        time_scale=time_scale, log=click.echo,
    )
    if not outcome["ok"]:
        raise SystemExit(1)


@bench_group.command("sentinel")
@click.option("--root", default=".", help="Directory holding BENCH_*.json.")
@click.option("--report", default=None, type=click.Path(exists=True),
              help="Fresh loadgen SLO report (slo_report.json) appended as "
                   "the candidate round — what CI gates before a record is "
                   "committed.")
@click.option("--band-pct", type=float, default=None,
              help="Regression band in percent (default: "
                   "PRIME_SENTINEL_BAND_PCT, 50).")
@click.option("--min-history", type=int, default=None,
              help="Prior rounds a metric needs before it gates (default: "
                   "PRIME_SENTINEL_MIN_HISTORY, 3).")
@click.option("--all-metrics", is_flag=True,
              help="Gate every delta-table row instead of the curated "
                   "headline set (CPU-smoke latency percentiles are noisy; "
                   "see docs/observability.md).")
@click.option("--output", "as_json", is_flag=False, flag_value="json", default=None,
              help="Set to 'json' for machine-readable output.")
def bench_sentinel(
    root: str, report: str | None, band_pct: float | None,
    min_history: int | None, all_metrics: bool, as_json: str | None,
) -> None:
    """Gate the perf trajectory: exit nonzero when the newest round (or a
    fresh --report) regresses beyond the configured bands. Same
    implementation as the delta table's `sentinel verdict` row
    (obs/sentinel.trajectory_verdicts) — stdlib-only, no jax."""
    from prime_tpu.loadgen.perf_delta import load_all_rounds, round_from_report
    from prime_tpu.obs.sentinel import trajectory_gate

    rounds: list = list(load_all_rounds(root))
    if report is not None:
        with open(report) as fh:
            rounds.append(round_from_report(json.load(fh), label="candidate"))
    gate = trajectory_gate(
        rounds,
        band_pct=band_pct,
        min_history=min_history,
        gate_metrics="all" if all_metrics else None,
    )
    if as_json == "json":
        click.echo(json.dumps(gate, indent=2))
    else:
        for verdict in gate["verdicts"]:
            line = f"{verdict['label']:<24} {verdict['verdict']}"
            if verdict["checked"]:
                line += f" ({verdict['checked']} gated metrics)"
            click.echo(line)
            for reg in verdict["regressions"]:
                click.echo(
                    f"    {reg['metric']}: {reg['value']:g} vs baseline "
                    f"{reg['baseline']:g} ({reg['delta_pct']:+.1f}%)"
                )
        latest = gate["latest"]
        click.echo(
            "sentinel: "
            + ("no rounds" if latest is None else f"latest={latest['label']} verdict={latest['verdict']}")
        )
    if not gate["ok"]:
        raise SystemExit(1)


@bench_group.command("autotune")
@click.option("--kernel", "kernels", multiple=True,
              help="Restrict the sweep to named kernels (repeatable; "
                   "default: all of ops/autotune.CANDIDATES).")
@click.option("--output", default=None, metavar="DIR",
              help="Artifact directory (default: the kernel_configs "
                   "resolution dir — PRIME_TPU_KERNEL_CONFIG_DIR or the "
                   "in-package registry).")
@click.option("--repeats", type=int, default=3,
              help="Timed runs per candidate (best-of).")
@click.option("--dry-run", is_flag=True,
              help="Tiny shapes, interpret mode, trimmed grids: proves the "
                   "sweep -> artifact -> resolution round-trip on CPU. "
                   "Timings are meaningless; point --output somewhere "
                   "disposable.")
def bench_autotune(
    kernels: tuple[str, ...], output: str | None, repeats: int, dry_run: bool
) -> None:
    """Time candidate pallas block configs and persist this device kind's
    winners (docs/kernels.md "Kernel campaign & autotune")."""
    from prime_tpu.ops import kernel_configs
    from prime_tpu.ops.autotune import run_autotune

    kind = kernel_configs.device_kind()
    click.echo(f"autotune: device_kind={kind} dry_run={dry_run}")
    winners = run_autotune(
        kernels=list(kernels) or None, dry_run=dry_run, repeats=repeats,
        log=click.echo,
    )
    if not winners:
        click.echo("no kernel produced a viable candidate; nothing saved")
        raise SystemExit(1)
    path = kernel_configs.save_artifact(winners, directory=output, kind=kind)
    click.echo(f"saved {len(winners)} kernel config(s) -> {path}")
    # prove the artifact round-trips through the resolution path the
    # kernels actually use (fails loudly here instead of silently
    # degrading to defaults at first dispatch)
    if output:
        import os

        os.environ["PRIME_TPU_KERNEL_CONFIG_DIR"] = output
        kernel_configs.invalidate_cache()
    loaded = kernel_configs.load_tuned(kind)
    if loaded is None:
        raise SystemExit("artifact failed to load back through kernel_configs")
    for name, params in loaded.items():
        resolved = {p: kernel_configs.resolve(name, p) for p in params}
        click.echo(f"  {name}: resolves {resolved}")
    click.echo(f"config source now: {kernel_configs.source()}")
