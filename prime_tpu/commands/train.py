"""`prime train` / `prime rl` — TOML-driven hosted training.

Reference surface: prime_cli/commands/rl.py (run dispatch :1246 with full-FT
detection :882, models/gpus→tpus, configs schema dump, init template :229,
list/get/stop/delete/restart, streaming logs :2298 with component filters,
metrics/rollouts/progress/distributions, checkpoints). `prime train <file.toml>`
is sugar for `prime train run <file.toml>` (reference DefaultGroup).
"""

from __future__ import annotations

import time
from pathlib import Path

import click
import pydantic

import prime_tpu.commands._deps as deps
from prime_tpu.api.rl import RLClient
from prime_tpu.api.training import HostedTrainingClient, build_payload_from_toml
from prime_tpu.core.client import APIClient
from prime_tpu.train.config import RL_TOML_TEMPLATE, RLConfig, load_rl_config
from prime_tpu.utils.render import Renderer, output_options
from prime_tpu.utils.short_id import resolve, shorten

LOG_POLL_INTERVAL_S = 3.0


class TrainGroup(click.Group):
    """`prime train foo.toml` → `prime train run foo.toml`."""

    def resolve_command(self, ctx, args):
        if args and args[0].endswith(".toml"):
            return super().resolve_command(ctx, ["run", *args])
        return super().resolve_command(ctx, args)


@click.group(name="train", cls=TrainGroup)
def train_group() -> None:
    """Launch and monitor hosted training runs on TPU slices."""


def _rl_client() -> RLClient:
    return RLClient(APIClient(config=deps.build_config(), transport=deps.transport_override))


def _resolve_run(client: RLClient, run_id: str) -> str:
    try:
        return resolve(run_id, [r.run_id for r in client.list_runs()])
    except ValueError as e:
        raise click.ClickException(str(e)) from None


@train_group.command("run")
@click.argument("config_file", type=click.Path(exists=True))
@click.option("--yes", "-y", is_flag=True, help="Skip the confirmation preview.")
@click.option("--follow", "-f", is_flag=True, help="Stream logs after dispatch.")
@output_options
def run_cmd(render: Renderer, config_file: str, yes: bool, follow: bool) -> None:
    """Dispatch a training run from a TOML config."""
    try:
        config, warnings = load_rl_config(config_file)
    except pydantic.ValidationError as e:
        msgs = "; ".join(
            f"{'.'.join(str(p) for p in err['loc'])}: {err['msg']}" for err in e.errors()
        )
        raise click.ClickException(f"Invalid config: {msgs}") from None
    except Exception as e:
        raise click.ClickException(f"Could not parse {config_file}: {e}") from None
    for warning in warnings:
        render.message(f"warning: {warning}", err=True)

    if config.is_full_finetune:
        # full-FT: whole TOML shipped opaque; only allowlisted env vars ride
        # along (reference commands/rl.py:985 — WANDB_API_KEY/HF_TOKEN)
        from prime_tpu.utils.env_vars import FULL_FT_ALLOWED_KEYS, collect_env_vars

        payload = build_payload_from_toml(
            config_file, env_vars=collect_env_vars(allowed=FULL_FT_ALLOWED_KEYS)
        )
        if not yes and not click.confirm(
            f"Dispatch FULL-FINETUNE '{config.name}' ({config.model}) on "
            f"{payload['tpuType']} x{payload['numSlices']}?",
            default=True,
        ):
            render.message("Aborted.")
            return
        client = HostedTrainingClient(
            APIClient(config=deps.build_config(), transport=deps.transport_override)
        )
        run = client.create_run(payload)
        run_id = run.get("runId", "")
    else:
        if not yes and not click.confirm(
            f"Dispatch LoRA run '{config.name}' ({config.model}, env {config.env.id}) on "
            f"{config.infrastructure.tpu_type} x{config.infrastructure.num_slices}?",
            default=True,
        ):
            render.message("Aborted.")
            return
        run_model = _rl_client().create_run(config.to_payload())
        run_id = run_model.run_id
    if render.is_json:
        render.json({"runId": run_id, "type": config.type})
    else:
        render.message(f"Run {shorten(run_id)} dispatched. Logs: prime train logs {shorten(run_id)} -f")
    if follow:
        _stream_logs(render, run_id)


@train_group.command("request")
@click.option("--models", "-m", "models_text", default=None,
              help="Model(s) to request (comma-separated); prompts when omitted.")
@click.option("--context", "context_text", default=None,
              help="Use case / why this model matters.")
def request_models_cmd(models_text: str | None, context_text: str | None) -> None:
    """Request models for Hosted Training (lands as product feedback;
    reference rl.py:1803)."""
    prompted = models_text is None
    if prompted:
        models_text = click.prompt("Model(s) (provider/model names, comma-separated ok)")
    if not models_text.strip():
        raise click.ClickException("At least one model is required")
    if context_text is None:
        # only prompt in the interactive flow — `-m` from a script must not
        # hang on a stdin read for an OPTIONAL field
        context_text = (
            click.prompt("Use case or context (enter to skip)", default="", show_default=False)
            if prompted
            else ""
        )
    message = f"Hosted Training model request: {models_text.strip()}"
    if context_text.strip():
        message += f"\nContext: {context_text.strip()}"
    deps.build_client().post("/feedback", json={"message": message}, idempotent_post=True)
    click.echo("Request submitted. Thanks!")


@train_group.command("local")
@click.option("--model", "-m", default="tiny-test", help="Model preset to train.")
@click.option("--steps", type=int, default=20)
@click.option("--batch-size", "-b", type=int, default=8)
@click.option("--seq-len", type=int, default=128)
@click.option("--lr", type=float, default=3e-4)
@click.option("--accum", type=int, default=1, help="Gradient accumulation steps.")
@click.option("--remat", type=click.Choice(["none", "dots", "full"]), default="none",
              help="Activation checkpointing around the layer scan: 'dots' keeps "
                   "matmul outputs, 'full' recomputes everything in the backward pass.")
@click.option("--warmup", type=int, default=None, help="Warmup steps (default 1% of steps).")
@click.option("--data", "data_path", default=None, type=click.Path(exists=True),
              help="Text file (byte-tokenized LM data); default synthetic tokens.")
@click.option("--slice", "slice_name", default=None, help="Shard over this TPU slice's mesh.")
@click.option("--sp", "sp_degree", type=click.IntRange(min=2), default=None,
              help="Context-parallel degree: shard the SEQUENCE over an sp axis "
                   "with ring attention (long sequences train without fitting on "
                   "one chip). Needs --slice; the non-sp chips become fsdp.")
@click.option("--name", "run_name", default=None, help="Run name (default timestamped).")
@click.option("--output-dir", default="outputs/train")
@click.option("--checkpoint-every", type=int, default=0, help="orbax checkpoint cadence (0=off).")
@click.option("--resume", is_flag=True, help="Resume --name from its latest checkpoint.")
@click.option("--profile", is_flag=True, help="Capture a jax.profiler trace of steps 2-5.")
@click.option("--lora", is_flag=True,
              help="Train LoRA adapters over frozen base weights (saves an adapter artifact).")
@click.option("--lora-r", type=click.IntRange(min=1), default=16, help="LoRA rank.")
@click.option("--lora-alpha", type=click.IntRange(min=1), default=32,
              help="LoRA alpha (scale = alpha/r).")
@output_options
def local_cmd(
    render: Renderer,
    model: str,
    steps: int,
    batch_size: int,
    seq_len: int,
    lr: float,
    accum: int,
    remat: str,
    warmup: int | None,
    data_path: str | None,
    slice_name: str | None,
    sp_degree: int | None,
    run_name: str | None,
    output_dir: str,
    checkpoint_every: int,
    resume: bool,
    profile: bool,
    lora: bool,
    lora_r: int,
    lora_alpha: int,
) -> None:
    """Train MODEL locally on this slice (native JAX trainer, not hosted).

    The hosted path (`prime train run`) dispatches to the platform; this runs
    the framework's own sharded train step right here — metrics land in
    outputs/train/<run>/metrics.jsonl and chart in `prime lab`.
    """
    import jax
    import jax.numpy as jnp

    from prime_tpu.models import get_config
    from prime_tpu.models.llama import init_params
    from prime_tpu.train import (
        default_optimizer,
        init_train_state,
        make_train_step,
        train_loop,
        warmup_cosine,
    )
    from prime_tpu.train.data import synthetic_batches, text_batches
    from prime_tpu.train.metrics import MetricsLogger

    try:
        config = get_config(model)
    except ValueError as e:
        raise click.ClickException(str(e)) from None
    if accum < 1:
        raise click.ClickException(f"--accum must be >= 1 (got {accum})")
    if batch_size % accum:
        raise click.ClickException(f"--batch-size {batch_size} must divide by --accum {accum}")

    if resume and not run_name:
        raise click.ClickException("--resume needs --name (which run to continue)")
    if resume and not checkpoint_every:
        raise click.ClickException("--resume needs --checkpoint-every (to keep saving)")
    run_name = run_name or f"{model}-{time.strftime('%Y%m%d-%H%M%S')}"
    run_dir = Path(output_dir) / run_name
    if not resume and (run_dir / "metrics.jsonl").exists():
        # appending would interleave two runs' rows under duplicate steps
        raise click.ClickException(
            f"run {run_dir} already has metrics — pick a new --name or pass --resume"
        )
    run_dir.mkdir(parents=True, exist_ok=True)

    if lora and accum > 1:
        raise click.ClickException("--lora does not support --accum yet")
    if lora and getattr(config, "mla", False):
        raise click.ClickException(
            "--lora does not support MLA configs (no wq/wk/wv projections)"
        )

    schedule = warmup_cosine(lr, total_steps=steps, warmup_steps=warmup)
    optimizer = default_optimizer(schedule)
    params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.bfloat16)

    mesh = None
    if sp_degree is not None:
        # context parallelism: sequence over sp, remaining chips on fsdp
        # (ring attention composes with tp via sharding.ring_qkv_axes, but
        # train local keeps the mesh policy simple: fsdp x sp)
        if slice_name is None:
            raise click.ClickException("--sp needs --slice (which chips form the mesh)")
        if lora:
            raise click.ClickException("--sp does not support --lora yet")
        if seq_len % sp_degree:
            raise click.ClickException(
                f"--seq-len {seq_len} must divide by --sp {sp_degree}"
            )
        if config.sliding_window and config.sliding_pattern != "uniform":
            raise click.ClickException(
                f"--sp supports uniform window schedules only "
                f"(model {model!r} uses {config.sliding_pattern!r})"
            )
        from prime_tpu.parallel.mesh import make_mesh
        from prime_tpu.parallel.topology import parse_slice

        chips = parse_slice(slice_name).chips
        if chips % sp_degree:
            raise click.ClickException(
                f"--sp {sp_degree} must divide the slice's {chips} chips"
            )
        mesh = make_mesh({"dp": 1, "fsdp": chips // sp_degree, "sp": sp_degree})
        render.message(f"mesh: {dict(mesh.shape)} (context-parallel)")
    elif slice_name is not None:
        from prime_tpu.parallel.mesh import mesh_for_slice

        mesh = mesh_for_slice(
            slice_name,
            expert_parallel="auto" if config.is_moe else None,
            n_experts=config.n_experts or None,
        )
        render.message(f"mesh: {dict(mesh.shape)}")

    lora_cfg = None
    if lora:
        from prime_tpu.train.lora import (
            LoraConfig,
            init_lora_params,
            init_lora_state,
            make_lora_train_step,
            shard_lora_state,
        )

        lora_cfg = LoraConfig(r=lora_r, alpha=lora_alpha)
        adapters = init_lora_params(jax.random.PRNGKey(1), config, lora_cfg)
        state = init_lora_state(adapters, optimizer)
        if mesh is not None:
            from prime_tpu.parallel.sharding import shard_params

            params = shard_params(params, mesh, config)
            state = shard_lora_state(state, mesh, config, lora_cfg)
        lora_step = make_lora_train_step(config, lora_cfg, optimizer, remat=remat)

        def step_fn(s, tokens, targets, mask):
            return lora_step(s, params, tokens, targets, mask)

        render.message(
            f"LoRA r={lora_r} alpha={lora_alpha}: "
            f"{sum(x.size for x in jax.tree.leaves(adapters)):,} trainable params "
            f"(base {config.param_count:,} frozen)"
        )
    else:
        state = init_train_state(params, optimizer)
        if mesh is not None:
            from prime_tpu.train import shard_train_state

            state = shard_train_state(state, mesh, config)
        step_fn = make_train_step(
            config, optimizer, accum_steps=accum, remat=remat,
            attn_impl="ring" if sp_degree else "auto",
            ring_mesh=mesh if sp_degree else None,
        )

    if data_path:
        batches = text_batches(data_path, batch_size, seq_len, steps)
    else:
        batches = synthetic_batches(config.vocab_size, batch_size, seq_len, steps)
    if mesh is not None:
        from prime_tpu.parallel.sharding import cp_batch_spec, shard_batch

        batch_sp = cp_batch_spec() if sp_degree else None
        batches = (
            tuple(shard_batch(x, mesh, spec=batch_sp) for x in b) for b in batches
        )

    checkpoints = None
    start_step = 0
    if checkpoint_every:
        from prime_tpu.train.checkpoint import CheckpointManager

        checkpoints = CheckpointManager(run_dir / "checkpoints")
        if resume:
            try:
                state = checkpoints.restore(state)
            except FileNotFoundError as e:
                checkpoints.close()
                raise click.ClickException(str(e)) from None
            start_step = int(jax.device_get(state.step))
            render.message(f"resumed {run_name} from step {start_step}")

    def on_step(step: int, row: dict) -> None:
        if step % 5 == 0 or step == steps - 1:
            render.message(
                f"  step {step}: loss={row['loss']:.4f} "
                f"{row['tokens_per_sec']:.0f} tok/s"
            )

    # a short run must still honor --profile: shrink the trace window to fit
    profile_window = (2, 5) if steps >= 5 else (0, min(2, steps))
    state, report = train_loop(
        state,
        step_fn,
        batches,
        metrics=MetricsLogger(run_dir),
        checkpoints=checkpoints,
        checkpoint_every=checkpoint_every,
        profile_dir=str(run_dir / "trace") if profile else None,
        profile_window=profile_window,
        on_step=on_step,
        start_step=start_step,
    )
    if checkpoints is not None:
        checkpoints.close()
    payload = {"runDir": str(run_dir), **report.as_dict()}
    _save_adapter_artifact(render, payload, run_dir, state, lora_cfg, config, params)
    if render.is_json:
        render.json(payload)
    else:
        render.message(
            f"done: {report.steps} steps, final loss {report.final_loss:.4f}, "
            f"{report.tokens_per_sec:.0f} tok/s -> {run_dir}"
        )


@train_group.command("local-rl")
@click.argument("env_ref")
@click.option("--model", "-m", default="tiny-test", help="Model preset (or checkpoint dir).")
@click.option("--checkpoint", default=None, type=click.Path(exists=True),
              help="Local HF checkpoint dir to start from.")
@click.option("--tokenizer", default=None, help="Tokenizer name/path (default: checkpoint's).")
@click.option("--steps", type=int, default=50)
@click.option("--group-size", "-g", type=int, default=8, help="Completions per prompt (GRPO G).")
@click.option("--prompts-per-step", "-p", type=int, default=4)
@click.option("--max-prompt-len", type=int, default=128)
@click.option("--max-new-tokens", type=int, default=64)
@click.option("--temperature", type=float, default=1.0)
@click.option("--top-p", type=float, default=1.0)
@click.option("--lr", type=float, default=1e-5)
@click.option("--clip-eps", type=float, default=0.2)
@click.option("--kl-coef", type=float, default=0.0,
              help="KL penalty vs the frozen start policy (doubles param memory).")
@click.option("--epochs-per-batch", type=int, default=1, help="Updates per rollout batch (GRPO mu).")
@click.option("--slice", "slice_name", default=None, help="Shard over this TPU slice's mesh.")
@click.option("--name", "run_name", default=None, help="Run name (default timestamped).")
@click.option("--output-dir", default="outputs/rl")
@click.option("--checkpoint-every", type=int, default=0, help="orbax checkpoint cadence (0=off).")
@click.option("--lora", is_flag=True,
              help="Train LoRA adapters over the frozen base (the hosted default run type).")
@click.option("--lora-r", type=click.IntRange(min=1), default=16, help="LoRA rank.")
@click.option("--lora-alpha", type=click.IntRange(min=1), default=32,
              help="LoRA alpha (scale = alpha/r).")
@click.option("--remat", type=click.Choice(["none", "dots", "full"]), default="none",
              help="Activation checkpointing in the update forward.")
@output_options
def local_rl_cmd(
    render: Renderer,
    env_ref: str,
    model: str,
    checkpoint: str | None,
    tokenizer: str | None,
    steps: int,
    group_size: int,
    prompts_per_step: int,
    max_prompt_len: int,
    max_new_tokens: int,
    temperature: float,
    top_p: float,
    lr: float,
    clip_eps: float,
    kl_coef: float,
    epochs_per_batch: int,
    slice_name: str | None,
    run_name: str | None,
    output_dir: str,
    checkpoint_every: int,
    lora: bool,
    lora_r: int,
    lora_alpha: int,
    remat: str,
) -> None:
    """GRPO fine-tune MODEL against ENV_REF locally on this slice.

    The hosted path (`prime train run rl.toml`) dispatches RL to the platform;
    this runs the framework's own GRPO loop natively: the env's dataset and
    scorer (environment execution protocol, same contract `prime eval run`
    uses) drive sharded rollouts and clipped-surrogate updates on the chips in
    front of you. ENV_REF resolves like eval envs: local dir, installed env,
    hub slug, or the built-in `arith`.
    """
    import jax
    import jax.numpy as jnp

    from prime_tpu.models import get_config
    from prime_tpu.models.llama import init_params
    from prime_tpu.train.grpo import GrpoConfig, run_grpo
    from prime_tpu.train.metrics import MetricsLogger
    from prime_tpu.train.trainer import default_optimizer

    # -- environment: same execution protocol as `prime eval run` ------------
    examples, scorer, env_name, env_defaults = _rl_environment(render, env_ref)

    # env-declared eval defaults apply unless the flag was given explicitly
    from prime_tpu.utils.render import flag_is_default

    if "max_new_tokens" in env_defaults and flag_is_default("max_new_tokens"):
        max_new_tokens = int(env_defaults["max_new_tokens"])
    if "temperature" in env_defaults and flag_is_default("temperature"):
        env_temp = float(env_defaults["temperature"])
        if env_temp > 0.0:
            temperature = env_temp
        else:
            click.echo(
                "warning: env declares temperature=0 (greedy eval) — GRPO rollouts "
                f"need temperature > 0; keeping {temperature}",
                err=True,
            )

    try:
        cfg = GrpoConfig(
            group_size=group_size,
            prompts_per_step=prompts_per_step,
            max_prompt_len=max_prompt_len,
            max_new_tokens=max_new_tokens,
            temperature=temperature,
            top_p=top_p,
            clip_eps=clip_eps,
            kl_coef=kl_coef,
            epochs_per_batch=epochs_per_batch,
            steps=steps,
            learning_rate=lr,
            remat=remat,
        )
    except ValueError as e:
        raise click.ClickException(str(e)) from None

    # -- model + tokenizer ---------------------------------------------------
    from prime_tpu.evals.tokenizer import load_tokenizer

    if checkpoint is None and Path(model).is_dir():
        checkpoint = model
    try:
        tok = load_tokenizer(tokenizer or checkpoint)
    except ValueError as e:
        raise click.ClickException(str(e)) from None
    if checkpoint is not None:
        from prime_tpu.models.hf_loader import load_hf_checkpoint

        params, config = load_hf_checkpoint(checkpoint, dtype=jnp.bfloat16)
    else:
        try:
            config = get_config(model)
        except ValueError as e:
            raise click.ClickException(str(e)) from None
        params = init_params(jax.random.PRNGKey(0), config, dtype=jnp.bfloat16)

    mesh = None
    if slice_name is not None:
        from prime_tpu.parallel.mesh import mesh_for_slice

        mesh = mesh_for_slice(slice_name)
        render.message(f"mesh: {dict(mesh.shape)}")

    lora_cfg = None
    if lora:
        from prime_tpu.train.lora import LoraConfig

        if getattr(config, "mla", False):
            raise click.ClickException(
                "--lora does not support MLA configs (no wq/wk/wv projections)"
            )
        lora_cfg = LoraConfig(r=lora_r, alpha=lora_alpha)
        render.message(f"LoRA r={lora_r} alpha={lora_alpha} (base frozen)")

    run_name = run_name or f"{env_name}-{time.strftime('%Y%m%d-%H%M%S')}"
    run_dir = Path(output_dir) / run_name
    if (run_dir / "metrics.jsonl").exists():
        raise click.ClickException(
            f"run {run_dir} already has metrics — pick a new --name"
        )
    run_dir.mkdir(parents=True, exist_ok=True)

    checkpoints = None
    if checkpoint_every:
        from prime_tpu.train.checkpoint import CheckpointManager

        checkpoints = CheckpointManager(run_dir / "checkpoints")

    def on_step(step: int, row: dict) -> None:
        if step % 5 == 0 or step == steps - 1:
            render.message(
                f"  step {step}: reward={row['reward_mean']:.3f} "
                f"loss={row['loss']:.4f} kl={row['kl']:.4f}"
            )

    render.message(
        f"GRPO: {config.name} x {env_name} ({len(examples)} examples), "
        f"{steps} steps, G={group_size} P={prompts_per_step}"
    )
    try:
        state, report = run_grpo(
            config,
            params,
            tok,
            examples,
            scorer,
            cfg,
            optimizer=default_optimizer(lr, weight_decay=0.0),
            mesh=mesh,
            metrics=MetricsLogger(run_dir),
            checkpoints=checkpoints,
            checkpoint_every=checkpoint_every,
            on_step=on_step,
            lora=lora_cfg,
            # the CLI never reuses `params` after this call — skip the safety
            # copy and donate the tree (one full model of HBM on big models)
            copy_params=False,
        )
    except ValueError as e:
        raise click.ClickException(str(e)) from None
    finally:
        if checkpoints is not None:
            checkpoints.close()
    payload = {"runDir": str(run_dir), "env": env_name, **report.as_dict()}
    _save_adapter_artifact(render, payload, run_dir, state, lora_cfg, config, params)
    if render.is_json:
        render.json(payload)
    else:
        render.message(
            f"done: {report.steps} steps, reward {report.first_reward:.3f} -> "
            f"{report.last_reward:.3f}, final loss {report.final_loss:.4f} -> {run_dir}"
        )


def _save_adapter_artifact(
    render: Renderer, payload: dict, run_dir: Path, state, lora_cfg, config, base_params
) -> None:
    """Shared tail of every --lora run (SFT and GRPO): write the adapter
    artifact next to the run and surface the eval-merge hint."""
    if lora_cfg is None:
        return
    import jax

    from prime_tpu.train.lora import save_adapters

    adapter_dir = save_adapters(
        run_dir / "adapters", jax.device_get(state.params), lora_cfg, config,
        base_params=base_params,
    )
    payload["adapterDir"] = str(adapter_dir)
    render.message(f"adapters -> {adapter_dir} (eval run --adapter {adapter_dir})")


def _rl_environment(render: Renderer, env_ref: str):
    """Resolve ENV_REF to (examples, scorer, name, defaults) for GRPO."""
    if env_ref == "arith":
        from prime_tpu.evals.datasets import synthetic_arithmetic

        examples = [
            {"prompt": e.prompt, "answer": e.answer} for e in synthetic_arithmetic(256)
        ]
        return examples, None, "arith", {}

    from prime_tpu.commands.env import build_hub_client, load_resolved_environment
    from prime_tpu.envhub.execution import EnvResolutionError, resolve_environment

    try:
        resolved = resolve_environment(env_ref, hub_client=build_hub_client())
    except EnvResolutionError as e:
        raise click.ClickException(str(e)) from None
    loaded = load_resolved_environment(render, resolved)
    return loaded.examples, loaded.scorer, loaded.name, loaded.defaults


@train_group.command("init")
@click.argument("name")
@click.option("--out", default=None, help="Output file (default <name>.toml)")
def init_cmd(name: str, out: str | None) -> None:
    """Write a starter training TOML."""
    path = Path(out or f"{name}.toml")
    if path.exists():
        raise click.ClickException(f"{path} already exists")
    path.write_text(RL_TOML_TEMPLATE.format(name=name))
    click.echo(f"Wrote {path}. Edit it and dispatch with: prime train {path}")


@train_group.command("configs")
@output_options
def configs_cmd(render: Renderer) -> None:
    """Dump the training config schema (reference: prime train configs)."""
    render.json(RLConfig.model_json_schema())


@train_group.command("models")
@output_options
def models_cmd(render: Renderer) -> None:
    """List trainable models with pricing."""
    models = _rl_client().list_models()
    render.table(
        ["MODEL", "PARAMS(B)", "TRAIN $/HR", "DEFAULT TPU"],
        [
            [
                m.name,
                m.params_b,
                f"{m.resolve_price().train_per_hour:.2f}" if m.resolve_price() else "",
                m.default_tpu or "",
            ]
            for m in models
        ],
        title="Trainable models",
        json_rows=[m.model_dump(by_alias=True) for m in models],
    )


@train_group.command("tpus")
@output_options
def tpus_cmd(render: Renderer) -> None:
    """List TPU slice options for hosted training."""
    rows = _rl_client().list_tpus()
    render.table(
        ["SLICE", "CHIPS", "HOSTS", "$/HR"],
        [[r["sliceName"], r["chips"], r["hosts"], f"{r['priceHourly']:.2f}"] for r in rows],
        title="Training TPUs",
        json_rows=rows,
    )


@train_group.command("list")
@output_options
def list_cmd(render: Renderer) -> None:
    runs = _rl_client().list_runs()
    render.table(
        ["ID", "NAME", "MODEL", "TYPE", "STATUS", "TPU", "SLICES"],
        [
            [shorten(r.run_id), r.name, r.model, r.run_type, r.status, r.tpu_type or "", r.num_slices]
            for r in runs
        ],
        title="Training runs",
        json_rows=[r.model_dump(by_alias=True) for r in runs],
    )


@train_group.command("get")
@click.argument("run_id")
@output_options
def get_cmd(render: Renderer, run_id: str) -> None:
    client = _rl_client()
    run = client.get_run(_resolve_run(client, run_id))
    render.detail(run.model_dump(by_alias=True), title=f"Run {shorten(run.run_id)}")


@train_group.command("stop")
@click.argument("run_id")
@output_options
def stop_cmd(render: Renderer, run_id: str) -> None:
    client = _rl_client()
    run = client.stop_run(_resolve_run(client, run_id))
    render.message(f"Run {shorten(run.run_id)} is {run.status}.")


@train_group.command("restart")
@click.argument("run_id")
@output_options
def restart_cmd(render: Renderer, run_id: str) -> None:
    """Restart a run from its latest checkpoint."""
    client = _rl_client()
    run = client.restart_run(_resolve_run(client, run_id))
    render.message(f"Run {shorten(run.run_id)} restarted: {run.status}.")


@train_group.command("delete")
@click.argument("run_id")
@click.option("--yes", "-y", is_flag=True)
@output_options
def delete_cmd(render: Renderer, run_id: str, yes: bool) -> None:
    client = _rl_client()
    full_id = _resolve_run(client, run_id)
    if not yes and not click.confirm(f"Delete run {shorten(full_id)}?"):
        render.message("Aborted.")
        return
    client.delete_run(full_id)
    render.message(f"Run {shorten(full_id)} deleted.")


def _stream_logs(
    render: Renderer,
    run_id: str,
    component: str | None = None,
    worker_index: int | None = None,
    env_name: str | None = None,
    max_polls: int | None = None,
) -> None:
    """Poll-stream logs with dedup until the run is terminal (reference :2298)."""
    client = _rl_client()
    seen: set[str] = set()
    polls = 0
    while True:
        logs = client.get_logs(run_id, component=component, worker_index=worker_index, env_name=env_name)
        for row in logs:
            key = f"{row.get('ts', '')}|{row.get('component', '')}|{row.get('workerIndex', '')}|{row.get('message', '')}"
            if key in seen:
                continue
            seen.add(key)
            prefix = f"[{row.get('component', '?')}{':' + str(row['workerIndex']) if row.get('workerIndex') is not None else ''}]"
            click.echo(f"{row.get('ts', '')} {prefix} {row.get('message', '')}")
        run = client.get_run(run_id)
        if run.status in ("COMPLETED", "FAILED", "STOPPED"):
            render.message(f"Run {shorten(run_id)} finished: {run.status}")
            if run.failure_analysis:
                render.message(f"Failure analysis: {run.failure_analysis}", err=True)
            return
        polls += 1
        if max_polls is not None and polls >= max_polls:
            return
        time.sleep(LOG_POLL_INTERVAL_S)


@train_group.command("logs")
@click.argument("run_id")
@click.option("--follow", "-f", is_flag=True)
@click.option("--component", default=None, help="trainer | inference | env")
@click.option("--worker", "worker_index", type=int, default=None)
@click.option("--env-name", default=None)
@output_options
def logs_cmd(
    render: Renderer,
    run_id: str,
    follow: bool,
    component: str | None,
    worker_index: int | None,
    env_name: str | None,
) -> None:
    client = _rl_client()
    full_id = _resolve_run(client, run_id)
    if follow:
        _stream_logs(render, full_id, component=component, worker_index=worker_index, env_name=env_name)
        return
    logs = client.get_logs(full_id, component=component, worker_index=worker_index, env_name=env_name)
    if render.is_json:
        render.json(logs)
    else:
        for row in logs:
            click.echo(f"{row.get('ts', '')} [{row.get('component', '?')}] {row.get('message', '')}")


@train_group.command("components")
@click.argument("run_id")
@output_options
def components_cmd(render: Renderer, run_id: str) -> None:
    client = _rl_client()
    rows = client.components(_resolve_run(client, run_id))
    render.table(["COMPONENT"], [[c] for c in rows], title="Components", json_rows=rows)


@train_group.command("metrics")
@click.argument("run_id")
@output_options
def metrics_cmd(render: Renderer, run_id: str) -> None:
    client = _rl_client()
    render.detail(client.metrics(_resolve_run(client, run_id)), title="Metrics")


@train_group.command("rollouts")
@click.argument("run_id")
@click.option("--limit", type=int, default=20)
@output_options
def rollouts_cmd(render: Renderer, run_id: str, limit: int) -> None:
    client = _rl_client()
    rows = client.rollouts(_resolve_run(client, run_id), limit=limit)
    render.table(
        ["STEP", "REWARD", "COMPLETION"],
        [[r.get("step"), r.get("reward"), str(r.get("completion", ""))[:60]] for r in rows],
        title="Rollouts",
        json_rows=rows,
    )


@train_group.command("progress")
@click.argument("run_id")
@output_options
def progress_cmd(render: Renderer, run_id: str) -> None:
    client = _rl_client()
    render.detail(client.progress(_resolve_run(client, run_id)), title="Progress")


@train_group.command("distributions")
@click.argument("run_id")
@output_options
def distributions_cmd(render: Renderer, run_id: str) -> None:
    client = _rl_client()
    render.detail(client.distributions(_resolve_run(client, run_id)), title="Distributions")


@train_group.command("checkpoints")
@click.argument("run_id")
@output_options
def checkpoints_cmd(render: Renderer, run_id: str) -> None:
    client = _rl_client()
    checkpoints = client.list_checkpoints(_resolve_run(client, run_id))
    render.table(
        ["ID", "STEP", "CREATED"],
        [[shorten(c.checkpoint_id), c.step, c.created_at or ""] for c in checkpoints],
        title="Checkpoints",
        json_rows=[c.model_dump(by_alias=True) for c in checkpoints],
    )
