"""`prime login` / `prime logout` — browser challenge auth.

Reference flow (prime_cli/commands/login.py:88-246): generate an ephemeral
RSA-2048 keypair → POST the public key to /auth_challenge/generate → user
approves in the browser → poll /auth_challenge/status until approved → the
response carries the API key OAEP-encrypted to our ephemeral key → decrypt,
save, whoami, optional team pick.
"""

from __future__ import annotations

import base64
import time
import webbrowser

import click

import prime_tpu.commands._deps as deps
from prime_tpu.utils.render import Renderer, output_options

POLL_INTERVAL_S = 2.0
POLL_ATTEMPTS = 150  # five minutes

# test injection point: replaces webbrowser.open
browser_open = webbrowser.open


@click.command("login")
@click.option("--no-browser", is_flag=True, help="Print the approval URL instead of opening it.")
@output_options
def login(render: Renderer, no_browser: bool) -> None:
    """Authenticate via the browser and store the API key."""
    # lazy: cryptography is only needed by the actual login handshake —
    # importing it at module scope broke `prime --help` (which loads every
    # command group) on containers without the wheel
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import padding, rsa

    config = deps.build_config()
    api = deps.build_client(config)

    private_key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    public_pem = private_key.public_key().public_bytes(
        encoding=serialization.Encoding.PEM,
        format=serialization.PublicFormat.SubjectPublicKeyInfo,
    ).decode()

    challenge = api.post("/auth_challenge/generate", json={"publicKey": public_pem})
    url = challenge["verificationUrl"]
    challenge_id = challenge["challengeId"]
    if no_browser:
        render.message(f"Open this URL to approve the login:\n  {url}")
    else:
        render.message(f"Opening {url} ...")
        browser_open(url)

    for _ in range(POLL_ATTEMPTS):
        status = api.get(f"/auth_challenge/status/{challenge_id}")
        if status.get("status") == "approved":
            encrypted = base64.b64decode(status["encryptedApiKey"])
            api_key = private_key.decrypt(
                encrypted,
                padding.OAEP(
                    mgf=padding.MGF1(algorithm=hashes.SHA256()),
                    algorithm=hashes.SHA256(),
                    label=None,
                ),
            ).decode()
            config.api_key = api_key
            config.save()
            whoami = deps.build_client(config).get("/user/whoami")
            config.user_id = whoami.get("userId", "")
            config.save()
            render.message(f"Logged in as {whoami.get('email', whoami.get('userId', '?'))}.")
            teams = deps.build_client(config).get("/teams")
            if teams and not config.team_id:
                render.message("Teams available — set one with: prime teams switch <team-id>")
            return
        if status.get("status") == "denied":
            raise click.ClickException("Login request was denied.")
        time.sleep(POLL_INTERVAL_S)
    raise click.ClickException("Login timed out waiting for browser approval.")


@click.command("logout")
def logout() -> None:
    """Clear the stored API key."""
    config = deps.build_config()
    config.api_key = ""
    config.save()
    click.echo("Logged out (API key cleared).")
