"""`prime serve` — OpenAI-compatible inference on the local TPU slice.

The TPU-native counterpart of the platform's hosted inference endpoint
(reference api/inference.py consumes api.pinference.ai): serve a model —
optionally pjit-sharded over the slice with --slice/--tp — and point any
OpenAI client (including this CLI's own `prime inference chat`, via
PRIME_INFERENCE_URL) at it.
"""

from __future__ import annotations

import click


@click.command(name="serve")
@click.option("--model", "-m", required=True, help="Model preset or local HF checkpoint dir.")
@click.option("--checkpoint", default=None, help="Local HF checkpoint dir for weights.")
@click.option("--tokenizer", default=None)
@click.option("--slice", "slice_name", default=None, help="Shard over this TPU slice's mesh.")
@click.option("--tp", "tensor_parallel", type=int, default=None)
@click.option("--sp", "sequence_parallel", type=click.IntRange(min=2), default=None,
              help="Sequence-parallel axis for --slice: shard the KV cache's "
                   "slot dimension across the slice (long-context serving).")
@click.option("--kv-quant", is_flag=True, help="int8 KV cache (halved decode HBM traffic).")
@click.option("--weight-quant", is_flag=True, help="Quantized weights (halved+ weight HBM traffic).")
@click.option(
    "--weight-bits", type=click.Choice(["8", "4"]), default="8",
    help="Weight quantization width for --weight-quant: 8 = W8A16 "
         "per-channel, 4 = W4A16 group-wise (another 2x fewer weight bytes).",
)
@click.option("--adapter", default=None, type=click.Path(exists=True),
              help="LoRA adapter dir (from train local --lora) to merge into the model.")
@click.option("--host", default="127.0.0.1")
@click.option("--port", type=int, default=8000)
@click.option(
    "--continuous", is_flag=True,
    help="Continuous batching: concurrent requests share the chip via KV-cache "
    "slots; streaming emits tokens as they decode.",
)
@click.option("--slots", type=int, default=8, help="Max concurrent requests (--continuous).")
@click.option(
    "--slot-capacity", type=int, default=2048,
    help="Per-request KV capacity in tokens (--continuous).",
)
@click.option(
    "--chunk", type=int, default=8,
    help="Decode steps per dispatch — lower admits new requests sooner (--continuous).",
)
@click.option(
    "--speculative", is_flag=True,
    help="Prompt-lookup speculative decoding (greedy: exact tokens; sampled: "
         "exact distribution). With --continuous, per-slot drafts ride one "
         "verify pass per tick.",
)
@click.option("--draft-len", type=click.IntRange(min=1), default=4,
              help="Speculative draft tokens per step.")
def serve_cmd(
    model: str,
    checkpoint: str | None,
    tokenizer: str | None,
    slice_name: str | None,
    tensor_parallel: int | None,
    sequence_parallel: int | None,
    kv_quant: bool,
    weight_quant: bool,
    weight_bits: str,
    adapter: str | None,
    host: str,
    port: int,
    continuous: bool,
    slots: int,
    slot_capacity: int,
    chunk: int,
    speculative: bool,
    draft_len: int,
) -> None:
    """Serve MODEL over an OpenAI-compatible HTTP API (blocks until Ctrl-C)."""
    from prime_tpu.serve import serve_model

    if weight_bits == "4" and not weight_quant:
        # silently serving bf16 at 4x the expected HBM footprint would be a
        # nasty surprise; make the dependency explicit
        raise click.UsageError("--weight-bits 4 requires --weight-quant")

    try:
        server = serve_model(
            model,
            checkpoint=checkpoint,
            tokenizer=tokenizer,
            slice_name=slice_name,
            tensor_parallel=tensor_parallel,
            sequence_parallel=sequence_parallel,
            kv_quant=kv_quant,
            weight_quant=("int4" if weight_bits == "4" else True) if weight_quant else False,
            adapter=adapter,
            host=host,
            port=port,
            continuous=continuous,
            max_slots=slots,
            slot_capacity=slot_capacity,
            chunk=chunk,
            speculative=speculative,
            draft_len=draft_len,
        )
    except (ValueError, OSError) as e:
        raise click.ClickException(str(e)) from None
    click.echo(f"Serving {model} at {server.url}/v1 (Ctrl-C to stop)")
    click.echo(
        f"  e.g. PRIME_INFERENCE_URL={server.url}/v1 prime inference chat {model} -m 'hi'"
    )
    click.echo(f"  metrics: {server.url}/metrics")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        click.echo("\nStopped.")
        server.stop()
