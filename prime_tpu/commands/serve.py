"""`prime serve` — OpenAI-compatible inference on the local TPU slice.

The TPU-native counterpart of the platform's hosted inference endpoint
(reference api/inference.py consumes api.pinference.ai): serve a model —
optionally pjit-sharded over the slice with --slice/--tp — and point any
OpenAI client (including this CLI's own `prime inference chat`, via
PRIME_INFERENCE_URL) at it.
"""

from __future__ import annotations

import click

from prime_tpu.utils.render import Renderer, output_options


@click.group(name="serve", invoke_without_command=True)
@click.option("--model", "-m", default=None, help="Model preset or local HF checkpoint dir.")
@click.option("--checkpoint", default=None, help="Local HF checkpoint dir for weights.")
@click.option("--tokenizer", default=None)
@click.option("--slice", "slice_name", default=None, help="Shard over this TPU slice's mesh.")
@click.option(
    "--mesh", "mesh_spec", default=None, metavar="SPEC",
    help="Sharded replica (--continuous): declarative serving-mesh axes, "
         "e.g. 'dp=1,fsdp=2,tp=2' or 'dp,fsdp,tp' (the last unsized axis "
         "absorbs remaining devices). One engine spans the whole mesh: "
         "params and paged KV shard onto it, decode runs the shard_mapped "
         "flash kernel when eligible. Default: unset (PRIME_SERVE_MESH). "
         "Mutually exclusive with --slice.",
)
@click.option("--tp", "tensor_parallel", type=int, default=None)
@click.option("--sp", "sequence_parallel", type=click.IntRange(min=2), default=None,
              help="Sequence-parallel axis for --slice: shard the KV cache's "
                   "slot dimension across the slice (long-context serving).")
@click.option("--kv-quant", is_flag=True, help="int8 KV cache (halved decode HBM traffic).")
@click.option("--weight-quant", is_flag=True, help="Quantized weights (halved+ weight HBM traffic).")
@click.option(
    "--weight-bits", type=click.Choice(["8", "4"]), default="8",
    help="Weight quantization width for --weight-quant: 8 = W8A16 "
         "per-channel, 4 = W4A16 group-wise (another 2x fewer weight bytes).",
)
@click.option("--adapter", default=None, type=click.Path(exists=True),
              help="LoRA adapter dir (from train local --lora) to merge into the model.")
@click.option(
    "--adapters", "adapters_spec", default=None, metavar="NAME=DIR,...",
    help="Batched multi-LoRA serving (--continuous): comma-separated "
         "name=artifact-dir entries loaded UNMERGED into a device-resident "
         "bank — the OpenAI `model` field selects the adapter per request "
         "and a mixed-adapter batch decodes as one program. "
         "Default: unset (PRIME_SERVE_ADAPTERS).",
)
@click.option("--host", default="127.0.0.1")
@click.option("--port", type=int, default=8000)
@click.option(
    "--continuous", is_flag=True,
    help="Continuous batching: concurrent requests share the chip via KV-cache "
    "slots; streaming emits tokens as they decode.",
)
@click.option("--slots", type=int, default=8, help="Max concurrent requests (--continuous).")
@click.option(
    "--slot-capacity", type=int, default=2048,
    help="Per-request KV capacity in tokens (--continuous).",
)
@click.option(
    "--chunk", type=int, default=8,
    help="Decode steps per dispatch — lower admits new requests sooner (--continuous).",
)
@click.option(
    "--speculative/--no-speculative", "speculative", default=None,
    help="Prompt-lookup speculative decoding (greedy: exact tokens; sampled: "
         "exact distribution). With --continuous, draft proposal + verify "
         "run device-resident and ride the overlap pipeline and the --mesh "
         "sharded replica. Default: off (PRIME_SERVE_SPEC).",
)
@click.option("--draft-len", type=click.IntRange(min=1), default=None,
              help="Speculative draft tokens per verify window. "
                   "Default: 4 (PRIME_SERVE_DRAFT_LEN).")
@click.option(
    "--overlap/--no-overlap", "overlap", default=None,
    help="Overlapped decode pipeline (--continuous): dispatch chunk N+1 "
         "before syncing chunk N so host bookkeeping hides inside device "
         "compute. Default: on (PRIME_SERVE_OVERLAP).",
)
@click.option(
    "--warmup/--no-warmup", "warmup", default=None,
    help="Compile the engine's full program set at startup so no cold XLA "
         "compile lands mid-request (--continuous). Default: off "
         "(PRIME_SERVE_WARMUP).",
)
@click.option(
    "--profile/--no-profile", "profile", default=None,
    help="Sampled device-time step clock (--continuous): fence 1-of-N "
         "dispatches per phase into serve_device_step_seconds{phase=...} "
         "plus XLA-compile, HBM, and cost-model MFU accounting; "
         "/admin/profile and `prime serve profile` capture a Perfetto "
         "trace window. Default: off (PRIME_SERVE_PROFILE).",
)
@click.option(
    "--prefix-cache-mb", type=float, default=None,
    help="Byte budget (MiB) of the radix prefix-KV cache: shared prompt "
         "blocks are cached once and reused across admissions; 0 disables "
         "(--continuous). Default: 256 (PRIME_SERVE_PREFIX_CACHE_MB).",
)
@click.option(
    "--prefix-cache-host-mb", type=float, default=None,
    help="Byte budget (MiB) of the prefix cache's host-RAM spill tier "
         "(--continuous): the device LRU demotes cold KV segments to pinned "
         "host buffers instead of freeing them, and a later hit re-uploads "
         "through the same one-dispatch assemble path; 0 disables. "
         "Default: 0 (PRIME_SERVE_PREFIX_CACHE_HOST_MB).",
)
@click.option(
    "--adapter-max-inflight", type=int, default=None,
    help="Per-tenant fair admission (--adapters): max admitted slots one "
         "adapter (base included) may hold; queued overflow waits in its "
         "own bucket while other tenants admit. 0 = uncapped. "
         "Default: 0 (PRIME_SERVE_ADAPTER_MAX_INFLIGHT).",
)
@click.option(
    "--adapter-weight", "adapter_weight_entries", multiple=True, metavar="NAME=K",
    help="Weighted admission shares (--adapters, repeatable): give tenant "
         "NAME K admission slots per fair-rotation instead of 1 ('base' is "
         "the base model's tenant). Unlisted tenants keep weight 1. "
         "Default: uniform (PRIME_SERVE_ADAPTER_WEIGHTS).",
)
@click.option(
    "--max-queue", type=int, default=None,
    help="Bound the engine's pending queue (--continuous): submissions past "
         "it get 429 + Retry-After instead of queueing unboundedly. "
         "0 = unbounded. Default: 0 (PRIME_SERVE_MAX_QUEUE).",
)
@click.option(
    "--role", type=click.Choice(["prefill", "decode", "any"]), default=None,
    help="Phase role in a disaggregated fleet, advertised in /healthz: a "
         "`prime serve fleet` router with both explicit roles present "
         "prefills on a prefill replica and migrates the KV to a decode "
         "replica over /admin/kv. Pair with --mesh role:prefill / "
         "role:decode for the role-preset mesh layout. Default: any "
         "(PRIME_SERVE_ROLE).",
)
@click.option(
    "--replica-of", default=None, metavar="ROUTER_URL",
    help="Register this server with a running `prime serve fleet` router "
         "(POST ROUTER_URL/admin/join) once the model is loaded.",
)
@click.option(
    "--advertise-url", default=None, metavar="URL",
    help="URL the fleet router should reach this replica at (--replica-of). "
         "Required when binding 0.0.0.0: the bind address is not reachable "
         "from another host, so it cannot be advertised.",
)
@click.option(
    "--fleet-token", default=None, envvar="PRIME_FLEET_ADMIN_TOKEN",
    help="Bearer token for the router's admin surface (--replica-of against "
         "a router started with --admin-token).",
)
@click.pass_context
def serve_cmd(
    ctx: click.Context,
    model: str | None,
    checkpoint: str | None,
    tokenizer: str | None,
    slice_name: str | None,
    mesh_spec: str | None,
    tensor_parallel: int | None,
    sequence_parallel: int | None,
    kv_quant: bool,
    weight_quant: bool,
    weight_bits: str,
    adapter: str | None,
    adapters_spec: str | None,
    host: str,
    port: int,
    continuous: bool,
    slots: int,
    slot_capacity: int,
    chunk: int,
    speculative: bool | None,
    draft_len: int | None,
    overlap: bool | None,
    warmup: bool | None,
    profile: bool | None,
    prefix_cache_mb: float | None,
    prefix_cache_host_mb: float | None,
    adapter_max_inflight: int | None,
    adapter_weight_entries: tuple[str, ...],
    max_queue: int | None,
    role: str | None,
    replica_of: str | None,
    advertise_url: str | None,
    fleet_token: str | None,
) -> None:
    """Serve MODEL over an OpenAI-compatible HTTP API (blocks until Ctrl-C)."""
    if ctx.invoked_subcommand is not None:
        return  # `prime serve metrics` — the subcommand runs instead
    if model is None:
        raise click.UsageError("Missing option '--model' / '-m'.")
    from prime_tpu.serve import serve_model

    if mesh_spec and slice_name:
        raise click.UsageError(
            "--mesh and --slice both describe the serving mesh; pass one"
        )
    if mesh_spec and not continuous:
        raise click.UsageError("--mesh requires --continuous (the sharded replica is engine-only)")
    if adapters_spec and not continuous:
        raise click.UsageError(
            "--adapters requires --continuous (batched multi-LoRA serving "
            "is engine-only; --adapter merges one adapter for the one-shot path)"
        )
    if adapters_spec and adapter:
        raise click.UsageError(
            "--adapter and --adapters are mutually exclusive (merged base "
            "weights would fail the bank's base-fingerprint check)"
        )
    if weight_bits == "4" and not weight_quant:
        # silently serving bf16 at 4x the expected HBM footprint would be a
        # nasty surprise; make the dependency explicit
        raise click.UsageError("--weight-bits 4 requires --weight-quant")
    if replica_of and advertise_url is None and host in ("0.0.0.0", "::"):
        # pure CLI-argument error: fail BEFORE minutes of checkpoint loading.
        # The bind-any address is meaningless to a remote router — it would
        # route traffic to itself (or nowhere).
        raise click.UsageError(
            "--replica-of with --host 0.0.0.0 requires --advertise-url "
            "(the URL the router can reach this replica at)"
        )

    try:
        server = serve_model(
            model,
            checkpoint=checkpoint,
            tokenizer=tokenizer,
            slice_name=slice_name,
            tensor_parallel=tensor_parallel,
            sequence_parallel=sequence_parallel,
            kv_quant=kv_quant,
            weight_quant=("int4" if weight_bits == "4" else True) if weight_quant else False,
            adapter=adapter,
            adapters=adapters_spec,
            host=host,
            port=port,
            continuous=continuous,
            mesh=mesh_spec,
            max_slots=slots,
            slot_capacity=slot_capacity,
            chunk=chunk,
            speculative=speculative,
            draft_len=draft_len,
            overlap=overlap,
            warmup=warmup,
            profile=profile,
            prefix_cache_mb=prefix_cache_mb,
            prefix_cache_host_mb=prefix_cache_host_mb,
            adapter_max_inflight=adapter_max_inflight,
            # joined back to the "name=K,..." env-spec shape; None defers
            # to PRIME_SERVE_ADAPTER_WEIGHTS inside the engine
            adapter_weights=",".join(adapter_weight_entries) or None,
            max_queue=max_queue,
            role=role,
        )
    except (ValueError, OSError) as e:
        raise click.ClickException(str(e)) from None
    if replica_of:
        import httpx

        try:
            response = httpx.post(
                f"{replica_of.rstrip('/')}/admin/join",
                json={"url": advertise_url or server.url},
                headers=(
                    {"Authorization": f"Bearer {fleet_token}"} if fleet_token else None
                ),
                timeout=5,
            )
            response.raise_for_status()
            click.echo(f"Joined fleet at {replica_of} as {response.json().get('joined')}")
        except (httpx.HTTPError, ValueError) as e:
            # serve anyway: the operator can join manually once the router
            # is up (POST /admin/join {"url": ...})
            click.echo(f"warning: could not join fleet at {replica_of}: {e}", err=True)
    click.echo(f"Serving {model} at {server.url}/v1 (Ctrl-C to stop)")
    click.echo(
        f"  e.g. PRIME_INFERENCE_URL={server.url}/v1 prime inference chat {model} -m 'hi'"
    )
    click.echo(f"  metrics: {server.url}/metrics  (prometheus: {server.url}/metrics?format=prometheus)")
    click.echo(f"  health:  {server.url}/healthz")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        click.echo("\nStopped.")
        server.stop()


@serve_cmd.command(name="fleet")
@click.option(
    "--replica", "replicas", multiple=True, metavar="URL",
    help="Upstream replica base URL (repeatable). Replicas can also join "
         "later via `prime serve --replica-of` or POST /admin/join.",
)
@click.option("--host", default="127.0.0.1", show_default=True)
@click.option("--port", type=int, default=8080, show_default=True)
@click.option("--model", "model_id", default=None,
              help="Model id for /v1/models when no replica is reachable.")
@click.option(
    "--max-inflight", type=click.IntRange(min=1), default=64, show_default=True,
    help="Admission control: chat requests proxied concurrently before the "
         "router answers 429 + Retry-After.",
)
@click.option(
    "--queue-wait", "queue_wait_s", type=float, default=0.25, show_default=True,
    help="Seconds a request may wait for an in-flight permit before 429.",
)
@click.option(
    "--affinity-blocks", type=click.IntRange(min=1), default=2, show_default=True,
    help="Leading MIN_BUCKET-aligned prompt blocks hashed for prefix "
         "affinity (same block size as the engines' prefix-KV cache).",
)
@click.option(
    "--health-interval", "poll_interval", type=float, default=1.0, show_default=True,
    help="Seconds between /healthz polls of each replica.",
)
@click.option(
    "--fail-threshold", type=click.IntRange(min=1), default=3, show_default=True,
    help="Consecutive connect failures before a replica's breaker opens.",
)
@click.option(
    "--cooldown", type=float, default=5.0, show_default=True,
    help="Seconds an open breaker waits before a half-open probe.",
)
@click.option(
    "--admin-token", default=None, envvar="PRIME_FLEET_ADMIN_TOKEN",
    help="Require `Authorization: Bearer <token>` on the mutating admin "
         "surface (/admin/join, /admin/drain). Unset = open (loopback only!).",
)
@click.option(
    "--model-alias", "model_aliases", multiple=True, metavar="MODEL=ADAPTER",
    help="Router model registry (repeatable): map an OpenAI `model` name to "
         "an adapter id for multi-LoRA placement ('base' pins it to base "
         "routing). Names not aliased resolve against what replicas "
         "advertise in /healthz.",
)
@click.option(
    "--autoscale/--no-autoscale", "autoscale", default=None,
    help="Elastic fleet actuator (docs/architecture.md \"Elastic fleet\"): "
         "consume the observatory's scale signals each poll cycle and "
         "spawn/retire replicas via --launch, under the min/max bounds, "
         "per-direction cooldowns, and safety interlocks (drain-before-"
         "kill, inflight guard, breaker-storm pause). "
         "Default: off (PRIME_FLEET_AUTOSCALE).",
)
@click.option(
    "--min-replicas", type=click.IntRange(min=0), default=None,
    help="Autoscale floor: never retire below this many replicas. "
         "Default: 1 (PRIME_FLEET_AUTOSCALE_MIN).",
)
@click.option(
    "--max-replicas", type=click.IntRange(min=1), default=None,
    help="Autoscale ceiling: never spawn past this many replicas. "
         "Default: 4 (PRIME_FLEET_AUTOSCALE_MAX).",
)
@click.option(
    "--scale-cooldown", "scale_cooldown", type=click.FloatRange(min=0), default=None,
    help="Seconds between scale-UP actions (scale-downs wait "
         "PRIME_FLEET_AUTOSCALE_DOWN_COOLDOWN_S, 3x longer by default). "
         "Default: 10 (PRIME_FLEET_AUTOSCALE_COOLDOWN_S).",
)
@click.option(
    "--launch", "launch_cmd", default=None, metavar="CMD",
    help="Replica launch command template for --autoscale, with {host} "
         "{port} {router} placeholders — e.g. \"prime serve -m MODEL "
         "--continuous --port {port} --replica-of {router}\". The spawned "
         "process must answer /healthz on {host}:{port}.",
)
def serve_fleet_cmd(
    replicas: tuple[str, ...],
    host: str,
    port: int,
    model_id: str | None,
    max_inflight: int,
    queue_wait_s: float,
    affinity_blocks: int,
    poll_interval: float,
    fail_threshold: int,
    cooldown: float,
    admin_token: str | None,
    model_aliases: tuple[str, ...],
    autoscale: bool | None,
    min_replicas: int | None,
    max_replicas: int | None,
    scale_cooldown: float | None,
    launch_cmd: str | None,
) -> None:
    """Route an OpenAI-compatible endpoint across N engine replicas:
    prefix-affinity scheduling (shared-prefix traffic lands on the replica
    whose KV cache is warm), health-gated failover with circuit breaking,
    and fleet-level admission control. See docs/architecture.md
    "Serve fleet"."""
    from prime_tpu.core.config import env_flag
    from prime_tpu.serve.fleet import FleetRouter

    registry: dict[str, str | None] = {}
    for entry in model_aliases:
        name, eq, target = entry.partition("=")
        if not eq or not name or not target:
            raise click.UsageError(f"--model-alias {entry!r} must be MODEL=ADAPTER")
        registry[name] = None if target == "base" else target
    if autoscale is None:
        autoscale = env_flag("PRIME_FLEET_AUTOSCALE", False)
    if autoscale and not launch_cmd:
        # pure CLI-argument error: an actuator with no way to create
        # capacity can only ever refuse its own decisions
        raise click.UsageError(
            "--autoscale needs --launch (the replica launch command "
            "template the supervisor spawns scale-ups with)"
        )
    try:
        router = FleetRouter(
            replicas,
            host=host,
            port=port,
            model_id=model_id,
            max_inflight=max_inflight,
            queue_wait_s=queue_wait_s,
            affinity_blocks=affinity_blocks,
            poll_interval=poll_interval,
            fail_threshold=fail_threshold,
            cooldown=cooldown,
            admin_token=admin_token,
            model_registry=registry or None,
        )
    except OSError as e:
        raise click.ClickException(str(e)) from None
    if autoscale:
        from prime_tpu.serve.fleet import (
            AutoscalerConfig,
            FleetAutoscaler,
            LocalProcessLauncher,
            ReplicaSupervisor,
        )

        try:
            config = AutoscalerConfig.from_env(
                min_replicas=min_replicas,
                max_replicas=max_replicas,
                up_cooldown_s=scale_cooldown,
            )
        except ValueError as e:
            raise click.UsageError(str(e)) from None
        # replicas spawn on loopback: the launcher runs them on THIS host,
        # and a 0.0.0.0 router bind is not a reachable replica address
        launcher = LocalProcessLauncher(launch_cmd, router_url=router.url)
        router.attach_autoscaler(
            FleetAutoscaler(ReplicaSupervisor(launcher, membership=router.membership), config)
        )
    click.echo(f"Fleet router at {router.url}/v1 over {len(replicas)} replica(s)")
    if autoscale:
        click.echo(
            f"  autoscale: {router.autoscaler.config.min_replicas}"
            f"..{router.autoscaler.config.max_replicas} replicas "
            f"(status: GET {router.url}/admin/autoscaler, pause/resume: POST)"
        )
    click.echo(f"  join:    POST {router.url}/admin/join  {{\"url\": ...}}")
    click.echo(f"  drain:   POST {router.url}/admin/drain?replica=<id>")
    click.echo(f"  fleet:   {router.url}/admin/fleet")
    click.echo(f"  metrics: {router.url}/metrics  (prometheus: {router.url}/metrics?format=prometheus)")
    # SIGTERM (systemd/k8s stop) takes the same clean path as Ctrl-C: with
    # an autoscaler attached, router.stop() must run so the supervisor
    # reaps the replica subprocesses it launched — a bare SIGTERM death
    # would orphan them
    import signal

    def _sigterm(_signo, _frame):
        raise KeyboardInterrupt

    signal.signal(signal.SIGTERM, _sigterm)
    try:
        router.serve_forever()
    except KeyboardInterrupt:
        click.echo("\nStopped.")
        router.stop()


@serve_cmd.command(name="top")
@click.option(
    "--url", default="http://127.0.0.1:8080", show_default=True,
    help="Base URL of a `prime serve fleet` router (or a single `prime "
         "serve` instance — the single-replica view renders too).",
)
@click.option(
    "--interval", type=click.FloatRange(min=0.1), default=2.0, show_default=True,
    help="Seconds between refreshes (ignored with --once).",
)
@click.option(
    "--once", is_flag=True,
    help="Render one view and exit (with --output json: the raw view JSON, "
         "for scripts).",
)
@click.option(
    "--admin-token", default=None, envvar="PRIME_FLEET_ADMIN_TOKEN",
    help="Bearer token when the target gates /admin/observatory.",
)
@output_options
def serve_top_cmd(
    render: "Renderer",
    url: str,
    interval: float,
    once: bool,
    admin_token: str | None,
) -> None:
    """Live fleet SLO view: GET /admin/observatory rendered as a plain-text
    table — windowed rates/percentiles, burn alerts, the current scale
    signal, and the per-replica split — refreshed every --interval seconds.
    See docs/observability.md "Observatory"."""
    import time as _time

    import httpx

    base = url.rstrip("/")
    headers = {"Authorization": f"Bearer {admin_token}"} if admin_token else None
    first = True
    while True:
        try:
            response = httpx.get(
                f"{base}/admin/observatory", headers=headers, timeout=10
            )
            if response.status_code == 403:
                raise click.ClickException(
                    f"{base}/admin/observatory requires an admin token "
                    "(--admin-token / PRIME_FLEET_ADMIN_TOKEN)"
                )
            response.raise_for_status()
            view = response.json()
        except (httpx.HTTPError, ValueError) as e:
            if once or first:
                raise click.ClickException(
                    f"could not read {base}/admin/observatory: {e}"
                ) from None
            # a live dashboard survives a router restart or one slow
            # scrape: show the miss and retry at the next tick
            click.echo(f"(scrape failed: {e}; retrying in {interval}s)", err=True)
            _time.sleep(interval)
            continue
        if render.is_json:
            render.json(view)
            return  # one machine-readable view; scripts loop themselves
        if not once and not first:
            click.clear()
        first = False
        _render_observatory_view(render, view)
        if once:
            return
        _time.sleep(interval)


def _fmt(value, digits: int = 3) -> str:
    if value is None:
        return "-"
    if isinstance(value, float):
        return f"{value:.{digits}f}".rstrip("0").rstrip(".") or "0"
    return str(value)


def _render_observatory_view(render: "Renderer", view: dict) -> None:
    """Plain-text tables for one /admin/observatory payload — fleet-router
    shape (``replicas``/``fleet``) and single-replica shape
    (``replica``/``serving``) both render."""
    signal = view.get("signal") or {}
    click.echo(
        f"signal: {signal.get('direction', '?')} — {signal.get('reason', '')}"
    )
    autoscaler = view.get("autoscaler") or {}
    if autoscaler.get("enabled"):
        last = autoscaler.get("last_action") or {}
        last_desc = (
            f"{last.get('direction')}/{last.get('outcome')}"
            + (f" x{last.get('count')}" if last.get("count") else "")
            if last
            else "none yet"
        )
        config = autoscaler.get("config") or {}
        click.echo(
            f"autoscaler: {autoscaler.get('state', '?')} "
            f"[{config.get('min_replicas', '?')}..{config.get('max_replicas', '?')}] "
            f"— last action: {last_desc}"
        )
    breached = [
        v for v in view.get("slo", []) if isinstance(v, dict) and v.get("breached")
    ]
    for verdict in breached:
        fast, slow = verdict.get("fast", {}), verdict.get("slow", {})
        click.echo(
            f"  BURN {verdict.get('policy')}: "
            f"{_fmt(fast.get('burn'), 2)}x fast / {_fmt(slow.get('burn'), 2)}x slow "
            f"(objective {_fmt(verdict.get('objective'))})"
        )
    incidents = view.get("incidents") or {}
    if incidents.get("total"):
        click.echo(f"incidents: {incidents.get('total')} recorded")
        for row in (incidents.get("recent") or [])[:3]:
            click.echo(
                f"  INCIDENT {row.get('id')} {row.get('rule')} "
                f"[{row.get('severity')}] scope={row.get('scope')} "
                f"value={_fmt(row.get('value'))} "
                f"baseline={_fmt(row.get('baseline'))}"
            )
    windows = view.get("fleet") or view.get("serving") or {}
    window_rows = [
        [
            f"{label} {int(entry.get('window_s', 0))}s",
            _fmt(entry.get("span_s"), 1),
            _fmt(entry.get("tok_s")),
            _fmt(entry.get("admitted_per_s")),
            _fmt(entry.get("ttft_p95_s")),
            _fmt(entry.get("queue_wait_p95_s")),
            _fmt(entry.get("reject_rate"), 4),
        ]
        for label, entry in windows.items()
        if isinstance(entry, dict)
    ]
    render.table(
        ["window", "span_s", "tok/s", "adm/s", "ttft p95", "queue p95", "429 rate"],
        window_rows,
        title="Fleet windows" if "fleet" in view else "Serving windows",
    )
    replicas = view.get("replicas")
    if replicas is None and isinstance(view.get("replica"), dict):
        replicas = [view["replica"]]
    rows = [
        [
            r.get("id") or r.get("model", "?"),
            r.get("role", "-"),
            r.get("state", "?"),
            # autoscaler lifecycle for supervisor-managed replicas;
            # operator-joined rows show "-" (the actuator never touches them)
            r.get("managed") or "-",
            r.get("breaker", "-"),
            r.get("queue_depth", 0),
            f"{r.get('active_slots', 0)}/{r.get('max_slots', 0)}",
            _fmt(r.get("tok_s")),
            r.get("samples", 0),
            r.get("resets", 0),
        ]
        for r in replicas or []
    ]
    render.table(
        ["replica", "role", "state", "managed", "breaker", "queue", "slots",
         "tok/s", "samples", "resets"],
        rows,
        title="Replicas",
    )


@serve_cmd.command(name="profile")
@click.option(
    "--url", default="http://127.0.0.1:8000", show_default=True,
    help="Base URL of a running `prime serve --continuous` instance OR a "
         "`prime serve fleet` router (the capture fans out to every "
         "routable replica).",
)
@click.option(
    "--seconds", type=click.FloatRange(min=0.1), default=2.0, show_default=True,
    help="Capture window length: every dispatch in the window is fenced "
         "and lands in the trace (sampling is bypassed while capturing).",
)
@click.option(
    "--trace-out", default="trace.json", show_default=True,
    type=click.Path(dir_okay=False, writable=True),
    help="Where to write the merged Chrome-trace timeline (host spans + "
         "device step samples + XLA compiles). Load it in Perfetto or "
         "chrome://tracing. Router captures write one file per replica "
         "(trace-<replica>.json).",
)
@click.option(
    "--admin-token", default=None, envvar="PRIME_FLEET_ADMIN_TOKEN",
    help="Bearer token when the target gates /admin/profile.",
)
@output_options
def serve_profile_cmd(
    render: "Renderer",
    url: str,
    seconds: float,
    trace_out: str,
    admin_token: str | None,
) -> None:
    """Capture a device-time window from a live server: POST /admin/profile
    start, wait --seconds while traffic flows, stop, then render the
    per-phase breakdown (step seconds, compiles, cost-model MFU) and write
    the Perfetto-loadable trace.json. See docs/observability.md
    "Device time"."""
    import json as _json
    import os as _os
    import time as _time

    import httpx

    base = url.rstrip("/")
    headers = {"Authorization": f"Bearer {admin_token}"} if admin_token else None

    def _post(action: str) -> dict:
        try:
            response = httpx.post(
                f"{base}/admin/profile",
                json={"action": action},
                headers=headers,
                timeout=30,
            )
        except httpx.HTTPError as e:
            raise click.ClickException(
                f"could not reach {base}/admin/profile: {e}"
            ) from None
        if response.status_code == 403:
            raise click.ClickException(
                f"{base}/admin/profile requires an admin token "
                "(--admin-token / PRIME_FLEET_ADMIN_TOKEN)"
            )
        if response.status_code == 404:
            raise click.ClickException(
                f"{base} has no device profiler (serve with --continuous)"
            )
        if response.status_code >= 400:
            try:
                message = response.json().get("error", {}).get("message", "")
            except ValueError:
                message = ""
            raise click.ClickException(
                f"{base}/admin/profile: {message or f'status {response.status_code}'}"
            )
        try:
            return response.json()
        except ValueError as e:
            raise click.ClickException(
                f"{base}/admin/profile returned non-JSON: {e}"
            ) from None

    _post("start")
    click.echo(f"capturing {_fmt(seconds, 2)}s from {base} ...", err=True)
    _time.sleep(seconds)
    result = _post("stop")
    if render.is_json:
        render.json(result)
    # single-replica stop returns the capture itself; the router returns
    # {"replicas": {id: capture}} — normalize to one iterable shape
    replicas = result.get("replicas")
    captures = (
        replicas.items() if isinstance(replicas, dict) else [("", result)]
    )
    stem, ext = _os.path.splitext(trace_out)
    wrote_any = False
    for rid, capture in captures:
        if not isinstance(capture, dict) or "summary" not in capture:
            message = "no capture"
            if isinstance(capture, dict):
                message = (capture.get("error") or {}).get("message", message)
            click.echo(f"warning: {rid or base}: {message}", err=True)
            continue
        summary = capture.get("summary") or {}
        if not render.is_json:
            _render_profile_summary(render, capture, summary, rid or base)
        trace = capture.get("trace")
        if trace is not None:
            path = f"{stem}-{rid}{ext or '.json'}" if rid else trace_out
            with open(path, "w", encoding="utf-8") as f:
                _json.dump(trace, f)
            wrote_any = True
            if not render.is_json:
                click.echo(
                    f"  trace: {path} (load in Perfetto / chrome://tracing)"
                )
    if not wrote_any and not render.is_json:
        raise click.ClickException(
            "no replica returned a capture (was any traffic flowing, and "
            "was a capture already stopped?)"
        )


@serve_cmd.command(name="incidents")
@click.option(
    "--url", default="http://127.0.0.1:8080", show_default=True,
    help="Base URL of a `prime serve fleet` router (merged fleet view) or "
         "a single `prime serve` replica.",
)
@click.option(
    "--id", "incident_id", default=None,
    help="Fetch one incident bundle (full forensics: flight timelines, "
         "registry deltas, journal tail) instead of the summary list.",
)
@click.option(
    "--admin-token", default=None, envvar="PRIME_FLEET_ADMIN_TOKEN",
    help="Bearer token when the target gates /admin/incidents.",
)
@output_options
def serve_incidents_cmd(
    render: "Renderer",
    url: str,
    incident_id: str | None,
    admin_token: str | None,
) -> None:
    """Sentinel incidents: GET /admin/incidents[/{id}] rendered as a table
    (or the full bundle JSON with --id / --output json). See
    docs/observability.md "Sentinel & incidents"."""
    import httpx

    base = url.rstrip("/")
    headers = {"Authorization": f"Bearer {admin_token}"} if admin_token else None
    path = f"/admin/incidents/{incident_id}" if incident_id else "/admin/incidents"
    try:
        response = httpx.get(f"{base}{path}", headers=headers, timeout=10)
    except httpx.HTTPError as e:
        raise click.ClickException(f"could not reach {base}{path}: {e}") from None
    if response.status_code == 403:
        raise click.ClickException(
            f"{base}{path} requires an admin token "
            "(--admin-token / PRIME_FLEET_ADMIN_TOKEN)"
        )
    if response.status_code == 404:
        raise click.ClickException(f"no incident {incident_id!r} at {base}")
    response.raise_for_status()
    try:
        payload = response.json()
    except ValueError as e:
        raise click.ClickException(f"{base}{path} returned non-JSON: {e}") from None
    if render.is_json or incident_id:
        render.json(payload)
        return
    # fleet shape ({"router": [...], "replicas": {id: {...}}}) and
    # single-replica shape ({"incidents": [...]}) both flatten to one table
    rows = []
    for scope, summaries in [("router", payload.get("router"))] + [
        (rid, (entry or {}).get("incidents"))
        for rid, entry in (payload.get("replicas") or {}).items()
    ] + [("", payload.get("incidents"))]:
        for row in summaries or []:
            rows.append(
                [
                    row.get("id", "?"),
                    scope or row.get("scope", "?"),
                    row.get("rule", "?"),
                    row.get("severity", "?"),
                    _fmt(row.get("value")),
                    _fmt(row.get("baseline")),
                    _fmt(row.get("ratio"), 2),
                    row.get("flights", 0),
                ]
            )
    render.table(
        ["id", "scope", "rule", "severity", "value", "baseline", "ratio",
         "flights"],
        rows,
        title="Incidents",
    )
    if not rows:
        click.echo("no incidents recorded")


def _render_profile_summary(
    render: "Renderer", capture: dict, summary: dict, target: str
) -> None:
    """The per-phase breakdown table for one /admin/profile stop payload."""
    rows = [
        [
            phase,
            entry.get("samples", 0),
            _fmt(
                entry.get("mean_s") * 1e3
                if entry.get("mean_s") is not None
                else None,
            ),
            _fmt(entry.get("total_s"), 4),
            _fmt(entry.get("achieved_tflops"), 2),
            _fmt(entry.get("mfu"), 4),
            _fmt(entry.get("achieved_gbps"), 2),
        ]
        for phase, entry in sorted((summary.get("phases") or {}).items())
    ]
    render.table(
        ["phase", "samples", "mean_ms", "total_s", "TFLOP/s", "MFU", "GB/s"],
        rows,
        title=f"Device time @ {target}",
    )
    compiles = summary.get("compiles") or {}
    peak = summary.get("peak_tflops")
    roofline = (
        f"peak {_fmt(peak, 1)} bf16 TFLOP/s"
        if peak is not None
        # MFU needs a peak-FLOPs roofline; the table only knows TPU
        # generations (docs/observability.md "Device time")
        else "no roofline for this backend (MFU/TFLOP columns empty)"
    )
    click.echo(
        f"  window {_fmt(capture.get('duration_s'), 2)}s: "
        f"{capture.get('samples', 0)} device samples, "
        f"{capture.get('host_spans', 0)} host spans, "
        f"{compiles.get('total', 0)} compiles "
        f"({_fmt(compiles.get('seconds'), 3)}s) — {roofline}"
    )


@serve_cmd.command(name="metrics")
@click.option(
    "--url", default="http://127.0.0.1:8000", show_default=True,
    help="Base URL of a running `prime serve` instance OR a "
         "`prime serve fleet` router (router-specific series render too).",
)
@click.option(
    "--prometheus", is_flag=True,
    help="Dump the raw Prometheus text exposition instead of a table.",
)
@click.option(
    "--debug-url", default=None, metavar="URL",
    help="Print the flight-recorder view (GET /debug/requests) of a server "
         "or router instead of scraping metrics. See docs/observability.md.",
)
@click.option(
    "--request", "request_id", default=None, metavar="ID",
    help="With --debug-url: print one request's full timeline "
         "(engine request id or W3C trace id).",
)
@click.option(
    "--admin-token", default=None, envvar="PRIME_FLEET_ADMIN_TOKEN",
    help="Bearer token for /debug/requests when the target gates it.",
)
@click.option(
    "--watch", "watch_s", type=click.FloatRange(min=0.01), default=None,
    metavar="SECONDS",
    help="Repeat the scrape every SECONDS, adding a windowed per-second "
         "rate column for every counter (computed through the observatory "
         "timeseries ring, not ad-hoc subtraction — "
         "docs/observability.md \"Observatory\").",
)
@click.option(
    "--count", type=click.IntRange(min=0), default=0,
    help="With --watch: refreshes before exiting (0 = until Ctrl-C).",
)
@output_options
def serve_metrics_cmd(
    render: "Renderer",
    url: str,
    prometheus: bool,
    debug_url: str | None,
    request_id: str | None,
    admin_token: str | None,
    watch_s: float | None,
    count: int,
) -> None:
    """Scrape a running server's metrics registry: counters, gauges, and
    latency histograms (TTFT, queue wait, prefill/decode) with estimated
    p50/p95 — or, with --debug-url, the flight-recorder request timelines.
    See docs/architecture.md "Observability" and docs/observability.md."""
    import time as _time

    import httpx

    if prometheus and render.is_json:
        # the exposition IS a text format; silently emitting it where a
        # script asked for JSON would break a downstream `| jq`
        raise click.UsageError(
            "--prometheus emits text exposition format; drop it or use "
            "--output json without it for the registry JSON"
        )
    if request_id and not debug_url:
        raise click.UsageError("--request requires --debug-url")
    if watch_s is not None and (prometheus or debug_url or render.is_json):
        raise click.UsageError(
            "--watch renders live tables; it does not compose with "
            "--prometheus, --debug-url, or --output json (scripts should "
            "poll /metrics?format=registry, or `prime serve top --once`)"
        )
    if debug_url:
        _render_flight_view(render, debug_url, request_id, admin_token)
        return
    base = url.rstrip("/")
    if prometheus:
        try:
            response = httpx.get(
                f"{base}/metrics", params={"format": "prometheus"}, timeout=10
            )
            response.raise_for_status()
        except httpx.HTTPError as e:
            raise click.ClickException(f"could not scrape {base}/metrics: {e}") from None
        click.echo(response.text, nl=False)
        return
    if watch_s is not None:
        from prime_tpu.obs.timeseries import SnapshotRing

        # one client-side ring per scraped section: each refresh appends the
        # scrape and reads the windowed rate back out — the SAME query the
        # observatory serves, so the delta column can never drift from it
        rings: dict[str, SnapshotRing] = {}
        iteration = 0
        while True:
            payload = _scrape_registry(base)
            for section, snapshot in payload.items():
                rings.setdefault(section, SnapshotRing()).append(snapshot)
            if iteration:
                click.clear()
            _render_registry_tables(
                render, payload, rings=rings, rate_window_s=watch_s * 3
            )
            iteration += 1
            if count and iteration >= count:
                return
            _time.sleep(watch_s)
    payload = _scrape_registry(base)
    if render.is_json:
        render.json(payload)
        return
    _render_registry_tables(render, payload)


def _scrape_registry(base: str) -> dict:
    """GET ``/metrics?format=registry`` and validate the snapshot shape."""
    import httpx

    try:
        response = httpx.get(
            f"{base}/metrics", params={"format": "registry"}, timeout=10
        )
        response.raise_for_status()
        payload = response.json()
    except (httpx.HTTPError, ValueError) as e:
        raise click.ClickException(f"could not scrape {base}/metrics: {e}") from None
    if not isinstance(payload, dict) or not all(
        isinstance(registry, dict)
        and all(isinstance(family, dict) and "series" in family for family in registry.values())
        for registry in payload.values()
    ):
        # e.g. a pre-telemetry server that answered the bare /metrics JSON
        raise click.ClickException(
            f"{base}/metrics?format=registry did not return registry snapshots "
            "(is the server running this repo's serve build?)"
        )
    return payload


def _render_registry_tables(
    render: "Renderer",
    payload: dict,
    rings=None,
    rate_window_s: float | None = None,
) -> None:
    """The registry scrape rendered as tables. With ``rings`` (watch mode),
    counters gain a windowed per-second rate column read from the
    per-section timeseries ring."""
    from prime_tpu.obs.metrics import quantile_from_snapshot

    value_rows: list[list] = []
    hist_rows: list[list] = []
    for section, registry in payload.items():
        ring = rings.get(section) if rings else None
        for name, family in registry.items():
            for series in family["series"]:
                labels = ",".join(f"{k}={v}" for k, v in series["labels"].items())
                if family["type"] == "histogram":
                    count = series["count"]
                    mean = series["sum"] / count if count else 0.0
                    p50 = quantile_from_snapshot(series["buckets"], series["counts"], 0.5)
                    p95 = quantile_from_snapshot(series["buckets"], series["counts"], 0.95)
                    hist_rows.append(
                        [section, name, labels, count,
                         round(mean, 6), round(p50, 6), round(p95, 6)]
                    )
                else:
                    row = [section, name, labels, family["type"], series["value"]]
                    if rings is not None:
                        rate = None
                        if family["type"] == "counter" and ring is not None:
                            rate = ring.rate(
                                name, rate_window_s or 1.0, series["labels"]
                            )
                        row.append(round(rate, 3) if rate is not None else "-")
                    value_rows.append(row)
    headers = ["section", "metric", "labels", "type", "value"]
    if rings is not None:
        headers.append("per_s")
    render.table(headers, value_rows, title="Counters & gauges")
    render.table(
        ["section", "metric", "labels", "count", "mean", "p50", "p95"], hist_rows,
        title="Histograms (seconds unless named otherwise)",
    )
    if "router" in payload:
        # fleet-router scrape: condense the router-specific families
        # (fleet_requests_total by replica/outcome, breaker-state gauges,
        # the affinity ratio) into one per-replica table — the series render
        # in the generic tables above too, but the routing question is
        # always "who got the traffic and who is broken"
        router = payload["router"]

        def series_of(name: str) -> list[dict]:
            return router.get(name, {}).get("series", [])

        per_replica: dict[str, dict[str, int]] = {}
        for series in series_of("fleet_requests_total"):
            labels = series["labels"]
            per_replica.setdefault(labels.get("replica", "?"), {})[
                labels.get("outcome", "?")
            ] = int(series["value"])
        breakers = {
            series["labels"].get("replica", "?"): {0: "closed", 1: "half-open", 2: "open"}.get(
                int(series["value"]), str(series["value"])
            )
            for series in series_of("fleet_breaker_state")
        }
        fleet_rows = [
            [
                rid,
                breakers.get(rid, "?"),
                sum(outcomes.values()),
                ", ".join(f"{k}={v}" for k, v in sorted(outcomes.items())) or "-",
            ]
            for rid, outcomes in sorted(per_replica.items())
        ]
        ratio = next(
            (s["value"] for s in series_of("fleet_affinity_hit_ratio")), None
        )
        render.table(
            ["replica", "breaker", "requests", "outcomes"], fleet_rows,
            title="Fleet routing"
            + (f" (affinity hit ratio {ratio})" if ratio is not None else ""),
        )


def _render_flight_view(
    render: "Renderer", debug_url: str, request_id: str | None, admin_token: str | None
) -> None:
    """`prime serve metrics --debug-url`: the flight-recorder view of a
    server or router — recent + in-flight request summaries, or one full
    timeline with --request."""
    import httpx

    base = debug_url.rstrip("/")
    path = f"/debug/requests/{request_id}" if request_id else "/debug/requests"
    headers = {"Authorization": f"Bearer {admin_token}"} if admin_token else None
    try:
        response = httpx.get(f"{base}{path}", headers=headers, timeout=10)
        if response.status_code == 403:
            raise click.ClickException(
                f"{base}{path} requires an admin token (--admin-token / "
                "PRIME_FLEET_ADMIN_TOKEN)"
            )
        if response.status_code == 404:
            raise click.ClickException(f"no request {request_id!r} at {base}")
        response.raise_for_status()
        payload = response.json()
    except (httpx.HTTPError, ValueError) as e:
        raise click.ClickException(f"could not read {base}{path}: {e}") from None
    if render.is_json:
        render.json(payload)
        return
    if request_id:
        # one timeline (server shape) or {"router": ..., "replica": ...}
        sections = (
            payload.items() if "router" in payload else [("request", payload)]
        )
        for section, timeline in sections:
            if not isinstance(timeline, dict):
                continue
            header = ", ".join(
                f"{k}={v}" for k, v in timeline.items() if k != "events"
            )
            click.echo(f"--- {section}: {header}")
            for event in timeline.get("events", []):
                detail = ", ".join(
                    f"{k}={v}" for k, v in event.items() if k not in ("t_s", "event")
                )
                click.echo(
                    f"{event['t_s'] * 1e3:>10.2f} ms  {event['event']}"
                    + (f" ({detail})" if detail else "")
                )
        return
    summaries = payload.get("router", payload)
    rows = [
        [
            entry.get("id", "?")[:16],
            entry.get("state", "?"),
            entry.get("outcome") or "-",
            round(entry.get("duration_s", 0.0), 3),
            entry.get("events", 0),
            entry.get("last_event") or "-",
            entry.get("replica") or "-",
        ]
        for bucket in ("inflight", "recent")
        for entry in summaries.get(bucket, [])
    ]
    render.table(
        ["request", "state", "outcome", "duration_s", "events", "last_event", "replica"],
        rows,
        title=f"Flight recorder @ {base} (in-flight first, then recent)",
    )
