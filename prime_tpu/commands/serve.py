"""`prime serve` — OpenAI-compatible inference on the local TPU slice.

The TPU-native counterpart of the platform's hosted inference endpoint
(reference api/inference.py consumes api.pinference.ai): serve a model —
optionally pjit-sharded over the slice with --slice/--tp — and point any
OpenAI client (including this CLI's own `prime inference chat`, via
PRIME_INFERENCE_URL) at it.
"""

from __future__ import annotations

import click

from prime_tpu.utils.render import Renderer, output_options


@click.group(name="serve", invoke_without_command=True)
@click.option("--model", "-m", default=None, help="Model preset or local HF checkpoint dir.")
@click.option("--checkpoint", default=None, help="Local HF checkpoint dir for weights.")
@click.option("--tokenizer", default=None)
@click.option("--slice", "slice_name", default=None, help="Shard over this TPU slice's mesh.")
@click.option("--tp", "tensor_parallel", type=int, default=None)
@click.option("--sp", "sequence_parallel", type=click.IntRange(min=2), default=None,
              help="Sequence-parallel axis for --slice: shard the KV cache's "
                   "slot dimension across the slice (long-context serving).")
@click.option("--kv-quant", is_flag=True, help="int8 KV cache (halved decode HBM traffic).")
@click.option("--weight-quant", is_flag=True, help="Quantized weights (halved+ weight HBM traffic).")
@click.option(
    "--weight-bits", type=click.Choice(["8", "4"]), default="8",
    help="Weight quantization width for --weight-quant: 8 = W8A16 "
         "per-channel, 4 = W4A16 group-wise (another 2x fewer weight bytes).",
)
@click.option("--adapter", default=None, type=click.Path(exists=True),
              help="LoRA adapter dir (from train local --lora) to merge into the model.")
@click.option("--host", default="127.0.0.1")
@click.option("--port", type=int, default=8000)
@click.option(
    "--continuous", is_flag=True,
    help="Continuous batching: concurrent requests share the chip via KV-cache "
    "slots; streaming emits tokens as they decode.",
)
@click.option("--slots", type=int, default=8, help="Max concurrent requests (--continuous).")
@click.option(
    "--slot-capacity", type=int, default=2048,
    help="Per-request KV capacity in tokens (--continuous).",
)
@click.option(
    "--chunk", type=int, default=8,
    help="Decode steps per dispatch — lower admits new requests sooner (--continuous).",
)
@click.option(
    "--speculative", is_flag=True,
    help="Prompt-lookup speculative decoding (greedy: exact tokens; sampled: "
         "exact distribution). With --continuous, per-slot drafts ride one "
         "verify pass per tick.",
)
@click.option("--draft-len", type=click.IntRange(min=1), default=4,
              help="Speculative draft tokens per step.")
@click.option(
    "--overlap/--no-overlap", "overlap", default=None,
    help="Overlapped decode pipeline (--continuous): dispatch chunk N+1 "
         "before syncing chunk N so host bookkeeping hides inside device "
         "compute. Default: on (PRIME_SERVE_OVERLAP).",
)
@click.option(
    "--warmup/--no-warmup", "warmup", default=None,
    help="Compile the engine's full program set at startup so no cold XLA "
         "compile lands mid-request (--continuous). Default: off "
         "(PRIME_SERVE_WARMUP).",
)
@click.option(
    "--prefix-cache-mb", type=float, default=None,
    help="Byte budget (MiB) of the radix prefix-KV cache: shared prompt "
         "blocks are cached once and reused across admissions; 0 disables "
         "(--continuous). Default: 256 (PRIME_SERVE_PREFIX_CACHE_MB).",
)
@click.pass_context
def serve_cmd(
    ctx: click.Context,
    model: str | None,
    checkpoint: str | None,
    tokenizer: str | None,
    slice_name: str | None,
    tensor_parallel: int | None,
    sequence_parallel: int | None,
    kv_quant: bool,
    weight_quant: bool,
    weight_bits: str,
    adapter: str | None,
    host: str,
    port: int,
    continuous: bool,
    slots: int,
    slot_capacity: int,
    chunk: int,
    speculative: bool,
    draft_len: int,
    overlap: bool | None,
    warmup: bool | None,
    prefix_cache_mb: float | None,
) -> None:
    """Serve MODEL over an OpenAI-compatible HTTP API (blocks until Ctrl-C)."""
    if ctx.invoked_subcommand is not None:
        return  # `prime serve metrics` — the subcommand runs instead
    if model is None:
        raise click.UsageError("Missing option '--model' / '-m'.")
    from prime_tpu.serve import serve_model

    if weight_bits == "4" and not weight_quant:
        # silently serving bf16 at 4x the expected HBM footprint would be a
        # nasty surprise; make the dependency explicit
        raise click.UsageError("--weight-bits 4 requires --weight-quant")

    try:
        server = serve_model(
            model,
            checkpoint=checkpoint,
            tokenizer=tokenizer,
            slice_name=slice_name,
            tensor_parallel=tensor_parallel,
            sequence_parallel=sequence_parallel,
            kv_quant=kv_quant,
            weight_quant=("int4" if weight_bits == "4" else True) if weight_quant else False,
            adapter=adapter,
            host=host,
            port=port,
            continuous=continuous,
            max_slots=slots,
            slot_capacity=slot_capacity,
            chunk=chunk,
            speculative=speculative,
            draft_len=draft_len,
            overlap=overlap,
            warmup=warmup,
            prefix_cache_mb=prefix_cache_mb,
        )
    except (ValueError, OSError) as e:
        raise click.ClickException(str(e)) from None
    click.echo(f"Serving {model} at {server.url}/v1 (Ctrl-C to stop)")
    click.echo(
        f"  e.g. PRIME_INFERENCE_URL={server.url}/v1 prime inference chat {model} -m 'hi'"
    )
    click.echo(f"  metrics: {server.url}/metrics  (prometheus: {server.url}/metrics?format=prometheus)")
    click.echo(f"  health:  {server.url}/healthz")
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        click.echo("\nStopped.")
        server.stop()


@serve_cmd.command(name="metrics")
@click.option(
    "--url", default="http://127.0.0.1:8000", show_default=True,
    help="Base URL of a running `prime serve` instance.",
)
@click.option(
    "--prometheus", is_flag=True,
    help="Dump the raw Prometheus text exposition instead of a table.",
)
@output_options
def serve_metrics_cmd(render: "Renderer", url: str, prometheus: bool) -> None:
    """Scrape a running server's metrics registry: counters, gauges, and
    latency histograms (TTFT, queue wait, prefill/decode) with estimated
    p50/p95. See docs/architecture.md "Observability"."""
    import httpx

    from prime_tpu.obs.metrics import quantile_from_snapshot

    if prometheus and render.is_json:
        # the exposition IS a text format; silently emitting it where a
        # script asked for JSON would break a downstream `| jq`
        raise click.UsageError(
            "--prometheus emits text exposition format; drop it or use "
            "--output json without it for the registry JSON"
        )
    base = url.rstrip("/")
    try:
        if prometheus:
            response = httpx.get(
                f"{base}/metrics", params={"format": "prometheus"}, timeout=10
            )
            response.raise_for_status()
            click.echo(response.text, nl=False)
            return
        response = httpx.get(
            f"{base}/metrics", params={"format": "registry"}, timeout=10
        )
        response.raise_for_status()
        payload = response.json()
    except (httpx.HTTPError, ValueError) as e:
        raise click.ClickException(f"could not scrape {base}/metrics: {e}") from None
    if not isinstance(payload, dict) or not all(
        isinstance(registry, dict)
        and all(isinstance(family, dict) and "series" in family for family in registry.values())
        for registry in payload.values()
    ):
        # e.g. a pre-telemetry server that answered the bare /metrics JSON
        raise click.ClickException(
            f"{base}/metrics?format=registry did not return registry snapshots "
            "(is the server running this repo's serve build?)"
        )

    value_rows: list[list] = []
    hist_rows: list[list] = []
    for section, registry in payload.items():
        for name, family in registry.items():
            for series in family["series"]:
                labels = ",".join(f"{k}={v}" for k, v in series["labels"].items())
                if family["type"] == "histogram":
                    count = series["count"]
                    mean = series["sum"] / count if count else 0.0
                    p50 = quantile_from_snapshot(series["buckets"], series["counts"], 0.5)
                    p95 = quantile_from_snapshot(series["buckets"], series["counts"], 0.95)
                    hist_rows.append(
                        [section, name, labels, count,
                         round(mean, 6), round(p50, 6), round(p95, 6)]
                    )
                else:
                    value_rows.append(
                        [section, name, labels, family["type"], series["value"]]
                    )
    if render.is_json:
        render.json(payload)
        return
    render.table(
        ["section", "metric", "labels", "type", "value"], value_rows,
        title="Counters & gauges",
    )
    render.table(
        ["section", "metric", "labels", "count", "mean", "p50", "p95"], hist_rows,
        title="Histograms (seconds unless named otherwise)",
    )
