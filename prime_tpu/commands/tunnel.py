"""`prime tunnel` — expose local ports (reference: commands/tunnel.py:48-561)."""

from __future__ import annotations

import signal

import click

import prime_tpu.commands._deps as deps
from prime_tpu.tunnel import Tunnel, TunnelError
from prime_tpu.tunnel.binary import FrpcUnavailable
from prime_tpu.utils.render import Renderer, output_options


@click.group(name="tunnel")
def tunnel_group() -> None:
    """Expose local ports via managed tunnels."""


@tunnel_group.command("start")
@click.argument("port", type=int)
@click.option("--auth", default=None, help="user:password basic auth on the public URL.")
@output_options
def start_cmd(render: Renderer, port: int, auth: str | None) -> None:
    """Start a tunnel to localhost:PORT (runs until Ctrl-C)."""
    basic_auth = None
    if auth:
        if ":" not in auth:
            raise click.ClickException("--auth must be user:password")
        basic_auth = tuple(auth.split(":", 1))
    tunnel = Tunnel(port, client=deps.build_client(), basic_auth=basic_auth)  # type: ignore[arg-type]
    try:
        url = tunnel.start()
    except (TunnelError, FrpcUnavailable) as e:
        raise click.ClickException(str(e)) from None
    render.message(f"Tunnel up: {url} -> localhost:{port} (Ctrl-C to stop)")

    stop = {"requested": False}

    def handle_sigint(signum, frame):
        stop["requested"] = True

    signal.signal(signal.SIGINT, handle_sigint)
    import time

    while not stop["requested"]:
        if tunnel.process and tunnel.process.poll() is not None:
            render.error("frpc exited unexpectedly")
            break
        time.sleep(0.5)
    tunnel.stop()
    render.message("Tunnel stopped.")


@tunnel_group.command("list")
@output_options
def list_cmd(render: Renderer) -> None:
    data = deps.build_client().get("/tunnels")
    items = data.get("items", []) if isinstance(data, dict) else data
    render.table(
        ["ID", "PORT", "URL", "STATUS"],
        [[t["tunnelId"], t.get("localPort", ""), t.get("url", ""), t.get("status", "")] for t in items],
        title="Tunnels",
        json_rows=items,
    )


@tunnel_group.command("status")
@click.argument("tunnel_id")
@output_options
def status_cmd(render: Renderer, tunnel_id: str) -> None:
    render.detail(deps.build_client().get(f"/tunnels/{tunnel_id}"), title=f"Tunnel {tunnel_id}")


@tunnel_group.command("stop")
@click.argument("tunnel_ids", nargs=-1, required=True)
@output_options
def stop_cmd(render: Renderer, tunnel_ids: tuple[str, ...]) -> None:
    """Delete tunnel registrations (bulk-capable)."""
    client = deps.build_client()
    for tunnel_id in tunnel_ids:
        client.delete(f"/tunnels/{tunnel_id}")
        render.message(f"Tunnel {tunnel_id} deleted.")
