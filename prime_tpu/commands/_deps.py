"""Shared client construction for CLI commands.

``transport_override`` exists so CLI tests can point every command at the
in-process fake control plane without sockets or monkeypatching client methods
(SURVEY.md §4's hermetic-tier upgrade).
"""

from __future__ import annotations

import httpx

from prime_tpu.core.client import APIClient
from prime_tpu.core.config import Config

transport_override: httpx.BaseTransport | None = None


def build_config() -> Config:
    return Config()


def build_client(config: Config | None = None) -> APIClient:
    return APIClient(config=config or build_config(), transport=transport_override)
