"""`prime images` + `prime registry` — sandbox image builds and registry access
(reference: commands/images.py:379-1604, registry.py)."""

from __future__ import annotations

import base64
from pathlib import Path

import click

from prime_tpu.commands._deps import build_client
from prime_tpu.utils.render import Renderer, output_options
from prime_tpu.utils.short_id import shorten


@click.group(name="images")
def images_group() -> None:
    """Build and publish sandbox images (JAX/libtpu base by default)."""


@images_group.command("list")
@output_options
def list_cmd(render: Renderer) -> None:
    data = build_client().get("/images")
    items = data.get("items", []) if isinstance(data, dict) else data
    render.table(
        ["ID", "NAME", "STATUS", "VISIBILITY"],
        [[shorten(i["imageId"]), i.get("name", ""), i.get("status", ""), i.get("visibility", "")] for i in items],
        title="Images",
        json_rows=items,
    )


@images_group.command("push")
@click.option("--name", required=True)
@click.option("--dockerfile", type=click.Path(exists=True), default="Dockerfile")
@click.option("--visibility", type=click.Choice(["private", "public"]), default="private")
@output_options
def push_cmd(render: Renderer, name: str, dockerfile: str, visibility: str) -> None:
    """Build an image from a Dockerfile (server-side build)."""
    contents = Path(dockerfile).read_text()
    result = build_client().post(
        "/images/build",
        json={
            "name": name,
            "dockerfileB64": base64.b64encode(contents.encode()).decode(),
            "visibility": visibility,
        },
        idempotent_post=True,
    )
    if render.is_json:
        render.json(result)
    else:
        render.message(f"Image {shorten(result['imageId'])} building (build {result.get('buildId')}).")


@images_group.command("build-status")
@click.argument("image_id")
@output_options
def build_status_cmd(render: Renderer, image_id: str) -> None:
    render.detail(build_client().get(f"/images/{image_id}/build-status"), title=f"Image {shorten(image_id)}")


@images_group.command("publish")
@click.argument("image_id")
@output_options
def publish_cmd(render: Renderer, image_id: str) -> None:
    result = build_client().post(f"/images/{image_id}/publish", idempotent_post=True)
    render.message(f"Image {shorten(image_id)} is now {result.get('visibility')}.")


@click.group(name="registry")
def registry_group() -> None:
    """Container registry credentials and access checks."""


@registry_group.command("credentials")
@output_options
def credentials_cmd(render: Renderer) -> None:
    data = build_client().get("/registry/credentials")
    items = data.get("items", []) if isinstance(data, dict) else data
    render.table(
        ["REGISTRY", "USERNAME"],
        [[c.get("registry", ""), c.get("username", "")] for c in items],
        title="Registry credentials",
        json_rows=items,
    )


@registry_group.command("check-access")
@click.argument("image")
@output_options
def check_access_cmd(render: Renderer, image: str) -> None:
    result = build_client().post("/registry/check-access", json={"image": image}, idempotent_post=True)
    if render.is_json:
        render.json(result)
    else:
        status = "accessible" if result.get("accessible") else "NOT accessible"
        render.message(f"{image}: {status}")
