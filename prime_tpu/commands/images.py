"""`prime images` + `prime registry` — sandbox image builds and registry access.

Reference surface: commands/images.py:379-1604 (push/build-vm/list/publish/
unpublish/visibility + artifact partition rendering), images_bulk.py
(manifest-driven concurrent builds with retry), images_transfer_bulk.py,
images_update_bulk.py, images_hf.py, registry.py. The HF flow is redesigned
TPU-first: instead of dataset-driven bulk pushes, ``images hf-cache`` bakes
HF checkpoint caches into an image partition so sandboxes cold-start with
model weights local to the TPU VM.
"""

from __future__ import annotations

import json
import time
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

import click

from prime_tpu.commands._deps import build_client
from prime_tpu.core.exceptions import RateLimitError
from prime_tpu.sandboxes.images import ImageClient
from prime_tpu.utils.render import Renderer, output_options
from prime_tpu.utils.short_id import shorten

BULK_WORKERS = 4
BULK_RETRIES = 3


def _image_client() -> ImageClient:
    return ImageClient(build_client())


@click.group(name="images")
def images_group() -> None:
    """Build and publish sandbox images (JAX/libtpu base by default)."""


@images_group.command("list")
@output_options
def list_cmd(render: Renderer) -> None:
    items = _image_client().list()
    render.table(
        ["ID", "NAME", "KIND", "STATUS", "VISIBILITY", "SIZE_MB"],
        [
            [
                shorten(i["imageId"]),
                i.get("name", ""),
                i.get("kind", "container"),
                i.get("status", ""),
                i.get("visibility", ""),
                sum(a.get("sizeMb", 0) for a in i.get("artifacts", [])),
            ]
            for i in items
        ],
        title="Images",
        json_rows=items,
    )


@images_group.command("get")
@click.argument("image_id")
@output_options
def get_cmd(render: Renderer, image_id: str) -> None:
    """Show one image including its artifact partitions."""
    image = _image_client().get(image_id)
    if render.is_json:
        render.json(image)
        return
    render.detail(
        {k: v for k, v in image.items() if k != "artifacts"}, title=f"Image {shorten(image_id)}"
    )
    render.table(
        ["PARTITION", "TYPE", "SIZE_MB", "STATUS"],
        [
            [a.get("partition", ""), a.get("type", ""), a.get("sizeMb", 0), a.get("status", "")]
            for a in image.get("artifacts", [])
        ],
        title="Artifacts",
        json_rows=None,
    )


@images_group.command("push")
@click.option("--name", required=True)
@click.option("--dockerfile", type=click.Path(exists=True), default="Dockerfile")
@click.option("--visibility", type=click.Choice(["private", "public"]), default="private")
@output_options
def push_cmd(render: Renderer, name: str, dockerfile: str, visibility: str) -> None:
    """Build an image from a Dockerfile (server-side build)."""
    result = _image_client().build(name, dockerfile=dockerfile, visibility=visibility)
    if render.is_json:
        render.json(result)
    else:
        render.message(f"Image {shorten(result['imageId'])} building (build {result.get('buildId')}).")


@images_group.command("build-vm")
@click.option("--name", required=True)
@click.option("--base-image", required=True, help="Platform image to base the VM on.")
@click.option("--boot-disk-gb", type=int, default=50)
@click.option("--visibility", type=click.Choice(["private", "public"]), default="private")
@output_options
def build_vm_cmd(render: Renderer, name: str, base_image: str, boot_disk_gb: int, visibility: str) -> None:
    """Build a VM image (for VM-kind sandboxes). Reference images.py:766."""
    result = _image_client().build_vm(name, base_image, boot_disk_gb, visibility)
    if render.is_json:
        render.json(result)
    else:
        render.message(
            f"VM image {shorten(result['imageId'])} building from {base_image} "
            f"({boot_disk_gb} GB boot disk)."
        )


@images_group.command("hf-cache")
@click.option("--name", required=True)
@click.option("--model", "models", multiple=True, required=True,
              help="HF model id to bake into the cache partition (repeatable).")
@click.option("--visibility", type=click.Choice(["private", "public"]), default="private")
@output_options
def hf_cache_cmd(render: Renderer, name: str, models: tuple[str, ...], visibility: str) -> None:
    """Build an image with HF checkpoint caches preloaded (TPU cold-start)."""
    result = _image_client().build_hf_cache(name, list(models), visibility)
    if render.is_json:
        render.json(result)
    else:
        render.message(
            f"HF-cache image {shorten(result['imageId'])} building with {len(models)} model(s)."
        )


@images_group.command("transfer")
@click.argument("source")
@click.option("--name", default=None, help="Target image name (default: derived from source).")
@click.option("--visibility", type=click.Choice(["private", "public"]), default="private")
@output_options
def transfer_cmd(render: Renderer, source: str, name: str | None, visibility: str) -> None:
    """Transfer an existing registry image into the platform."""
    result = _image_client().transfer(source, name=name, visibility=visibility)
    if render.is_json:
        render.json(result)
    else:
        render.message(f"Transferring {source} as {result['name']} ({shorten(result['imageId'])}).")


@images_group.command("build-status")
@click.argument("image_id")
@output_options
def build_status_cmd(render: Renderer, image_id: str) -> None:
    render.detail(_image_client().build_status(image_id), title=f"Image {shorten(image_id)}")


@images_group.command("publish")
@click.argument("image_id")
@output_options
def publish_cmd(render: Renderer, image_id: str) -> None:
    result = _image_client().publish(image_id)
    render.message(f"Image {shorten(image_id)} is now {result.get('visibility')}.")


@images_group.command("unpublish")
@click.argument("image_id")
@output_options
def unpublish_cmd(render: Renderer, image_id: str) -> None:
    result = _image_client().unpublish(image_id)
    render.message(f"Image {shorten(image_id)} is now {result.get('visibility')}.")


@images_group.command("update")
@click.argument("image_id")
@click.option("--name", default=None, help="New image name.")
@click.option("--visibility", type=click.Choice(["public", "private"]), default=None)
@click.option("--description", default=None)
@output_options
def update_cmd(
    render: Renderer,
    image_id: str,
    name: str | None,
    visibility: str | None,
    description: str | None,
) -> None:
    """Update one image's metadata (reference images.py update)."""
    fields = {
        key: value
        for key, value in (
            ("name", name), ("visibility", visibility), ("description", description)
        )
        if value is not None
    }
    if not fields:
        raise click.ClickException("nothing to update — pass --name/--visibility/--description")
    # APIError -> ClickException happens in LazyGroup.invoke (main.py)
    _image_client().update(image_id, **fields)
    render.message(f"Image {shorten(image_id)} updated ({', '.join(sorted(fields))}).")


@images_group.command("delete")
@click.argument("image_id")
@click.option("--yes", "-y", is_flag=True, help="Skip the confirmation prompt.")
@output_options
def delete_cmd(render: Renderer, image_id: str, yes: bool) -> None:
    """Delete an image from the registry (reference images.py delete)."""
    if not yes and not click.confirm(f"Delete image {shorten(image_id)}?"):
        render.message("Aborted.")
        return
    _image_client().delete(image_id)
    render.message(f"Image {shorten(image_id)} deleted.")


@images_group.command("visibility")
@click.argument("visibility", type=click.Choice(["public", "private"]))
@click.argument("image_ids", nargs=-1, required=True)
@output_options
def visibility_cmd(render: Renderer, visibility: str, image_ids: tuple[str, ...]) -> None:
    """Set visibility on many images at once."""
    results = _image_client().set_visibility_bulk(list(image_ids), visibility)
    _render_bulk_results(render, results, f"visibility -> {visibility}")


# -- bulk operations (reference images_bulk / transfer_bulk / update_bulk) ----


def _load_manifest(path: str) -> list[dict]:
    try:
        data = json.loads(Path(path).read_text())
    except (OSError, json.JSONDecodeError) as e:
        raise click.ClickException(f"cannot read manifest {path}: {e}") from None
    if not isinstance(data, list) or not all(isinstance(x, dict) for x in data):
        raise click.ClickException(f"manifest {path} must be a JSON list of objects")
    if not data:
        raise click.ClickException(f"manifest {path} is empty")
    return data


def _entry_label(entry: dict) -> str | None:
    return entry.get("name") or entry.get("source") or entry.get("imageId")


def _bulk_sleep(seconds: float) -> None:  # seam: patched in tests
    time.sleep(seconds)


def _run_bulk(entries: list[dict], submit) -> list[dict]:
    """Run one submit(entry) per manifest entry with bounded concurrency and
    429-aware retries; failures become per-entry outcomes, never aborts."""

    def one(entry: dict) -> dict:
        label = _entry_label(entry)
        for attempt in range(BULK_RETRIES + 1):
            try:
                result = submit(entry)
                return {"entry": label, "ok": True, "imageId": result.get("imageId")}
            except RateLimitError as e:
                if attempt == BULK_RETRIES:
                    return {"entry": label, "ok": False, "error": str(e)}
                _bulk_sleep(min(e.retry_after or 2 ** attempt, 30))
            except Exception as e:  # noqa: BLE001 — one bad entry must not abort the batch
                return {"entry": label, "ok": False, "error": str(e)}
        return {"entry": label, "ok": False, "error": "unreachable"}

    with ThreadPoolExecutor(max_workers=BULK_WORKERS) as pool:
        return list(pool.map(one, entries))


def _render_bulk_results(render: Renderer, results: list[dict], title: str) -> None:
    ok = sum(1 for r in results if r.get("ok"))
    if render.is_json:
        render.json({"results": results, "ok": ok, "failed": len(results) - ok})
    else:
        for r in results:
            mark = "ok " if r.get("ok") else "ERR"
            label = r.get("entry") or r.get("imageId") or ""
            suffix = r.get("imageId") if r.get("ok") else r.get("error", "")
            render.message(f"  {mark} {label} {suffix or ''}")
        render.message(f"{title}: {ok}/{len(results)} succeeded")
    if ok < len(results):
        raise SystemExit(1)


@images_group.command("bulk-push")
@click.option("--manifest", required=True, type=click.Path(exists=True),
              help='JSON list: [{"name", "dockerfile"|"dockerfileText", "visibility"?}]')
@output_options
def bulk_push_cmd(render: Renderer, manifest: str) -> None:
    """Build many images concurrently from a manifest (reference images_bulk.py)."""
    entries = _load_manifest(manifest)
    base = Path(manifest).parent
    client = _image_client()

    def submit(entry: dict) -> dict:
        if "name" not in entry:
            raise click.ClickException(f"manifest entry missing 'name': {entry}")
        text = entry.get("dockerfileText")
        dockerfile = entry.get("dockerfile")
        if text is None and dockerfile is not None:
            dockerfile = str((base / dockerfile))
        return client.build(
            entry["name"], dockerfile=dockerfile, dockerfile_text=text,
            visibility=entry.get("visibility", "private"),
        )

    _render_bulk_results(render, _run_bulk(entries, submit), "bulk push")


@images_group.command("bulk-transfer")
@click.option("--manifest", required=True, type=click.Path(exists=True),
              help='JSON list: [{"source", "name"?, "visibility"?}]')
@output_options
def bulk_transfer_cmd(render: Renderer, manifest: str) -> None:
    """Transfer many registry images (reference images_transfer_bulk.py)."""
    entries = _load_manifest(manifest)
    client = _image_client()

    def submit(entry: dict) -> dict:
        if "source" not in entry:
            raise click.ClickException(f"manifest entry missing 'source': {entry}")
        return client.transfer(
            entry["source"], name=entry.get("name"), visibility=entry.get("visibility", "private")
        )

    _render_bulk_results(render, _run_bulk(entries, submit), "bulk transfer")


@images_group.command("bulk-update")
@click.option("--manifest", required=True, type=click.Path(exists=True),
              help='JSON list: [{"imageId", "name"?, "visibility"?, "description"?}]')
@output_options
def bulk_update_cmd(render: Renderer, manifest: str) -> None:
    """Update many logical images in one call (reference images_update_bulk.py)."""
    entries = _load_manifest(manifest)
    results = _image_client().update_bulk(entries)
    normalized = [
        {"entry": r.get("imageId"), "ok": r.get("ok", False), "imageId": r.get("imageId"),
         "error": r.get("error")}
        for r in results
    ]
    _render_bulk_results(render, normalized, "bulk update")


@click.group(name="registry")
def registry_group() -> None:
    """Container registry credentials and access checks."""


@registry_group.command("credentials")
@output_options
def credentials_cmd(render: Renderer) -> None:
    data = build_client().get("/registry/credentials")
    items = data.get("items", []) if isinstance(data, dict) else data
    render.table(
        ["REGISTRY", "USERNAME"],
        [[c.get("registry", ""), c.get("username", "")] for c in items],
        title="Registry credentials",
        json_rows=items,
    )


@registry_group.command("check-access")
@click.argument("image")
@output_options
def check_access_cmd(render: Renderer, image: str) -> None:
    result = build_client().post("/registry/check-access", json={"image": image}, idempotent_post=True)
    if render.is_json:
        render.json(result)
    else:
        status = "accessible" if result.get("accessible") else "NOT accessible"
        render.message(f"{image}: {status}")
