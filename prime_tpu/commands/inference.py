"""`prime inference` — models + chat from the CLI (reference: commands/inference.py)."""

from __future__ import annotations

import click

import prime_tpu.commands._deps as deps
from prime_tpu.api.inference import InferenceClient
from prime_tpu.utils.render import Renderer, output_options


@click.group(name="inference")
def inference_group() -> None:
    """Query the inference API."""


def _client() -> InferenceClient:
    return InferenceClient(config=deps.build_config(), transport=deps.transport_override)


@inference_group.command("models")
@output_options
def models_cmd(render: Renderer) -> None:
    models = _client().list_models()
    render.table(
        ["ID", "OWNED BY", "CONTEXT"],
        [[m.get("id"), m.get("owned_by", ""), m.get("context_length", "")] for m in models],
        title="Inference models",
        json_rows=models,
    )


@inference_group.command("retrieve")
@click.argument("model_id")
@output_options
def retrieve_cmd(render: Renderer, model_id: str) -> None:
    render.detail(_client().retrieve_model(model_id), title=model_id)


@inference_group.command("chat")
@click.argument("model")
@click.option("--message", "-m", "message", required=True, help="User message.")
@click.option("--system", default=None)
@click.option("--max-tokens", type=int, default=None)
@click.option("--temperature", "-t", type=float, default=None)
@click.option("--stream/--no-stream", default=True)
@output_options
def chat_cmd(
    render: Renderer,
    model: str,
    message: str,
    system: str | None,
    max_tokens: int | None,
    temperature: float | None,
    stream: bool,
) -> None:
    """One-shot chat completion."""
    messages = ([{"role": "system", "content": system}] if system else []) + [
        {"role": "user", "content": message}
    ]
    client = _client()
    if stream and not render.is_json:
        for chunk in client.chat_completion_stream(
            model, messages, max_tokens=max_tokens, temperature=temperature
        ):
            for choice in chunk.get("choices", []):
                delta = choice.get("delta", {}).get("content")
                if delta:
                    click.echo(delta, nl=False)
        click.echo()
        return
    response = client.chat_completion(model, messages, max_tokens=max_tokens, temperature=temperature)
    if render.is_json:
        render.json(response)
    else:
        for choice in response.get("choices", []):
            click.echo(choice.get("message", {}).get("content", ""))
