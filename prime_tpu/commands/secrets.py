"""`prime secrets` — account-level secret CRUD (reference: commands/secrets.py)."""

from __future__ import annotations

import click

from prime_tpu.commands._deps import build_client
from prime_tpu.utils.render import Renderer, output_options


@click.group(name="secrets")
def secrets_group() -> None:
    """Manage account-level secrets (injected into runs/sandboxes by name)."""


@secrets_group.command("list")
@output_options
def list_cmd(render: Renderer) -> None:
    data = build_client().get("/secrets")
    keys = data.get("keys", []) if isinstance(data, dict) else data
    render.table(["KEY"], [[k] for k in keys], title="Secrets", json_rows=keys)


@secrets_group.command("set")
@click.argument("key")
@click.argument("value", required=False)
def set_cmd(key: str, value: str | None) -> None:
    if value is None:
        value = click.prompt(f"Value for {key}", hide_input=True)
    build_client().put(f"/secrets/{key}", json={"value": value})
    click.echo(f"Secret {key} set.")


@secrets_group.command("delete")
@click.argument("key")
@click.option("--yes", "-y", is_flag=True)
def delete_cmd(key: str, yes: bool) -> None:
    if not yes and not click.confirm(f"Delete secret {key}?"):
        click.echo("Aborted.")
        return
    build_client().delete(f"/secrets/{key}")
    click.echo(f"Secret {key} deleted.")
