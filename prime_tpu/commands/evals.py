"""`prime eval` — run local JAX evals, push results, browse the hub.

Reference surface: prime_cli/commands/evals.py:1392 (run: local passthrough
or --hosted), :1182 (push), list/get/samples. The local path here drives the
native JAX runner instead of shelling out to the `verifiers` package — the
runner keeps the same env-resolution → execute → results-dir → upload
architecture (SURVEY.md §3.3), so hub pushes stay contract-compatible.
"""

from __future__ import annotations

from pathlib import Path

import click

import prime_tpu.commands._deps as deps
from prime_tpu.core.client import APIClient
from prime_tpu.evals import EvalsClient
from prime_tpu.utils.render import Renderer, output_options
from prime_tpu.utils.short_id import shorten


@click.group(name="eval")
def eval_group() -> None:
    """Run and manage model evaluations."""


def build_evals_client() -> EvalsClient:
    api = APIClient(config=deps.build_config(), transport=deps.transport_override)
    return EvalsClient(api)


POLL_INTERVAL_S = 3.0
# a hosted run's log stream attaches some time after submission; up to this
# many 404 polls are "still starting", after which the 404 is a real error
LOG_STARTUP_MAX_POLLS = 40


def _hosted_logs_tolerant(client, hosted_id: str, state: dict) -> list[str]:
    """Fetch hosted-eval logs, tolerating the startup window where the log
    endpoint 404s because the runner hasn't attached yet (the train path's
    behavior; reference rl.py:2276-2295). Mutates ``state`` to bound the
    tolerance — a 404 that persists past the window is a real error."""
    from prime_tpu.core.exceptions import NotFoundError

    try:
        lines = client.hosted_logs(hosted_id)
    except NotFoundError:
        state["misses"] = state.get("misses", 0) + 1
        if state["misses"] == 1:
            click.echo("waiting for the hosted eval to start producing logs...", err=True)
        if state["misses"] > LOG_STARTUP_MAX_POLLS:
            raise
        return []
    state["misses"] = 0
    return lines


@eval_group.command("run")
@click.argument("env")
@click.option("--model", "-m", required=True, help="Model preset or local HF checkpoint dir.")
@click.option("--dataset", default=None, help="Local jsonl dataset (gsm8k format).")
@click.option("--limit", "-n", type=int, default=64)
@click.option("--batch-size", "-b", type=int, default=8)
@click.option("--max-new-tokens", type=int, default=256)
@click.option("--temperature", "-t", type=float, default=0.0)
@click.option("--checkpoint", default=None, help="Local HF checkpoint dir for weights.")
@click.option("--tokenizer", default=None, help="Tokenizer name/path (default: from checkpoint, else byte).")
@click.option("--output-dir", default="outputs/evals")
@click.option("--push/--no-push", "do_push", default=True, help="Push results to the Evals Hub.")
@click.option("--hosted", is_flag=True, help="Run on the platform instead of locally.")
@click.option("--tpu", "tpu_type", default="v5e-8", help="TPU slice for --hosted runs.")
@click.option(
    "--slice", "slice_name", default=None,
    help="Shard the local model over this TPU slice's mesh (e.g. v5e-8).",
)
@click.option("--tp", "tensor_parallel", type=int, default=None, help="Tensor-parallel axis for --slice.")
@click.option("--sp", "sequence_parallel", type=click.IntRange(min=2), default=None,
              help="Sequence-parallel axis for --slice: shard the KV cache's slot "
                   "dimension so a long-context cache spreads across the slice.")
@click.option("--kv-quant", is_flag=True, help="int8 KV cache (halved decode HBM traffic).")
@click.option("--weight-quant", is_flag=True, help="int8 weights (W8A16) for serving-side evals.")
@click.option("--speculative", is_flag=True,
              help="Prompt-lookup speculative decoding (greedy: exact tokens; "
                   "sampled: exact distribution via rejection sampling).")
@click.option("--draft-len", type=click.IntRange(min=1), default=4,
              help="Draft tokens per verify pass.")
@click.option("--adapter", default=None, type=click.Path(exists=True),
              help="LoRA adapter dir (from train local --lora) to merge into the model.")
@click.option("--endpoints-path", default=None,
              help="Endpoints alias table (default: configs/endpoints.toml). An alias "
                   "maps -m to a model id, optionally with a base_url for "
                   "inference-backed evals.")
@output_options
def run_eval_cmd(
    render: Renderer,
    env: str,
    model: str,
    dataset: str | None,
    limit: int,
    batch_size: int,
    max_new_tokens: int,
    temperature: float,
    checkpoint: str | None,
    tokenizer: str | None,
    output_dir: str,
    do_push: bool,
    hosted: bool,
    tpu_type: str,
    slice_name: str | None,
    tensor_parallel: int | None,
    sequence_parallel: int | None,
    kv_quant: bool,
    weight_quant: bool,
    speculative: bool,
    draft_len: int,
    adapter: str | None,
    endpoints_path: str | None,
) -> None:
    """Run ENV against a model (local TPU by default, --hosted for platform)."""
    from prime_tpu.evals.endpoints import (
        EvalPreflightError,
        preflight_billing,
        resolve_endpoint_alias,
        validate_model,
    )
    from prime_tpu.evals.runner import EvalRunSpec, push_eval_results, run_eval

    # endpoint aliasing first — both the hosted and local paths see the
    # resolved model id (reference verifiers_bridge.py:823-845)
    def warn(message: str) -> None:
        # click.echo directly: must reach stderr even in --output json mode
        click.echo(f"warning: {message}", err=True)

    try:
        resolution = resolve_endpoint_alias(model, endpoints_path)
    except EvalPreflightError as e:
        raise click.ClickException(str(e)) from None
    api_base = None
    alias_name = model  # what the user typed — error messages must use it
    if resolution is not None:
        render.message(f"Endpoint alias {model!r} -> {resolution.model}")
        model = resolution.model
        api_base = resolution.base_url

    if hosted:
        if api_base is not None:
            # a base_url alias targets a specific endpoint; --hosted runs on
            # the platform TPU fleet — honoring the model id but not the
            # endpoint would silently evaluate a different deployment
            raise click.ClickException(
                f"alias {alias_name!r} carries a base_url, which "
                "conflicts with --hosted (hosted evals run on the platform, "
                "not against an endpoint) — drop --hosted or use a "
                "rename-only alias"
            )
        # local-only flags HARD-FAIL with --hosted: a user who asked for
        # int8-KV or an adapter must not get silently different physics
        # (VERDICT r3 weak #6 — was a warning)
        rejected = [
            name
            for name, value in (
                ("--dataset", dataset),
                ("--checkpoint", checkpoint),
                ("--tokenizer", tokenizer),
                ("--adapter", adapter),
                ("--slice", slice_name),
                ("--tp", tensor_parallel),
                ("--sp", sequence_parallel),
            )
            if value is not None
        ]
        rejected += [
            name
            for name, flag in (
                ("--kv-quant", kv_quant),
                ("--speculative", speculative),
                ("--weight-quant", weight_quant),
                ("--no-push", not do_push),
            )
            if flag
        ]
        if rejected:
            raise click.ClickException(
                f"{', '.join(rejected)} only apply to local runs — remove "
                "them or drop --hosted"
            )
        # fail-fast preflights against the platform inference API: bad model
        # id 404s and an empty wallet 402s BEFORE a TPU slice is provisioned
        # (reference verifiers_bridge.py:858-897); timeouts warn + continue
        try:
            validate_model(model, warn=warn)
            preflight_billing(model, warn=warn)
        except EvalPreflightError as e:
            raise click.ClickException(str(e)) from None
        _run_hosted(render, env, model, limit, batch_size, max_new_tokens, temperature, tpu_type)
        return

    # environment execution protocol: resolve (local dir / installed / hub)
    # → import load_environment() → its dataset+scorer drive the generator.
    # Built-in labels and explicit --dataset runs skip resolution entirely:
    # a hub env named "gsm8k" must not shadow the built-in, and a
    # user-supplied dataset must not be silently replaced by env data.
    from prime_tpu.commands.env import build_hub_client, load_resolved_environment
    from prime_tpu.envhub.execution import (
        BUILTIN_ENVS,
        EnvResolutionError,
        resolve_environment,
    )

    env_examples = env_scorer = None
    run_env_name = env
    resolved = None
    if env not in BUILTIN_ENVS and dataset is None:
        try:
            resolved = resolve_environment(env, hub_client=build_hub_client())
        except EnvResolutionError as e:
            if Path(env).suffix == "" and "/" in env:
                # looked like a path/slug and nothing else will supply data
                raise click.ClickException(str(e)) from None
    if resolved is not None:
        loaded = load_resolved_environment(render, resolved)
        from prime_tpu.evals.datasets import EvalExample

        env_examples = [
            EvalExample(question=str(e["prompt"]), answer=str(e["answer"]), prompt=str(e["prompt"]))
            for e in loaded.examples
        ]
        env_scorer = loaded.scorer
        run_env_name = loaded.name
        # env-declared eval defaults apply unless the flag was given explicitly
        from prime_tpu.utils.render import flag_is_default

        if "max_new_tokens" in loaded.defaults and flag_is_default("max_new_tokens"):
            max_new_tokens = int(loaded.defaults["max_new_tokens"])
        if "temperature" in loaded.defaults and flag_is_default("temperature"):
            temperature = float(loaded.defaults["temperature"])

    # an alias with a base_url makes this run inference-backed: generation
    # happens on the remote OpenAI-compatible endpoint, everything else
    # (env resolution, scoring, results dir, hub push) is unchanged
    api_generator = None
    if api_base is not None:
        conflicting = [
            name
            for name, value in (
                ("--checkpoint", checkpoint),
                ("--tokenizer", tokenizer),
                ("--slice", slice_name),
                ("--tp", tensor_parallel),
                ("--sp", sequence_parallel),
                ("--adapter", adapter),
            )
            if value is not None
        ]
        conflicting += [
            name
            for name, flag in (
                ("--kv-quant", kv_quant),
                ("--weight-quant", weight_quant),
                ("--speculative", speculative),
            )
            if flag
        ]
        if conflicting:
            raise click.ClickException(
                f"{', '.join(conflicting)} configure the local JAX runner and "
                f"don't apply to the endpoint-backed alias (base_url set)"
            )
        from prime_tpu.evals.endpoints import ApiGenerator

        # preflight only our own platform: foreign endpoints may not accept
        # the configured credentials for /models (reference skips there too).
        # Both sides normalized — a trailing-slash mismatch must not
        # silently skip the documented fail-fast
        if api_base.rstrip("/") == deps.build_config().inference_url.rstrip("/"):
            try:
                validate_model(model, base_url=api_base, warn=warn)
                preflight_billing(model, base_url=api_base, warn=warn)
            except EvalPreflightError as e:
                raise click.ClickException(str(e)) from None
        api_generator = ApiGenerator(model, base_url=api_base)

    spec = EvalRunSpec(
        env=run_env_name,
        model=model,
        dataset_path=dataset,
        limit=limit,
        batch_size=batch_size,
        max_new_tokens=max_new_tokens,
        temperature=temperature,
        checkpoint=checkpoint,
        tokenizer=tokenizer,
        output_dir=output_dir,
        slice_name=slice_name,
        tensor_parallel=tensor_parallel,
        sequence_parallel=sequence_parallel,
        kv_quant=kv_quant,
        weight_quant=weight_quant,
        speculative=speculative,
        draft_len=draft_len,
        adapter=adapter,
    )

    def progress(done: int, total: int) -> None:
        render.message(f"  {done}/{total} samples")

    render.message(f"Running {run_env_name} with {model} (limit {limit}, batch {batch_size})...")
    try:
        result = run_eval(
            spec, generator=api_generator, progress=progress,
            examples=env_examples, scorer=env_scorer,
        )
    except (ValueError, FileNotFoundError) as e:
        raise click.ClickException(str(e)) from None
    payload = {
        "runDir": str(result.run_dir),
        "metrics": result.metrics,
    }
    if do_push:
        eval_id, metrics = push_eval_results(result.run_dir, build_evals_client())
        payload["evalId"] = eval_id
        render.message(f"Pushed to hub: {shorten(eval_id)}")
    if render.is_json:
        render.json(payload)
    else:
        render.message(
            f"accuracy={result.metrics['accuracy']:.3f} "
            f"samples/sec={result.metrics['samples_per_sec']:.2f} "
            f"({int(result.metrics['num_samples'])} samples) -> {result.run_dir}"
        )


@eval_group.command("push")
@click.option("--run-dir", default=None, help="Specific run dir (default: newest under outputs/evals).")
@click.option("--env", default=None)
@click.option("--model", default=None)
@click.option("--output-dir", default="outputs/evals")
@output_options
def push_cmd(
    render: Renderer, run_dir: str | None, env: str | None, model: str | None, output_dir: str
) -> None:
    """Push a finished eval run directory to the Evals Hub."""
    from prime_tpu.evals.runner import find_latest_run, push_eval_results

    try:
        target = run_dir or find_latest_run(output_dir, env=env, model=model)
    except FileNotFoundError as e:
        raise click.ClickException(str(e)) from None
    eval_id, metrics = push_eval_results(target, build_evals_client())
    if render.is_json:
        render.json({"evalId": eval_id, "metrics": metrics, "runDir": str(target)})
    else:
        render.message(f"Pushed {target} as {shorten(eval_id)}: {metrics}")


@eval_group.command("list")
@click.option("--env", default=None)
@output_options
def list_cmd(render: Renderer, env: str | None) -> None:
    evaluations = build_evals_client().list_evaluations(env=env)
    render.table(
        ["ID", "ENV", "MODEL", "STATUS", "SAMPLES", "ACCURACY"],
        [
            [
                shorten(e.eval_id),
                shorten(e.env_id),
                e.model,
                e.status,
                e.sample_count,
                f"{e.metrics.get('accuracy', 0):.3f}" if e.metrics else "",
            ]
            for e in evaluations
        ],
        title="Evaluations",
        json_rows=[e.model_dump(by_alias=True) for e in evaluations],
    )


@eval_group.command("get")
@click.argument("eval_id")
@output_options
def get_cmd(render: Renderer, eval_id: str) -> None:
    evaluation = build_evals_client().get_evaluation(eval_id)
    render.detail(evaluation.model_dump(by_alias=True), title=f"Evaluation {shorten(eval_id)}")


@eval_group.command("samples")
@click.argument("eval_id")
@click.option("--limit", type=int, default=20)
@click.option("--offset", type=int, default=0)
@output_options
def samples_cmd(render: Renderer, eval_id: str, limit: int, offset: int) -> None:
    samples = build_evals_client().get_samples(eval_id, limit=limit, offset=offset)
    render.table(
        ["ID", "CORRECT", "ANSWER", "COMPLETION"],
        [
            [s.sample_id, "Y" if s.correct else "n", s.answer or "", (s.completion or "")[:60]]
            for s in samples
        ],
        title=f"Samples for {shorten(eval_id)}",
        json_rows=[s.model_dump(by_alias=True) for s in samples],
    )


def _run_hosted(
    render: Renderer,
    env: str,
    model: str,
    limit: int,
    batch_size: int,
    max_new_tokens: int,
    temperature: float,
    tpu_type: str,
) -> None:
    """Submit a platform-side eval and poll status/logs until terminal
    (reference commands/evals.py:565-716)."""
    import time

    from prime_tpu.utils.hosted_eval import EvalStatus, HostedEvalConfig

    config = HostedEvalConfig(
        env=env,
        model=model,
        limit=limit,
        batch_size=batch_size,
        max_new_tokens=max_new_tokens,
        temperature=temperature,
        tpu_type=tpu_type,
    )
    client = build_evals_client()
    run = client.create_hosted(config.model_dump(by_alias=True, exclude_none=True))
    hosted_id = run["hostedId"]
    render.message(f"Hosted eval {shorten(hosted_id)} submitted on {tpu_type}.")
    seen_lines = 0
    startup_state: dict = {}
    try:
        while True:
            run = client.get_hosted(hosted_id)
            lines = _hosted_logs_tolerant(client, hosted_id, startup_state)
            for line in lines[seen_lines:]:
                render.message(f"  {line}")
            seen_lines = max(seen_lines, len(lines))
            if run["status"] in EvalStatus.TERMINAL:
                break
            time.sleep(POLL_INTERVAL_S)
    except KeyboardInterrupt:
        click.echo(
            f"\nDetached — the hosted eval is still running. "
            f"Cancel with: prime eval stop {hosted_id}",
            err=True,
        )
        raise SystemExit(130) from None
    if render.is_json:
        render.json(run)
    else:
        render.message(f"Hosted eval {shorten(hosted_id)}: {run['status']} {run.get('metrics', {})}")
    if run["status"] != EvalStatus.COMPLETED:
        raise SystemExit(1)  # FAILED/CANCELLED must not look like success to scripts


@eval_group.command("view")
@click.argument("target", required=False)
@click.option("--samples", "sample_count", type=int, default=10, help="Samples to show.")
@click.option("--output-dir", default="outputs/evals")
@output_options
def view_cmd(render: Renderer, target: str | None, sample_count: int, output_dir: str) -> None:
    """View one eval run: a local run dir (default: newest), a hub eval id
    (eval_...), or a hosted run id (heval_...). Reference evals.py:1149."""
    import json as _json

    # exact id prefixes only — a path like evals/arith--m/run1 must not be
    # mistaken for a hub id, and an id must not fall through to the dir path
    if target and target.startswith("heval_") and not Path(target).exists():
        client = build_evals_client()
        run = client.get_hosted(target)
        logs = client.hosted_logs(target)
        if render.is_json:
            render.json({**run, "logs": logs})
            return
        render.detail(
            {k: v for k, v in run.items() if k != "logs"}, title=f"Hosted eval {shorten(target)}"
        )
        for line in logs[-sample_count:]:
            render.message(f"  {line}")
        return

    if target and target.startswith("eval_") and not Path(target).exists():
        client = build_evals_client()
        evaluation = client.get_evaluation(target)
        samples = client.get_samples(target, limit=sample_count)
        if render.is_json:
            render.json(
                {
                    "evaluation": evaluation.model_dump(by_alias=True),
                    "samples": [s.model_dump(by_alias=True) for s in samples],
                }
            )
            return
        render.detail(evaluation.model_dump(by_alias=True), title=f"Evaluation {shorten(target)}")
        _render_sample_table(render, [s.model_dump() for s in samples], sample_count)
        return

    from prime_tpu.evals.runner import find_latest_run

    if target:
        run_dir = Path(target)
        if not run_dir.is_dir():
            # never silently fall back to a different run than the one named
            raise click.ClickException(
                f"{target!r} is not a run directory, a hub eval id (eval_...), "
                "or a hosted run id (heval_...)"
            )
    else:
        try:
            run_dir = find_latest_run(output_dir)
        except FileNotFoundError as e:
            raise click.ClickException(str(e)) from None
    metadata_path = run_dir / "metadata.json"
    if not metadata_path.exists():
        raise click.ClickException(f"{run_dir} has no metadata.json — not an eval run dir")
    metadata = _json.loads(metadata_path.read_text())
    results_path = run_dir / "results.jsonl"
    if not results_path.exists():
        raise click.ClickException(f"{run_dir} has no results.jsonl (run interrupted?)")
    rows = []
    with open(results_path) as f:
        for line in f:
            if line.strip():
                rows.append(_json.loads(line))
    correct = sum(1 for r in rows if r.get("correct"))
    if render.is_json:
        render.json({"runDir": str(run_dir), "metadata": metadata, "samples": rows[:sample_count]})
        return
    render.detail(
        {
            "runDir": str(run_dir),
            "env": metadata.get("env"),
            "model": metadata.get("model"),
            "samples": f"{correct}/{len(rows)} correct",
            **{f"metric.{k}": v for k, v in metadata.get("metrics", {}).items()},
        },
        title=f"Eval run {run_dir.name}",
    )
    _render_sample_table(render, rows, sample_count)


def _render_sample_table(render: Renderer, rows: list[dict], sample_count: int) -> None:
    render.table(
        ["ID", "OK", "ANSWER", "COMPLETION"],
        [
            [
                r.get("sample_id", r.get("sampleId", "")),
                "Y" if r.get("correct") else "n",
                str(r.get("answer", ""))[:20],
                str(r.get("completion", "")).replace("\n", " ")[:60],
            ]
            for r in rows[:sample_count]
        ],
        title="Samples",
        json_rows=None,
    )


@eval_group.command("compare")
@click.argument("run_a")
@click.argument("run_b")
@click.option("--samples", "show_samples", type=int, default=10, help="Flipped samples to show.")
@output_options
def compare_cmd(render: Renderer, run_a: str, run_b: str, show_samples: int) -> None:
    """Compare two local eval run dirs: metric deltas and per-sample flips."""
    import json as _json

    def load_run(target: str):
        run_dir = Path(target)
        if not run_dir.is_dir() or not (run_dir / "metadata.json").exists():
            raise click.ClickException(f"{target!r} is not an eval run directory")
        metadata = _json.loads((run_dir / "metadata.json").read_text())
        samples = {}
        results = run_dir / "results.jsonl"
        if results.exists():
            for line in results.read_text().splitlines():
                if line.strip():
                    row = _json.loads(line)
                    samples[row.get("prompt", row.get("sample_id"))] = row
        return metadata, samples

    meta_a, samples_a = load_run(run_a)
    meta_b, samples_b = load_run(run_b)
    metrics_a = meta_a.get("metrics", {})
    metrics_b = meta_b.get("metrics", {})
    def delta_of(a, b):
        # a delta only makes sense when BOTH runs recorded the metric
        if isinstance(a, (int, float)) and isinstance(b, (int, float)):
            return b - a
        return None

    deltas = {
        key: {
            "a": metrics_a.get(key),
            "b": metrics_b.get(key),
            "delta": delta_of(metrics_a.get(key), metrics_b.get(key)),
        }
        for key in sorted(set(metrics_a) | set(metrics_b))
        if isinstance(metrics_a.get(key), (int, float)) or isinstance(metrics_b.get(key), (int, float))
    }

    shared = set(samples_a) & set(samples_b)
    regressions = [
        key for key in shared
        if samples_a[key].get("correct") and not samples_b[key].get("correct")
    ]
    improvements = [
        key for key in shared
        if not samples_a[key].get("correct") and samples_b[key].get("correct")
    ]
    payload = {
        "runA": run_a,
        "runB": run_b,
        "metrics": deltas,
        "sharedSamples": len(shared),
        "regressions": len(regressions),
        "improvements": len(improvements),
    }
    if render.is_json:
        payload["regressedPrompts"] = regressions[:show_samples]
        payload["improvedPrompts"] = improvements[:show_samples]
        render.json(payload)
        return
    render.table(
        ["METRIC", "A", "B", "DELTA"],
        [
            [key, f"{d['a']:.4g}" if d["a"] is not None else "—",
             f"{d['b']:.4g}" if d["b"] is not None else "—",
             f"{d['delta']:+.4g}" if d["delta"] is not None else "—"]
            for key, d in deltas.items()
        ],
        title=f"{meta_a.get('env')}/{meta_a.get('model')} vs {meta_b.get('env')}/{meta_b.get('model')}",
        json_rows=None,
    )
    render.message(
        f"{len(shared)} shared samples: {len(improvements)} improved, {len(regressions)} regressed"
    )
    for key in regressions[:show_samples]:
        render.message(f"  regressed: {str(key)[:90]}")


@eval_group.command("tui")
@click.option("--dir", "workspace", default=".", type=click.Path())
def eval_tui_cmd(workspace: str) -> None:
    """Open the Lab shell focused on evals (reference evals.py:1166)."""
    import prime_tpu.commands._deps as _deps
    from prime_tpu.lab.tui import open_shell

    try:
        open_shell(workspace, api_client=_deps.build_client(), section="evals")
    except RuntimeError as e:
        raise click.ClickException(str(e)) from None


@eval_group.command("logs")
@click.argument("hosted_id")
@click.option("--follow", "-f", is_flag=True, help="Poll until the run is terminal.")
@output_options
def logs_cmd(render: Renderer, hosted_id: str, follow: bool) -> None:
    """Stream a hosted eval's logs (reference evals.py:1357)."""
    import time

    from prime_tpu.utils.hosted_eval import EvalStatus

    client = build_evals_client()
    seen = 0
    full_lines: list[str] = []
    startup_state: dict = {}
    while True:
        lines = (
            _hosted_logs_tolerant(client, hosted_id, startup_state)
            if follow
            else client.hosted_logs(hosted_id)
        )
        # a tolerated mid-stream 404 returns [] — never rewind `seen` (a
        # reset would replay the whole log on the next good poll) and keep
        # the longest fetch for the final JSON document
        if len(lines) > len(full_lines):
            full_lines = lines
        if not render.is_json:
            for line in lines[seen:]:
                render.message(line)
        seen = max(seen, len(lines))
        if not follow:
            if render.is_json:
                render.json({"logs": lines})
            return
        run = client.get_hosted(hosted_id)
        if run["status"] in EvalStatus.TERMINAL:
            # JSON follow mode: one final document with the full log + status
            if render.is_json:
                render.json({"logs": full_lines, "status": run["status"]})
            else:
                render.message(f"[{run['status']}]")
            return
        time.sleep(POLL_INTERVAL_S)


@eval_group.command("stop")
@click.argument("hosted_id")
@output_options
def stop_hosted_cmd(render: Renderer, hosted_id: str) -> None:
    """Cancel a hosted eval."""
    run = build_evals_client().cancel_hosted(hosted_id)
    if render.is_json:
        render.json(run)
    else:
        render.message(f"Hosted eval {shorten(hosted_id)}: {run['status']}")
