"""`prime sandbox` — sandbox lifecycle + exec + files over the SDK.

Reference surface: prime_cli/commands/sandbox.py:258-1868 (list/get/create/
delete incl. bulk preview+confirm, logs, run, upload/download, network,
expose/unexpose/list-ports, reset-cache).
"""

from __future__ import annotations

import subprocess
import sys

import click

import prime_tpu.commands._deps as deps
from prime_tpu.core.client import APIClient
from prime_tpu.sandboxes import CreateSandboxRequest, EgressPolicy, SandboxClient
from prime_tpu.sandboxes.auth import SandboxAuthCache
from prime_tpu.utils.render import Renderer, output_options
from prime_tpu.utils.short_id import resolve, shorten


# Injection point for tests (no real ssh in CI).
ssh_runner = subprocess.run


@click.group(name="sandbox")
def sandbox_group() -> None:
    """Run code in JAX/libtpu-preloaded sandboxes."""


def build_sandbox_client() -> SandboxClient:
    api = APIClient(config=deps.build_config(), transport=deps.transport_override)
    return SandboxClient(client=api, gateway_transport=deps.transport_override)


def _resolve_id(client: SandboxClient, sandbox_id: str) -> str:
    return _resolve_ids(client, [sandbox_id])[0]


def _resolve_ids(client: SandboxClient, sandbox_ids: list[str] | tuple[str, ...]) -> list[str]:
    """Resolve many short IDs against ONE listing (no N+1 list calls)."""
    candidates = [s.sandbox_id for s in client.list()]
    try:
        return [resolve(sid, candidates) for sid in sandbox_ids]
    except ValueError as e:
        raise click.ClickException(str(e)) from None


def _parse_kv(pairs: tuple[str, ...], option: str) -> dict[str, str]:
    out: dict[str, str] = {}
    for kv in pairs:
        if "=" not in kv:
            raise click.ClickException(f"Invalid {option} value {kv!r}: expected KEY=VALUE")
        key, _, value = kv.partition("=")
        out[key] = value
    return out


@sandbox_group.command("list")
@click.option("--label", "labels", multiple=True, help="Filter by label key=value (repeatable).")
@output_options
def list_sandboxes(render: Renderer, labels: tuple[str, ...]) -> None:
    label_map = _parse_kv(labels, "--label") if labels else None
    sandboxes = build_sandbox_client().list(labels=label_map)
    render.table(
        ["ID", "NAME", "STATUS", "IMAGE", "TPU", "CREATED"],
        [
            [shorten(s.sandbox_id), s.name or "", s.status, s.docker_image, s.tpu_type or "-", s.created_at or ""]
            for s in sandboxes
        ],
        title="Sandboxes",
        json_rows=[s.model_dump(by_alias=True) for s in sandboxes],
    )


@sandbox_group.command("create")
@click.option("--name", default=None)
@click.option("--image", default=None, help="Docker image (defaults to the JAX/libtpu image).")
@click.option("--tpu", "tpu_type", default=None, help="Attach a single-host TPU slice, e.g. v5e-1.")
@click.option("--vm", "is_vm", is_flag=True, help="TPU-VM sandbox (streaming exec transport).")
@click.option("--cpu", "cpu_cores", type=int, default=2)
@click.option("--memory-gib", type=int, default=4)
@click.option("--disk-gib", type=int, default=20)
@click.option("--timeout-minutes", type=int, default=60)
@click.option("--env", "env_vars", multiple=True, help="KEY=VALUE (repeatable).")
@click.option("--label", "labels", multiple=True, help="key=value (repeatable).")
@click.option("--wait/--no-wait", default=True, help="Wait until RUNNING.")
@output_options
def create_sandbox(
    render: Renderer,
    name: str | None,
    image: str | None,
    tpu_type: str | None,
    is_vm: bool,
    cpu_cores: int,
    memory_gib: int,
    disk_gib: int,
    timeout_minutes: int,
    env_vars: tuple[str, ...],
    labels: tuple[str, ...],
    wait: bool,
) -> None:
    """Create a sandbox (JAX/libtpu image by default)."""
    try:
        request = CreateSandboxRequest(
            name=name,
            tpu_type=tpu_type,
            is_vm=is_vm,
            cpu_cores=cpu_cores,
            memory_gib=memory_gib,
            disk_gib=disk_gib,
            timeout_minutes=timeout_minutes,
            env_vars=_parse_kv(env_vars, "--env"),
            labels=_parse_kv(labels, "--label"),
            **({"docker_image": image} if image else {}),
        )
    except ValueError as e:
        import pydantic

        if isinstance(e, pydantic.ValidationError):
            msgs = "; ".join(
                f"{'.'.join(str(p) for p in err['loc'])}: {err['msg'].removeprefix('Value error, ')}"
                for err in e.errors()
            )
            raise click.ClickException(msgs) from None
        raise click.ClickException(str(e)) from None
    client = build_sandbox_client()
    sandbox = client.create(request)
    if wait:
        render.message(f"Sandbox {shorten(sandbox.sandbox_id)} created; waiting for RUNNING...")
        sandbox = client.wait_for_creation(sandbox.sandbox_id)
    if render.is_json:
        render.json(sandbox.model_dump(by_alias=True))
    else:
        render.message(f"Sandbox {shorten(sandbox.sandbox_id)} is {sandbox.status}")


@sandbox_group.command("get")
@click.argument("sandbox_id")
@output_options
def get_sandbox(render: Renderer, sandbox_id: str) -> None:
    client = build_sandbox_client()
    sandbox = client.get(_resolve_id(client, sandbox_id))
    render.detail(sandbox.model_dump(by_alias=True), title=f"Sandbox {shorten(sandbox.sandbox_id)}")


@sandbox_group.command("delete")
@click.argument("sandbox_ids", nargs=-1, required=True)
@click.option("--yes", "-y", is_flag=True)
@output_options
def delete_sandbox(render: Renderer, sandbox_ids: tuple[str, ...], yes: bool) -> None:
    """Delete one or more sandboxes (bulk deletes show a preview first)."""
    client = build_sandbox_client()
    full_ids = _resolve_ids(client, sandbox_ids)
    if len(full_ids) > 1 and not yes:
        click.echo("Will delete:")
        for sid in full_ids:
            click.echo(f"  {shorten(sid)}")
        if not click.confirm(f"Delete {len(full_ids)} sandboxes?"):
            render.message("Aborted.")
            return
    if len(full_ids) == 1:
        client.delete(full_ids[0])
        render.message(f"Sandbox {shorten(full_ids[0])} deleted.")
    else:
        result = client.bulk_delete(full_ids)
        render.message(f"Deleted {len(result.get('deleted', []))} sandboxes.")


@sandbox_group.command("logs")
@click.argument("sandbox_id")
@output_options
def logs(render: Renderer, sandbox_id: str) -> None:
    client = build_sandbox_client()
    click.echo(client.logs(_resolve_id(client, sandbox_id)))


@sandbox_group.command("run")
@click.argument("sandbox_id")
@click.argument("command")
@click.option("--timeout", "timeout_s", type=float, default=300.0)
@click.option("--env", "env_vars", multiple=True, help="KEY=VALUE (repeatable).")
@output_options
def run_command(
    render: Renderer, sandbox_id: str, command: str, timeout_s: float, env_vars: tuple[str, ...]
) -> None:
    """Execute a command and print its output (exit code is propagated)."""
    client = build_sandbox_client()
    result = client.execute_command(
        _resolve_id(client, sandbox_id),
        command,
        timeout_s=timeout_s,
        env=_parse_kv(env_vars, "--env") if env_vars else None,
    )
    if render.is_json:
        render.json(result.model_dump(by_alias=True))
    else:
        if result.stdout:
            click.echo(result.stdout, nl=False)
        if result.stderr:
            click.echo(result.stderr, nl=False, err=True)
    if result.exit_code != 0:
        sys.exit(result.exit_code)


@sandbox_group.command("upload")
@click.argument("sandbox_id")
@click.argument("local_path", type=click.Path(exists=True))
@click.argument("remote_path")
@output_options
def upload(render: Renderer, sandbox_id: str, local_path: str, remote_path: str) -> None:
    client = build_sandbox_client()
    client.upload_file(_resolve_id(client, sandbox_id), local_path, remote_path)
    render.message(f"Uploaded {local_path} -> {remote_path}")


@sandbox_group.command("download")
@click.argument("sandbox_id")
@click.argument("remote_path")
@click.argument("local_path", type=click.Path())
@output_options
def download(render: Renderer, sandbox_id: str, remote_path: str, local_path: str) -> None:
    client = build_sandbox_client()
    client.download_file(_resolve_id(client, sandbox_id), remote_path, local_path)
    render.message(f"Downloaded {remote_path} -> {local_path}")


@sandbox_group.command("network")
@click.argument("sandbox_id")
@click.option("--default-action", type=click.Choice(["allow", "deny"]), default=None)
@click.option("--allow", "allow_hosts", multiple=True)
@click.option("--deny", "deny_hosts", multiple=True)
@output_options
def network(
    render: Renderer,
    sandbox_id: str,
    default_action: str | None,
    allow_hosts: tuple[str, ...],
    deny_hosts: tuple[str, ...],
) -> None:
    """Show or update the egress policy."""
    client = build_sandbox_client()
    full_id = _resolve_id(client, sandbox_id)
    if default_action is None and not allow_hosts and not deny_hosts:
        policy = client.get_egress(full_id)
    else:
        current = client.get_egress(full_id)
        try:
            policy = client.set_egress(
                full_id,
                EgressPolicy(
                    default_action=default_action or current.default_action,
                    allow_hosts=list(allow_hosts) or current.allow_hosts,
                    deny_hosts=list(deny_hosts) or current.deny_hosts,
                ),
            )
        except ValueError as e:
            raise click.ClickException(str(e)) from None
    render.detail(policy.model_dump(by_alias=True), title="Egress policy")


@sandbox_group.command("expose")
@click.argument("sandbox_id")
@click.argument("port", type=int)
@click.option("--no-auth", is_flag=True, help="Expose without gateway auth.")
@output_options
def expose(render: Renderer, sandbox_id: str, port: int, no_auth: bool) -> None:
    client = build_sandbox_client()
    exposed = client.expose(_resolve_id(client, sandbox_id), port, auth_required=not no_auth)
    if render.is_json:
        render.json(exposed.model_dump(by_alias=True))
    else:
        render.message(f"Port {port} exposed at {exposed.url}")


@sandbox_group.command("unexpose")
@click.argument("sandbox_id")
@click.argument("port", type=int)
@output_options
def unexpose(render: Renderer, sandbox_id: str, port: int) -> None:
    client = build_sandbox_client()
    client.unexpose(_resolve_id(client, sandbox_id), port)
    render.message(f"Port {port} unexposed.")


@sandbox_group.command("list-ports")
@click.argument("sandbox_id")
@output_options
def list_ports(render: Renderer, sandbox_id: str) -> None:
    client = build_sandbox_client()
    ports = client.list_ports(_resolve_id(client, sandbox_id))
    render.table(
        ["PORT", "URL", "AUTH"],
        [[p.port, p.url, "yes" if p.auth_required else "no"] for p in ports],
        title="Exposed ports",
        json_rows=[p.model_dump(by_alias=True) for p in ports],
    )


@sandbox_group.command("reset-cache")
@output_options
def reset_cache(render: Renderer) -> None:
    """Clear the on-disk gateway auth-token cache."""
    SandboxAuthCache().clear()
    render.message("Sandbox auth cache cleared.")


@sandbox_group.command("ssh")
@click.argument("sandbox_id")
@output_options
def ssh_cmd(render: Renderer, sandbox_id: str) -> None:
    """SSH into a VM sandbox (mints short-lived credentials)."""
    import os
    import tempfile

    client = build_sandbox_client()
    session = client.create_ssh_session(_resolve_id(client, sandbox_id))
    fd, key_path = tempfile.mkstemp(prefix="prime-sbx-key-")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(session.private_key_pem)
        os.chmod(key_path, 0o600)
        args = [
            "ssh",
            "-i",
            key_path,
            "-o",
            "StrictHostKeyChecking=no",
            "-p",
            str(session.port),
            f"{session.username}@{session.host}",
        ]
        result = ssh_runner(args)
        if getattr(result, "returncode", 0) != 0:
            raise SystemExit(result.returncode)
    finally:
        os.unlink(key_path)
