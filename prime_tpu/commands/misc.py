"""`prime usage` / `prime upgrade` / `prime feedback` / `prime lab`.

Reference: commands/usage.py (per-run usage incl. --watch), upgrade.py:15-60
(install-method detection), feedback.py, lab.py (setup/doctor; the full
Textual TUI is gated behind the optional dependency).
"""

from __future__ import annotations

import sys
import time

import click

import prime_tpu.commands._deps as deps
from prime_tpu.utils.render import Renderer, output_options


@click.command("usage")
@click.option("--watch", "-w", is_flag=True, help="Refresh every few seconds.")
@click.option("--interval", type=float, default=5.0)
@click.option("--iterations", type=int, default=None, hidden=True)  # test hook
@output_options
def usage(render: Renderer, watch: bool, interval: float, iterations: int | None) -> None:
    """Show per-run token/cost usage."""
    count = 0
    while True:
        data = deps.build_client().get("/billing/usage")
        rows = data.get("items", []) if isinstance(data, dict) else data
        render.table(
            ["RUN", "TOKENS", "COST $"],
            [[r.get("runId", ""), r.get("tokens", 0), f"{r.get('costUsd', 0):.2f}"] for r in rows],
            title="Usage",
            json_rows=rows,
        )
        count += 1
        if not watch or (iterations is not None and count >= iterations):
            return
        time.sleep(interval)


def detect_install_method() -> str:
    """uv tool / pipx / pip / source checkout (reference upgrade.py:15-60)."""
    exe = sys.prefix
    if "uv/tools" in exe or "/uv/" in exe:
        return "uv-tool"
    if "pipx" in exe:
        return "pipx"
    import prime_tpu

    if "site-packages" not in (prime_tpu.__file__ or ""):
        return "source"
    return "pip"


@click.command("upgrade")
@output_options
def upgrade(render: Renderer) -> None:
    """Show how to upgrade prime-tpu for this install method."""
    method = detect_install_method()
    commands = {
        "uv-tool": "uv tool upgrade prime-tpu",
        "pipx": "pipx upgrade prime-tpu",
        "pip": f"{sys.executable} -m pip install --upgrade prime-tpu",
        "source": "git pull (source checkout)",
    }
    if render.is_json:
        render.json({"installMethod": method, "command": commands[method]})
    else:
        render.message(f"Install method: {method}")
        render.message(f"Upgrade with: {commands[method]}")


@click.command("feedback")
@click.argument("message", required=False)
@output_options
def feedback(render: Renderer, message: str | None) -> None:
    """Send feedback to the platform team."""
    if not message:
        message = click.prompt("Your feedback")
    deps.build_client().post("/feedback", json={"message": message}, idempotent_post=True)
    render.message("Thanks — feedback submitted.")


@click.group(name="lab", invoke_without_command=True)
@click.pass_context
def lab_group(ctx: click.Context) -> None:
    """Lab workspace: bare `prime lab` opens the interactive shell;
    subcommands cover setup, doctor, sync, and the one-shot dashboard."""
    if ctx.invoked_subcommand is None:
        ctx.invoke(lab_tui)


@lab_group.command("tui")
@click.option("--dir", "workspace", default=".", type=click.Path())
def lab_tui(workspace: str = ".") -> None:
    """Interactive three-pane Lab shell (nav / selector / inspector)."""
    from prime_tpu.lab.tui import open_shell

    try:
        open_shell(workspace, api_client=deps.build_client())
    except RuntimeError as e:
        raise click.ClickException(str(e)) from None


@lab_group.command("setup")
@click.option("--dir", "workspace", default=".", type=click.Path())
@click.option(
    "--agent", "agents", multiple=True, default=("claude", "codex"),
    help="Agent surface(s) to generate: claude, codex, cursor, gemini, windsurf (repeatable).",
)
@click.option("--force-skills", is_flag=True, help="Overwrite bundled skill docs.")
@output_options
def lab_setup(render: Renderer, workspace: str, agents: tuple[str, ...], force_skills: bool) -> None:
    """Bootstrap a Lab workspace: config, versioned skill bundle, agent
    surface matrix (guide + MCP registration per flavor), chat-agent config,
    gitignore hygiene, and a hygiene preflight."""
    from prime_tpu.lab.setup import setup_workspace

    try:
        report = setup_workspace(workspace, agents=tuple(agents), force_skills=force_skills)
    except ValueError as e:
        raise click.ClickException(str(e)) from None
    if render.is_json:
        render.json(report.as_dict())
        return
    for path in report.created:
        render.message(f"  created {path}")
    for path in report.updated:
        render.message(f"  updated {path}")
    for note in report.skipped:
        render.message(f"  skipped {note}")
    for finding in report.hygiene:
        render.message(f"  [{finding['severity']}] {finding['code']}: {finding['message']}")
    render.message(
        f"Lab workspace ready ({len(report.created)} created, {len(report.updated)} updated"
        + (f", {len(report.skipped)} skipped" if report.skipped else "")
        + "). Run `prime lab` for the shell."
    )


@lab_group.command("mcp")
@click.option("--dir", "workspace", default=".", type=click.Path(exists=True, file_okay=False))
def lab_mcp(workspace: str) -> None:
    """Run the stdio MCP server exposing Lab tools (for agent clients)."""
    from prime_tpu.lab.mcp import serve

    serve(workspace)


@lab_group.command("agent")
@click.argument("prompt_text", metavar="PROMPT")
@click.option("--command", "agent_command", required=True,
              help="Agent server command line (spawned as a subprocess).")
@click.option(
    "--dialect", type=click.Choice(["simple", "acp", "codex", "letta"]), default="acp"
)
@click.option("--timeout", "timeout_s", type=float, default=120.0)
def lab_agent(prompt_text: str, agent_command: str, dialect: str, timeout_s: float) -> None:
    """One chat turn against a stdio agent (ACP / Codex app-server / Letta /
    simple JSONL dialect). Widget tool calls print as [widget:NAME] lines."""
    import shlex

    from prime_tpu.lab.agents import AgentError, AgentRuntime

    runtime = AgentRuntime(shlex.split(agent_command), dialect=dialect)
    try:
        with runtime:
            for event in runtime.prompt(prompt_text, timeout_s=timeout_s):
                if event.kind == "widget" and event.widget:
                    click.echo(f"\n[widget:{event.widget['name']}] {event.widget['args']}")
                else:
                    click.echo(event.text, nl=False)
        click.echo()
    except AgentError as e:
        raise click.ClickException(str(e)) from None


@lab_group.command("hygiene")
@click.option("--dir", "workspace", default=".", type=click.Path())
@click.option("--fix", "do_fix", is_flag=True, help="Append gitignore entries for fixable findings.")
@output_options
def lab_hygiene(render: Renderer, workspace: str, do_fix: bool) -> None:
    """Preflight the workspace for leaks: secrets, outputs, oversized files."""
    from prime_tpu.lab.hygiene import apply_fixes, check_workspace

    try:
        findings = check_workspace(workspace)
        fixed: list[str] = []
        if do_fix:
            fixed = apply_fixes(workspace, findings)
            findings = check_workspace(workspace)  # re-check after fixes
    except FileNotFoundError as e:
        raise click.ClickException(str(e)) from None
    if render.is_json:
        render.json({"findings": [f.as_dict() for f in findings], "fixed": fixed})
    else:
        for entry in fixed:
            render.message(f"  ignored {entry}")
        if not findings:
            render.message("hygiene: clean")
        for f in findings:
            render.message(f"  [{f.severity}] {f.code}: {f.message}")
    if any(f.severity == "error" for f in findings):
        raise SystemExit(1)


@lab_group.command("register-github")
@click.option("--dir", "workspace", default=".", type=click.Path())
@output_options
def lab_register_github(render: Renderer, workspace: str) -> None:
    """Write a GitHub Actions workflow that runs the Lab hygiene preflight
    on every push/PR (reference commands/lab.py:106-113)."""
    from prime_tpu.lab.hygiene import write_github_workflow

    try:
        path = write_github_workflow(workspace)
    except OSError as e:
        raise click.ClickException(str(e)) from None
    if render.is_json:
        render.json({"path": str(path)})
    else:
        render.message(f"Wrote {path}")


@lab_group.command("doctor")
@output_options
def lab_doctor(render: Renderer) -> None:
    """Check the local environment for Lab prerequisites."""
    import importlib.util
    from pathlib import Path

    checks = {
        "config": deps.build_config().config_file.exists(),
        "api_key": bool(deps.build_config().api_key),
        "workspace": Path(".prime-lab/lab.toml").exists(),
        "textual": importlib.util.find_spec("textual") is not None,
        "jax": importlib.util.find_spec("jax") is not None,
    }
    render.table(
        ["CHECK", "OK"],
        [[name, "yes" if ok else "NO"] for name, ok in checks.items()],
        title="Lab doctor",
        json_rows=checks,
    )


@lab_group.command("sync")
@output_options
def lab_sync(render: Renderer) -> None:
    """Refresh the Lab cache from the platform."""
    from prime_tpu.lab import LabDataSource

    snap = LabDataSource().refresh()
    counts = {section: len(rows) for section, rows in snap.platform.items()}
    for section, error in snap.errors.items():
        click.echo(f"warning: {section} failed to sync: {error}", err=True)
    if render.is_json:
        render.json({"counts": counts, "errors": snap.errors})
    else:
        render.message("Synced: " + ", ".join(f"{k}={v}" for k, v in counts.items()))
    if snap.errors and len(snap.errors) == len(counts):
        raise SystemExit(1)  # every section failed — that's not a sync


@lab_group.command("view")
@click.option("--refresh/--cached", default=True, help="Hydrate from the platform first.")
def lab_view(refresh: bool) -> None:
    """Render the Lab dashboard (one-shot snapshot; full TUI needs `textual`)."""
    from rich.console import Console
    from rich.panel import Panel
    from rich.table import Table

    from prime_tpu.lab import LabDataSource

    source = LabDataSource()
    # hydrate only the sections the dashboard renders
    snap = source.refresh(sections=("evals", "training", "pods")) if refresh else source.snapshot()
    for section, error in snap.errors.items():
        click.echo(f"warning: {section} failed to refresh: {error}", err=True)
    console = Console()

    def section_table(title, columns, rows, stale):
        table = Table(title=title + (" (stale)" if stale else ""), expand=True)
        for col in columns:
            table.add_column(col)
        for row in rows[:12]:
            table.add_row(*(str(v) if v is not None else "" for v in row))
        return table

    console.print(
        Panel(
            f"local eval runs: {len(snap.local_eval_runs)}   "
            f"installed envs: {len(snap.installed_envs)}",
            title="prime lab",
        )
    )
    console.print(
        section_table(
            "Evaluations",
            ["id", "model", "status", "accuracy"],
            [
                [e.get("evalId"), e.get("model"), e.get("status"), e.get("metrics", {}).get("accuracy")]
                for e in snap.platform["evals"]
            ],
            not snap.freshness["evals"],
        )
    )
    console.print(
        section_table(
            "Training runs",
            ["id", "name", "status", "tpu"],
            [
                [r.get("runId"), r.get("name"), r.get("status"), r.get("tpuType")]
                for r in snap.platform["training"]
            ],
            not snap.freshness["training"],
        )
    )
    console.print(
        section_table(
            "Pods",
            ["id", "slice", "status"],
            [[p.get("podId"), p.get("sliceName"), p.get("status")] for p in snap.platform["pods"]],
            not snap.freshness["pods"],
        )
    )
    if snap.local_eval_runs:
        console.print(
            section_table(
                "Local eval runs",
                ["env", "model", "accuracy", "samples"],
                [
                    [r["env"], r["model"], r.get("accuracy"), r.get("samples")]
                    for r in snap.local_eval_runs
                ],
                False,
            )
        )
