"""`prime` CLI entry point.

Command groups are assembled here in three help panels mirroring the reference
(prime_cli/main.py:36-84): Lab, Compute, Account. Subcommand modules register
lazily to keep CLI startup fast (the reference enforces this with a startup
test, tests/test_windows_cli.py:6-40).
"""

from __future__ import annotations

import click

import prime_tpu


@click.group(name="prime")
@click.version_option(prime_tpu.__version__, prog_name="prime-tpu")
@click.option(
    "--context",
    default=None,
    envvar="PRIME_CONTEXT",
    help="Use a named config context for this invocation.",
)
@click.pass_context
def cli(ctx: click.Context, context: str | None) -> None:
    """prime — TPU-native compute platform CLI."""
    ctx.ensure_object(dict)
    ctx.obj["context"] = context
    if context:
        import os

        os.environ["PRIME_CONTEXT"] = context


def main() -> None:  # pragma: no cover - exercised via subprocess
    cli(prog_name="prime")


if __name__ == "__main__":  # pragma: no cover
    main()
