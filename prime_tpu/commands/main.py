"""`prime` CLI entry point.

Command groups are assembled into three help panels mirroring the reference
(prime_cli/main.py:36-84): Lab, Compute, Account. Subcommand modules load
lazily so CLI startup stays fast — the reference enforces the same contract
with a startup test (tests/test_windows_cli.py:6-40); ours asserts `--help`
never imports jax or the SDK heavyweights (tests/test_cli.py).
"""

from __future__ import annotations

import importlib
import os

import click

import prime_tpu
from prime_tpu.core.config import env_flag

# command name → (module, attribute). Modules import only on dispatch.
_LAZY_COMMANDS: dict[str, tuple[str, str]] = {
    # Compute
    "availability": ("prime_tpu.commands.availability", "availability_group"),
    "pods": ("prime_tpu.commands.pods", "pods_group"),
    "disks": ("prime_tpu.commands.disks", "disks_group"),
    "sandbox": ("prime_tpu.commands.sandbox", "sandbox_group"),
    "tunnel": ("prime_tpu.commands.tunnel", "tunnel_group"),
    "images": ("prime_tpu.commands.images", "images_group"),
    "registry": ("prime_tpu.commands.images", "registry_group"),
    "inference": ("prime_tpu.commands.inference", "inference_group"),
    "serve": ("prime_tpu.commands.serve", "serve_cmd"),
    "bench": ("prime_tpu.commands.bench", "bench_group"),
    # Lab
    "env": ("prime_tpu.commands.env", "env_group"),
    "eval": ("prime_tpu.commands.evals", "eval_group"),
    "train": ("prime_tpu.commands.train", "train_group"),
    "rl": ("prime_tpu.commands.train", "train_group"),
    "lab": ("prime_tpu.commands.misc", "lab_group"),
    "deployments": ("prime_tpu.commands.deployments", "deployments_group"),
    "fork": ("prime_tpu.commands.gepa_fork", "fork"),
    "gepa": ("prime_tpu.commands.gepa_fork", "gepa"),
    # Account
    "login": ("prime_tpu.commands.login", "login"),
    "logout": ("prime_tpu.commands.login", "logout"),
    "whoami": ("prime_tpu.commands.account", "whoami"),
    "teams": ("prime_tpu.commands.account", "teams_group"),
    "switch": ("prime_tpu.commands.account", "switch_cmd"),
    "config": ("prime_tpu.commands.config_cmd", "config_group"),
    "wallet": ("prime_tpu.commands.account", "wallet"),
    "usage": ("prime_tpu.commands.misc", "usage"),
    "secrets": ("prime_tpu.commands.secrets", "secrets_group"),
    "upgrade": ("prime_tpu.commands.misc", "upgrade"),
    "feedback": ("prime_tpu.commands.misc", "feedback"),
}


class LazyGroup(click.Group):
    def list_commands(self, ctx: click.Context) -> list[str]:
        return sorted(_LAZY_COMMANDS)

    def get_command(self, ctx: click.Context, name: str) -> click.Command | None:
        spec = _LAZY_COMMANDS.get(name)
        if spec is None:
            return None
        module_name, attr = spec
        try:
            module = importlib.import_module(module_name)
        except ModuleNotFoundError as e:
            if e.name == module_name:
                return None  # subcommand module not built yet
            raise  # a real dependency is missing — surface it, don't mask as "no such command"
        return getattr(module, attr)

    def invoke(self, ctx: click.Context):
        # Backend errors must never reach the user as tracebacks.
        from prime_tpu.core.exceptions import APIError, ValidationError

        try:
            return super().invoke(ctx)
        except ValidationError as e:
            fields = "; ".join(e.field_messages())
            raise click.ClickException(f"{e.message}" + (f" ({fields})" if fields else "")) from e
        except APIError as e:
            raise click.ClickException(e.message) from e


@click.group(name="prime", cls=LazyGroup)
@click.version_option(prime_tpu.__version__, prog_name="prime-tpu")
@click.option(
    "--context",
    default=None,
    help="Use a named config context for this invocation.",
)
def cli(context: str | None) -> None:
    """prime — TPU-native compute platform CLI.

    Compute: availability, pods, disks, sandbox, tunnel, images, inference.
    Lab: env, eval, train/rl, deployments, lab.
    Account: login, whoami, teams, config, wallet, secrets.

    Tip for scripts and AI agents: pass --plain or --output json.
    """
    if context:
        os.environ["PRIME_CONTEXT"] = context
    if not env_flag("PRIME_DISABLE_VERSION_CHECK", False):
        from prime_tpu.utils.version_check import check_for_update

        newer = check_for_update(prime_tpu.__version__)
        if newer:
            click.echo(
                f"A newer prime-tpu is available ({newer} > {prime_tpu.__version__}); "
                "run `prime upgrade` for instructions.",
                err=True,
            )


def main() -> None:  # pragma: no cover - exercised via subprocess
    cli(prog_name="prime")


if __name__ == "__main__":  # pragma: no cover
    main()
