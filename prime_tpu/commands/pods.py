"""`prime pods` — TPU slice VM lifecycle.

Reference surface: prime_cli/commands/pods.py:401 (interactive create wizard,
``--yes`` bypass), :1048 (connect: poll for SSH then exec ssh), :1096-1110
(multi-node picker). TPU-native: the wizard walks generation → slice size →
offer (price-sorted), and ``connect`` offers a per-host worker picker for
multi-host slices (every TPU VM worker is individually SSH-able).
"""

from __future__ import annotations

import subprocess
import time

import click

from prime_tpu.api.availability import AvailabilityClient
from prime_tpu.api.pods import CreatePodRequest, PodsClient
from prime_tpu.commands._deps import build_client, build_config
from prime_tpu.parallel.topology import list_slice_names, parse_slice
from prime_tpu.utils import prompt
from prime_tpu.utils.render import Renderer, output_options
from prime_tpu.utils.short_id import resolve, shorten

# TPU VM runtime images, newest first (the wizard's runtime step)
DEFAULT_RUNTIMES = ("tpu-ubuntu2204-base", "v2-alpha-tpuv5-lite", "v2-alpha-tpuv5")

# Injection point for tests (no real ssh in CI).
ssh_runner = subprocess.run

POLL_INTERVAL_S = 5.0
CONNECT_WAIT_ATTEMPTS = 60


@click.group(name="pods")
def pods_group() -> None:
    """Create, inspect, and connect to TPU slice pods."""


def _resolve_pod_id(client: PodsClient, pod_id: str) -> str:
    ids = [p.pod_id for p in client.list()]
    try:
        return resolve(pod_id, ids)
    except ValueError as e:
        raise click.ClickException(str(e)) from None


def _pod_rows(pods: list) -> list[list]:
    return [
        [
            shorten(p.pod_id),
            p.name,
            p.slice_name,
            p.hosts,
            p.ici_topology,
            p.status,
            p.provider,
            p.region,
            f"{p.price_hourly:.2f}" if p.price_hourly is not None else "",
        ]
        for p in pods
    ]


_POD_COLUMNS = ["ID", "NAME", "SLICE", "HOSTS", "ICI", "STATUS", "PROVIDER", "REGION", "$/HR"]


@pods_group.command("list")
@output_options
def list_pods(render: Renderer) -> None:
    """List running pods."""
    pods = PodsClient(build_client()).list()
    render.table(
        _POD_COLUMNS,
        _pod_rows(pods),
        title="Pods",
        json_rows=[p.model_dump(by_alias=True) for p in pods],
    )


@pods_group.command("history")
@output_options
def history(render: Renderer) -> None:
    """List terminated pods."""
    pods = PodsClient(build_client()).history()
    render.table(
        _POD_COLUMNS,
        _pod_rows(pods),
        title="Pod history",
        json_rows=[p.model_dump(by_alias=True) for p in pods],
    )


@pods_group.command("get")
@click.argument("pod_id")
@output_options
def get_pod(render: Renderer, pod_id: str) -> None:
    """Show a pod's full metadata."""
    client = PodsClient(build_client())
    pod = client.get(_resolve_pod_id(client, pod_id))
    render.detail(pod.model_dump(by_alias=True), title=f"Pod {shorten(pod.pod_id)}")


@pods_group.command("status")
@click.argument("pod_id")
@output_options
def status(render: Renderer, pod_id: str) -> None:
    """Show a pod's provisioning status and SSH endpoints."""
    client = PodsClient(build_client())
    st = client.get_status(_resolve_pod_id(client, pod_id))
    render.detail(st.model_dump(by_alias=True), title=f"Status {shorten(st.pod_id)}")


@pods_group.command("terminate")
@click.argument("pod_id")
@click.option("--yes", "-y", is_flag=True, help="Skip the confirmation prompt.")
@output_options
def terminate(render: Renderer, pod_id: str, yes: bool) -> None:
    """Terminate a pod."""
    client = PodsClient(build_client())
    full_id = _resolve_pod_id(client, pod_id)
    if not yes and not click.confirm(f"Terminate pod {shorten(full_id)}?"):
        render.message("Aborted.")
        return
    client.terminate(full_id)
    if render.is_json:
        render.json({"podId": full_id, "status": "TERMINATED"})
    else:
        render.message(f"Pod {shorten(full_id)} terminated.")


@pods_group.command("create")
@click.option("--name", default=None, help="Pod name (generated when omitted).")
@click.option("--slice", "slice_name", default=None, help="TPU slice, e.g. v5e-8.")
@click.option("--provider", default=None)
@click.option("--region", default=None)
@click.option("--runtime-version", default=None, help="TPU VM runtime image.")
@click.option("--disk-size-gib", type=int, default=None)
@click.option("--spot", is_flag=True, default=False)
@click.option("--yes", "-y", is_flag=True, help="Skip confirmation (non-interactive).")
@output_options
def create(
    render: Renderer,
    name: str | None,
    slice_name: str | None,
    provider: str | None,
    region: str | None,
    runtime_version: str | None,
    disk_size_gib: int | None,
    spot: bool,
    yes: bool,
) -> None:
    """Create a TPU slice pod (interactive wizard unless --slice is given)."""
    api = build_client()
    avail = AvailabilityClient(api)

    wizard = slice_name is None
    if slice_name is None:
        # Wizard (reference pods.py:401-780 shape, TPU-flavored):
        # generation → slice size → offer by price → runtime → disk.
        gen_row = prompt.pick(
            "TPU generations",
            avail.list_tpu_types(),
            describe=lambda t: (
                f"{t['tpuType']}  ({t['minChips']}-{t['maxChips']} chips, "
                f"from ${t['minPriceHourly']:.2f}/hr)"
            ),
            assume_default=yes,
            prompt="Select generation",
        )
        slice_name = prompt.pick(
            "Slice sizes",
            list_slice_names(gen_row["tpuType"]),
            describe=lambda s: (
                lambda sp: f"{s}  ({sp.chips} chips, {sp.hosts} host(s), ICI {sp.topology})"
            )(parse_slice(s)),
            assume_default=yes,
            prompt="Select slice",
        )

    try:
        spec = parse_slice(slice_name)
    except ValueError as e:
        raise click.ClickException(str(e)) from None

    offer = None
    if provider is None or region is None:
        # spot is always a concrete bool here: on-demand users must never be
        # auto-matched to a cheaper preemptible offer by the price sort.
        offers = avail.list_tpus(tpu_type=spec.generation.value, spot=spot)
        offers = [o for o in offers if o.slice_name == spec.name and o.stock_status != "unavailable"]
        if region:
            offers = [o for o in offers if o.region == region]
        if not offers:
            raise click.ClickException(f"No available offers for {spec.name}")
        offers.sort(key=lambda o: o.price_hourly)
        offer = prompt.pick(
            "Offers (price-sorted)",
            offers,
            describe=lambda o: (
                f"{o.provider}/{o.region}  ${o.price_hourly:.2f}/hr"
                f"{'  [spot]' if o.spot else ''}"
            ),
            assume_default=yes,
            prompt="Select offer",
        )
        provider, region = offer.provider, offer.region

    # only the wizard asks follow-ups: a fully-specified `create --slice ...`
    # must keep reading exactly one confirm from stdin, as before
    if wizard and not yes:
        if runtime_version is None:
            runtime_version = prompt.pick(
                "TPU runtime (VM image)",
                list(DEFAULT_RUNTIMES),
                prompt="Select runtime",
            )
        if disk_size_gib is None:
            disk_size_gib = prompt.prompt_int("Boot disk GiB", default=100, minimum=20, maximum=3000)

    name = name or f"{spec.name}-{int(time.time()) % 100000}"
    summary = (
        f"{spec.name} ({spec.chips} chips / {spec.hosts} host(s), ICI {spec.topology}) "
        f"on {provider}/{region}{' [spot]' if spot else ''}"
    )
    if not yes and not click.confirm(f"Create pod '{name}': {summary}?", default=True):
        render.message("Aborted.")
        return

    pod = PodsClient(api).create(
        CreatePodRequest(
            name=name,
            slice_name=spec.name,
            offer_id=offer.offer_id if offer else None,
            provider=provider,
            region=region,
            runtime_version=runtime_version,
            disk_size_gib=disk_size_gib,
            spot=spot,
        )
    )
    if render.is_json:
        render.json(pod.model_dump(by_alias=True))
    else:
        render.message(f"Pod {shorten(pod.pod_id)} ({pod.name}) created: {pod.status}")
        render.message(f"Track it with: prime pods status {shorten(pod.pod_id)}")


@pods_group.command("connect")
@click.argument("pod_id")
@click.option("--worker", type=int, default=None, help="Worker host index for multi-host slices.")
@click.option("--command", "remote_command", default=None, help="Run a command instead of a shell.")
@click.option("--all-workers", is_flag=True, help="Run --command on every worker host (SPMD fan-out).")
@output_options
def connect(
    render: Renderer,
    pod_id: str,
    worker: int | None,
    remote_command: str | None,
    all_workers: bool,
) -> None:
    """SSH into a pod (waits for it to become reachable first)."""
    config = build_config()
    client = PodsClient(build_client(config))
    full_id = _resolve_pod_id(client, pod_id)

    ssh_connections = None
    for _ in range(CONNECT_WAIT_ATTEMPTS):
        st = client.get_status(full_id)
        if st.status in ("ERROR", "TERMINATED"):
            raise click.ClickException(f"Pod is {st.status}" + (f": {st.installation_failure}" if st.installation_failure else ""))
        if st.ssh_connections:
            ssh_connections = st.ssh_connections
            break
        render.message(f"Pod {shorten(full_id)} is {st.status}; waiting for SSH...")
        time.sleep(POLL_INTERVAL_S)
    if not ssh_connections:
        raise click.ClickException("Timed out waiting for the pod to become reachable.")

    if all_workers:
        if not remote_command:
            raise click.ClickException("--all-workers requires --command (SPMD fan-out runs the same command on every worker).")
        targets = list(enumerate(ssh_connections))
    elif len(ssh_connections) > 1 and worker is None:
        click.echo(f"Slice spans {len(ssh_connections)} worker hosts:")
        for i, conn in enumerate(ssh_connections):
            click.echo(f"  {i}. {conn}")
        worker = click.prompt("Select worker", type=click.IntRange(0, len(ssh_connections) - 1), default=0)
        targets = [(worker, ssh_connections[worker])]
    else:
        w = worker or 0
        if w >= len(ssh_connections):
            raise click.ClickException(f"Worker {w} out of range (slice has {len(ssh_connections)} hosts)")
        targets = [(w, ssh_connections[w])]

    failures: list[tuple[int, int]] = []
    for idx, conn in targets:
        user_host, _, port = conn.partition(":")
        args = [
            "ssh",
            "-i",
            config.ssh_key_path,
            "-o",
            "StrictHostKeyChecking=no",
            "-p",
            port or "22",
            user_host,
        ]
        if remote_command:
            args.append(remote_command)
        if len(targets) > 1:
            render.message(f"[worker {idx}] {conn}")
        result = ssh_runner(args)
        rc = getattr(result, "returncode", 0)
        if rc != 0:
            failures.append((idx, rc))
    if failures:
        if len(targets) > 1:
            detail = ", ".join(f"worker {i} rc={rc}" for i, rc in failures)
            render.message(f"SPMD fan-out failed on {len(failures)}/{len(targets)} workers: {detail}", err=True)
        raise SystemExit(failures[0][1])


@pods_group.command("ssh", hidden=True)
@click.argument("pod_id")
@click.pass_context
def ssh_alias(ctx: click.Context, pod_id: str) -> None:
    """Alias for connect."""
    ctx.invoke(connect, pod_id=pod_id, worker=None, remote_command=None, all_workers=False, plain=False, output="table")
