"""`prime config` — view/set config + named context management.

Reference surface: prime_cli/commands/config.py (view/set-api-key/set-team-id/
set-ssh-key-path/set-base-url/envs save/use/delete).
"""

from __future__ import annotations

import click

from prime_tpu.commands._deps import build_config
from prime_tpu.core.config import InvalidContextName
from prime_tpu.utils.render import Renderer, output_options


@click.group(name="config")
def config_group() -> None:
    """View and edit CLI configuration."""


@config_group.command("view")
@output_options
def view(render: Renderer) -> None:
    """Show the effective configuration (env overrides applied, key masked)."""
    render.detail(build_config().view(), title="Configuration")


def _set(field: str, value: str) -> None:
    cfg = build_config()
    setattr(cfg, field, value)
    cfg.save()
    click.echo(f"{field} updated.")


@config_group.command("set-api-key")
@click.argument("value", required=False)
def set_api_key(value: str | None) -> None:
    """Set the API key (prompts with hidden input when omitted)."""
    if value is None:
        value = click.prompt("API key", hide_input=True)
    _set("api_key", value)


@config_group.command("set-team-id")
@click.argument("value")
def set_team_id(value: str) -> None:
    _set("team_id", value)


@config_group.command("set-base-url")
@click.argument("value")
def set_base_url(value: str) -> None:
    _set("base_url", value)


@config_group.command("set-inference-url")
@click.argument("value")
def set_inference_url(value: str) -> None:
    _set("inference_url", value)


@config_group.command("set-frontend-url")
@click.argument("value")
def set_frontend_url(value: str) -> None:
    _set("frontend_url", value)


@config_group.command("remove-team-id")
def remove_team_id() -> None:
    """Clear the active team (back to personal scope)."""
    _set("team_id", "")


@config_group.command("set-share-resources-with-team")
@click.argument("enabled", type=click.Choice(["true", "false"]))
def set_share_resources_with_team(enabled: str) -> None:
    """Auto-share newly created resources with all team members."""
    cfg = build_config()
    cfg.share_resources_with_team = enabled == "true"
    cfg.save()
    click.echo(f"Share resources with team set to: {enabled}")


@config_group.command("reset")
@click.option("--yes", "-y", is_flag=True, help="Skip the confirmation prompt.")
def reset_cmd(yes: bool) -> None:
    """Reset configuration to defaults (reference commands/config.py reset)."""
    if not yes and not click.confirm("Reset all settings to defaults?"):
        click.echo("Aborted.")
        return
    cfg = build_config()
    # a fresh ConfigModel: EVERY field resets (user_id, ssh_key_path, and
    # any field added later included), no hand-maintained list to drift
    cfg.reset()
    cfg.save()
    click.echo("Configuration reset to defaults.")


@config_group.command("set-ssh-key-path")
@click.argument("value", type=click.Path())
def set_ssh_key_path(value: str) -> None:
    _set("ssh_key_path", value)


@config_group.group("envs")
def envs_group() -> None:
    """Manage named config contexts."""


@envs_group.command("save")
@click.argument("name")
def envs_save(name: str) -> None:
    """Save the current config as a named context."""
    try:
        path = build_config().save_context(name)
    except InvalidContextName as e:
        raise click.ClickException(str(e)) from None
    click.echo(f"Context '{name}' saved to {path}")


@envs_group.command("use")
@click.argument("name")
def envs_use(name: str) -> None:
    """Switch the active config to a named context."""
    try:
        build_config().use_context(name)
    except (FileNotFoundError, InvalidContextName) as e:
        raise click.ClickException(str(e)) from None
    click.echo(f"Switched to context '{name}'")


@envs_group.command("delete")
@click.argument("name")
def envs_delete(name: str) -> None:
    try:
        deleted = build_config().delete_context(name)
    except InvalidContextName as e:
        raise click.ClickException(str(e)) from None
    click.echo(f"Context '{name}' deleted." if deleted else f"No context named '{name}'.")


@envs_group.command("list")
@output_options
def envs_list(render: Renderer) -> None:
    contexts = build_config().list_contexts()
    render.table(["CONTEXT"], [[c] for c in contexts], title="Contexts", json_rows=contexts)
