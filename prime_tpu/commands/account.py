"""`prime whoami` / `prime teams` / `prime wallet` — identity + billing.

Reference surface: prime_cli/commands/{whoami,teams,switch,wallet}.py.
"""

from __future__ import annotations

import click

from prime_tpu.commands._deps import build_client, build_config
from prime_tpu.utils.render import Renderer, output_options


@click.command("whoami")
@output_options
def whoami(render: Renderer) -> None:
    """Show the authenticated identity."""
    info = build_client().get("/user/whoami")
    cfg = build_config()
    info["teamId"] = cfg.team_id or None
    render.detail(info, title="Identity")


@click.group(name="teams")
def teams_group() -> None:
    """List and switch teams."""


@teams_group.command("list")
@output_options
def teams_list(render: Renderer) -> None:
    teams = build_client().get("/teams")
    cfg = build_config()
    render.table(
        ["TEAM ID", "NAME", "ACTIVE"],
        [[t["teamId"], t["name"], "*" if t["teamId"] == cfg.team_id else ""] for t in teams],
        title="Teams",
        json_rows=teams,
    )


@teams_group.command("switch")
@click.argument("team_id", required=False)
def teams_switch(team_id: str | None) -> None:
    """Switch the active team (pass no argument for personal scope)."""
    cfg = build_config()
    cfg.team_id = team_id or ""
    cfg.save()
    click.echo(f"Active team: {team_id or '(personal)'}")


@click.command("switch")
@click.argument("target", required=False)
def switch_cmd(target: str | None) -> None:
    """Switch between your personal account and team contexts.

    TARGET is a team slug, a team id, or 'personal'; omit it to pick
    interactively (reference commands/switch.py)."""
    cfg = build_config()

    def go_personal() -> None:
        cfg.team_id = ""
        cfg.save()
        click.echo("Switched to personal account.")

    if target and target.strip().lower() == "personal":
        go_personal()
        return
    teams = build_client().get("/teams")
    if target:
        wanted = target.strip().lower()
        match = next(
            (
                t
                for t in teams
                if str(t.get("slug", "")).strip().lower() == wanted
                or str(t.get("teamId", "")).strip().lower() == wanted
            ),
            None,
        )
        if match is None:
            slugs = sorted(str(t.get("slug") or t["teamId"]) for t in teams)
            raise click.ClickException(
                f"No team matches {target!r}. Available: {', '.join(slugs)} "
                "(or 'personal')"
            )
    else:
        if not teams:
            raise click.ClickException("No teams available — you are on your personal account")
        for index, team in enumerate(teams, 1):
            marker = "*" if team["teamId"] == cfg.team_id else " "
            click.echo(f" {marker} {index}. {team['name']} ({team.get('slug', team['teamId'])})")
        choice = click.prompt(
            "Team number (0 for personal)", type=click.IntRange(0, len(teams))
        )
        if choice == 0:
            go_personal()
            return
        match = teams[choice - 1]
    cfg.team_id = match["teamId"]
    cfg.save()
    click.echo(f"Switched to team '{match['name']}'.")


@click.command("wallet")
@output_options
def wallet(render: Renderer) -> None:
    """Show wallet balance."""
    render.detail(build_client().get("/wallet"), title="Wallet")
