"""`prime whoami` / `prime teams` / `prime wallet` — identity + billing.

Reference surface: prime_cli/commands/{whoami,teams,switch,wallet}.py.
"""

from __future__ import annotations

import click

from prime_tpu.commands._deps import build_client, build_config
from prime_tpu.utils.render import Renderer, output_options


@click.command("whoami")
@output_options
def whoami(render: Renderer) -> None:
    """Show the authenticated identity."""
    info = build_client().get("/user/whoami")
    cfg = build_config()
    info["teamId"] = cfg.team_id or None
    render.detail(info, title="Identity")


@click.group(name="teams")
def teams_group() -> None:
    """List and switch teams."""


@teams_group.command("list")
@output_options
def teams_list(render: Renderer) -> None:
    teams = build_client().get("/teams")
    cfg = build_config()
    render.table(
        ["TEAM ID", "NAME", "ACTIVE"],
        [[t["teamId"], t["name"], "*" if t["teamId"] == cfg.team_id else ""] for t in teams],
        title="Teams",
        json_rows=teams,
    )


@teams_group.command("switch")
@click.argument("team_id", required=False)
def teams_switch(team_id: str | None) -> None:
    """Switch the active team (pass no argument for personal scope)."""
    cfg = build_config()
    cfg.team_id = team_id or ""
    cfg.save()
    click.echo(f"Active team: {team_id or '(personal)'}")


@click.command("wallet")
@output_options
def wallet(render: Renderer) -> None:
    """Show wallet balance."""
    render.detail(build_client().get("/wallet"), title="Wallet")
