"""`prime availability` — TPU slice / disk capacity queries.

Reference surface: prime_cli/commands/availability.py (gpu-types/list/disks
tables with short IDs), re-keyed on TPU slices.
"""

from __future__ import annotations

import click

from prime_tpu.api.availability import AvailabilityClient
from prime_tpu.commands._deps import build_client
from prime_tpu.utils.render import Renderer, output_options
from prime_tpu.utils.short_id import shorten


@click.group(name="availability")
def availability_group() -> None:
    """Query available TPU slices, generations, and disks."""


@availability_group.command("tpu-types")
@output_options
def tpu_types(render: Renderer) -> None:
    """List TPU generations with size and price ranges."""
    rows = AvailabilityClient(build_client()).list_tpu_types()
    render.table(
        ["TPU TYPE", "MIN CHIPS", "MAX CHIPS", "FROM $/HR", "PROVIDERS"],
        [
            [r["tpuType"], r["minChips"], r["maxChips"], f"{r['minPriceHourly']:.2f}", ",".join(r["providers"])]
            for r in rows
        ],
        title="TPU generations",
        json_rows=rows,
    )


@availability_group.command("list")
@click.option("--tpu-type", default=None, help="Filter by generation (v4, v5e, v5p, v6e).")
@click.option("--min-chips", type=int, default=None, help="Minimum chips in the slice.")
@click.option("--region", default=None)
@click.option("--provider", default=None)
@click.option("--spot/--on-demand", "spot", default=None, help="Only spot / only on-demand offers.")
@click.option("--multi-host/--single-host", "multi_host", default=None)
@output_options
def list_offers(
    render: Renderer,
    tpu_type: str | None,
    min_chips: int | None,
    region: str | None,
    provider: str | None,
    spot: bool | None,
    multi_host: bool | None,
) -> None:
    """List rentable TPU slice offers (sorted by generation, size, price)."""
    offers = AvailabilityClient(build_client()).list_tpus(
        tpu_type=tpu_type,
        min_chips=min_chips,
        region=region,
        provider=provider,
        spot=spot,
        multi_host=multi_host,
    )
    render.table(
        ["ID", "SLICE", "CHIPS", "HOSTS", "ICI", "PROVIDER", "REGION", "$/HR", "SPOT", "STOCK"],
        [
            [
                shorten(o.offer_id),
                o.slice_name,
                o.chips,
                o.hosts,
                o.ici_topology,
                o.provider,
                o.region,
                f"{o.price_hourly:.2f}",
                "yes" if o.spot else "",
                o.stock_status,
            ]
            for o in offers
        ],
        title="TPU slice offers",
        json_rows=[o.model_dump(by_alias=True) for o in offers],
    )


@availability_group.command("disks")
@click.option("--region", default=None)
@click.option("--provider", default=None)
@output_options
def disks(render: Renderer, region: str | None, provider: str | None) -> None:
    """List available persistent disk configurations."""
    rows = AvailabilityClient(build_client()).list_disks(region=region, provider=provider)
    render.table(
        ["PROVIDER", "REGION", "TYPE", "MIN GiB", "MAX GiB", "$/GiB-MO"],
        [
            [d.provider, d.region, d.disk_type, d.min_size_gib, d.max_size_gib, f"{d.price_gib_month:.2f}"]
            for d in rows
        ],
        title="Disk availability",
        json_rows=[d.model_dump(by_alias=True) for d in rows],
    )
