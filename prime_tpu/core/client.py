"""Sync + async HTTP transport clients with typed errors and tiered retries.

This merges the reference's two client variants into one good client
(SURVEY.md §7 stage 1): the CLI client's /api/v1 prefixing + bearer auth +
status→exception mapping (prime_cli/core/client.py:70,206,17-67) and the
sandboxes SDK client's idempotency-aware retry tiers
(prime_sandboxes/core/client.py:35,76,106-193):

- idempotent verbs (GET/HEAD/PUT/DELETE) retry on connection errors, read
  errors, and retryable 5xx ({500,502,503,504,524});
- POST retries on connection errors only (request provably never sent), unless
  the caller opts into ``idempotent_post=True``, which auto-generates an
  ``Idempotency-Key`` header (uuid4) when the caller didn't supply one;
- requests carrying file objects are never re-sent after a failed attempt
  (the stream may be partially consumed — a retry would upload truncated data).

Both clients share one request-building/response-mapping core so the async
surface cannot drift from the sync one (the reference duplicates ~1,100 lines
between its mirrors; see SURVEY.md §7 "hard parts").

Every request records latency/status/retry-count into the process-wide
metrics registry (prime_tpu.obs; docs/architecture.md "Observability") —
the sync and async mirrors share the recording helper too.
"""

from __future__ import annotations

import platform
import random
import time
import uuid
from typing import Any, AsyncIterator, Iterator

import httpx

import prime_tpu
from prime_tpu.core.config import Config
from prime_tpu.obs.metrics import REGISTRY
from prime_tpu.obs.trace import TRACEPARENT_HEADER, TRACER, new_traceparent
from prime_tpu.core.exceptions import (
    APIConnectionError,
    APIError,
    APITimeoutError,
    NotFoundError,
    PaymentRequiredError,
    RateLimitError,
    UnauthorizedError,
    ValidationError,
)

RETRYABLE_STATUS = frozenset({500, 502, 503, 504, 524})
DEFAULT_TIMEOUT = httpx.Timeout(30.0, connect=10.0)
API_PREFIX = "/api/v1"
MAX_ATTEMPTS = 4
BACKOFF_BASE = 0.5
BACKOFF_MAX = 30.0
IDEMPOTENT_METHODS = frozenset({"GET", "HEAD", "PUT", "DELETE"})


# HTTP transport metrics (process-wide default registry, shared by the sync
# and async clients so the mirrors cannot drift): outcome label is the HTTP
# status code, or "connection_error"/"timeout" when no response arrived.
_HTTP_REQUESTS = REGISTRY.counter(
    "client_http_requests_total", "Backend API requests by final outcome",
    labelnames=("method", "status"),
)
_HTTP_LATENCY = REGISTRY.histogram(
    "client_http_request_seconds",
    "Backend API request wall time (all attempts + backoff)",
    labelnames=("method",),
)
_HTTP_RETRIES = REGISTRY.counter(
    "client_http_retries_total", "Retry attempts beyond each request's first",
    labelnames=("method",),
)


def _observe_request(method: str, status: str, t0: float, attempt: int) -> None:
    """Record one logical request's outcome: final status, total wall time,
    and how many extra attempts the retry tiers spent on it."""
    _HTTP_REQUESTS.inc(method=method, status=status)
    _HTTP_LATENCY.observe(time.monotonic() - t0, method=method)
    if attempt:
        _HTTP_RETRIES.inc(attempt, method=method)


def user_agent() -> str:
    return (
        f"prime-tpu/{prime_tpu.__version__} "
        f"python/{platform.python_version()} {platform.system().lower()}"
    )


def _backoff(attempt: int) -> float:
    """Exponential backoff with full jitter, capped at BACKOFF_MAX."""
    return random.uniform(0, min(BACKOFF_MAX, BACKOFF_BASE * (2**attempt)))


def raise_for_status(response: httpx.Response) -> None:
    """Map HTTP status to the typed exception taxonomy."""
    if response.status_code < 400:
        return
    try:
        body = response.json()
    except Exception:
        body = response.text
    detail = body.get("detail") if isinstance(body, dict) else None
    message = None
    if isinstance(detail, str):
        message = detail
    elif isinstance(body, dict):
        message = body.get("message") or body.get("error")

    status = response.status_code
    if status == 401:
        raise UnauthorizedError(message or "Unauthorized. Run `prime login` or set PRIME_API_KEY.")
    if status == 402:
        raise PaymentRequiredError(message or "Payment required: insufficient wallet balance.")
    if status == 404:
        raise NotFoundError(message or f"Resource not found: {response.request.url.path}")
    if status == 422:
        raise ValidationError(message or "Validation error.", errors=detail)
    if status == 429:
        retry_after = None
        ra = response.headers.get("Retry-After")
        if ra:
            try:
                retry_after = float(ra)
            except ValueError:
                retry_after = None
        raise RateLimitError(message or "Rate limited.", retry_after=retry_after)
    raise APIError(
        message or f"API request failed with status {status}",
        status_code=status,
        body=body,
    )


def _should_retry_exception(
    exc: Exception, method: str, idempotent_post: bool, replayable: bool
) -> bool:
    if isinstance(exc, httpx.ConnectError | httpx.ConnectTimeout):
        return True  # request never reached the server — always safe
    if not replayable:
        # A file-object payload may be partially consumed after a failed send;
        # re-sending it would silently upload truncated/empty content.
        return False
    if method in IDEMPOTENT_METHODS or idempotent_post:
        return isinstance(exc, httpx.TransportError)
    return False


def _should_retry_status(status: int, method: str, idempotent_post: bool, replayable: bool) -> bool:
    if status not in RETRYABLE_STATUS or not replayable:
        return False
    return method in IDEMPOTENT_METHODS or idempotent_post


class _RequestCore:
    """Shared request building + response mapping for sync and async clients."""

    def __init__(
        self,
        config: Config | None = None,
        base_url: str | None = None,
        api_key: str | None = None,
        api_prefix: str = API_PREFIX,
        team_id: str | None = None,
    ) -> None:
        self.config = config or Config()
        self.base_url = (base_url or self.config.base_url).rstrip("/")
        self.api_key = api_key if api_key is not None else self.config.api_key
        self.api_prefix = api_prefix
        self.team_id = team_id if team_id is not None else self.config.team_id

    def url(self, path: str) -> str:
        if path.startswith(("http://", "https://")):
            return path
        if not path.startswith("/"):
            path = "/" + path
        if self.api_prefix and not path.startswith(self.api_prefix):
            path = self.api_prefix + path
        return self.base_url + path

    def headers(self, extra: dict[str, str] | None = None) -> dict[str, str]:
        headers = {
            "User-Agent": user_agent(),
            "Accept": "application/json",
        }
        if self.api_key:
            headers["Authorization"] = f"Bearer {self.api_key}"
        if self.team_id:
            headers["X-Prime-Team-ID"] = self.team_id
        if extra:
            headers.update(extra)
        if TRACER.enabled and not any(
            k.lower() == TRACEPARENT_HEADER for k in headers
        ):
            # outermost-hop trace context (docs/observability.md): the SDK is
            # where a request's distributed trace begins, unless a caller
            # (e.g. api/inference.py, which spans the whole retry loop)
            # already injected one
            headers[TRACEPARENT_HEADER] = new_traceparent()
        return headers

    @staticmethod
    def parse(response: httpx.Response) -> Any:
        raise_for_status(response)
        if response.status_code == 204 or not response.content:
            return None
        ctype = response.headers.get("Content-Type", "")
        if "application/json" in ctype:
            return response.json()
        return response.text


class APIClient:
    """Synchronous backend API client."""

    def __init__(
        self,
        config: Config | None = None,
        base_url: str | None = None,
        api_key: str | None = None,
        timeout: httpx.Timeout | float = DEFAULT_TIMEOUT,
        transport: httpx.BaseTransport | None = None,
        api_prefix: str = API_PREFIX,
        team_id: str | None = None,
        max_attempts: int = MAX_ATTEMPTS,
    ) -> None:
        self._core = _RequestCore(config, base_url, api_key, api_prefix, team_id)
        self.max_attempts = max_attempts
        self._client = httpx.Client(timeout=timeout, transport=transport)

    @property
    def config(self) -> Config:
        return self._core.config

    @property
    def team_id(self) -> str | None:
        return self._core.team_id

    def close(self) -> None:
        self._client.close()

    def __enter__(self) -> "APIClient":
        return self

    def __exit__(self, *exc: Any) -> None:
        self.close()

    def request(
        self,
        method: str,
        path: str,
        *,
        json: Any = None,
        params: dict[str, Any] | None = None,
        headers: dict[str, str] | None = None,
        content: bytes | None = None,
        files: Any = None,
        idempotent_post: bool = False,
        timeout: httpx.Timeout | float | None = None,
    ) -> Any:
        method = method.upper()
        url = self._core.url(path)
        if idempotent_post and not (headers and "Idempotency-Key" in headers):
            headers = {**(headers or {}), "Idempotency-Key": str(uuid.uuid4())}
        hdrs = self._core.headers(headers)
        replayable = files is None
        last_exc: Exception | None = None
        t0 = time.monotonic()
        for attempt in range(self.max_attempts):
            try:
                response = self._client.request(
                    method,
                    url,
                    json=json,
                    params=params,
                    headers=hdrs,
                    content=content,
                    files=files,
                    timeout=timeout if timeout is not None else httpx.USE_CLIENT_DEFAULT,
                )
            except httpx.TimeoutException as exc:
                last_exc = exc
                if (
                    not _should_retry_exception(exc, method, idempotent_post, replayable)
                    or attempt == self.max_attempts - 1
                ):
                    _observe_request(method, "timeout", t0, attempt)
                    raise APITimeoutError(f"{method} {url} timed out: {exc}") from exc
                time.sleep(_backoff(attempt))
                continue
            except httpx.TransportError as exc:
                last_exc = exc
                if (
                    not _should_retry_exception(exc, method, idempotent_post, replayable)
                    or attempt == self.max_attempts - 1
                ):
                    _observe_request(method, "connection_error", t0, attempt)
                    raise APIConnectionError(f"Could not reach {url}: {exc}") from exc
                time.sleep(_backoff(attempt))
                continue
            if (
                _should_retry_status(response.status_code, method, idempotent_post, replayable)
                and attempt < self.max_attempts - 1
            ):
                time.sleep(_backoff(attempt))
                continue
            _observe_request(method, str(response.status_code), t0, attempt)
            return self._core.parse(response)
        raise APIConnectionError(f"Could not reach {url}: {last_exc}")  # pragma: no cover

    def get(self, path: str, **kw: Any) -> Any:
        return self.request("GET", path, **kw)

    def post(self, path: str, **kw: Any) -> Any:
        return self.request("POST", path, **kw)

    def put(self, path: str, **kw: Any) -> Any:
        return self.request("PUT", path, **kw)

    def patch(self, path: str, **kw: Any) -> Any:
        return self.request("PATCH", path, **kw)

    def delete(self, path: str, **kw: Any) -> Any:
        return self.request("DELETE", path, **kw)

    def stream_lines(
        self,
        method: str,
        path: str,
        *,
        json: Any = None,
        params: dict[str, Any] | None = None,
        headers: dict[str, str] | None = None,
        timeout: httpx.Timeout | float | None = None,
    ) -> Iterator[str]:
        """Stream response lines (SSE / JSONL endpoints). No retries. The
        latency metric covers time-to-headers, not the stream's lifetime —
        a long-lived SSE tail would drown the histogram otherwise."""
        method = method.upper()
        url = self._core.url(path)
        t0 = time.monotonic()
        observed = False
        try:
            with self._client.stream(
                method,
                url,
                json=json,
                params=params,
                headers=self._core.headers(headers),
                timeout=timeout if timeout is not None else httpx.USE_CLIENT_DEFAULT,
            ) as response:
                _observe_request(method, str(response.status_code), t0, 0)
                observed = True
                if response.status_code >= 400:
                    response.read()
                    raise_for_status(response)
                yield from response.iter_lines()
        except httpx.TimeoutException as exc:
            if not observed:
                _observe_request(method, "timeout", t0, 0)
            raise APITimeoutError(f"{method} {url} timed out: {exc}") from exc
        except httpx.TransportError as exc:
            if not observed:
                _observe_request(method, "connection_error", t0, 0)
            raise APIConnectionError(f"Could not reach {url}: {exc}") from exc


class AsyncAPIClient:
    """Asynchronous mirror of :class:`APIClient` (same retry semantics)."""

    def __init__(
        self,
        config: Config | None = None,
        base_url: str | None = None,
        api_key: str | None = None,
        timeout: httpx.Timeout | float = DEFAULT_TIMEOUT,
        transport: httpx.AsyncBaseTransport | None = None,
        api_prefix: str = API_PREFIX,
        team_id: str | None = None,
        max_attempts: int = MAX_ATTEMPTS,
    ) -> None:
        self._core = _RequestCore(config, base_url, api_key, api_prefix, team_id)
        self.max_attempts = max_attempts
        self._client = httpx.AsyncClient(timeout=timeout, transport=transport)

    @property
    def config(self) -> Config:
        return self._core.config

    @property
    def team_id(self) -> str | None:
        return self._core.team_id

    async def close(self) -> None:
        await self._client.aclose()

    async def __aenter__(self) -> "AsyncAPIClient":
        return self

    async def __aexit__(self, *exc: Any) -> None:
        await self.close()

    async def request(
        self,
        method: str,
        path: str,
        *,
        json: Any = None,
        params: dict[str, Any] | None = None,
        headers: dict[str, str] | None = None,
        content: bytes | None = None,
        files: Any = None,
        idempotent_post: bool = False,
        timeout: httpx.Timeout | float | None = None,
    ) -> Any:
        import anyio

        method = method.upper()
        url = self._core.url(path)
        if idempotent_post and not (headers and "Idempotency-Key" in headers):
            headers = {**(headers or {}), "Idempotency-Key": str(uuid.uuid4())}
        hdrs = self._core.headers(headers)
        replayable = files is None
        last_exc: Exception | None = None
        t0 = time.monotonic()
        for attempt in range(self.max_attempts):
            try:
                response = await self._client.request(
                    method,
                    url,
                    json=json,
                    params=params,
                    headers=hdrs,
                    content=content,
                    files=files,
                    timeout=timeout if timeout is not None else httpx.USE_CLIENT_DEFAULT,
                )
            except httpx.TimeoutException as exc:
                last_exc = exc
                if (
                    not _should_retry_exception(exc, method, idempotent_post, replayable)
                    or attempt == self.max_attempts - 1
                ):
                    _observe_request(method, "timeout", t0, attempt)
                    raise APITimeoutError(f"{method} {url} timed out: {exc}") from exc
                await anyio.sleep(_backoff(attempt))
                continue
            except httpx.TransportError as exc:
                last_exc = exc
                if (
                    not _should_retry_exception(exc, method, idempotent_post, replayable)
                    or attempt == self.max_attempts - 1
                ):
                    _observe_request(method, "connection_error", t0, attempt)
                    raise APIConnectionError(f"Could not reach {url}: {exc}") from exc
                await anyio.sleep(_backoff(attempt))
                continue
            if (
                _should_retry_status(response.status_code, method, idempotent_post, replayable)
                and attempt < self.max_attempts - 1
            ):
                await anyio.sleep(_backoff(attempt))
                continue
            _observe_request(method, str(response.status_code), t0, attempt)
            return self._core.parse(response)
        raise APIConnectionError(f"Could not reach {url}: {last_exc}")  # pragma: no cover

    async def get(self, path: str, **kw: Any) -> Any:
        return await self.request("GET", path, **kw)

    async def post(self, path: str, **kw: Any) -> Any:
        return await self.request("POST", path, **kw)

    async def put(self, path: str, **kw: Any) -> Any:
        return await self.request("PUT", path, **kw)

    async def patch(self, path: str, **kw: Any) -> Any:
        return await self.request("PATCH", path, **kw)

    async def delete(self, path: str, **kw: Any) -> Any:
        return await self.request("DELETE", path, **kw)

    async def stream_lines(
        self,
        method: str,
        path: str,
        *,
        json: Any = None,
        params: dict[str, Any] | None = None,
        headers: dict[str, str] | None = None,
        timeout: httpx.Timeout | float | None = None,
    ) -> AsyncIterator[str]:
        method = method.upper()
        url = self._core.url(path)
        t0 = time.monotonic()
        observed = False
        try:
            async with self._client.stream(
                method,
                url,
                json=json,
                params=params,
                headers=self._core.headers(headers),
                timeout=timeout if timeout is not None else httpx.USE_CLIENT_DEFAULT,
            ) as response:
                _observe_request(method, str(response.status_code), t0, 0)
                observed = True
                if response.status_code >= 400:
                    await response.aread()
                    raise_for_status(response)
                async for line in response.aiter_lines():
                    yield line
        except httpx.TimeoutException as exc:
            if not observed:
                _observe_request(method, "timeout", t0, 0)
            raise APITimeoutError(f"{method} {url} timed out: {exc}") from exc
        except httpx.TransportError as exc:
            if not observed:
                _observe_request(method, "connection_error", t0, 0)
            raise APIConnectionError(f"Could not reach {url}: {exc}") from exc
