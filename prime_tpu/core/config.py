"""Persistent configuration + named environment contexts.

Capability parity with the reference Config (prime_cli/core/config.py:10-389):
- JSON persistence under a config dir (default ``~/.prime``, override with
  ``PRIME_CONFIG_DIR``)
- env-var precedence over file values (``PRIME_API_KEY`` > file, etc.,
  reference core/config.py:81-82)
- named environment *contexts* under ``<config_dir>/environments/*.json`` with
  save/use/delete/list and path-traversal-safe names (reference :215-224,244-389)
- team/user identity, SSH key path, base/frontend/inference URLs
"""

from __future__ import annotations

import json
import os
import re
import tempfile
from pathlib import Path
from typing import Any

from pydantic import BaseModel, Field

# Environment knobs: the one sanctioned way to read a ``PRIME_*`` knob.
# Every knob must (a) be read through one of these helpers, (b) have a row
# in the "Environment knobs" table in docs/architecture.md, and (c) agree
# with its paired CLI flag's default — all three enforced by the
# knob-registry checker in ``prime_tpu/analysis``. Direct ``os.environ``
# reads of PRIME_* names anywhere else are lint findings. The implementation
# lives in the stdlib-only leaf ``prime_tpu.utils.env`` so the obs layer can
# read its knobs without pulling this module's pydantic dependency; this
# re-export is the canonical import surface for everything else.
from prime_tpu.utils.env import (  # noqa: F401
    env_flag,
    env_float,
    env_int,
    env_str,
)

DEFAULT_BASE_URL = "https://api.primeintellect.ai"
DEFAULT_FRONTEND_URL = "https://app.primeintellect.ai"
DEFAULT_INFERENCE_URL = "https://api.pinference.ai/api/v1"

_CONTEXT_NAME_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")


class ConfigModel(BaseModel):
    """On-disk schema for config.json and context files."""

    api_key: str = ""
    team_id: str = ""
    user_id: str = ""
    base_url: str = DEFAULT_BASE_URL
    frontend_url: str = DEFAULT_FRONTEND_URL
    inference_url: str = DEFAULT_INFERENCE_URL
    ssh_key_path: str = Field(default_factory=lambda: str(Path.home() / ".ssh" / "id_rsa"))
    # auto-share newly created resources with the active team
    share_resources_with_team: bool = False
    # TPU-native defaults: which accelerator generation the create-wizard proposes.
    default_tpu_type: str = "v5e"


class InvalidContextName(ValueError):
    pass


def sanitize_context_name(name: str) -> str:
    """Reject path-traversal / hidden-file context names (reference :215-224)."""
    name = name.strip()
    if not _CONTEXT_NAME_RE.match(name) or ".." in name:
        raise InvalidContextName(
            f"Invalid context name {name!r}: use letters, digits, '.', '_', '-' "
            "(max 64 chars, must not start with '.')"
        )
    return name


class Config:
    """Read-write config store with env-var precedence and named contexts."""

    ENV_VARS = {
        "api_key": "PRIME_API_KEY",
        "team_id": "PRIME_TEAM_ID",
        "base_url": "PRIME_BASE_URL",
        "frontend_url": "PRIME_FRONTEND_URL",
        "inference_url": "PRIME_INFERENCE_URL",
        "ssh_key_path": "PRIME_SSH_KEY_PATH",
    }

    def __init__(self, config_dir: str | Path | None = None) -> None:
        env_dir = os.environ.get("PRIME_CONFIG_DIR")
        base = Path(config_dir) if config_dir else (Path(env_dir) if env_dir else Path.home() / ".prime")
        self.config_dir = base
        self.config_file = base / "config.json"
        self.environments_dir = base / "environments"
        self._model = self._load()
        # `PRIME_CONTEXT` switches the active context for a single invocation
        # (reference main.py:87-117) without rewriting config.json.
        ctx = os.environ.get("PRIME_CONTEXT")
        if ctx:
            # An unusable PRIME_CONTEXT must not brick every invocation — fall
            # back to config.json the same way _load() tolerates corruption.
            try:
                self._model = self._load_context_model(sanitize_context_name(ctx))
            except (FileNotFoundError, InvalidContextName, json.JSONDecodeError, ValueError):
                pass

    # -- persistence ---------------------------------------------------------

    def _load(self) -> ConfigModel:
        if self.config_file.exists():
            try:
                return ConfigModel.model_validate(json.loads(self.config_file.read_text()))
            except (json.JSONDecodeError, ValueError):
                return ConfigModel()
        return ConfigModel()

    def reset(self) -> None:
        """Replace the in-memory model with a fresh default ConfigModel —
        every field (including ones added later) resets, with no
        hand-maintained enumeration to drift."""
        self._model = ConfigModel()

    def save(self) -> None:
        self.config_dir.mkdir(parents=True, exist_ok=True)
        self._atomic_write(self.config_file, self._model.model_dump())

    @staticmethod
    def _atomic_write(path: Path, data: dict[str, Any]) -> None:
        path.parent.mkdir(parents=True, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=str(path.parent), prefix=".tmp-cfg-")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(data, f, indent=2)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    # -- value access with env precedence ------------------------------------

    def _get(self, field: str) -> str:
        env_name = self.ENV_VARS.get(field)
        if env_name:
            env_val = os.environ.get(env_name)
            if env_val:
                return env_val
        return getattr(self._model, field)

    @property
    def api_key(self) -> str:
        return self._get("api_key")

    @api_key.setter
    def api_key(self, value: str) -> None:
        self._model.api_key = value

    @property
    def team_id(self) -> str:
        return self._get("team_id")

    @team_id.setter
    def team_id(self, value: str) -> None:
        self._model.team_id = value

    @property
    def user_id(self) -> str:
        return self._model.user_id

    @user_id.setter
    def user_id(self, value: str) -> None:
        self._model.user_id = value

    @property
    def base_url(self) -> str:
        return self._get("base_url").rstrip("/")

    @base_url.setter
    def base_url(self, value: str) -> None:
        self._model.base_url = value

    @property
    def frontend_url(self) -> str:
        return self._get("frontend_url").rstrip("/")

    @frontend_url.setter
    def frontend_url(self, value: str) -> None:
        self._model.frontend_url = value

    @property
    def share_resources_with_team(self) -> bool:
        return bool(self._model.share_resources_with_team)

    @share_resources_with_team.setter
    def share_resources_with_team(self, value: bool) -> None:
        self._model.share_resources_with_team = bool(value)

    @property
    def inference_url(self) -> str:
        return self._get("inference_url").rstrip("/")

    @inference_url.setter
    def inference_url(self, value: str) -> None:
        self._model.inference_url = value

    @property
    def ssh_key_path(self) -> str:
        return self._get("ssh_key_path")

    @ssh_key_path.setter
    def ssh_key_path(self, value: str) -> None:
        self._model.ssh_key_path = value

    @property
    def default_tpu_type(self) -> str:
        return self._model.default_tpu_type

    @default_tpu_type.setter
    def default_tpu_type(self, value: str) -> None:
        self._model.default_tpu_type = value

    def view(self) -> dict[str, Any]:
        """Current effective values (env overrides applied), api_key masked."""
        data = self._model.model_dump()
        for field in self.ENV_VARS:
            data[field] = self._get(field)
        if data.get("api_key"):
            key = data["api_key"]
            data["api_key"] = key[:4] + "..." + key[-4:] if len(key) > 12 else "***"
        return data

    # -- named contexts ------------------------------------------------------

    def _context_path(self, name: str) -> Path:
        return self.environments_dir / f"{sanitize_context_name(name)}.json"

    def _load_context_model(self, name: str) -> ConfigModel:
        path = self._context_path(name)
        if not path.exists():
            raise FileNotFoundError(f"No saved context named {name!r}")
        return ConfigModel.model_validate(json.loads(path.read_text()))

    def save_context(self, name: str) -> Path:
        """Snapshot the current (file) config as a named context."""
        path = self._context_path(name)
        self._atomic_write(path, self._model.model_dump())
        return path

    def use_context(self, name: str) -> None:
        """Load a named context and make it the active config.json."""
        self._model = self._load_context_model(name)
        self.save()

    def delete_context(self, name: str) -> bool:
        path = self._context_path(name)
        if path.exists():
            path.unlink()
            return True
        return False

    def list_contexts(self) -> list[str]:
        if not self.environments_dir.exists():
            return []
        return sorted(p.stem for p in self.environments_dir.glob("*.json"))
