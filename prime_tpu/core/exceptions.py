"""Typed API error taxonomy.

Capability parity with the reference's status→exception mapping
(prime_cli/core/client.py:17-67): 401/402/404/422 get dedicated types, 422
carries structured per-field errors, timeouts and connection failures are
distinguished so retry policy can key on them.
"""

from __future__ import annotations

from typing import Any


class APIError(Exception):
    """Base class for all backend API errors."""

    def __init__(
        self,
        message: str,
        status_code: int | None = None,
        body: Any = None,
    ) -> None:
        super().__init__(message)
        self.message = message
        self.status_code = status_code
        self.body = body


class UnauthorizedError(APIError):
    """401 — missing/invalid API key."""

    def __init__(self, message: str = "Unauthorized. Run `prime login` or set PRIME_API_KEY.") -> None:
        super().__init__(message, status_code=401)


class PaymentRequiredError(APIError):
    """402 — insufficient balance."""

    def __init__(self, message: str = "Payment required: insufficient wallet balance.") -> None:
        super().__init__(message, status_code=402)


class NotFoundError(APIError):
    """404 — resource does not exist."""

    def __init__(self, message: str = "Resource not found.") -> None:
        super().__init__(message, status_code=404)


class RateLimitError(APIError):
    """429 — rate limited; carries Retry-After when the server sent one."""

    def __init__(self, message: str = "Rate limited.", retry_after: float | None = None) -> None:
        super().__init__(message, status_code=429)
        self.retry_after = retry_after


class ValidationError(APIError):
    """422 — structured field errors.

    `errors` is a list of {"loc": [...], "msg": str, "type": str} dicts when the
    backend returns FastAPI-style detail; otherwise the raw detail payload.
    """

    def __init__(self, message: str = "Validation error.", errors: Any = None) -> None:
        super().__init__(message, status_code=422)
        self.errors = errors or []

    def field_messages(self) -> list[str]:
        out: list[str] = []
        if isinstance(self.errors, list):
            for err in self.errors:
                if isinstance(err, dict):
                    loc = ".".join(str(p) for p in err.get("loc", []) if p != "body")
                    msg = err.get("msg", "")
                    out.append(f"{loc}: {msg}" if loc else str(msg))
                else:
                    out.append(str(err))
        elif self.errors:
            out.append(str(self.errors))
        return out


class APITimeoutError(APIError):
    """Request exceeded its deadline (client side)."""

    def __init__(self, message: str = "Request timed out.") -> None:
        super().__init__(message, status_code=None)


class APIConnectionError(APIError):
    """Could not reach the backend at all."""

    def __init__(self, message: str = "Could not connect to the API.") -> None:
        super().__init__(message, status_code=None)
