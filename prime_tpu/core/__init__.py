from prime_tpu.core.config import Config
from prime_tpu.core.exceptions import (
    APIError,
    APIConnectionError,
    APITimeoutError,
    NotFoundError,
    PaymentRequiredError,
    RateLimitError,
    UnauthorizedError,
    ValidationError,
)

__all__ = [
    "Config",
    "APIError",
    "APIConnectionError",
    "APITimeoutError",
    "NotFoundError",
    "PaymentRequiredError",
    "RateLimitError",
    "UnauthorizedError",
    "ValidationError",
]
