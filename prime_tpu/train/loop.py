"""Training loop driver: timing, throughput, profiling, checkpoint cadence.

The jitted step (trainer.make_train_step) is pure compute; this loop owns the
host-side concerns the VERDICT flagged as missing: per-step wall-clock timing
(with a forced device sync so tunneled backends can't report ~0s), tokens/sec,
metrics.jsonl logging, periodic orbax checkpoints, and an optional
``jax.profiler`` trace window for a chosen step range (view with
tensorboard/xprof).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable

import jax.numpy as jnp


@dataclass
class LoopReport:
    steps: int = 0
    final_loss: float = float("nan")
    mean_step_time_s: float = float("nan")
    tokens_per_sec: float = float("nan")
    step_times_s: list[float] = field(default_factory=list)

    def as_dict(self) -> dict[str, Any]:
        return {
            "steps": self.steps,
            "final_loss": self.final_loss,
            "mean_step_time_s": self.mean_step_time_s,
            "tokens_per_sec": self.tokens_per_sec,
        }


def train_loop(
    state,
    step_fn: Callable,
    batches: Iterable[tuple],          # yields (tokens, targets, mask)
    *,
    metrics=None,                      # train.metrics.MetricsLogger or None
    checkpoints=None,                  # train.checkpoint.CheckpointManager or None
    checkpoint_every: int = 0,
    profile_dir: str | None = None,
    profile_window: tuple[int, int] = (2, 5),   # [start, stop) steps to trace
    log_every: int = 1,
    on_step: Callable[[int, dict], None] | None = None,
    start_step: int = 0,
):
    """Drive ``step_fn`` over ``batches``. Returns (state, LoopReport).
    ``start_step`` offsets logged step numbers when resuming a run."""
    import jax

    report = LoopReport()
    profiling = False
    try:
        for step, (tokens, targets, mask) in enumerate(batches):
            if profile_dir is not None and step == profile_window[0]:
                jax.profiler.start_trace(profile_dir)
                profiling = True

            t0 = time.perf_counter()
            state, step_metrics = step_fn(state, tokens, targets, mask)
            # scalar fetch = device sync: block_until_ready is a no-op on some
            # tunneled backends and would time dispatch, not execution
            loss = float(step_metrics["loss"])
            dt = time.perf_counter() - t0

            if profiling and step + 1 == profile_window[1]:
                jax.profiler.stop_trace()
                profiling = False

            tokens_this_step = int(jnp.size(tokens))
            report.steps = step + 1
            report.final_loss = loss
            report.step_times_s.append(dt)
            row = {
                "loss": loss,
                "grad_norm": float(step_metrics.get("grad_norm", float("nan"))),
                "step_time_s": dt,
                "tokens_per_sec": tokens_this_step / dt if dt > 0 else 0.0,
            }
            if metrics is not None and step % max(log_every, 1) == 0:
                metrics.log(start_step + step, **row)
            if on_step is not None:
                on_step(start_step + step, row)
            if checkpoints is not None and checkpoint_every and (step + 1) % checkpoint_every == 0:
                checkpoints.save(state, metrics={"loss": loss})
    finally:
        if profiling:
            jax.profiler.stop_trace()

    if report.step_times_s:
        # first step pays compile; report steady-state timing when possible
        steady = report.step_times_s[1:] or report.step_times_s
        report.mean_step_time_s = sum(steady) / len(steady)
        per_step_tokens = tokens_this_step
        report.tokens_per_sec = (
            per_step_tokens / report.mean_step_time_s if report.mean_step_time_s > 0 else 0.0
        )
    return state, report
