"""Trainer checkpoint/resume via orbax (SURVEY.md §5 "checkpoint/resume").

The platform client manages server-side checkpoints (api/rl.py); the native
trainer saves its own: sharded-aware orbax checkpoints of the full TrainState
(params + optimizer moments + step) with retention, plus metadata for
warm-start bookkeeping. Restore places leaves back onto the saved shardings
when a mesh is provided.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any

from prime_tpu.train.trainer import TrainState


class CheckpointManager:
    def __init__(self, directory: str | Path, keep: int = 3) -> None:
        import orbax.checkpoint as ocp

        self.directory = Path(directory).resolve()
        self.directory.mkdir(parents=True, exist_ok=True)
        self._manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=keep, create=True),
        )

    def save(self, state: TrainState, metrics: dict[str, Any] | None = None) -> int:
        import jax
        import orbax.checkpoint as ocp

        step = int(jax.device_get(state.step))
        self._manager.save(step, args=ocp.args.StandardSave(state._asdict()))
        self._manager.wait_until_finished()
        if metrics is not None:
            (self.directory / f"metrics-{step}.json").write_text(json.dumps(metrics, default=float))
        return step

    def latest_step(self) -> int | None:
        return self._manager.latest_step()

    def restore(self, template: TrainState, step: int | None = None) -> TrainState:
        """Restore into the structure (and shardings) of ``template``."""
        import orbax.checkpoint as ocp

        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"No checkpoints under {self.directory}")
        restored = self._manager.restore(
            step, args=ocp.args.StandardRestore(template._asdict())
        )
        return TrainState(**restored)

    def close(self) -> None:
        self._manager.close()
