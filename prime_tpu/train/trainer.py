"""Sharded training step for the Llama stack.

The reference platform dispatches training server-side (SURVEY.md §2.10); the
TPU-native framework carries its own compute path, so fine-tuning runs on the
slices this CLI provisions. One jitted train step, sharded via NamedShardings
over the (dp, fsdp, tp) mesh: XLA emits reduce-scatter/all-gather for fsdp and
psums for tp over ICI.

bf16 params/activations, fp32 optimizer state and loss. ``remat`` wraps the
model's scan body in ``jax.checkpoint`` — reverse-mode AD otherwise saves
every layer's residuals, so long-sequence training is activation-bound
without it ("dots" keeps matmul outputs, "full" recomputes everything).
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from prime_tpu.models.config import ModelConfig
from prime_tpu.models.llama import forward
from prime_tpu.parallel.sharding import param_shardings


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jnp.ndarray


def cross_entropy_loss(
    logits: jnp.ndarray,   # (B, S, V) fp32
    targets: jnp.ndarray,  # (B, S) int32
    mask: jnp.ndarray,     # (B, S) 1.0 for real tokens
) -> jnp.ndarray:
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    token_ll = jnp.take_along_axis(logprobs, targets[..., None], axis=-1)[..., 0]
    return -jnp.sum(token_ll * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def warmup_cosine(
    peak_lr: float, total_steps: int, warmup_steps: int | None = None, final_lr_frac: float = 0.1
):
    """Linear warmup → cosine decay, the standard LLM schedule."""
    if warmup_steps is None:
        warmup_steps = max(1, total_steps // 100)
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=peak_lr,
        warmup_steps=warmup_steps,
        decay_steps=total_steps,
        end_value=peak_lr * final_lr_frac,
    )


def default_optimizer(
    learning_rate: float | optax.Schedule = 3e-4,
    weight_decay: float = 0.1,
    max_grad_norm: float = 1.0,
) -> optax.GradientTransformation:
    """AdamW + global-norm clipping. ``learning_rate`` may be a schedule
    (see :func:`warmup_cosine`).

    Mixed-precision policy lives in the train step, not here: the step feeds
    the optimizer fp32 gradients and an fp32 view of the params, so BOTH Adam
    moments stay fp32 even with bf16 params (optax has no nu_dtype knob, and
    nu accumulates squared gradients — exactly what bf16's ~3 significant
    digits destroy)."""
    def decay_mask(tree):
        # DeepSeek-V3's score_bias is a SELECTION-ONLY buffer: it has zero
        # gradient (it only feeds argmax), so with unmasked AdamW each step
        # would be pure decay, exponentially erasing a loaded checkpoint's
        # routing balance. Everything else keeps the standard decay.
        def keep(path, _x):
            return not any(
                getattr(key, "key", None) == "score_bias" for key in path
            )

        return jax.tree_util.tree_map_with_path(keep, tree)

    return optax.chain(
        optax.clip_by_global_norm(max_grad_norm),
        optax.adamw(
            learning_rate, b1=0.9, b2=0.95, weight_decay=weight_decay,
            mask=decay_mask,
        ),
    )


def _f32(tree):
    return jax.tree.map(lambda x: x.astype(jnp.float32), tree)


def init_train_state(params, optimizer: optax.GradientTransformation) -> TrainState:
    # fp32 skeleton: Adam's mu/nu are created in fp32 even for bf16 params
    # (the step always hands the optimizer fp32 grads/param views)
    skeleton = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
    return TrainState(
        params=params, opt_state=optimizer.init(skeleton), step=jnp.zeros((), jnp.int32)
    )


def make_train_step(
    config: ModelConfig,
    optimizer: optax.GradientTransformation,
    attn_impl: str = "auto",
    accum_steps: int = 1,
    aux_weight: float = 0.01,   # MoE load-balance loss weight (Switch default)
    remat: str = "none",        # "none" | "full" | "dots" activation checkpointing
    ring_mesh=None,             # attn_impl="ring": context-parallel training
):
    """Build the jitted train step. Shardings propagate from the placed
    inputs (shard_train_state / shard_batch) — the jit is mesh-agnostic.
    (``attn_impl="ring"`` + ``ring_mesh`` is the exception: context-parallel
    training shards the SEQUENCE over the mesh's sp axis and attention
    rotates KV blocks around the ring — sequences longer than one chip's
    activation memory train without rematerializing the whole batch.)

    ``accum_steps > 1`` scans microbatches (the leading batch dim must be a
    multiple) accumulating fp32 gradients at constant memory before one
    optimizer update. Microbatch gradients are combined weighted by their
    real-token counts, so ragged masks give the SAME global token-mean
    objective as the full-batch step — not a mean of per-microbatch means.
    """

    def loss_fn(params, tokens, targets, mask):
        if config.is_moe:
            logits, _, aux = forward(
                params, tokens, config, cache=None, attn_impl=attn_impl,
                return_aux=True, remat=remat, mesh=ring_mesh,
            )
            return cross_entropy_loss(logits, targets, mask) + aux_weight * aux
        logits, _ = forward(
            params, tokens, config, cache=None, attn_impl=attn_impl, remat=remat,
            mesh=ring_mesh,
        )
        return cross_entropy_loss(logits, targets, mask)

    def grads_of(params, tokens, targets, mask):
        if accum_steps == 1:
            loss, grads = jax.value_and_grad(loss_fn)(params, tokens, targets, mask)
            return loss, _f32(grads)
        batch = tokens.shape[0]
        if batch % accum_steps:
            raise ValueError(f"batch {batch} not divisible by accum_steps {accum_steps}")
        micro = batch // accum_steps

        def shaped(x):
            return x.reshape(accum_steps, micro, *x.shape[1:])

        def micro_step(carry, xs):
            loss_sum, token_sum, grad_sum = carry
            _, _, m = xs
            loss, grads = jax.value_and_grad(loss_fn)(params, *xs)
            tokens_here = jnp.sum(m).astype(jnp.float32)
            grad_sum = jax.tree.map(
                lambda acc, g: acc + g.astype(jnp.float32) * tokens_here, grad_sum, grads
            )
            return (loss_sum + loss * tokens_here, token_sum + tokens_here, grad_sum), None

        zeros = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)
        (loss_sum, token_sum, grad_sum), _ = jax.lax.scan(
            micro_step,
            (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32), zeros),
            (shaped(tokens), shaped(targets), shaped(mask)),
        )
        total = jnp.maximum(token_sum, 1.0)
        return loss_sum / total, jax.tree.map(lambda g: g / total, grad_sum)

    def train_step(state: TrainState, tokens, targets, mask):
        loss, grads = grads_of(state.params, tokens, targets, mask)
        new_state, grad_norm = apply_gradients(state, grads, optimizer)
        return new_state, {"loss": loss, "grad_norm": grad_norm}

    return jax.jit(train_step, donate_argnums=(0,))


def apply_gradients(state: TrainState, grads, optimizer) -> tuple[TrainState, jnp.ndarray]:
    """The one fp32 update path (shared with the pipeline step): fp32 grads +
    fp32 param view -> fp32 moments and updates; params round back to their
    storage dtype once. Returns (new state, fp32 grad norm)."""
    grads32 = _f32(grads)
    updates, new_opt_state = optimizer.update(grads32, state.opt_state, _f32(state.params))
    new_params = jax.tree.map(
        lambda p, u: (p.astype(jnp.float32) + u).astype(p.dtype), state.params, updates
    )
    return (
        TrainState(new_params, new_opt_state, state.step + 1),
        optax.global_norm(grads32),
    )


def shard_train_state(
    state: TrainState, mesh, config: ModelConfig, shardings=None
) -> TrainState:
    """Place a TrainState onto the mesh: params per the megatron/fsdp specs,
    optimizer moments mirroring their param's sharding, scalars replicated.

    ``shardings`` overrides the params' NamedSharding tree — LoRA states pass
    their adapter-factor layouts (train.lora) through the same placement."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    p_shardings = shardings if shardings is not None else param_shardings(mesh, config)
    replicated = NamedSharding(mesh, P())
    params = jax.device_put(state.params, p_shardings)

    # Optimizer moments (adam mu/nu) are param-structured subtrees — place
    # them with the params' shardings BY TREE POSITION. (Matching by shape is
    # wrong: wq and wo have identical shapes whenever n_heads*head_dim ==
    # d_model — every llama preset — but transposed PartitionSpecs.)
    param_struct = jax.tree.structure(state.params)

    def place_subtree(node):
        if jax.tree.structure(node) == param_struct:
            return jax.device_put(node, p_shardings)
        return jax.tree.map(lambda leaf: jax.device_put(leaf, replicated), node)

    opt_state = jax.tree.map(
        place_subtree,
        state.opt_state,
        is_leaf=lambda n: jax.tree.structure(n) == param_struct,
    )
    step = jax.device_put(state.step, replicated)
    return TrainState(params=params, opt_state=opt_state, step=step)


@functools.partial(jax.jit, static_argnames=("config", "attn_impl"))
def eval_loss(params, tokens, targets, mask, config: ModelConfig, attn_impl: str = "auto"):
    logits, _ = forward(params, tokens, config, cache=None, attn_impl=attn_impl)
    return cross_entropy_loss(logits, targets, mask)
