"""Sharded training step for the Llama stack.

The reference platform dispatches training server-side (SURVEY.md §2.10); the
TPU-native framework carries its own compute path, so fine-tuning runs on the
slices this CLI provisions. One jitted train step, sharded via NamedShardings
over the (dp, fsdp, tp) mesh: XLA emits reduce-scatter/all-gather for fsdp and
psums for tp over ICI.

bf16 params/activations, fp32 optimizer state and loss; optional
``jax.checkpoint`` rematerialization around the layer scan comes from the
model's scan structure (XLA remats scan bodies well by default).
"""

from __future__ import annotations

import functools
from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax

from prime_tpu.models.config import ModelConfig
from prime_tpu.models.llama import forward
from prime_tpu.parallel.sharding import param_shardings


class TrainState(NamedTuple):
    params: Any
    opt_state: Any
    step: jnp.ndarray


def cross_entropy_loss(
    logits: jnp.ndarray,   # (B, S, V) fp32
    targets: jnp.ndarray,  # (B, S) int32
    mask: jnp.ndarray,     # (B, S) 1.0 for real tokens
) -> jnp.ndarray:
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    token_ll = jnp.take_along_axis(logprobs, targets[..., None], axis=-1)[..., 0]
    return -jnp.sum(token_ll * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def default_optimizer(learning_rate: float = 3e-4, weight_decay: float = 0.1) -> optax.GradientTransformation:
    return optax.chain(
        optax.clip_by_global_norm(1.0),
        optax.adamw(learning_rate, b1=0.9, b2=0.95, weight_decay=weight_decay),
    )


def init_train_state(params, optimizer: optax.GradientTransformation) -> TrainState:
    return TrainState(params=params, opt_state=optimizer.init(params), step=jnp.zeros((), jnp.int32))


def make_train_step(
    config: ModelConfig,
    optimizer: optax.GradientTransformation,
    attn_impl: str = "auto",
):
    """Build the jitted train step. Shardings propagate from the placed
    inputs (shard_train_state / shard_batch) — the jit is mesh-agnostic."""

    def loss_fn(params, tokens, targets, mask):
        logits, _ = forward(params, tokens, config, cache=None, attn_impl=attn_impl)
        return cross_entropy_loss(logits, targets, mask)

    def train_step(state: TrainState, tokens, targets, mask):
        loss, grads = jax.value_and_grad(loss_fn)(state.params, tokens, targets, mask)
        updates, new_opt_state = optimizer.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
        new_state = TrainState(new_params, new_opt_state, state.step + 1)
        return new_state, {"loss": loss, "grad_norm": optax.global_norm(grads)}

    return jax.jit(train_step, donate_argnums=(0,))


def shard_train_state(state: TrainState, mesh, config: ModelConfig) -> TrainState:
    """Place a TrainState onto the mesh: params per the megatron/fsdp specs,
    optimizer moments mirroring their param's sharding, scalars replicated."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    p_shardings = param_shardings(mesh, config)
    replicated = NamedSharding(mesh, P())
    params = jax.device_put(state.params, p_shardings)

    # Optimizer moments (adam mu/nu) are param-structured subtrees — place
    # them with the params' shardings BY TREE POSITION. (Matching by shape is
    # wrong: wq and wo have identical shapes whenever n_heads*head_dim ==
    # d_model — every llama preset — but transposed PartitionSpecs.)
    param_struct = jax.tree.structure(state.params)

    def place_subtree(node):
        if jax.tree.structure(node) == param_struct:
            return jax.device_put(node, p_shardings)
        return jax.tree.map(lambda leaf: jax.device_put(leaf, replicated), node)

    opt_state = jax.tree.map(
        place_subtree,
        state.opt_state,
        is_leaf=lambda n: jax.tree.structure(n) == param_struct,
    )
    step = jax.device_put(state.step, replicated)
    return TrainState(params=params, opt_state=opt_state, step=step)


@functools.partial(jax.jit, static_argnames=("config", "attn_impl"))
def eval_loss(params, tokens, targets, mask, config: ModelConfig, attn_impl: str = "auto"):
    logits, _ = forward(params, tokens, config, cache=None, attn_impl=attn_impl)
    return cross_entropy_loss(logits, targets, mask)
