"""Native GRPO: group-relative policy optimization on the TPU slice.

The reference's RL product runs server-side — the CLI only *configures* it
(TOML pass-through, reference commands/rl.py:913 dispatch; SURVEY.md §2.10
"training parallelism lives server-side in the separate prime-rl project").
This framework carries its own compute path, so RL fine-tuning runs natively:
rollouts come from the same jitted generator that serves evals
(models/sampler.generate — which already returns per-token logprobs),
rewards from the environment-execution protocol (envhub/execution.py), and
updates ride the sharded trainer core (train/trainer.apply_gradients), so a
mesh'd run gets megatron-TP + ZeRO-3 fsdp for free.

TPU-first shape discipline: prompts are bucketed to a fixed ``max_prompt_len``
and completions to ``max_new_tokens``, so every rollout step re-enters the
same three compiled programs (generate, score-pass, update) — no shape churn,
no recompiles. The update is token-level clipped-surrogate GRPO
(group-standardized advantages; the token-level mean is the Dr.GRPO/DAPO
variant — per-sequence length normalization biases against long correct
answers) with an optional k3 KL penalty against the frozen starting policy
(``kl_coef > 0`` keeps a reference param copy — doubles param memory).
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax

from prime_tpu.models.config import ModelConfig
from prime_tpu.models.llama import forward
from prime_tpu.models.sampler import generate
from prime_tpu.train.trainer import TrainState, apply_gradients, init_train_state


@dataclass
class GrpoConfig:
    group_size: int = 8              # G completions per prompt
    prompts_per_step: int = 4        # P prompts sampled per optimizer step
    max_prompt_len: int = 128        # S: prompts truncated (keep tail) / padded
    max_new_tokens: int = 64         # N: completion budget
    temperature: float = 1.0         # rollout sampling temperature (> 0)
    top_p: float = 1.0
    clip_eps: float = 0.2            # PPO-style ratio clip
    kl_coef: float = 0.0             # k3 KL vs frozen ref policy (0 = off)
    epochs_per_batch: int = 1        # GRPO mu: updates per rollout batch
    adv_eps: float = 1e-4            # std floor in group normalization
    steps: int = 20
    learning_rate: float = 1e-5
    remat: str = "none"              # activation checkpointing in the update forward

    def __post_init__(self) -> None:
        if self.temperature <= 0.0:
            raise ValueError("GRPO rollouts need temperature > 0 (greedy groups are identical)")
        if self.group_size < 2:
            raise ValueError("group_size must be >= 2 — advantages are group-relative")
        if self.remat not in ("none", "full", "dots"):
            raise ValueError(f"Unknown remat {self.remat!r} (want 'none' | 'full' | 'dots')")


def group_advantages(rewards: np.ndarray, eps: float = 1e-4) -> np.ndarray:
    """(P, G) rewards → group-standardized advantages. A group with zero
    spread (all-same rewards) gets zero advantage — no learning signal, which
    is exactly GRPO's behavior (and why group_size > 1 matters)."""
    mean = rewards.mean(axis=1, keepdims=True)
    std = rewards.std(axis=1, keepdims=True)
    return (rewards - mean) / (std + eps)


def _token_logprobs_inline(params, tokens, config, attn_impl, remat="none"):
    logits, _ = forward(params, tokens, config, cache=None, attn_impl=attn_impl, remat=remat)
    logprobs = jax.nn.log_softmax(logits[:, :-1].astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(logprobs, tokens[:, 1:, None], axis=-1)[..., 0]
    return jnp.pad(picked, ((0, 0), (1, 0)))


@functools.partial(jax.jit, static_argnames=("config", "attn_impl"))
def token_logprobs(
    params, tokens: jnp.ndarray, config: ModelConfig, attn_impl: str = "auto"
) -> jnp.ndarray:
    """Teacher-forced per-token logprobs: out[:, t] = log p(tokens[:, t] | <t).
    Position 0 (no context) gets 0. Used for the behavior-policy snapshot and
    the frozen-reference KL — both under the *untempered* policy."""
    return _token_logprobs_inline(params, tokens, config, attn_impl)


def make_grpo_step(
    config: ModelConfig,
    optimizer: optax.GradientTransformation,
    clip_eps: float = 0.2,
    kl_coef: float = 0.0,
    attn_impl: str = "auto",
    on_policy: bool = False,
    lora=None,  # train.lora.LoraConfig -> the state holds adapters, not params
    remat: str = "none",  # activation checkpointing in the update forward
):
    """Jitted GRPO update. Inputs: full packed sequences (B, T), a completion
    mask (1.0 exactly on the tokens the policy sampled, EOS included), one
    advantage per sequence, and the behavior/reference logprob snapshots.
    Shardings propagate from the placed state/batch; the jit is mesh-agnostic
    (same contract as trainer.make_train_step).

    ``on_policy=True`` (valid when every rollout batch gets exactly one
    update and there is no KL reference) skips the snapshot arguments:
    old/ref default to stop_gradient of the current logprobs — the ratio is
    identically 1, clipping is inert, and the caller saves one full
    teacher-forced forward pass per step. Pass zeros for old_lp/ref_lp.

    The step signature is ``(state, base_params, tokens, mask, advantages,
    old_lp, ref_lp)``. ``base_params`` is None for full-parameter GRPO; with
    ``lora`` set it carries the frozen base (not donated) and the state holds
    only the adapter factors — the hosted product's default run type, trained
    on-slice."""

    def policy_of(policy_params, base_params):
        if lora is None:
            return policy_params
        from prime_tpu.train.lora import merge_lora

        return merge_lora(base_params, policy_params, lora)

    def loss_fn(policy_params, base_params, tokens, mask, advantages, old_lp, ref_lp):
        lp = _token_logprobs_inline(
            policy_of(policy_params, base_params), tokens, config, attn_impl, remat=remat
        )
        if on_policy:
            old_lp = ref_lp = jax.lax.stop_gradient(lp)
        ratio = jnp.exp(lp - old_lp)
        clipped = jnp.clip(ratio, 1.0 - clip_eps, 1.0 + clip_eps)
        adv = advantages[:, None]
        surrogate = jnp.minimum(ratio * adv, clipped * adv)
        n_tokens = jnp.maximum(jnp.sum(mask), 1.0)
        pg_loss = -jnp.sum(surrogate * mask) / n_tokens
        # k3 estimator: unbiased, positive, low-variance (Schulman 2020)
        kl = jnp.sum((jnp.exp(ref_lp - lp) - (ref_lp - lp) - 1.0) * mask) / n_tokens
        clip_frac = jnp.sum((jnp.abs(ratio - 1.0) > clip_eps) * mask) / n_tokens
        loss = pg_loss + kl_coef * kl
        return loss, {"pg_loss": pg_loss, "kl": kl, "clip_frac": clip_frac,
                      "ratio_mean": jnp.sum(ratio * mask) / n_tokens}

    def grpo_step(state: TrainState, base_params, tokens, mask, advantages, old_lp, ref_lp):
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, base_params, tokens, mask, advantages, old_lp, ref_lp
        )
        new_state, grad_norm = apply_gradients(state, grads, optimizer)
        return new_state, {"loss": loss, "grad_norm": grad_norm, **aux}

    return jax.jit(grpo_step, donate_argnums=(0,))


def pack_rollouts(
    prompt_ids: Sequence[Sequence[int]],   # B ragged prompts (already truncated to S)
    gen_tokens: np.ndarray,                # (B, N) sampler output (pad after EOS)
    gen_lengths: np.ndarray,               # (B,) pre-EOS lengths
    pad_id: int,
    total_len: int,                        # S + N, the static train width
    eos_id: int = -1,
) -> tuple[np.ndarray, np.ndarray]:
    """Repack prompt+completion CONTIGUOUSLY (B, S+N) + completion mask.

    Generation ran with the prompt left-aligned and the completion appended at
    position prompt_len via the KV cache — so the trained sequence must be
    prompt tokens immediately followed by completion tokens (no pad gap in the
    middle; a gap would teacher-force different positions than the policy saw).
    The mask covers sampled tokens only, INCLUDING the EOS sample when it
    fired (ending is a policy decision worth credit).
    """
    batch = len(prompt_ids)
    n = gen_tokens.shape[1]
    tokens = np.full((batch, total_len), pad_id, dtype=np.int32)
    mask = np.zeros((batch, total_len), dtype=np.float32)
    for i, prompt in enumerate(prompt_ids):
        p = len(prompt)
        gl = int(gen_lengths[i])
        eff = min(gl + 1, n) if (eos_id >= 0 and gl < n) else gl
        tokens[i, :p] = prompt
        tokens[i, p : p + eff] = gen_tokens[i, :eff]
        mask[i, p : p + eff] = 1.0
    return tokens, mask


@dataclass
class GrpoReport:
    steps: int = 0
    mean_rewards: list[float] = field(default_factory=list)
    final_loss: float = float("nan")
    wall_time_s: float = 0.0

    @property
    def first_reward(self) -> float:
        return self.mean_rewards[0] if self.mean_rewards else float("nan")

    @property
    def last_reward(self) -> float:
        return self.mean_rewards[-1] if self.mean_rewards else float("nan")

    def as_dict(self) -> dict[str, Any]:
        return {
            "steps": self.steps,
            "first_reward": self.first_reward,
            "last_reward": self.last_reward,
            "final_loss": self.final_loss,
            "wall_time_s": self.wall_time_s,
        }


def run_grpo(
    config: ModelConfig,
    params,
    tokenizer,
    examples: Sequence[dict],                      # [{"prompt":..., "answer":...}]
    scorer: Callable[[str, str], float] | None,
    cfg: GrpoConfig,
    *,
    optimizer: optax.GradientTransformation | None = None,
    mesh=None,
    rng: jax.Array | None = None,
    metrics=None,                                  # train.metrics.MetricsLogger
    checkpoints=None,                              # train.checkpoint.CheckpointManager
    checkpoint_every: int = 0,
    on_step: Callable[[int, dict], None] | None = None,
    attn_impl: str = "auto",
    lora=None,   # train.lora.LoraConfig: train adapters over the frozen base
    copy_params: bool = True,
) -> tuple[TrainState, GrpoReport]:
    """Drive the GRPO loop: sample P prompts → G rollouts each → score →
    group advantages → mu clipped-surrogate updates. Returns the final
    TrainState and a report with the reward trajectory.

    ``scorer(completion, answer) -> float`` is the env contract
    (envhub/execution.py LoadedEnvironment); None falls back to exact-match
    via evals.datasets.score_completion.

    ``copy_params=False`` (dense path) skips the safety copy of ``params``
    and donates the caller's tree directly — saves one full model of HBM on
    big models, but the passed tree is CONSUMED (unusable after the call).
    """
    import contextlib

    from jax.sharding import NamedSharding

    from prime_tpu.evals.datasets import score_completion
    from prime_tpu.parallel.sharding import (
        batch_spec,
        cache_spec_for,
        lengths_spec,
        shard_batch,
    )

    if not examples:
        raise ValueError("GRPO needs at least one {prompt, answer} example")
    if optimizer is None:
        optimizer = optax.chain(
            optax.clip_by_global_norm(1.0), optax.adamw(cfg.learning_rate, b1=0.9, b2=0.95)
        )
    rng = jax.random.PRNGKey(0) if rng is None else rng

    base_params = None
    ref_params = None
    if lora is not None:
        from prime_tpu.train.lora import init_lora_params, shard_lora_state

        rng, lora_rng = jax.random.split(rng)
        state = init_train_state(init_lora_params(lora_rng, config, lora), optimizer)
        base_params = params  # frozen; doubles as the KL reference (the
        # zero-effect adapter init makes base == start policy exactly)
        if mesh is not None:
            from prime_tpu.parallel.sharding import shard_params

            base_params = shard_params(base_params, mesh, config)
            state = shard_lora_state(state, mesh, config, lora)
    else:
        # real copy, not an alias: the update step donates state.params, and a
        # donated alias would leave the CALLER's params tree pointing at
        # deleted buffers after the first step (crashing any later host-side
        # reuse — saving, comparing, a second run_grpo call). copy_params=False
        # skips the extra model of HBM and consumes the caller's tree instead.
        start = jax.tree.map(jnp.copy, params) if copy_params else params
        state = init_train_state(start, optimizer)
        if cfg.kl_coef > 0.0:
            ref_params = jax.tree.map(jnp.copy, params)
        if mesh is not None:
            from prime_tpu.train.trainer import shard_train_state as _sts

            state = _sts(state, mesh, config)
            if ref_params is not None:
                from prime_tpu.parallel.sharding import shard_params

                ref_params = shard_params(ref_params, mesh, config)

    pad_id = tokenizer.pad_id
    eos_id = getattr(tokenizer, "eos_id", -1)
    batch = cfg.prompts_per_step * cfg.group_size
    total_len = cfg.max_prompt_len + cfg.max_new_tokens
    if mesh is not None:
        data = mesh.shape.get("dp", 1) * mesh.shape.get("fsdp", 1)
        if batch % data:
            raise ValueError(
                f"rollout batch {batch} (= prompts_per_step * group_size) must be "
                f"divisible by the mesh data axes ({data})"
            )

    def place(x, spec):
        if mesh is None:
            return x
        return jax.device_put(x, NamedSharding(mesh, spec))

    report = GrpoReport()
    t0 = time.monotonic()
    # prompt schedule derives from the caller's rng key — different keys give
    # different schedules (a fixed host seed would repeat the same subset)
    example_rng = np.random.default_rng(
        np.asarray(jax.random.key_data(rng)).ravel().tolist()
    )
    mesh_ctx = (lambda: jax.set_mesh(mesh)) if mesh is not None else contextlib.nullcontext
    gen_kw: dict = {"attn_impl": attn_impl}
    score_impl = attn_impl
    if mesh is not None:
        gen_kw["cache_spec"] = cache_spec_for(config)  # MLA latent head stays replicated
        if mesh.size > 1:
            # pallas is not SPMD-partitionable; both generate and the
            # teacher-forced score/update passes must take the XLA paths
            gen_kw["attn_impl"] = "xla"
            score_impl = "xla"
    # one update per batch and no KL reference → the ratio is identically 1:
    # skip the behavior-policy snapshot pass entirely (stop_gradient inside)
    on_policy = cfg.epochs_per_batch == 1 and cfg.kl_coef == 0.0
    step_fn = make_grpo_step(
        config, optimizer, cfg.clip_eps, cfg.kl_coef, score_impl,
        on_policy=on_policy, lora=lora, remat=cfg.remat,
    )

    for step in range(cfg.steps):
        picks = example_rng.choice(len(examples), size=cfg.prompts_per_step, replace=True)
        chosen = [examples[int(i)] for i in picks]
        prompt_ids = [
            tokenizer.encode(e["prompt"])[-cfg.max_prompt_len :] for e in chosen
        ]
        # each prompt repeated G times, groups contiguous: row i*G+g
        grouped_ids = [p for p in prompt_ids for _ in range(cfg.group_size)]
        prompts = np.full((batch, cfg.max_prompt_len), pad_id, dtype=np.int32)
        lengths = np.zeros((batch,), dtype=np.int32)
        for i, ids in enumerate(grouped_ids):
            prompts[i, : len(ids)] = ids
            lengths[i] = len(ids)

        rng, roll_rng = jax.random.split(rng)
        if lora is not None:
            from prime_tpu.train.lora import merge_lora

            policy_params = merge_lora(base_params, state.params, lora)
        else:
            policy_params = state.params
        with mesh_ctx():
            result = generate(
                policy_params,
                place(jnp.asarray(prompts), batch_spec()),
                place(jnp.asarray(lengths), lengths_spec()),
                config,
                roll_rng,
                max_new_tokens=cfg.max_new_tokens,
                temperature=cfg.temperature,
                top_p=cfg.top_p,
                nucleus=cfg.top_p < 1.0,
                eos_id=eos_id,
                pad_id=pad_id,
                **gen_kw,
            )
        gen_tokens = np.asarray(jax.device_get(result.tokens))
        gen_lengths = np.asarray(jax.device_get(result.lengths))

        completions = [
            tokenizer.decode(gen_tokens[i, : gen_lengths[i]].tolist()) for i in range(batch)
        ]
        rewards = np.zeros((cfg.prompts_per_step, cfg.group_size), dtype=np.float32)
        for i in range(batch):
            answer = chosen[i // cfg.group_size].get("answer", "")
            text = completions[i]
            if scorer is not None:
                rewards[i // cfg.group_size, i % cfg.group_size] = float(scorer(text, answer))
            else:
                rewards[i // cfg.group_size, i % cfg.group_size] = float(
                    score_completion(text, str(answer))
                )
        advantages = group_advantages(rewards, cfg.adv_eps).reshape(batch)

        tokens, mask = pack_rollouts(
            grouped_ids, gen_tokens, gen_lengths, pad_id, total_len, eos_id=eos_id
        )
        tokens_j = jnp.asarray(tokens)
        mask_j = jnp.asarray(mask)
        adv_j = jnp.asarray(advantages)
        if mesh is not None:
            tokens_j, mask_j = shard_batch(tokens_j, mesh), shard_batch(mask_j, mesh)
            adv_j = place(adv_j, lengths_spec())

        with mesh_ctx():
            if on_policy:
                del policy_params  # the in-jit merge must be the only live copy
                zeros = jnp.zeros_like(mask_j)
                state, step_metrics = step_fn(
                    state, base_params, tokens_j, mask_j, adv_j, zeros, zeros
                )
            else:
                old_lp = token_logprobs(policy_params, tokens_j, config, attn_impl=score_impl)
                del policy_params  # see above
                kl_reference = base_params if lora is not None else ref_params
                ref_lp = (
                    token_logprobs(kl_reference, tokens_j, config, attn_impl=score_impl)
                    if (kl_reference is not None and cfg.kl_coef > 0.0)
                    else old_lp
                )
                for _ in range(cfg.epochs_per_batch):
                    state, step_metrics = step_fn(
                        state, base_params, tokens_j, mask_j, adv_j, old_lp, ref_lp
                    )

        mean_reward = float(rewards.mean())
        loss = float(step_metrics["loss"])
        report.steps = step + 1
        report.mean_rewards.append(mean_reward)
        report.final_loss = loss
        row = {
            "reward_mean": mean_reward,
            "reward_std": float(rewards.std()),
            "loss": loss,
            "kl": float(step_metrics["kl"]),
            "clip_frac": float(step_metrics["clip_frac"]),
            "grad_norm": float(step_metrics["grad_norm"]),
            "completion_len_mean": float(gen_lengths.mean()),
        }
        if metrics is not None:
            metrics.log(step, **row)
        if on_step is not None:
            on_step(step, row)
        if checkpoints is not None and checkpoint_every and (step + 1) % checkpoint_every == 0:
            checkpoints.save(state, metrics={"reward_mean": mean_reward})

    report.wall_time_s = time.monotonic() - t0
    return state, report
