"""Local training metrics log: append-only jsonl + simple aggregation.

Client-side observability (SURVEY.md §5): hosted runs stream metrics from the
backend; local runs write the same shape to ``metrics.jsonl`` so the same
tooling (`prime train metrics`-style views, Lab charts later) reads both.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Any


class MetricsLogger:
    def __init__(self, directory: str | Path) -> None:
        self.path = Path(directory) / "metrics.jsonl"
        self.path.parent.mkdir(parents=True, exist_ok=True)

    def log(self, step: int, **metrics: Any) -> None:
        row = {"step": step, "ts": time.time()}
        for key, value in metrics.items():
            try:
                row[key] = float(value)
            except (TypeError, ValueError):
                row[key] = value
        with open(self.path, "a") as f:
            f.write(json.dumps(row) + "\n")

    def read(self) -> list[dict[str, Any]]:
        if not self.path.exists():
            return []
        return [json.loads(line) for line in self.path.read_text().splitlines() if line.strip()]

    def last(self) -> dict[str, Any] | None:
        rows = self.read()
        return rows[-1] if rows else None
