"""LoRA adapters for the native trainer (the reference's default run type).

The reference's hosted RL defaults to ``type = "lora"`` with an
``[adapter]`` section (r, alpha, dropout — reference commands/rl.py:362-763)
but trains server-side; here adapters train on the local slice.

TPU-first construction: no model surgery. The base params stay frozen; each
step materializes the merged weight ``W + (alpha/r) A @ B`` functionally
inside the loss and differentiates w.r.t. the adapters alone. On TPU the
merge is two small matmuls fused into the weight load — the win LoRA
actually buys is optimizer memory (Adam moments shrink from every weight to
the adapter factors, ~1000x smaller at r=16 on an 8B model) plus tiny
checkpoint/deploy artifacts, and both survive this formulation. Adapters
shard with their base weight's PartitionSpec axes (A takes the input/fsdp
axis, B the output/tp axis), so the merged weight has the same layout XLA
already expects.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path
from typing import Any

import jax
import jax.numpy as jnp
import optax

from prime_tpu.models.config import ModelConfig
from prime_tpu.models.llama import forward
from prime_tpu.train.trainer import TrainState, cross_entropy_loss, init_train_state

# projection weights eligible for adaptation: name -> (in_dim, out_dim) fns
_TARGET_DIMS = {
    "wq": lambda c: (c.d_model, c.n_heads * c.head_dim),
    "wk": lambda c: (c.d_model, c.n_kv_heads * c.head_dim),
    "wv": lambda c: (c.d_model, c.n_kv_heads * c.head_dim),
    "wo": lambda c: (c.n_heads * c.head_dim, c.d_model),
    "w_gate": lambda c: (c.d_model, c.d_ff),
    "w_up": lambda c: (c.d_model, c.d_ff),
    "w_down": lambda c: (c.d_ff, c.d_model),
}

DEFAULT_TARGETS = ("wq", "wk", "wv", "wo")


@dataclass(frozen=True)
class LoraConfig:
    r: int = 16
    alpha: int = 32
    targets: tuple[str, ...] = DEFAULT_TARGETS

    def __post_init__(self) -> None:
        if self.r < 1:
            raise ValueError(f"LoRA rank must be >= 1 (got {self.r})")
        unknown = [t for t in self.targets if t not in _TARGET_DIMS]
        if unknown:
            raise ValueError(
                f"Unknown LoRA targets {unknown}; choose from {sorted(_TARGET_DIMS)}"
            )

    @property
    def scale(self) -> float:
        return self.alpha / self.r


def init_lora_params(
    rng: jax.Array, config: ModelConfig, lora: LoraConfig, dtype=jnp.float32
) -> dict[str, Any]:
    """A zero-effect init: A ~ normal(0, 1/r), B = 0 — merged weights equal
    the base exactly until the first update (the standard LoRA init).

    MoE configs adapt their ATTENTION projections (identical layout to
    dense models); the expert MLP stacks carry an extra expert axis the
    (L, d_in, r) factors cannot address, so MLP targets reject loudly.
    MLA configs have no wq/wk/wv at all (low-rank q/kv projections) and
    reject as a whole."""
    if getattr(config, "mla", False):
        raise NotImplementedError(
            "LoRA targets (wq/wk/wv/wo) do not exist in MLA configs "
            "(attention runs through low-rank wq_a/wq_b/wkv_a/wkv_b)"
        )
    if config.is_moe:
        mlp_targets = set(lora.targets) & {"w_gate", "w_up", "w_down"}
        if mlp_targets:
            raise NotImplementedError(
                f"LoRA on MoE expert MLPs is not supported (targets "
                f"{sorted(mlp_targets)} have a stacked expert axis); "
                "target the attention projections instead"
            )
    layers = config.n_layers
    adapters: dict[str, Any] = {}
    keys = jax.random.split(rng, len(lora.targets))
    for key, name in zip(keys, lora.targets):
        d_in, d_out = _TARGET_DIMS[name](config)
        adapters[name] = {
            "a": (jax.random.normal(key, (layers, d_in, lora.r), jnp.float32) / lora.r).astype(dtype),
            "b": jnp.zeros((layers, lora.r, d_out), dtype),
        }
    return {"layers": adapters}


def merge_lora(params: dict, adapters: dict, lora: LoraConfig) -> dict:
    """Base params + scale * A @ B on every adapted projection. Pure — usable
    inside a jitted loss (train-time) or once up front (serving)."""
    merged_layers = dict(params["layers"])
    for name, ab in adapters["layers"].items():
        base = merged_layers[name]
        delta = jnp.einsum(
            "lir,lro->lio", ab["a"].astype(jnp.float32), ab["b"].astype(jnp.float32)
        ) * lora.scale
        # the delta is computed in fp32 but ADDED in the base dtype: upcasting
        # the base would materialize a full fp32 copy of every adapted weight
        # stack — multi-GB temporaries for models that only fit sharded
        merged_layers[name] = base + delta.astype(base.dtype)
    return {**params, "layers": merged_layers}


def lora_param_specs(config: ModelConfig, lora: LoraConfig) -> dict[str, Any]:
    """PartitionSpecs mirroring each target's base layout: A inherits the
    input axis, B the output axis, rank replicated."""
    from jax.sharding import PartitionSpec as P

    from prime_tpu.parallel.sharding import param_specs

    base = param_specs(config)["layers"]
    specs: dict[str, Any] = {}
    for name in lora.targets:
        w = base[name]  # P(None, in_axis, out_axis)
        specs[name] = {"a": P(None, w[1], None), "b": P(None, None, w[2])}
    return {"layers": specs}


def shard_lora_state(state: TrainState, mesh, config: ModelConfig, lora: LoraConfig) -> TrainState:
    """Adapter-state placement = the base trainer's placement with the
    adapter-factor sharding tree swapped in (one owner for the
    structure-matched optimizer-moment logic)."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from prime_tpu.train.trainer import shard_train_state

    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        lora_param_specs(config, lora),
        is_leaf=lambda x: isinstance(x, P),
    )
    return shard_train_state(state, mesh, config, shardings=shardings)


def make_lora_train_step(
    config: ModelConfig,
    lora: LoraConfig,
    optimizer: optax.GradientTransformation,
    attn_impl: str = "auto",
    remat: str = "none",  # activation checkpointing (same modes as make_train_step)
    aux_weight: float = 0.01,  # MoE load-balance weight (same as make_train_step)
):
    """Jitted LoRA step: state holds ONLY the adapters; the frozen base
    params ride as a non-donated argument. fp32 adapter math throughout (the
    factors are tiny — no reason to round them)."""

    def loss_fn(adapters, base_params, tokens, targets, mask):
        merged = merge_lora(base_params, adapters, lora)
        if config.is_moe:
            # attention adapters steer the hidden states the router reads,
            # so the balance loss stays in the objective exactly as in the
            # full trainer
            logits, _, aux = forward(
                merged, tokens, config, cache=None, attn_impl=attn_impl,
                remat=remat, return_aux=True,
            )
            return cross_entropy_loss(logits, targets, mask) + aux_weight * aux
        logits, _ = forward(
            merged, tokens, config, cache=None, attn_impl=attn_impl, remat=remat
        )
        return cross_entropy_loss(logits, targets, mask)

    def step(state: TrainState, base_params, tokens, targets, mask):
        from prime_tpu.train.trainer import apply_gradients

        loss, grads = jax.value_and_grad(loss_fn)(
            state.params, base_params, tokens, targets, mask
        )
        new_state, grad_norm = apply_gradients(state, grads, optimizer)
        return new_state, {"loss": loss, "grad_norm": grad_norm}

    return jax.jit(step, donate_argnums=(0,))


def init_lora_state(adapters: dict, optimizer: optax.GradientTransformation) -> TrainState:
    return init_train_state(adapters, optimizer)


# ---- adapter artifacts -------------------------------------------------------


def base_fingerprint(params: dict) -> list[float]:
    """A cheap content fingerprint of the base weights. Catches the
    silent-corruption case the base-model *name* can't: adapters trained over
    the local trainer's random-init base merging into a real checkpoint that
    happens to share the config name. Samples leaves ACROSS the tree (embed +
    a fixed attention and MLP slice of layer 0) so drift outside the embedding
    — e.g. an SFT variant with frozen embeddings — still trips the check."""
    slices = [params["embed"][:256]]
    layers = params.get("layers", {})
    for key in ("wq", "w_down"):
        if key in layers:
            slices.append(layers[key][0, :64])
    out: list[float] = []
    for s in slices:
        s = s.astype(jnp.float32)
        out += [float(jnp.mean(s)), float(jnp.std(s))]
    return out


def fingerprints_match(a: list[float], b: list[float], rtol: float = 1e-2) -> bool:
    """Loose comparison: bf16-vs-fp32 loads of the same checkpoint must
    match; a random init vs a trained checkpoint must not.

    Legacy compat: artifacts saved before the multi-leaf scheme record only
    the 2 embedding moments — those compare against the first 2 elements of
    a current fingerprint (embed comes first) instead of being rejected with
    a misleading 'different base weights' diagnosis. Any other length
    mismatch is a mismatch — zip truncation must not weaken the check."""
    if len(a) != len(b):
        if 2 in (len(a), len(b)) and min(len(a), len(b)) == 2:
            a, b = a[:2], b[:2]
        else:
            return False
    return all(abs(x - y) <= rtol * max(abs(x), abs(y), 1e-6) for x, y in zip(a, b))


def save_adapters(
    path: str | Path,
    adapters: dict,
    lora: LoraConfig,
    config: ModelConfig,
    base_params: dict | None = None,
) -> Path:
    """Write a self-describing adapter artifact (.npz + json sidecar)."""
    import json

    import numpy as np

    path = Path(path)
    path.mkdir(parents=True, exist_ok=True)
    flat = {
        f"{name}.{piece}": np.asarray(ab[piece])
        for name, ab in adapters["layers"].items()
        for piece in ("a", "b")
    }
    np.savez(path / "adapters.npz", **flat)
    meta = {
        "r": lora.r,
        "alpha": lora.alpha,
        "targets": list(lora.targets),
        "base_model": config.name,
    }
    if base_params is not None:
        meta["base_fingerprint"] = base_fingerprint(base_params)
    (path / "adapter_config.json").write_text(json.dumps(meta, indent=2))
    return path


def load_adapters(path: str | Path) -> tuple[dict, LoraConfig, dict]:
    """Read (adapters, LoraConfig, metadata) back from an artifact. The
    metadata dict carries at least ``base_model`` and, when the trainer
    recorded one, ``base_fingerprint``."""
    import json

    import numpy as np

    path = Path(path)
    meta = json.loads((path / "adapter_config.json").read_text())
    lora = LoraConfig(r=meta["r"], alpha=meta["alpha"], targets=tuple(meta["targets"]))
    data = np.load(path / "adapters.npz")
    adapters: dict[str, Any] = {"layers": {}}
    for name in lora.targets:
        adapters["layers"][name] = {
            "a": jnp.asarray(data[f"{name}.a"]),
            "b": jnp.asarray(data[f"{name}.b"]),
        }
    return adapters, lora, meta
