"""Hosted-training TOML config schema (reference: commands/rl.py:362-913).

Pydantic with ``extra="forbid"`` everywhere — typos in TOML keys are errors,
not silently ignored config. Deprecated keys are stripped with warnings
(reference :829); GPU-era keys map to their TPU replacements. Full-finetune
detection (reference :882) switches dispatch to the dedicated trainer.
"""

from __future__ import annotations

from pathlib import Path
from typing import Literal

from pydantic import BaseModel, ConfigDict, Field

from prime_tpu.utils.compat import tomllib

# GPU-era keys → TPU replacement (or None if dropped outright)
DEPRECATED_KEYS: dict[str, str | None] = {
    "gpu_type": "infrastructure.tpu_type",
    "num_gpus": "infrastructure.tpu_type (slice size)",
    "gpus": "infrastructure.tpu_type (slice size)",
    "interconnect": None,
    "nccl_timeout": None,
}


class EnvSection(BaseModel):
    model_config = ConfigDict(extra="forbid")

    id: str
    version: str | None = None
    max_input_tokens: int | None = None
    max_output_tokens: int | None = None
    max_total_tokens: int | None = None


class SamplingSection(BaseModel):
    model_config = ConfigDict(extra="forbid")

    temperature: float = 1.0
    top_p: float = 1.0
    max_tokens: int = 512
    seq_len: int = 4096


class EvalSection(BaseModel):
    model_config = ConfigDict(extra="forbid")

    interval: int = 100
    n_samples: int = 64


class WandbSection(BaseModel):
    model_config = ConfigDict(extra="forbid")

    project: str | None = None
    entity: str | None = None


class CheckpointsSection(BaseModel):
    model_config = ConfigDict(extra="forbid")

    interval: int = 500
    keep: int = 3


class AdapterSection(BaseModel):
    model_config = ConfigDict(extra="forbid")

    r: int = 16
    alpha: int = 32
    dropout: float = 0.0


class InfrastructureSection(BaseModel):
    model_config = ConfigDict(extra="forbid")

    tpu_type: str = "v5e-8"        # slice name — chips implied by the slice
    num_slices: int = 1            # DCN data parallelism across slices


class RLConfig(BaseModel):
    model_config = ConfigDict(extra="forbid")

    name: str
    model: str
    type: Literal["lora", "full_finetune"] = "lora"
    env: EnvSection
    learning_rate: float = 1e-5
    batch_size: int = 32
    max_steps: int = 1000
    checkpoint_id: str | None = None     # warm start (reference :778)
    sampling: SamplingSection = Field(default_factory=SamplingSection)
    eval: EvalSection = Field(default_factory=EvalSection)
    wandb: WandbSection = Field(default_factory=WandbSection)
    checkpoints: CheckpointsSection = Field(default_factory=CheckpointsSection)
    adapter: AdapterSection = Field(default_factory=AdapterSection)
    infrastructure: InfrastructureSection = Field(default_factory=InfrastructureSection)

    @property
    def is_full_finetune(self) -> bool:
        return self.type == "full_finetune"

    def to_payload(self) -> dict:
        payload = {
            "name": self.name,
            "model": self.model,
            "runType": self.type,
            "env": self.env.model_dump(exclude_none=True),
            "learningRate": self.learning_rate,
            "batchSize": self.batch_size,
            "maxSteps": self.max_steps,
            "sampling": self.sampling.model_dump(),
            "eval": self.eval.model_dump(),
            "checkpoints": self.checkpoints.model_dump(),
            "adapter": self.adapter.model_dump(),
            "tpuType": self.infrastructure.tpu_type,
            "numSlices": self.infrastructure.num_slices,
        }
        if self.checkpoint_id:
            payload["checkpointId"] = self.checkpoint_id
        if self.wandb.project:
            payload["wandb"] = self.wandb.model_dump(exclude_none=True)
        return payload


def strip_deprecated(raw: dict) -> tuple[dict, list[str]]:
    """Remove deprecated keys anywhere in the tree; return warnings."""
    warnings = []

    def walk(node: dict) -> dict:
        out = {}
        for key, value in node.items():
            if key in DEPRECATED_KEYS:
                replacement = DEPRECATED_KEYS[key]
                hint = f" — use {replacement}" if replacement else " (no TPU equivalent)"
                warnings.append(f"deprecated key '{key}' ignored{hint}")
                continue
            out[key] = walk(value) if isinstance(value, dict) else value
        return out

    return walk(raw), warnings


def load_rl_config(toml_path: str | Path) -> tuple[RLConfig, list[str]]:
    raw = tomllib.loads(Path(toml_path).read_text())
    cleaned, warnings = strip_deprecated(raw)
    return RLConfig.model_validate(cleaned), warnings


RL_TOML_TEMPLATE = """\
name = "{name}"
model = "llama3-8b"
type = "lora"            # or "full_finetune"
learning_rate = 1e-5
batch_size = 32
max_steps = 1000

[env]
id = "gsm8k"

[sampling]
temperature = 1.0
max_tokens = 512
seq_len = 4096

[adapter]
r = 16
alpha = 32

[infrastructure]
tpu_type = "v5e-8"       # TPU slice per worker
num_slices = 1           # DCN data parallelism across slices

[checkpoints]
interval = 500
keep = 3
"""
