"""Training: TOML config schema (pure pydantic) + sharded JAX trainer.

Lazy exports: ``prime_tpu.train.config`` is importable without pulling in
jax/optax (the CLI loads it for --help), while the trainer symbols resolve on
first access.
"""

_TRAINER_EXPORTS = {
    "TrainState",
    "cross_entropy_loss",
    "default_optimizer",
    "init_train_state",
    "make_train_step",
    "shard_train_state",
}

__all__ = sorted(_TRAINER_EXPORTS)


def __getattr__(name: str):
    if name in _TRAINER_EXPORTS:
        from prime_tpu.train import trainer

        return getattr(trainer, name)
    raise AttributeError(f"module 'prime_tpu.train' has no attribute {name!r}")
