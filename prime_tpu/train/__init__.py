from prime_tpu.train.trainer import (
    TrainState,
    cross_entropy_loss,
    default_optimizer,
    init_train_state,
    make_train_step,
    shard_train_state,
)

__all__ = [
    "TrainState",
    "cross_entropy_loss",
    "default_optimizer",
    "init_train_state",
    "make_train_step",
    "shard_train_state",
]
