"""Training: TOML config schema (pure pydantic) + sharded JAX trainer.

Lazy exports: ``prime_tpu.train.config`` is importable without pulling in
jax/optax (the CLI loads it for --help), while the trainer symbols resolve on
first access.
"""

_TRAINER_EXPORTS = {
    "TrainState",
    "cross_entropy_loss",
    "default_optimizer",
    "init_train_state",
    "make_train_step",
    "shard_train_state",
    "warmup_cosine",
}
_LOOP_EXPORTS = {"LoopReport", "train_loop"}
_GRPO_EXPORTS = {"GrpoConfig", "GrpoReport", "group_advantages", "run_grpo", "token_logprobs"}

__all__ = sorted(_TRAINER_EXPORTS | _LOOP_EXPORTS | _GRPO_EXPORTS)


def __getattr__(name: str):
    if name in _TRAINER_EXPORTS:
        from prime_tpu.train import trainer

        return getattr(trainer, name)
    if name in _LOOP_EXPORTS:
        from prime_tpu.train import loop

        return getattr(loop, name)
    if name in _GRPO_EXPORTS:
        from prime_tpu.train import grpo

        return getattr(grpo, name)
    raise AttributeError(f"module 'prime_tpu.train' has no attribute {name!r}")
