"""Token batch sources for local training.

Two sources, both static-shape and stream-friendly:
- ``text_batches``: a raw text file tokenized (byte tokenizer by default, any
  prime_tpu tokenizer otherwise) into one continuous stream, cut into
  (batch, seq+1) windows — next-token targets come from the shifted window.
- ``synthetic_batches``: random tokens, for smoke tests and throughput
  benches where data content is irrelevant.
"""

from __future__ import annotations

from pathlib import Path
from typing import Iterator

import numpy as np


def _windows(stream: np.ndarray, batch: int, seq: int, steps: int, seed: int) -> Iterator[tuple]:
    import jax.numpy as jnp

    window = seq + 1
    usable = len(stream) - window + 1  # number of valid window start positions
    if usable <= 0:
        raise ValueError(
            f"dataset has {len(stream)} tokens, need at least {window} for seq_len {seq}"
        )
    rng = np.random.default_rng(seed)
    for _ in range(steps):
        starts = rng.integers(0, usable, size=batch)  # exclusive high: last window included
        chunk = np.stack([stream[s : s + window] for s in starts])
        tokens = jnp.asarray(chunk[:, :-1], dtype=jnp.int32)
        targets = jnp.asarray(chunk[:, 1:], dtype=jnp.int32)
        yield tokens, targets, jnp.ones_like(tokens, jnp.float32)


def text_batches(
    path: str | Path,
    batch: int,
    seq: int,
    steps: int,
    tokenizer=None,
    seed: int = 0,
) -> Iterator[tuple]:
    """Batches from a text file. Default tokenizer: hermetic byte-level."""
    from prime_tpu.evals.tokenizer import load_tokenizer

    tokenizer = tokenizer or load_tokenizer(None)
    text = Path(path).read_text()
    stream = np.asarray(tokenizer.encode(text), dtype=np.int32)
    yield from _windows(stream, batch, seq, steps, seed)


def synthetic_batches(
    vocab_size: int, batch: int, seq: int, steps: int, seed: int = 0
) -> Iterator[tuple]:
    import jax
    import jax.numpy as jnp

    key = jax.random.PRNGKey(seed)
    for step in range(steps):
        key, sub = jax.random.split(key)
        tokens = jax.random.randint(sub, (batch, seq), 0, vocab_size)
        yield tokens, jnp.roll(tokens, -1, axis=1), jnp.ones_like(tokens, jnp.float32)
