"""Per-PR perf trajectory: diff committed BENCH_*.json rounds into a table.

Every bench round commits one JSON record (bench.py, last-JSON-line-wins).
This module — stdlib-only, importable by both ``scripts/perf_delta.py`` and
``prime bench delta`` — loads every committed round, labels each with its
record schema (schema 1: the pre-loadgen rounds, headline-only fields;
schema 2: adds the loadgen SLO report under ``loadgen``), and renders the
metric-by-round delta table that answers the only question a perf PR has to
answer: which headline moved, by how much, since the previous round.

Zero-valued headlines are real data (five rounds of ``0.0 tok/s — backend
unresponsive`` ARE the trajectory this tooling exists to end) and render as
written; deltas are computed against the latest previous round with a
usable value so one dead round doesn't blind the comparison.
"""

from __future__ import annotations

import glob
import json
import os
import re
from dataclasses import dataclass, field
from typing import Any

# record keys → table rows, in display order. Ratios render raw; everything
# else is a rate where bigger is better.
HEADLINE_METRICS: tuple[tuple[str, str], ...] = (
    ("headline tok/s", "value"),
    ("decode-only tok/s", "decode_only_tok_s"),
    ("eval samples/s", "eval_samples_per_sec"),
    ("serve tok/s", "serve_tok_s"),
    ("serve overlap ratio", "serve_overlap_ratio"),
    ("serve int8 tok/s", "serve_int8_tok_s"),
    ("prefixburst tok/s", "serve_prefixburst_tok_s"),
    ("prefixburst hit ratio", "serve_prefixburst_hit_ratio"),
    ("fleet tok/s", "serve_fleet_tok_s"),
    ("fleet affinity ratio", "serve_fleet_affinity_ratio"),
    ("int8 tok/s", "int8_weights_tok_s"),
    ("int4 tok/s", "int4_weights_tok_s"),
    ("longctx pallas speedup", "longctx_pallas_speedup"),
    ("trainstep tok/s", "trainstep_tok_s"),
)

_ROUND_RE = re.compile(r"BENCH_(?:(?P<kind>[a-z_]+)_)?r(?P<num>\d+)\.json$")


@dataclass
class Round:
    label: str
    path: str
    order: tuple
    schema: int
    record: dict[str, Any]
    metrics: dict[str, float] = field(default_factory=dict)

    @property
    def error(self) -> str | None:
        return self.record.get("error")


def _slo_metrics(report: dict) -> dict[str, float]:
    """Flatten a loadgen SLO report (schema 2 records carry one under
    ``loadgen``) into table rows: the aggregate headline plus per-scenario
    throughput and TTFT p50/p95."""
    out: dict[str, float] = {}
    headline = report.get("headline") or {}
    if isinstance(headline.get("tok_s"), (int, float)):
        out["loadgen tok/s"] = float(headline["tok_s"])
    for row in report.get("scenarios") or []:
        # "slo:" prefix keeps SLO-row names disjoint from HEADLINE_METRICS
        # labels — a scenario named "serve" must not silently overwrite the
        # record-field "serve tok/s" cell (different rounding, different
        # sourcing era)
        name = f"slo:{row.get('scenario', '?')}"
        if isinstance(row.get("tok_s"), (int, float)):
            out[f"{name} tok/s"] = float(row["tok_s"])
        for family, unit in (("ttft_s", "ttft"), ("tpot_s", "tpot")):
            quantiles = row.get(family) or {}
            for q in ("p50", "p95"):
                value = quantiles.get(q)
                if isinstance(value, (int, float)):
                    out[f"{name} {unit} {q} ms"] = round(value * 1e3, 3)
    return out


def _round_from_record(path: str, record: dict[str, Any]) -> Round:
    m = _ROUND_RE.search(os.path.basename(path))
    kind = (m.group("kind") if m else None) or ""
    # no r<N> in the name: sort AFTER every numbered round (it must never
    # become r01's delta baseline) and label it by its filename stem
    num = int(m.group("num")) if m else None
    # the driver wraps each round's bench record: {"n", "cmd", "rc", "tail",
    # "parsed": <last JSON line or null>}. Unwrap it; a null parse (the
    # round-3 mid-preflight kill) becomes an explicit error record rather
    # than a skipped round — a dead round is part of the trajectory.
    if "parsed" in record and "rc" in record:
        num = int(record.get("n") or num or 0)
        parsed = record["parsed"]
        if isinstance(parsed, dict):
            record = parsed
        else:
            record = {
                "value": 0.0,
                "error": f"record unparseable (driver rc={record.get('rc')})",
            }
    if num is None:
        label = os.path.basename(path)[: -len(".json")]
        order: tuple = (float("inf"), label)
    else:
        label = f"r{num:02d}" + (f"-{kind}" if kind else "")
        order = (num, kind)
    # schema 1: every round before the loadgen era (no "schema" key). The
    # labeling here is what lets a delta across nine historical rounds parse
    # without guessing which fields can exist.
    schema = int(record.get("schema", 1))
    metrics: dict[str, float] = {}
    for row_label, key in HEADLINE_METRICS:
        value = record.get(key)
        if isinstance(value, (int, float)) and not isinstance(value, bool):
            if key == "value" and not str(
                record.get("metric", "decode_tokens_per_sec")
            ).startswith("decode_tokens_per_sec"):
                # a CPU loadgen smoke's headline is not the TPU decode
                # headline — same row would render a nonsense cross-backend
                # delta; give it its own trajectory row
                row_label = "cpu-smoke tok/s"
            metrics[row_label] = float(value)
    if schema >= 2 and isinstance(record.get("loadgen"), dict):
        metrics.update(_slo_metrics(record["loadgen"]))
    # opportunistic/secondary records sort after the driver record of the
    # same round number
    return Round(
        label=label, path=path, order=order, schema=schema,
        record=record, metrics=metrics,
    )


def load_rounds(
    root: str = ".", pattern: str = "BENCH_*.json"
) -> list[Round]:
    """Every parseable committed round under ``root``, oldest first.
    Unparseable files are skipped (a half-written record must not take the
    delta table down); files without a BENCH_r<N> name sort last by name."""
    rounds: list[Round] = []
    for path in sorted(glob.glob(os.path.join(root, pattern))):
        try:
            with open(path) as f:
                record = json.load(f)
        except (OSError, ValueError):
            continue
        if isinstance(record, dict):
            rounds.append(_round_from_record(path, record))
    rounds.sort(key=lambda r: (r.order, r.label))
    return rounds


def _fmt(value: float) -> str:
    if value == int(value) and abs(value) >= 100:
        return str(int(value))
    return f"{value:.3g}" if abs(value) < 10 else f"{value:.1f}"


def delta_table(rounds: list[Round], *, min_rounds: int = 2) -> str:
    """Render the metric-by-round table with per-round deltas vs the latest
    previous round that measured the same metric (Δ% for rates/ratios)."""
    if len(rounds) < min_rounds:
        return (
            f"need at least {min_rounds} BENCH_*.json rounds for a delta "
            f"table; found {len(rounds)}"
        )
    metric_names: list[str] = []
    for r in rounds:
        for name in r.metrics:
            if name not in metric_names:
                metric_names.append(name)
    if not metric_names:
        return "no numeric metrics found in any round"
    label_w = max(len(n) for n in metric_names) + 2
    headers = [
        r.label + (f" (s{r.schema})" if r.schema == 1 else "") for r in rounds
    ]
    col_w = max(16, max(len(h) for h in headers) + 2)
    lines = ["".join([" " * label_w] + [f"{h:>{col_w}}" for h in headers])]
    for name in metric_names:
        cells = [f"{name:<{label_w}}"]
        prev: float | None = None
        for r in rounds:
            value = r.metrics.get(name)
            if value is None:
                cells.append(f"{'—':>{col_w}}")
                continue
            cell = _fmt(value)
            if prev not in (None, 0.0):
                pct = (value - prev) / prev * 100.0
                cell += f" ({pct:+.0f}%)"
            elif prev == 0.0 and value > 0:
                cell += " (∅→live)"
            cells.append(f"{cell:>{col_w}}")
            prev = value
        lines.append("".join(cells))
    notes = [
        f"{r.label}: {r.error}" for r in rounds if r.error
    ]
    if notes:
        lines.append("")
        lines.append("round errors:")
        lines.extend(f"  {n}" for n in notes)
    return "\n".join(lines)


def delta_json(rounds: list[Round]) -> dict[str, Any]:
    """Machine form of the same table (CI step summaries, dashboards)."""
    return {
        "rounds": [
            {
                "label": r.label,
                "path": os.path.basename(r.path),
                "schema": r.schema,
                "error": r.error,
                "metrics": r.metrics,
            }
            for r in rounds
        ]
    }
